#!/usr/bin/env bash
# Cron-able retrain + hot-redeploy loop.
# Parity: examples/redeploy-script/redeploy.sh — the reference's
# operational answer to model refresh. Here no restart is needed:
# train writes a new COMPLETED engine instance and /reload hot-swaps
# the serving models without dropping queries.
set -euo pipefail

ENGINE_DIR="${ENGINE_DIR:-$(dirname "$0")/recommendation}"
QUERY_HOST="${QUERY_HOST:-127.0.0.1}"
QUERY_PORT="${QUERY_PORT:-8000}"

python -m predictionio_tpu.tools.cli train --engine-dir "$ENGINE_DIR"
curl -fsS -X POST "http://${QUERY_HOST}:${QUERY_PORT}/reload"
echo
echo "redeployed $(date -u +%FT%TZ)"
