"""Import $set user-property events for the classification quickstart.

Parity: examples/scala-parallel-classification/*/data/import_eventserver.py
— users carry attr0/attr1/attr2 features and a plan label set via $set.

Usage:
    python import_eventserver.py --access-key KEY [--url http://localhost:7070]
"""

import argparse
import json
import random
import urllib.request


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--access-key", required=True)
    p.add_argument("--url", default="http://localhost:7070")
    p.add_argument("--users", type=int, default=120)
    args = p.parse_args()

    rng = random.Random(7)
    events = []
    for u in range(args.users):
        premium = u % 2 == 0
        base = 7.0 if premium else 2.0
        events.append({
            "event": "$set",
            "entityType": "user",
            "entityId": f"u{u}",
            "properties": {
                "attr0": base + rng.random() * 2,
                "attr1": base + rng.random() * 2,
                "attr2": rng.random() * 10,
                "plan": "premium" if premium else "basic",
            },
        })

    sent = 0
    for i in range(0, len(events), 50):  # event server batch limit is 50
        req = urllib.request.Request(
            f"{args.url}/batch/events.json?accessKey={args.access_key}",
            data=json.dumps(events[i : i + 50]).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req) as r:
            sent += sum(1 for x in json.loads(r.read()) if x["status"] == 201)
    print(f"imported {sent} events")


if __name__ == "__main__":
    main()
