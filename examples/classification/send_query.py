"""Query the deployed classification engine with a feature vector.

Usage:
    python send_query.py [--url http://localhost:8000] --features 8.1 7.9 4.2
"""

import argparse
import json
import urllib.request


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--url", default="http://localhost:8000")
    p.add_argument("--features", type=float, nargs=3, default=[8.0, 8.0, 5.0])
    args = p.parse_args()
    req = urllib.request.Request(
        f"{args.url}/queries.json",
        data=json.dumps({"features": args.features}).encode(),
        method="POST",
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req) as r:
        print(json.dumps(json.loads(r.read()), indent=2))


if __name__ == "__main__":
    main()
