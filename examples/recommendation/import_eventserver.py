"""Import sample rate/buy events through the REST event server.

Parity: examples/scala-parallel-recommendation/*/data/import_eventserver.py
(the reference ships an SDK import script per template).

Usage:
    python import_eventserver.py --access-key KEY [--url http://localhost:7070]
"""

import argparse
import json
import random
import urllib.request


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--access-key", required=True)
    p.add_argument("--url", default="http://localhost:7070")
    p.add_argument("--users", type=int, default=50)
    p.add_argument("--items", type=int, default=30)
    p.add_argument("--events-per-user", type=int, default=10)
    args = p.parse_args()

    rng = random.Random(3)
    events = []
    for u in range(args.users):
        for i in rng.sample(range(args.items), args.events_per_user):
            if rng.random() < 0.8:
                events.append(
                    {
                        "event": "rate",
                        "entityType": "user",
                        "entityId": f"u{u}",
                        "targetEntityType": "item",
                        "targetEntityId": f"i{i}",
                        "properties": {"rating": float(rng.randint(1, 5))},
                    }
                )
            else:
                events.append(
                    {
                        "event": "buy",
                        "entityType": "user",
                        "entityId": f"u{u}",
                        "targetEntityType": "item",
                        "targetEntityId": f"i{i}",
                    }
                )

    imported = 0
    for start in range(0, len(events), 50):  # batch limit is 50
        req = urllib.request.Request(
            f"{args.url}/batch/events.json?accessKey={args.access_key}",
            data=json.dumps(events[start : start + 50]).encode(),
            method="POST",
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req) as r:
            imported += sum(1 for x in json.loads(r.read()) if x["status"] == 201)
    print(f"Imported {imported} events.")


if __name__ == "__main__":
    main()
