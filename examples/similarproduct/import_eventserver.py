"""Import view events for the similar-product quickstart.

Parity: examples/scala-parallel-similarproduct/*/data/import_eventserver.py
— users view items; co-viewing defines similarity.

Usage:
    python import_eventserver.py --access-key KEY [--url http://localhost:7070]
"""

import argparse
import json
import random
import urllib.request


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--access-key", required=True)
    p.add_argument("--url", default="http://localhost:7070")
    p.add_argument("--users", type=int, default=60)
    p.add_argument("--items", type=int, default=40)
    args = p.parse_args()

    rng = random.Random(11)
    events = []
    for u in range(args.users):
        # two taste clusters so co-occurrence has structure to find
        lo, hi = (0, args.items // 2) if u % 2 else (args.items // 2, args.items)
        for i in rng.sample(range(lo, hi), 6):
            events.append({
                "event": "view",
                "entityType": "user",
                "entityId": f"u{u}",
                "targetEntityType": "item",
                "targetEntityId": f"i{i}",
            })

    sent = 0
    for i in range(0, len(events), 50):
        req = urllib.request.Request(
            f"{args.url}/batch/events.json?accessKey={args.access_key}",
            data=json.dumps(events[i : i + 50]).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req) as r:
            sent += sum(1 for x in json.loads(r.read()) if x["status"] == 201)
    print(f"imported {sent} events")


if __name__ == "__main__":
    main()
