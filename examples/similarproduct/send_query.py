"""Query the deployed similar-product engine with a seed item list.

Usage:
    python send_query.py [--url http://localhost:8000] --items i1 i2 [--num 4]
"""

import argparse
import json
import urllib.request


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--url", default="http://localhost:8000")
    p.add_argument("--items", nargs="+", default=["i1"])
    p.add_argument("--num", type=int, default=4)
    args = p.parse_args()
    req = urllib.request.Request(
        f"{args.url}/queries.json",
        data=json.dumps({"items": args.items, "num": args.num}).encode(),
        method="POST",
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req) as r:
        print(json.dumps(json.loads(r.read()), indent=2))


if __name__ == "__main__":
    main()
