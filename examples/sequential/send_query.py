"""Query the deployed sequential engine for a user's next items.

Usage:
    python send_query.py [--url http://localhost:8000] --user u1 [--num 3]
"""

import argparse
import json
import urllib.request


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--url", default="http://localhost:8000")
    p.add_argument("--user", default="u1")
    p.add_argument("--num", type=int, default=3)
    args = p.parse_args()
    req = urllib.request.Request(
        f"{args.url}/queries.json",
        data=json.dumps({"user": args.user, "num": args.num}).encode(),
        method="POST",
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req) as r:
        print(json.dumps(json.loads(r.read()), indent=2))


if __name__ == "__main__":
    main()
