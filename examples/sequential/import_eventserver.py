"""Import time-ordered view sequences for the sequential quickstart.

Each user walks a fixed item cycle from a random start, so the transformer
has a deterministic next-item structure to learn.

Usage:
    python import_eventserver.py --access-key KEY [--url http://localhost:7070]
"""

import argparse
import datetime
import json
import random
import urllib.request


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--access-key", required=True)
    p.add_argument("--url", default="http://localhost:7070")
    p.add_argument("--users", type=int, default=60)
    p.add_argument("--items", type=int, default=12)
    p.add_argument("--length", type=int, default=10)
    args = p.parse_args()

    rng = random.Random(19)
    events = []
    for u in range(args.users):
        start = rng.randrange(args.items)
        for t in range(args.length):
            events.append({
                "event": "view",
                "entityType": "user",
                "entityId": f"u{u}",
                "targetEntityType": "item",
                "targetEntityId": f"i{(start + t) % args.items}",
                # base + timedelta keeps any --length valid (hour arithmetic
                # beyond 24 would otherwise emit impossible timestamps)
                "eventTime": (
                    datetime.datetime(2026, 1, 1, tzinfo=datetime.timezone.utc)
                    + datetime.timedelta(hours=t)
                ).strftime("%Y-%m-%dT%H:%M:%S.000Z"),
            })

    sent = 0
    for i in range(0, len(events), 50):
        req = urllib.request.Request(
            f"{args.url}/batch/events.json?accessKey={args.access_key}",
            data=json.dumps(events[i : i + 50]).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req) as r:
            sent += sum(1 for x in json.loads(r.read()) if x["status"] == 201)
    print(f"imported {sent} events")


if __name__ == "__main__":
    main()
