"""Import view/buy events + item category properties for the e-commerce
quickstart.

Parity: examples/scala-parallel-ecommercerecommendation/*/data/
import_eventserver.py — items carry $set categories; the engine applies
live rules (unseenOnly, category filters, white/black lists) at predict
time against the event store.

Usage:
    python import_eventserver.py --access-key KEY [--url http://localhost:7070]
"""

import argparse
import json
import random
import urllib.request


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--access-key", required=True)
    p.add_argument("--url", default="http://localhost:7070")
    p.add_argument("--users", type=int, default=50)
    p.add_argument("--items", type=int, default=30)
    args = p.parse_args()

    rng = random.Random(13)
    events = []
    for i in range(args.items):
        events.append({
            "event": "$set",
            "entityType": "item",
            "entityId": f"i{i}",
            "properties": {"categories": ["electronics" if i % 2 else "books"]},
        })
    for u in range(args.users):
        for i in rng.sample(range(args.items), 8):
            events.append({
                "event": "view" if rng.random() < 0.7 else "buy",
                "entityType": "user",
                "entityId": f"u{u}",
                "targetEntityType": "item",
                "targetEntityId": f"i{i}",
            })

    sent = 0
    for i in range(0, len(events), 50):
        req = urllib.request.Request(
            f"{args.url}/batch/events.json?accessKey={args.access_key}",
            data=json.dumps(events[i : i + 50]).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req) as r:
            sent += sum(1 for x in json.loads(r.read()) if x["status"] == 201)
    print(f"imported {sent} events")


if __name__ == "__main__":
    main()
