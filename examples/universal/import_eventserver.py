"""Import multi-event (buy primary + view secondary) data for the
Universal Recommender quickstart.

The UR's cross-occurrence needs a primary conversion event plus secondary
indicator events; views correlate with later buys here.

Usage:
    python import_eventserver.py --access-key KEY [--url http://localhost:7070]
"""

import argparse
import json
import random
import urllib.request


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--access-key", required=True)
    p.add_argument("--url", default="http://localhost:7070")
    p.add_argument("--users", type=int, default=80)
    p.add_argument("--items", type=int, default=40)
    args = p.parse_args()

    rng = random.Random(17)
    events = []
    for u in range(args.users):
        lo, hi = (0, args.items // 2) if u % 2 else (args.items // 2, args.items)
        viewed = rng.sample(range(lo, hi), 8)
        for i in viewed:
            events.append({
                "event": "view", "entityType": "user", "entityId": f"u{u}",
                "targetEntityType": "item", "targetEntityId": f"i{i}",
            })
        for i in viewed[:3]:  # a subset of views convert
            events.append({
                "event": "buy", "entityType": "user", "entityId": f"u{u}",
                "targetEntityType": "item", "targetEntityId": f"i{i}",
            })

    sent = 0
    for i in range(0, len(events), 50):
        req = urllib.request.Request(
            f"{args.url}/batch/events.json?accessKey={args.access_key}",
            data=json.dumps(events[i : i + 50]).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req) as r:
            sent += sum(1 for x in json.loads(r.read()) if x["status"] == 201)
    print(f"imported {sent} events")


if __name__ == "__main__":
    main()
