#!/usr/bin/env bash
# Multi-host training quickstart, runnable on ONE machine: `pio launch`
# spawns N coordinated processes under the PIO_COORDINATOR contract —
# exactly how N real hosts run (each host executes the same `pio train`,
# meshes span processes, ingest is 1/N per process with entity-keyed
# DAO shard pushdown; see docs/operations.md "Multi-host training").
#
# Usage:  examples/multihost/run_local.sh [num_processes]
set -euo pipefail
N="${1:-2}"
HERE="$(cd "$(dirname "$0")"; pwd)"
REPO="$(cd "$HERE/../.."; pwd)"
WORK="$(mktemp -d)"
# KEEP=1 examples/multihost/run_local.sh  — keep the workdir for inspection
if [ "${KEEP:-0}" != "1" ]; then trap 'rm -rf "$WORK"' EXIT; fi
PYTHON="${PIO_PYTHON:-$(command -v python3 || command -v python)}"
export PYTHONPATH="$REPO${PYTHONPATH:+:$PYTHONPATH}"
# CPU-simulated chips so the example runs anywhere; on a real TPU pod,
# drop these two lines and run one process per host via --hosts
export JAX_PLATFORMS=cpu
export XLA_FLAGS="--xla_force_host_platform_device_count=2"
export PIO_STORAGE_SOURCES_DB_TYPE=sqlite
export PIO_STORAGE_SOURCES_DB_PATH="$WORK/pio.sqlite"
export PIO_STORAGE_REPOSITORIES_METADATA_SOURCE=DB
export PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE=DB
export PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE=DB
export PIO_BASE_DIR="$WORK/base"
PIO="$PYTHON -m predictionio_tpu.tools.cli"

echo "== seed events =="
$PIO app new mhapp >/dev/null
$PYTHON - << 'PY'
import os, numpy as np
from predictionio_tpu.data.storage.registry import Storage
from predictionio_tpu.data import Event
st = Storage.instance()
app = st.get_meta_data_apps().get_by_name("mhapp")
le = st.get_l_events(); le.init(app.id)
rng = np.random.default_rng(0)
evs = [Event(event="rate", entity_type="user", entity_id=f"u{u}",
             target_entity_type="item", target_entity_id=f"i{i}",
             properties={"rating": float(rng.integers(1, 6))})
       for u in range(40) for i in rng.choice(15, 5, replace=False)]
le.batch_insert(evs, app.id)
print(f"seeded {len(evs)} events")
PY

echo "== engine.json =="
cd "$WORK"
cat > engine.json << 'JSON'
{"id": "default",
 "engineFactory": "predictionio_tpu.templates.recommendation.RecommendationEngine",
 "datasource": {"params": {"appName": "mhapp"}},
 "algorithms": [{"name": "als", "params": {"rank": 4, "numIterations": 3}}]}
JSON

echo "== pio launch -n $N -- train  (watch the [p<i>] prefixes and the"
echo "   'sharded ingest pI/N: ...' lines: each process reads 1/N) =="
# a free port per run: a stale coordinator on the default port must not
# break the example (same free_port convention the test suite uses)
PORT=$($PYTHON -c "import socket; s=socket.socket(); s.bind(('127.0.0.1',0)); print(s.getsockname()[1]); s.close()")
$PIO launch -n "$N" --coordinator-port "$PORT" -- --verbose train 2>&1 \
  | tee "$WORK/train.log" \
  | grep -E "\[p[0-9]\] .*(sharded ingest|Training completed)" || true
grep -q "all $N processes completed" "$WORK/train.log"

echo "== exactly one COMPLETED instance (coordinator-only writes) =="
$PYTHON - << 'PY'
from predictionio_tpu.data.storage.registry import Storage
ei = Storage.instance().get_meta_data_engine_instances()
done = [i for i in ei.get_all() if i.status == ei.STATUS_COMPLETED]
print(f"COMPLETED instances: {len(done)} (ids: {[i.id for i in done]})")
PY
if [ "${KEEP:-0}" = "1" ]; then echo "workdir kept: $WORK"; fi
