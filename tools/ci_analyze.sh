#!/usr/bin/env bash
# CI / pre-commit static-analysis gate.
#
# Runs `pio analyze` scoped to the files changed vs HEAD (plus
# untracked), emitting SARIF for code-scanning upload.  The exit code is
# the gate: non-zero exactly when there are NEW errors — findings
# already acknowledged in .pio-analysis-baseline.json never fail the
# gate (they are counted, and the baseline diff is the regression
# record).  See docs/analysis.md.
#
# Usage:
#   tools/ci_analyze.sh [output.sarif]
#
# Environment:
#   PIO_ANALYZE_FULL=1   analyze every file, not just the changed set
#                        (what the nightly/full-CI lane runs)
set -euo pipefail

cd "$(dirname "$0")/.."

SARIF_OUT="${1:-analysis.sarif}"
SCOPE=(--changed-only)
if [ "${PIO_ANALYZE_FULL:-0}" = "1" ]; then
  SCOPE=()
fi

rc=0
python -m predictionio_tpu.tools.cli analyze "${SCOPE[@]}" \
  --format sarif >"$SARIF_OUT" || rc=$?

# the human-readable echo of the same scope, for the CI log
python -m predictionio_tpu.tools.cli analyze "${SCOPE[@]}" || true

n_results=$(python - "$SARIF_OUT" <<'PY'
import json, sys
with open(sys.argv[1]) as f:
    sarif = json.load(f)
print(sum(len(r.get("results", [])) for r in sarif.get("runs", [])))
PY
)
echo "[ci_analyze] ${n_results} finding(s) in scope -> ${SARIF_OUT}" >&2

if [ "$rc" -ne 0 ]; then
  echo "[ci_analyze] FAIL: new errors vs baseline (exit $rc)" >&2
  echo "[ci_analyze] fix them, suppress with '# pio: ignore[rule]' +" \
       "rationale, or acknowledge via --write-baseline" >&2
fi
exit "$rc"
