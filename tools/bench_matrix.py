#!/usr/bin/env python
"""One-shot TPU bench matrix → BENCH_TPU_MANUAL.json.

The four-cell table VERDICT r3 asked for (rebalance × distribution), plus
the bf16 cell, the dense-vs-segment solver A/B, serving latency, and the
measured-utilization fields — all from repeated ``bench.py`` runs so each
cell carries the full honesty contract. Run it the moment the tunnel
breathes::

    python tools/bench_matrix.py            # full 25M×20 matrix
    BENCH_RATINGS=1000000 BENCH_ITERS=3 python tools/bench_matrix.py  # smoke

Cells run in order of value (primary first) so a tunnel that dies mid-run
still leaves the most important numbers on disk: the artifact is REWRITTEN
after every cell.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "BENCH_TPU_MANUAL.json")

# EVERY matrix axis is pinned in every cell — an ambient BENCH_REBALANCE/
# BENCH_DTYPE/PIO_ALS_SOLVER left over from a manual run must never change
# what a labeled cell measures. Only the primary cell runs the expensive
# extras (serving latency, solver A/B, measured utilization).
_PIN = {"BENCH_REBALANCE": "1", "BENCH_DTYPE": "f32"}
_LEAN = {"BENCH_SERVING": "0", "BENCH_SOLVER_AB": "0", "BENCH_MEASURED": "0",
         "BENCH_INGEST": "0", "BENCH_OBS": "0", "BENCH_DURABILITY": "0",
         "BENCH_KERNEL": "0", "BENCH_TRAIN_KERNEL": "0", "BENCH_FLEET": "0",
         "BENCH_ELASTIC": "0", "BENCH_SHARDED": "0", "BENCH_RETRIEVAL": "0",
         "BENCH_FRESHNESS": "0", "BENCH_POD": "0", "BENCH_TENANT": "0",
         "BENCH_CANARY": "0"}

# (cell name, env overrides) — primary first
CELLS = [
    ("uniform_rebalance", {**_PIN, "BENCH_DIST": "uniform"}),
    ("zipf_rebalance", {**_PIN, **_LEAN, "BENCH_DIST": "zipf"}),
    ("uniform_norebalance", {**_PIN, **_LEAN, "BENCH_DIST": "uniform",
                             "BENCH_REBALANCE": "0"}),
    ("zipf_norebalance", {**_PIN, **_LEAN, "BENCH_DIST": "zipf",
                          "BENCH_REBALANCE": "0"}),
    ("uniform_bf16", {**_PIN, **_LEAN, "BENCH_DIST": "uniform",
                      "BENCH_DTYPE": "bf16"}),
]


def run_cell(name: str, overrides: dict) -> dict:
    env = dict(os.environ)
    env.pop("PIO_ALS_SOLVER", None)  # cells measure the default solver
    env.update(overrides)
    print(f"=== cell {name}: {overrides}", file=sys.stderr, flush=True)
    t0 = time.time()
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        env=env, capture_output=True, text=True, cwd=REPO,
    )
    sys.stderr.write(r.stderr[-2000:])
    if r.returncode != 0:
        return {"error": f"rc={r.returncode}", "stderr_tail": r.stderr[-500:]}
    try:
        record = json.loads(r.stdout.strip().splitlines()[-1])
    except (json.JSONDecodeError, IndexError) as e:
        return {"error": f"unparseable bench output: {e}"}
    record["cell_wall_sec"] = round(time.time() - t0, 1)
    return record


def main() -> int:
    artifact = {
        "generated_unix": time.time(),
        "note": (
            "rebalance × distribution matrix + bf16 cell (VERDICT r3 "
            "item 4); each cell is one full bench.py run with its own "
            "honesty fields"
        ),
        "cells": {},
    }
    # ALL cells stage into the side file; the TPU artifact is (over)written
    # only once EVERY cell proves genuine — a mid-run tunnel death or any
    # CPU-fallback cell can never corrupt prior TPU evidence
    staging = OUT.replace(".json", ".staging.json")
    for name, overrides in CELLS:
        artifact["cells"][name] = run_cell(name, overrides)
        with open(staging, "w") as f:
            json.dump(artifact, f, indent=1)
        print(f"=== wrote {staging} after {name}", file=sys.stderr,
              flush=True)

    def genuine(cell: dict) -> bool:
        return cell.get("platform") == "tpu" and not cell.get("fallback")

    all_tpu = all(genuine(c) for c in artifact["cells"].values())
    final = OUT if all_tpu else staging
    if all_tpu:
        os.replace(staging, OUT)
        print(f"=== all cells genuine TPU: promoted to {OUT}",
              file=sys.stderr)
    else:
        print(
            f"=== non-TPU cell(s) present: results stay in {staging}; "
            "the TPU artifact is untouched", file=sys.stderr,
        )
    primary = artifact["cells"].get("uniform_rebalance", {})
    # serving trajectory alongside the training metric: the primary cell
    # runs the http/scorer latency bench; surface its headline numbers at
    # the top level so round-over-round serving regressions are one grep
    http = (primary.get("predict_latency_ms") or {}).get("http") or {}
    serving = {
        "http_p50_ms": http.get("p50"),
        "http_p99_ms": http.get("p99"),
        "qps": http.get("qps"),
        "batch_occupancy": http.get("batch_occupancy"),
        "recompiles": http.get("recompiles"),
    }
    # the Zipf gap: skewed-traffic QPS over uniform QPS with the skew path
    # on (result cache + single-flight + hot-set). `zipf_gate: false` means
    # skewed traffic is SLOWER than uniform — the seed measured 0.57x, the
    # serving caches exist to hold this >= 1.0
    zipf = http.get("zipf") or {}
    serving["zipf_ratio"] = zipf.get("ratio_vs_uniform")
    serving["zipf_hit_rate"] = (zipf.get("zipf") or {}).get("hit_rate")
    serving["zipf_coalesce_rate"] = (zipf.get("zipf") or {}).get(
        "coalesce_rate"
    )
    serving["zipf_gate"] = (
        serving["zipf_ratio"] >= 1.0
        if isinstance(serving["zipf_ratio"], (int, float)) else None
    )
    artifact["serving"] = serving
    # resilience counters from the same loadtest: a NON-chaos bench run
    # must be clean (zero shed/deadline/degraded) — `clean: false` here is
    # a regression gate, same grep-ability as the serving block
    resilience = http.get("resilience") or {
        "shed": None, "deadline_exceeded": None, "breaker_open": None,
        "degraded": None, "query_errors": None, "clean": None,
    }
    artifact["resilience"] = resilience
    # ingest trajectory: the primary cell's sqlite ingest bench — the
    # batched-vs-per-event-commit ratio is THE acceptance number for the
    # write path, so it gets the same top-level grep-ability
    ingest = primary.get("ingest") or {}
    artifact["ingest"] = {
        "vs_baseline": ingest.get("vs_baseline"),
        "batched_events_per_sec": ingest.get("batched_events_per_sec"),
        "buffered_events_per_sec": ingest.get("buffered_events_per_sec"),
        "ack_p99_ms": ingest.get("ack_p99_ms"),
        "avg_flush_batch": ingest.get("avg_flush_batch"),
        "flush_errors": ingest.get("flush_errors"),
    }
    # durability cost from the primary cell: fast-ack throughput under each
    # WAL fsync policy — `group_vs_off` > 2 means the group-commit fsync is
    # no longer amortizing and the durability default is taxing ingest
    durability = primary.get("durability") or {}
    artifact["durability"] = {
        "fast_ack_events_per_sec": durability.get("fast_ack_events_per_sec"),
        "group_vs_off": durability.get("group_vs_off"),
        "always_vs_off": durability.get("always_vs_off"),
        "replay_sec_per_10k": durability.get("replay_sec_per_10k"),
    }
    # telemetry overhead gate from the primary cell: p50 with every request
    # traced vs telemetry compiled out — `gate_pass: false` means the obs
    # subsystem is taxing the hot loop beyond its <3% budget
    obs = primary.get("observability") or {}
    artifact["observability"] = {
        "overhead_ratio": obs.get("overhead_ratio"),
        "gate_pass": obs.get("gate_pass"),
        "p50_on_ms": obs.get("p50_on_ms"),
        "p50_off_ms": obs.get("p50_off_ms"),
        "metric_series": obs.get("metric_series"),
    }
    # serving-utilization gate (ISSUE 8): the live device accountant must
    # report real, non-null rates under the primary cell's loadtest — a
    # null or zero here means the serving path stopped recording
    # cost-annotated dispatches and MFU went back to being unmeasured
    su = http.get("serving_utilization") or {}
    artifact["serving_utilization"] = {
        "busy_fraction": su.get("busy_fraction"),
        "flops_per_s": su.get("flops_per_s"),
        "mfu": su.get("mfu"),
        "hbm_util": su.get("hbm_util"),
        "dispatches": su.get("dispatches"),
        "gate_pass": all(
            isinstance(su.get(k), (int, float)) and su.get(k) > 0
            for k in ("busy_fraction", "flops_per_s", "mfu")
        ),
    }
    # score-kernel gate (ISSUE 9): the fused Pallas kernel must sit at or
    # above the XLA reference — on the analytic intensity model always,
    # and on measured scores/s when the cell ran on silicon — and the
    # int8 factor variant must at least halve the resident footprint
    kern = primary.get("kernel") or {}
    f32_cell = (kern.get("dtypes") or {}).get("f32") or {}
    artifact["kernel"] = {
        "intensity_gain_f32": kern.get("intensity_gain_f32"),
        "int8_resident_vs_f32": kern.get("int8_resident_vs_f32"),
        "measured_gain_f32": f32_cell.get("measured_gain"),
        "measured_scores_per_sec_f32": f32_cell.get(
            "measured_scores_per_sec"
        ),
        "gate_pass": kern.get("gate_pass"),
    }
    # train-kernel gate (ISSUE 13): the fused gather-contract TRAINING
    # kernel must price strictly above the sector-amplified reference on
    # the analytic intensity model for every compute dtype, the int8
    # compute path's one-pass V read must be ≤ half the f32 bytes, and
    # fused-vs-reference f32 factors must come out bit-equal on the cell's
    # live equivalence train (measured updates/s gain rides along on TPU)
    tkern = primary.get("train_kernel") or {}
    tk_f32 = (tkern.get("dtypes") or {}).get("f32") or {}
    artifact["train_kernel"] = {
        "intensity_gain_f32": tkern.get("intensity_gain_f32"),
        "int8_vread_vs_f32": tkern.get("int8_vread_vs_f32"),
        "factors_bit_equal_f32": tkern.get("factors_bit_equal_f32"),
        "measured_gain_f32": tk_f32.get("measured_gain"),
        "measured_updates_per_sec_f32": tk_f32.get(
            "measured_updates_per_sec"
        ),
        "gate_pass": tkern.get("gate_pass"),
    }
    # fleet gate (ISSUE 10): with one injected slow replica, hedged p99
    # must come in at or under HALF the unhedged p99, and a rolling
    # deploy under load must be invisible to clients (zero non-200s) —
    # either failing means the router's tail-tolerance story regressed
    flt = primary.get("fleet") or {}
    roll = flt.get("roll") or {}
    hedge_ratio = flt.get("hedged_vs_unhedged_p99")
    artifact["fleet"] = {
        "qps_1_replica": flt.get("qps_1_replica"),
        "qps_3_replicas": flt.get("qps_3_replicas"),
        "scaling_3_over_1": flt.get("scaling_3_over_1"),
        "p99_unhedged_slow_ms": flt.get("p99_unhedged_slow_ms"),
        "p99_hedged_ms": flt.get("p99_hedged_ms"),
        "hedged_vs_unhedged_p99": hedge_ratio,
        "roll_client_errors": roll.get("client_errors"),
        "roll_ok": roll.get("ok"),
        "gate_pass": (
            isinstance(hedge_ratio, (int, float)) and hedge_ratio <= 0.5
            and roll.get("client_errors") == 0
        ),
    }
    # elastic gate (ISSUE 11): "SLO held while scaling" — a flash-crowd
    # scenario with a seeded mid-surge replica kill -9 must finish with
    # zero client-visible errors and flash-phase p99 within SLO, AND the
    # autoscaler must have both grown and drained the fleet, AND the
    # preemption must actually have fired (a chaos run where the kill
    # never landed proves nothing)
    ela = primary.get("elastic") or {}
    artifact["fleet"]["elastic"] = {
        "p99_while_scaling_ms": ela.get("p99_while_scaling_ms"),
        "slo_p99_ms": ela.get("slo_p99_ms"),
        "client_errors": ela.get("client_errors"),
        "shed": ela.get("shed"),
        "scale_ups": ela.get("scale_ups"),
        "scale_downs": ela.get("scale_downs"),
        "preemptions": ela.get("preemptions"),
        "gate_pass": ela.get("gate_pass"),
    }
    # streaming-freshness gate (ISSUE 17): sustained loadtest ingest with
    # the autoscaler active — every micro-generation must seal and be
    # acked by the full fleet, event→prediction-visible p99 must stay
    # within PIO_FRESHNESS_SLO_MS, and zero fast-acked events may be lost
    fresh = primary.get("freshness") or {}
    artifact["freshness"] = {
        "batches": fresh.get("batches"),
        "sealed": fresh.get("sealed"),
        "visible_p99_ms": fresh.get("visible_p99_ms"),
        "apply_wall_ms": fresh.get("apply_wall_ms"),
        "slo_ms": fresh.get("slo_ms"),
        "lost_acked_events": fresh.get("lost_acked_events"),
        "query_errors": fresh.get("query_errors"),
        "gate_pass": fresh.get("gate_pass"),
    }
    # sharded-serving gate (ISSUE 12): a catalog sized past one device's
    # (simulated) HBM budget, served partitioned under Zipf load — sharded
    # answers must be bit-identical to the replicated reference, per-shard
    # utilization must be non-null, and the popularity-aware plan's
    # max/min attributed busy balance must stay <= 1.5 (the naive
    # round-robin balance rides along uncapped for comparison)
    shd = (primary.get("multichip") or {}).get("sharded_serving") or {}
    shd_plans = shd.get("plans") or {}
    artifact["multichip"] = {
        "sharded_serving": {
            "catalog_bytes": shd.get("catalog_bytes"),
            "per_device_budget_bytes": shd.get("per_device_budget_bytes"),
            "n_shards": shd.get("n_shards"),
            "popularity_busy_balance": (
                shd_plans.get("popularity") or {}
            ).get("busy_balance"),
            "round_robin_busy_balance": (
                shd_plans.get("round_robin") or {}
            ).get("busy_balance"),
            "exact_match": all(
                (p or {}).get("exact_match") is True
                for p in shd_plans.values()
            ) if shd_plans else None,
            "gate_pass": shd.get("gate_pass"),
        },
    }
    # pod-serving gate (ISSUE 18): a real 2-process jax.distributed CPU
    # mesh serves a 2-host-group plan through the two-tier merge — the
    # pod answers must be bit-identical to the single-process replicated
    # reference AND the measured cross-host merge traffic must stay <=
    # the H*B*k*8 derivation in docs/perf_roofline.md (the flat
    # S*B*local_k collective rides along for the reduction factor)
    podb = (primary.get("multichip") or {}).get("pod_serving") or {}
    artifact["multichip"]["pod_serving"] = {
        "processes": podb.get("processes"),
        "host_groups": podb.get("host_groups"),
        "n_shards": podb.get("n_shards"),
        "exact_match": podb.get("exact_match"),
        "cross_host_merge_bytes": podb.get("cross_host_merge_bytes"),
        "cross_host_merge_bytes_derived": podb.get(
            "cross_host_merge_bytes_derived"
        ),
        "reduction_factor": podb.get("reduction_factor"),
        "gate_pass": podb.get("gate_pass"),
    }
    # IVF retrieval gate (ISSUE 16): at the default nprobe the pruned scan
    # must keep recall@10 >= 0.95 against the exact scorer while touching
    # <= 0.2 of the catalog's padded rows — both halves of the trade at
    # once, measured on the primary cell's clustered catalog
    rtr = primary.get("retrieval") or {}
    artifact["retrieval"] = {
        "nlist": rtr.get("nlist"),
        "nprobe": rtr.get("nprobe"),
        "recall_at_10": rtr.get("recall_at_10"),
        "scanned_fraction": rtr.get("scanned_fraction"),
        "analytic_scan_speedup": rtr.get("analytic_scan_speedup"),
        "measured": rtr.get("measured"),
        "gate_pass": rtr.get("gate_pass"),
    }
    # multi-tenant gate (ISSUE 19): one tenant saturating its qps quota
    # must be shed with quota-attributed 503s while the second tenant's
    # p99 stays inside its SLO with zero errors/sheds, AND the composed
    # IVF→fused-ALS pipeline must beat single-stage exact ALS on
    # scores/s at <= 1.5x the exact path's p99
    ten = primary.get("tenant") or {}
    ten_nn = ten.get("noisy_neighbor") or {}
    ten_pipe = ten.get("pipeline") or {}
    artifact["tenant"] = {
        "alpha_shed": (ten_nn.get("alpha") or {}).get("shed"),
        "alpha_shed_reasons": (ten_nn.get("alpha") or {}).get(
            "shed_reasons"
        ),
        "beta_errors": (ten_nn.get("beta") or {}).get("errors"),
        "beta_p99_ms": (ten_nn.get("beta") or {}).get("p99_ms"),
        "slo_ms": ten_nn.get("slo_ms"),
        "noisy_neighbor_gate": ten_nn.get("gate_pass"),
        "pipeline_speedup": ten_pipe.get("speedup"),
        "pipeline_scores_per_s": ten_pipe.get("pipeline_scores_per_s"),
        "exact_scores_per_s": ten_pipe.get("exact_scores_per_s"),
        "pipeline_p99_ms": ten_pipe.get("pipeline_p99_ms"),
        "exact_p99_ms": ten_pipe.get("exact_p99_ms"),
        "pipeline_gate": ten_pipe.get("gate_pass"),
        "gate_pass": ten.get("gate_pass"),
    }
    # canary gate (ISSUE 20): a deliberately bad candidate generation
    # canaried under load must be detected and auto-rolled-back with ZERO
    # client-visible errors, a blast radius no bigger than the canary
    # fraction (1/3 + slack for routing jitter), and a durable quarantine
    # receipt that survives restart (newest-COMPLETED selection resolves
    # the baseline) and refuses a re-deploy of the same generation
    cnr = primary.get("canary") or {}
    blast = cnr.get("blast_radius")
    artifact["canary"] = {
        "rolled_back": cnr.get("rolled_back"),
        "rollback_reason": cnr.get("rollback_reason"),
        "client_errors": cnr.get("client_errors"),
        "client_ok": cnr.get("client_ok"),
        "blast_radius": blast,
        "candidate_p99_ms": cnr.get("candidate_p99_ms"),
        "shadow_pairs": cnr.get("shadow_pairs"),
        "receipt_on_disk": cnr.get("receipt_on_disk"),
        "receipt_blocks_redeploy": cnr.get("receipt_blocks_redeploy"),
        "gate_pass": (
            cnr.get("rolled_back") is True
            and cnr.get("client_errors") == 0
            and isinstance(blast, (int, float)) and blast <= 0.5
            and cnr.get("receipt_on_disk") is True
            and cnr.get("receipt_blocks_redeploy") is True
        ),
    }
    # static-analysis gate: perf numbers from a repo carrying hot-path or
    # race hazards are not publishable — `pio analyze` must report zero
    # errors for the matrix to count
    ana_t0 = time.monotonic()
    ana = subprocess.run(
        [sys.executable, "-m", "predictionio_tpu.tools.cli",
         "analyze", "--format", "json", "--root", REPO],
        cwd=REPO, capture_output=True, text=True,
    )
    ana_wall = time.monotonic() - ana_t0
    # the interprocedural engine must stay cheap enough to run in tier-1:
    # a budget gate on wall time keeps it from quietly becoming unrunnable
    ana_budget_s = 60.0
    try:
        report = json.loads(ana.stdout)
        counts = report.get("counts", {})
        by_analyzer = report.get("by_analyzer") or {}
        artifact["analysis"] = {
            "errors": counts.get("error"),
            "warnings": counts.get("warning"),
            "baselined": report.get("baselined"),
            "errors_by_analyzer": {
                name: sev.get("error", 0)
                for name, sev in sorted(by_analyzer.items())
            },
            "callgraph": report.get("callgraph"),
            "wall_s": round(ana_wall, 2),
            "budget_s": ana_budget_s,
            "gate_pass": (
                counts.get("error") == 0 and ana_wall < ana_budget_s
            ),
        }
    except (json.JSONDecodeError, AttributeError):
        artifact["analysis"] = {
            "errors": None, "warnings": None, "baselined": None,
            "errors_by_analyzer": None, "callgraph": None,
            "wall_s": round(ana_wall, 2), "budget_s": ana_budget_s,
            "gate_pass": False,
            "stderr": (ana.stderr or "")[-500:],
        }
    with open(final, "w") as f:
        json.dump(artifact, f, indent=1)
    print(json.dumps({
        "artifact": final,
        "primary_value": primary.get("value"),
        "on_tpu": all_tpu,
        **serving,
        "resilience": resilience,
        "ingest": artifact["ingest"],
        "durability": artifact["durability"],
        "observability": artifact["observability"],
        "serving_utilization": artifact["serving_utilization"],
        "kernel": artifact["kernel"],
        "train_kernel": artifact["train_kernel"],
        "fleet": artifact["fleet"],
        "multichip": artifact["multichip"],
        "tenant": artifact["tenant"],
        "canary": artifact["canary"],
        "analysis": artifact["analysis"],
    }))
    return 0 if all_tpu else 1


if __name__ == "__main__":
    sys.exit(main())
