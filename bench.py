"""Benchmark: ALS training throughput (events/sec/chip) on the local device.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

The reference publishes no numbers (BASELINE.md); vs_baseline is measured
against the driver-set north star: MovieLens-25M × 20 iterations on v5e-16
in 60 s ⇒ ~520,833 events/sec/chip.  vs_baseline = value / north_star.

Honesty contract (VERDICT round 2, item 1): the JSON line always carries
``platform``, ``n_devices``, and the actual ``workload`` dims; when the
device backend is unreachable and the bench falls back to CPU, it reports
``"fallback": true`` and ``"vs_baseline": null`` — a CPU number must never
be readable as progress against the TPU north star.

Workload distributions (VERDICT item 2): by default the bench runs the
uniform workload (primary metric) AND a Zipf-skewed workload whose item
popularity follows a power law like MovieLens-25M's catalog (hot ids
contiguous — the worst case for range-blocking).  ``BENCH_DIST`` narrows to
``uniform`` or ``zipf``.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

NORTH_STAR_EVENTS_PER_SEC_PER_CHIP = 25_000_000 * 20 / (60 * 16)


def _device_backend_alive(timeout_s: int = 120, attempts: int = 4) -> bool:
    """Probe device init in a SUBPROCESS: the axon TPU tunnel can hang
    jax.devices() indefinitely; a hung probe must not hang the bench.

    The tunnel also flaps — retry with a growing pause before concluding
    the chip is gone, so a transient outage doesn't turn the round's perf
    artifact into a CPU number.
    """
    for attempt in range(attempts):
        try:
            r = subprocess.run(
                [sys.executable, "-c", "import jax; jax.devices()"],
                timeout=timeout_s,
                capture_output=True,
            )
            if r.returncode == 0:
                return True
        except subprocess.TimeoutExpired:
            pass
        if attempt + 1 < attempts:
            pause = 30 * (attempt + 1)
            print(
                f"WARNING: device probe {attempt + 1}/{attempts} failed; "
                f"retrying in {pause}s",
                file=sys.stderr,
            )
            time.sleep(pause)
    return False


def _sample_ids(rng, n: int, size: int, dist: str, s: float, q: float = 50.0) -> np.ndarray:
    """Entity ids from the named distribution.

    ``zipf``: Zipf-Mandelbrot P(id=k) ∝ (k+q)^-s over [0, n) with hot ids
    CONTIGUOUS at the low end — the adversarial layout for contiguous-range
    blocking.  The q shift matches real catalogs: at s=1.1, q=50 over 59k
    items the hottest item draws ~0.4% of ratings, like ML-25M's ~0.32%
    (a pure Zipf head would take ~10%, which no real catalog does).
    """
    from predictionio_tpu.tools.loadtest import zipf_mandelbrot_weights

    if dist == "uniform":
        return rng.integers(0, n, size).astype(np.int32)
    p = zipf_mandelbrot_weights(n, s=s, q=q)
    return rng.choice(n, size=size, p=p).astype(np.int32)


def _make_interactions(dist: str, n_users: int, n_items: int, n_ratings: int):
    from predictionio_tpu.data.batch import Interactions
    from predictionio_tpu.data.bimap import BiMap

    rng = np.random.default_rng(0)
    inter = Interactions(
        user=_sample_ids(rng, n_users, n_ratings, dist, s=0.7),
        item=_sample_ids(rng, n_items, n_ratings, dist, s=1.1),
        rating=rng.uniform(1.0, 5.0, n_ratings).astype(np.float32),
        t=np.zeros(n_ratings),
        user_map=None,
        item_map=None,
    )
    inter.user_map = BiMap({f"u{i}": i for i in range(n_users)})
    inter.item_map = BiMap({f"i{i}": i for i in range(n_items)})
    return inter


def _timed_run(ctx, inter, rank, iterations, dtype, n_chips, rebalance=True):
    from predictionio_tpu.models import als

    # warm-up: compile the step (first TPU compile is slow, cached after)
    als.train_als(
        ctx, inter, als.ALSConfig(rank=rank, iterations=1,
                                  compute_dtype=dtype, rebalance=rebalance)
    )
    t0 = time.perf_counter()
    model = als.train_als(
        ctx,
        inter,
        als.ALSConfig(rank=rank, iterations=iterations, compute_dtype=dtype,
                      rebalance=rebalance),
    )
    dt = time.perf_counter() - t0
    return len(inter.rating) * iterations / dt / n_chips, model, dt


# The per-chip peak table and the analytic ALS cost model moved to
# obs/devprof (shared with the live serving/train utilization accountants
# — one formula, one denominator, everywhere).  Aliased here so the bench
# artifact shape and the rest of this file are unchanged.  Note devprof's
# table carries a CPU entry, so fallback runs report a real (rough) mfu
# instead of null — the honesty contract still marks them "fallback".
from predictionio_tpu.obs.devprof import PEAKS as _PEAKS  # noqa: E402
from predictionio_tpu.obs.devprof import (  # noqa: E402
    train_utilization as _utilization,
)


def _device_busy_seconds(trace_dir: str) -> tuple:
    """Sum device-plane busy time from a jax.profiler xplane trace.

    Per plane, lines hold nested op events (durations overlap across
    levels); the max single-line sum is that device's busy wall — summed
    over ``/device:`` planes. Returns ``(busy_s, n_planes)`` or
    ``(None, 0)`` when the trace has no device plane (CPU runs: the host
    plane interleaves thread-pool events and would sum past the wall).
    """
    import glob

    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    files = glob.glob(
        os.path.join(trace_dir, "**", "*.xplane.pb"), recursive=True
    )
    if not files:
        raise FileNotFoundError(f"no xplane.pb under {trace_dir}")
    space = xplane_pb2.XSpace()
    with open(max(files, key=os.path.getmtime), "rb") as f:
        space.ParseFromString(f.read())

    def busy(plane):
        sums = [
            sum(ev.duration_ps for ev in line.events) / 1e12
            for line in plane.lines
        ]
        return max(sums) if sums else 0.0

    device = [p for p in space.planes if p.name.startswith("/device:")]
    if not device:
        return None, 0
    return sum(busy(p) for p in device), len(device)


def _measured_utilization(ctx, inter, rank, dtype, platform,
                          rebalance=True) -> dict:
    """MEASURED companions to the analytic cost model (VERDICT r4 weak 2):

    * ``measured_device_time_fraction`` — profiler-traced device busy time
      over the traced wall for a 2-iteration train (a wrong analytic
      model can't hide a regression here);
    * ``xla_*`` — the compiler's own flops/bytes for the actual optimized
      per-device HLO (``dense_step_cost_analysis``), with achieved rates
      + utilization against the same peaks as the analytic fields.
    """
    import tempfile

    import jax

    from predictionio_tpu.models import als

    out = {}
    # solver pinned to dense: the measured fields model the flagship path
    # regardless of a PIO_ALS_SOLVER A/B override in the environment;
    # rebalance follows the benched cell so the trace describes the SAME
    # layout the record's workload claims
    cfg = als.ALSConfig(
        rank=rank, iterations=2, compute_dtype=dtype, solver="dense",
        rebalance=rebalance,
    )
    als.train_als(ctx, inter, als.ALSConfig(
        rank=rank, iterations=1, compute_dtype=dtype, solver="dense",
        rebalance=rebalance,
    ))  # compile outside the trace
    with tempfile.TemporaryDirectory() as td:
        with jax.profiler.trace(td):
            # timed INSIDE the trace block: profiler stop + xplane
            # serialization must not deflate the measured rates
            t0 = time.perf_counter()
            als.train_als(ctx, inter, cfg)
            wall = time.perf_counter() - t0
        busy, n_planes = _device_busy_seconds(td)
        out["measured_device_time_fraction"] = (
            round(busy / (wall * n_planes), 4) if n_planes else None
        )
        out["traced_wall_sec"] = round(wall, 3)
    ca = als.dense_step_cost_analysis(ctx, inter, als.ALSConfig(
        rank=rank, iterations=1, compute_dtype=dtype, solver="dense",
        rebalance=rebalance,
    ))
    flops, nbytes = (
        ca["flops_per_iter_per_device"], ca["bytes_per_iter_per_device"]
    )
    if flops and nbytes:
        # Rate basis: the profiler's DEVICE BUSY time when the trace has
        # device planes — dividing compiled per-iteration device cost by
        # whole-call wall time (host blocking prep, dispatch, readback)
        # understates what the chip actually sustained while running.
        # CPU runs have no device plane; they fall back to wall and say so.
        if busy and n_planes:
            per_dev = busy / n_planes
            out["xla_rate_basis"] = "device_busy"
        else:
            per_dev = wall  # SPMD: all devices run the whole step
            out["xla_rate_basis"] = "wall"
        out["xla_flops_per_sec_per_chip"] = round(
            flops * cfg.iterations / per_dev / 1e9, 2
        )  # GFLOP/s
        out["xla_hbm_gbps_per_chip"] = round(
            nbytes * cfg.iterations / per_dev / 1e9, 2
        )
        peak = _PEAKS.get(platform)
        if peak:
            out["xla_mfu"] = round(
                flops * cfg.iterations / per_dev / peak["flops"], 6
            )
            out["xla_hbm_util"] = round(
                nbytes * cfg.iterations / per_dev / peak["hbm_gbps"], 6
            )
    return out


def _scorer_latency(ctx, model, on_device, n_queries=300, warmup=20) -> dict:
    """p50/p99 of direct ALSScorer.recommend (the in-process serving path)."""
    from predictionio_tpu.models.als import ALSScorer

    scorer = ALSScorer(ctx, model, on_device=on_device)
    rng = np.random.default_rng(7)
    users = rng.integers(0, model.user_factors.shape[0], n_queries + warmup)
    lat = []
    for i, u in enumerate(users):
        t0 = time.perf_counter()
        scorer.recommend(int(u), 10)
        if i >= warmup:
            lat.append(time.perf_counter() - t0)
    lat.sort()
    q = lambda p: round(lat[min(int(p * len(lat)), len(lat) - 1)] * 1e3, 3)
    return {
        "p50": q(0.50), "p99": q(0.99), "queries": n_queries,
        "on_device": scorer.on_device,
    }


def _zipf_serving_phase(engine, storage, ctx, users) -> dict:
    """The Zipf-gap record: same trained model, a SECOND QueryServer with
    the skew path on (result cache + single-flight + hot-set), driven with
    uniform-rotation traffic and then Zipf-Mandelbrot traffic over the
    same key set.

    The cache is sized WELL UNDER the key population (1024 entries vs
    ~4000 keys), so uniform rotation thrashes the LRU and earns ~nothing
    — the ratio isolates what the stack extracts from SKEW, not from
    caching per se.  ``ratio_vs_uniform`` is zipf QPS over uniform QPS;
    the gate (tools/bench_matrix.py) is >= 1.0, i.e. skewed traffic must
    be at least as fast as uniform instead of 0.57x (the pre-cache seed
    measurement).  Hit/coalesce rates come from the server's own stats
    deltas per phase, and the record carries proof the ``pio_result_cache_*``
    families were live at ``/metrics`` while the ratio was measured.
    """
    import urllib.request as _rq

    from predictionio_tpu.serving.query_server import QueryServer
    from predictionio_tpu.serving.result_cache import ResultCache
    from predictionio_tpu.tools.loadtest import run_loadtest, scrape_metrics

    n_keys = int(os.environ.get("BENCH_ZIPF_KEYS", 4000))
    requests = int(os.environ.get("BENCH_ZIPF_REQUESTS", 400))
    cache = ResultCache(
        max_entries=int(os.environ.get("BENCH_ZIPF_CACHE_MAX", 1024))
    )
    hot_env = {
        "PIO_HOTSET_SIZE": os.environ.get("BENCH_ZIPF_HOTSET", "256"),
        # re-rank often enough that a bench-sized run materializes a table
        "PIO_HOTSET_REFRESH_QUERIES": os.environ.get(
            "BENCH_ZIPF_HOTSET_REFRESH", "128"
        ),
    }
    prev = {k: os.environ.get(k) for k in hot_env}
    os.environ.update(hot_env)
    try:
        qs = QueryServer(
            engine, storage=storage, ctx=ctx, batching=True,
            result_cache=cache, coalesce=True,
        )
        port = qs.start("127.0.0.1", 0)
    finally:
        for k, v in prev.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    try:
        url = f"http://127.0.0.1:{port}"

        def stats() -> dict:
            with _rq.urlopen(url + "/", timeout=10) as r:
                return json.loads(r.read().decode())

        keys = [f"u{u}" for u in dict.fromkeys(users.tolist())][:n_keys]
        sample = {"user": keys}
        run_loadtest(url, {"num": 10}, requests=40, concurrency=2,
                     samples={"user": keys[:64]})  # warm jit + hot-set
        # each phase starts with a COLD result cache: hits below are earned
        # by repeats within the phase's own draw, i.e. by its skew alone
        cache.clear()
        s0 = stats()
        uni = run_loadtest(url, {"num": 10}, requests=requests,
                           concurrency=4, samples=sample)
        s1 = stats()
        cache.clear()
        zipf = run_loadtest(url, {"num": 10}, requests=requests,
                            concurrency=4, samples=sample, dist="zipf")
        s2 = stats()
        series = scrape_metrics(url)
        metrics_live = any(
            n == "pio_result_cache_lookups_total" for (n, _) in series
        )
        # which scan the cache-MISS path takes: pio_ivf_* families emit
        # only while an IVF index is live, so presence IS the backend
        ivf_live = any(n == "pio_ivf_info" for (n, _) in series)
        scanned = [
            v for (n, _), v in series.items()
            if n == "pio_ivf_scanned_fraction"
        ]
    finally:
        qs.stop()

    def phase_rates(a: dict, b: dict) -> dict:
        ca, cb = a.get("resultCache") or {}, b.get("resultCache") or {}
        ba, bb = a.get("batching") or {}, b.get("batching") or {}
        lookups = (cb.get("hits", 0) - ca.get("hits", 0)) + (
            cb.get("misses", 0) - ca.get("misses", 0)
        )
        hits = cb.get("hits", 0) - ca.get("hits", 0)
        queries = bb.get("queries", 0) - ba.get("queries", 0)
        coalesced = bb.get("coalesced", 0) - ba.get("coalesced", 0)
        return {
            "hit_rate": round(hits / lookups, 4) if lookups else None,
            "coalesce_rate": (
                round(coalesced / queries, 4) if queries else None
            ),
        }

    out = {
        "keys": len(keys),
        "cache_max": cache.max_entries,
        "uniform": {"qps": uni["qps"], "p50": uni["p50Ms"],
                    "p99": uni["p99Ms"], **phase_rates(s0, s1)},
        "zipf": {"qps": zipf["qps"], "p50": zipf["p50Ms"],
                 "p99": zipf["p99Ms"], **phase_rates(s1, s2)},
        "ratio_vs_uniform": (
            round(zipf["qps"] / uni["qps"], 4) if uni["qps"] else None
        ),
        "errors": uni["errors"] + zipf["errors"],
        "metrics_live": metrics_live,
        "retrieval_backend": "ivf" if ivf_live else "exact",
    }
    if scanned:
        out["ivf_scanned_fraction"] = max(scanned)
    hot = ((s2.get("fastpath") or [{}])[0] or {}).get("hotset")
    if hot:
        out["hotset"] = {
            "resident": hot.get("resident"), "hit_rate": hot.get("hit_rate"),
        }
    if zipf.get("perKey"):
        hotkeys = zipf["perKey"].get("hotKeys") or []
        cold = zipf["perKey"].get("coldTail") or {}
        out["zipf"]["hot_key_p50"] = (
            hotkeys[0]["p50Ms"] if hotkeys else None
        )
        out["zipf"]["cold_tail_p50"] = cold.get("p50Ms")
    return out


def _http_latency(ctx, dist, n_users, n_items) -> dict:
    """p50/p99 of the FULL REST predict path: synthetic events → real
    template train → QueryServer → loadtest POST /queries.json.

    Parity: the reference's per-request serving timer
    (core/.../workflow/CreateServer.scala:597-604). The model's factor
    SHAPES match the training bench (scoring cost is O(n_items·k) per
    query, independent of how many ratings trained it), so a small
    training pass serves an honestly-sized catalog.
    """
    import uuid

    from predictionio_tpu.core.workflow import run_train
    from predictionio_tpu.data import store as store_mod
    from predictionio_tpu.data.batch import EventBatch
    from predictionio_tpu.data.storage import App
    from predictionio_tpu.data.storage.registry import Storage
    from predictionio_tpu.serving.query_server import QueryServer
    from predictionio_tpu.templates.recommendation import RecommendationEngine
    from predictionio_tpu.tools.loadtest import run_loadtest

    n_events = int(os.environ.get("BENCH_SERVING_EVENTS", 1_000_000))
    src = "BENCH" + uuid.uuid4().hex[:6].upper()
    storage = Storage(env={
        f"PIO_STORAGE_SOURCES_{src}_TYPE": "memory",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": src,
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": src,
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": src,
    })
    store_mod.set_storage(storage)
    try:
        app_id = storage.get_meta_data_apps().insert(App(0, "benchapp"))
        storage.get_l_events().init(app_id)
        rng = np.random.default_rng(11)
        users = _sample_ids(rng, n_users, n_events, dist, s=0.7)
        items = _sample_ids(rng, n_items, n_events, dist, s=1.1)
        now = time.time()
        batch = EventBatch(
            event=np.full(n_events, "rate", object),
            entity_type=np.full(n_events, "user", object),
            entity_id=np.array([f"u{u}" for u in users], object),
            target_entity_type=np.full(n_events, "item", object),
            target_entity_id=np.array([f"i{i}" for i in items], object),
            event_time=np.full(n_events, now, np.float64),
            properties=[
                {"rating": float(r)}
                for r in rng.integers(1, 6, n_events)
            ],
        )
        storage.get_p_events().write(batch, app_id)
        engine = RecommendationEngine.apply()
        ep = engine.params_from_variant({
            "datasource": {"params": {"appName": "benchapp"}},
            "algorithms": [
                {"name": "als", "params": {"rank": 10, "numIterations": 2}}
            ],
        })
        run_train(engine, ep, "bench", storage=storage, ctx=ctx)
        # batching=True is the serving fast path under bench: AOT-warmed
        # bucketed compile cache + adaptive micro-batching (ISSUE r06)
        qs = QueryServer(engine, storage=storage, ctx=ctx, batching=True)
        port = qs.start("127.0.0.1", 0)
        try:
            url = f"http://127.0.0.1:{port}"

            def server_stats() -> dict:
                import urllib.request as _rq

                with _rq.urlopen(url + "/", timeout=10) as r:
                    return json.loads(r.read().decode())

            # ≥100 DISTINCT users rotated per request: one fixed payload
            # would measure one warm jit path + one hot cache line and
            # flatter the tail (VERDICT r4)
            distinct = [
                f"u{u}" for u in dict.fromkeys(users.tolist())
            ][:256]
            sample = {"user": distinct}
            run_loadtest(url, {"num": 10}, requests=40,
                         concurrency=2, samples=sample)  # warm path + jit
            before = server_stats()
            res = run_loadtest(
                url, {"num": 10},
                requests=int(os.environ.get("BENCH_HTTP_REQUESTS", 300)),
                concurrency=4, samples=sample,
            )
            after = server_stats()
        finally:
            qs.stop()

        def compiles(stats: dict) -> int:
            return sum(
                fp.get("compile_count", 0) for fp in stats.get("fastpath") or []
            )

        out = {
            "p50": res["p50Ms"], "p99": res["p99Ms"], "qps": res["qps"],
            "requests": res["requests"], "errors": res["errors"],
            "serving_events": n_events, "distinct_users": len(distinct),
            # acceptance: zero compiles DURING traffic — the bucket ladder
            # was fully AOT-warmed at deploy, so this must be 0
            "recompiles": compiles(after) - compiles(before),
        }
        batching = after.get("batching")
        if batching:
            out["batch_avg"] = batching.get("avg_batch")
            out["batches"] = batching.get("batches")
        fp_after = after.get("fastpath") or []
        if fp_after:
            out["fastpath_calls"] = sum(f.get("calls", 0) for f in fp_after)
            occ = [
                f["row_occupancy"]
                for f in fp_after
                if f.get("row_occupancy") is not None
            ]
            out["batch_occupancy"] = occ[0] if len(occ) == 1 else (occ or None)
        # live serving utilization (ISSUE 8): the scorer's cost-annotated
        # dispatch accountant, read through the same stats surface the
        # /metrics bridge uses — bench_matrix gates these being non-null
        dev = next(
            (f.get("devprof") for f in fp_after if f.get("devprof")), None
        ) or {}
        out["serving_utilization"] = {
            "busy_fraction": dev.get("busy_fraction"),
            "flops_per_s": dev.get("flops_per_s"),
            "hbm_gbps": dev.get("hbm_gbps"),
            "mfu": dev.get("mfu"),
            "hbm_util": dev.get("hbm_util"),
            "dispatches": dev.get("dispatches_total"),
        }
        # resilience layer under a NON-chaos run: every counter must be
        # quiet — any shed/deadline/degraded/error here is a regression
        res_stats = after.get("resilience") or {}
        counters = res_stats.get("counters") or {}
        out["resilience"] = {
            "shed": counters.get("shed", 0) + res.get("shed", 0),
            "deadline_exceeded": counters.get("deadline_exceeded", 0)
            + res.get("deadlineExceeded", 0),
            "breaker_open": counters.get("breaker_open", 0),
            "degraded": counters.get("degraded", 0),
            "query_errors": counters.get("query_errors", 0),
            "clean": res["errors"] == 0
            and counters.get("shed", 0) == 0
            and counters.get("deadline_exceeded", 0) == 0
            and counters.get("degraded", 0) == 0,
        }
        if os.environ.get("BENCH_ZIPF", "1") != "0":
            # the zipf-gap phase must never kill the http record it rides on
            try:
                out["zipf"] = _zipf_serving_phase(engine, storage, ctx, users)
            except Exception as e:
                print(f"WARNING: zipf serving phase failed: {e}",
                      file=sys.stderr)
                out["zipf"] = {"error": str(e)}
            print(f"INFO: zipf serving: {out['zipf']}", file=sys.stderr)
        return out
    finally:
        store_mod.set_storage(None)
        from predictionio_tpu.data.storage import memory

        memory.reset_store(src)


def _observability_bench(ctx) -> dict:
    """Telemetry overhead gate: HTTP serving p50 with the obs subsystem ON
    (trace sampling forced to 1.0 — every request traced, the worst case)
    vs OFF (``telemetry=False``: no registry, no tracer, the pre-obs hot
    loop), same trained model, same rotated payloads.

    ``overhead_ratio`` is p50_on / p50_off; the gate is <3%.  Each config
    takes the min-of-3 p50 so one GC pause or scheduler hiccup can't fail
    the gate on noise.  The ON server is also asked for ``/metrics`` and
    ``/trace/recent.json`` so the record carries proof the exposition was
    live while the gate was measured.
    """
    import urllib.request as _rq
    import uuid

    from predictionio_tpu.core.workflow import run_train
    from predictionio_tpu.data import store as store_mod
    from predictionio_tpu.data.batch import EventBatch
    from predictionio_tpu.data.storage import App
    from predictionio_tpu.data.storage.registry import Storage
    from predictionio_tpu.serving.query_server import QueryServer
    from predictionio_tpu.templates.recommendation import RecommendationEngine
    from predictionio_tpu.tools.loadtest import run_loadtest

    n_events = int(os.environ.get("BENCH_OBS_EVENTS", 100_000))
    n_users, n_items = 5000, 2000
    requests = int(os.environ.get("BENCH_OBS_REQUESTS", 300))
    src = "OBSBENCH" + uuid.uuid4().hex[:6].upper()
    storage = Storage(env={
        f"PIO_STORAGE_SOURCES_{src}_TYPE": "memory",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": src,
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": src,
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": src,
    })
    store_mod.set_storage(storage)
    prev_sample = os.environ.get("PIO_TRACE_SAMPLE")
    try:
        app_id = storage.get_meta_data_apps().insert(App(0, "obsbenchapp"))
        storage.get_l_events().init(app_id)
        rng = np.random.default_rng(23)
        users = rng.integers(0, n_users, n_events)
        items = rng.integers(0, n_items, n_events)
        now = time.time()
        batch = EventBatch(
            event=np.full(n_events, "rate", object),
            entity_type=np.full(n_events, "user", object),
            entity_id=np.array([f"u{u}" for u in users], object),
            target_entity_type=np.full(n_events, "item", object),
            target_entity_id=np.array([f"i{i}" for i in items], object),
            event_time=np.full(n_events, now, np.float64),
            properties=[
                {"rating": float(r)} for r in rng.integers(1, 6, n_events)
            ],
        )
        storage.get_p_events().write(batch, app_id)
        engine = RecommendationEngine.apply()
        ep = engine.params_from_variant({
            "datasource": {"params": {"appName": "obsbenchapp"}},
            "algorithms": [
                {"name": "als", "params": {"rank": 10, "numIterations": 2}}
            ],
        })
        run_train(engine, ep, "obsbench", storage=storage, ctx=ctx)
        distinct = [f"u{u}" for u in dict.fromkeys(users.tolist())][:256]
        sample = {"user": distinct}
        os.environ["PIO_TRACE_SAMPLE"] = "1.0"  # every request traced

        def measure(telemetry: bool) -> tuple:
            qs = QueryServer(
                engine, storage=storage, ctx=ctx, batching=True,
                telemetry=telemetry,
            )
            port = qs.start("127.0.0.1", 0)
            url = f"http://127.0.0.1:{port}"
            try:
                run_loadtest(url, {"num": 10}, requests=60, concurrency=2,
                             samples=sample)  # warm the path + jit
                p50s = []
                for _ in range(3):
                    r = run_loadtest(url, {"num": 10}, requests=requests,
                                     concurrency=4, samples=sample)
                    p50s.append(r["p50Ms"])
                proof = None
                if telemetry:
                    with _rq.urlopen(url + "/metrics", timeout=10) as r:
                        text = r.read().decode()
                    from predictionio_tpu.obs.metrics import parse_prometheus

                    series = parse_prometheus(text)
                    with _rq.urlopen(
                        url + "/trace/recent.json?limit=50", timeout=10
                    ) as r:
                        traces = json.loads(r.read().decode())["traces"]
                    # newest trace is the /metrics scrape itself; the proof
                    # wants a QUERY trace with the full stage breakdown
                    qtraces = [
                        t for t in traces
                        if "/queries.json" in t.get("name", "")
                    ]
                    proof = {
                        "metric_series": len(series),
                        "trace_stages": sorted(
                            qtraces[0]["stagesMs"]
                        ) if qtraces else [],
                    }
                return min(p50s), proof
            finally:
                qs.stop()

        p50_on, proof = measure(True)
        p50_off, _ = measure(False)
        ratio = p50_on / p50_off if p50_off > 0 else float("nan")
        return {
            "p50_on_ms": p50_on,
            "p50_off_ms": p50_off,
            "overhead_ratio": round(ratio, 4),
            "gate": 1.03,
            "gate_pass": bool(ratio <= 1.03),
            "trace_sample": 1.0,
            "requests_per_run": requests,
            **(proof or {}),
        }
    finally:
        if prev_sample is None:
            os.environ.pop("PIO_TRACE_SAMPLE", None)
        else:
            os.environ["PIO_TRACE_SAMPLE"] = prev_sample
        store_mod.set_storage(None)
        from predictionio_tpu.data.storage import memory

        memory.reset_store(src)


def _ingest_bench() -> dict:
    """Ingest fast-path evidence on the sqlite backend (the fsync-bound
    one): per-event-commit baseline vs one-transaction ``insert_batch`` vs
    the write-behind buffer, all single node, file-backed.

    The headline ``vs_baseline`` is batched/baseline events/s —
    acceptance wants ≥10x.  The buffer row adds concurrent durable-ack
    latency (client-observed p50/p99) and the flush batch-size histogram,
    the group-commit's signature.
    """
    import shutil
    import tempfile
    import threading

    from predictionio_tpu.data.api.ingest_buffer import IngestBuffer
    from predictionio_tpu.data.event import Event
    from predictionio_tpu.data.storage.registry import Storage
    from predictionio_tpu.data.storage.sqlite import close_db

    n = int(os.environ.get("BENCH_INGEST_EVENTS", 3000))
    # the per-event-commit baseline is ~20-50x slower; cap its share of
    # wall time without losing measurement stability
    n_base = int(os.environ.get("BENCH_INGEST_BASELINE_EVENTS", min(n, 1000)))
    batch_size = int(os.environ.get("BENCH_INGEST_BATCH", 50))
    tmp = tempfile.mkdtemp(prefix="pio-ingest-bench-")
    src = "INGESTBENCH"
    path = os.path.join(tmp, "events.sqlite")
    base_path = os.path.join(tmp, "events_baseline.sqlite")
    storage = Storage(env={
        f"PIO_STORAGE_SOURCES_{src}_TYPE": "sqlite",
        f"PIO_STORAGE_SOURCES_{src}_PATH": path,
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": src,
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": src,
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": src,
    })
    try:
        le = storage.get_l_events()
        le.init(1)

        def make_events(tag, count):
            return [
                Event(
                    event="rate", entity_type="user",
                    entity_id=f"{tag}u{i}", target_entity_type="item",
                    target_entity_id=f"i{i % 97}",
                    properties={"rating": float(i % 5 + 1)},
                )
                for i in range(count)
            ]

        # baseline: the pre-batching ingest path — one DAO insert (one
        # commit) per event, single thread, under the seed's sqlite
        # config (rollback journal, synchronous=FULL).  The PR moved the
        # events writer to WAL + synchronous=NORMAL, so the baseline runs
        # on its own file with the writer pragmas reset to the old values;
        # otherwise the comparison would hide the durability-config win.
        base_storage = Storage(env={
            f"PIO_STORAGE_SOURCES_{src}_TYPE": "sqlite",
            f"PIO_STORAGE_SOURCES_{src}_PATH": base_path,
            "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": src,
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": src,
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": src,
        })
        from predictionio_tpu.data.storage.sqlite import (
            _INSERT_EVENT_SQL, _event_row, new_event_id,
        )

        base_le = base_storage.get_l_events()
        base_le.init(1)
        bconn, block = base_le.conn, base_le.lock  # the shared DAO conn
        bconn.execute("PRAGMA synchronous=FULL")
        evs = make_events("base", n_base)
        t0 = time.perf_counter()
        for e in evs:
            row = _event_row(e, e.event_id or new_event_id(), 1, None)
            with block:
                bconn.execute(_INSERT_EVENT_SQL, row)
                bconn.commit()
        base_dt = time.perf_counter() - t0
        baseline = n_base / base_dt

        # batched: insert_batch in endpoint-sized chunks, single thread
        evs = make_events("batch", n)
        t0 = time.perf_counter()
        for s in range(0, n, batch_size):
            le.insert_batch(evs[s:s + batch_size], 1)
        batch_dt = time.perf_counter() - t0
        batched = n / batch_dt

        # write-behind: concurrent producers, durable ack (wait for the
        # group commit); per-event ack latency is the client-visible cost
        buf = IngestBuffer(le, flush_ms=2.0, durable_ack=True)
        evs = make_events("buf", n)
        # each durable-ack producer has one event in flight, so the flush
        # coalesces ~`workers` events per commit — concurrency IS the
        # group-commit batch size
        workers = int(os.environ.get("BENCH_INGEST_WORKERS", 32))
        acks: list[float] = []
        ack_lock = threading.Lock()

        def producer(w):
            local = []
            for e in evs[w::workers]:
                t0 = time.perf_counter()
                if not buf.submit(e, 1).wait(30.0):
                    raise RuntimeError("ingest buffer ack timed out")
                local.append(time.perf_counter() - t0)
            with ack_lock:
                acks.extend(local)

        threads = [
            threading.Thread(target=producer, args=(w,)) for w in range(workers)
        ]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        buf_dt = time.perf_counter() - t0
        buf_stats = buf.stats()
        buf.close()
        acks.sort()
        q = lambda p: round(
            acks[min(int(p * len(acks)), len(acks) - 1)] * 1e3, 3
        )
        return {
            "backend": "sqlite",
            "events": n,
            "batch_size": batch_size,
            "baseline_events": n_base,
            "baseline_config": "per-event commit, rollback journal, synchronous=FULL",
            "baseline_events_per_sec": round(baseline, 1),
            "batched_events_per_sec": round(batched, 1),
            # the acceptance ratio: batched DAO path vs per-event commits
            "vs_baseline": round(batched / baseline, 2),
            "buffered_events_per_sec": round(n / buf_dt, 1),
            "buffered_vs_baseline": round(n / buf_dt / baseline, 2),
            "ack_p50_ms": q(0.50),
            "ack_p99_ms": q(0.99),
            "flushes": buf_stats["flushes"],
            "avg_flush_batch": buf_stats["avg_flush_batch"],
            "flush_batch_hist": buf_stats["flush_batch_hist"],
            "flush_errors": buf_stats["flush_errors"],
        }
    finally:
        try:
            close_db(path)
            close_db(base_path)
        finally:
            shutil.rmtree(tmp, ignore_errors=True)


def _durability_bench() -> dict:
    """Durability cost evidence: fast-ack throughput with the ingest WAL
    at each fsync policy (off / group / always), plus replay speed.

    The acceptance gate is ``group_vs_off`` — the group-commit fsync
    policy must hold within 2x of no-fsync, which is the whole point of
    amortizing the fsync across the group window.  Replay is timed
    separately (journal ~10k events, then replay + batch-insert into a
    cold store) and normalized to seconds per 10k events.
    """
    import shutil
    import tempfile

    from predictionio_tpu.data.api.ingest_buffer import (
        IngestBuffer, wal_decode, wal_encode,
    )
    from predictionio_tpu.data.api.wal import WriteAheadLog
    from predictionio_tpu.data.event import Event
    from predictionio_tpu.data.storage.registry import Storage
    from predictionio_tpu.data.storage.sqlite import close_db

    n = int(os.environ.get("BENCH_DURABILITY_EVENTS", 3000))
    n_replay = int(os.environ.get("BENCH_DURABILITY_REPLAY_EVENTS", 10000))

    def make_events(tag, count):
        return [
            Event(
                event="rate", entity_type="user",
                entity_id=f"{tag}u{i}", target_entity_type="item",
                target_entity_id=f"i{i % 97}",
                properties={"rating": float(i % 5 + 1)},
            )
            for i in range(count)
        ]

    throughput: dict[str, float] = {}
    for policy in ("off", "group", "always"):
        tmp = tempfile.mkdtemp(prefix=f"pio-dur-bench-{policy}-")
        src = "DURBENCH"
        path = os.path.join(tmp, "events.sqlite")
        storage = Storage(env={
            f"PIO_STORAGE_SOURCES_{src}_TYPE": "sqlite",
            f"PIO_STORAGE_SOURCES_{src}_PATH": path,
            "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": src,
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": src,
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": src,
        })
        try:
            le = storage.get_l_events()
            le.init(1)
            wal = WriteAheadLog(os.path.join(tmp, "wal"), fsync=policy)
            # fast-ack: the WAL append inside submit() is the ack's
            # durability cost, so the submit loop's wall time IS the
            # client-visible fast-ack throughput under that policy
            buf = IngestBuffer(le, flush_ms=2.0, durable_ack=False, wal=wal)
            evs = make_events(policy, n)
            tickets = []
            t0 = time.perf_counter()
            for e in evs:
                tickets.append(buf.submit(e, 1))
            dt = time.perf_counter() - t0
            throughput[policy] = n / dt
            for t in tickets:
                t.wait(30.0)
            buf.close()
            wal.close()
        finally:
            try:
                close_db(path)
            finally:
                shutil.rmtree(tmp, ignore_errors=True)

    # replay: journal n_replay events, then cold-start replay them into a
    # fresh store the way the event server does on restart
    tmp = tempfile.mkdtemp(prefix="pio-dur-bench-replay-")
    src = "DURBENCH"
    path = os.path.join(tmp, "events.sqlite")
    storage = Storage(env={
        f"PIO_STORAGE_SOURCES_{src}_TYPE": "sqlite",
        f"PIO_STORAGE_SOURCES_{src}_PATH": path,
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": src,
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": src,
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": src,
    })
    try:
        wal = WriteAheadLog(os.path.join(tmp, "wal"), fsync="off")
        for e in make_events("replay", n_replay):
            wal.append(wal_encode(e, 1, None))
        wal.close()

        le = storage.get_l_events()
        le.init(1)
        wal2 = WriteAheadLog(os.path.join(tmp, "wal"), fsync="off")
        t0 = time.perf_counter()
        records = wal2.replay()
        events = [wal_decode(p)[0] for p in records]
        le.insert_batch(events, 1)
        wal2.reclaim_replayed()
        replay_dt = time.perf_counter() - t0
        wal2.close()
        replayed = len(records)
    finally:
        try:
            close_db(path)
        finally:
            shutil.rmtree(tmp, ignore_errors=True)

    return {
        "backend": "sqlite",
        "events": n,
        "fast_ack_events_per_sec": {
            k: round(v, 1) for k, v in throughput.items()
        },
        # acceptance: group-commit fsync within 2x of no fsync
        "group_vs_off": round(throughput["off"] / throughput["group"], 2),
        "always_vs_off": round(throughput["off"] / throughput["always"], 2),
        "replay_events": replayed,
        "replay_sec_per_10k": round(replay_dt * 10000.0 / max(replayed, 1), 3),
    }


def _kernel_bench(platform: str, n_items: int, rank: int) -> dict:
    """Score-kernel block: fused Pallas vs XLA reference, per factor dtype.

    Two kinds of evidence per dtype (f32/bf16/int8):

    * **Analytic roofline** at the artifact's serving shape — arithmetic
      intensity (FLOPs/byte) of one top-bucket dispatch for both kernels
      and the TPU-roofline MFU each can attain (min(peak, intensity·bw)
      / peak).  The fused kernel never round-trips the (B, I) score
      matrix through HBM, so its intensity gain over the reference is
      the headline number and the matrix gate (fused ≥ reference).
    * **Measured scores/s**, TPU only — on CPU the fused path runs the
      Pallas *interpreter*, so timing it would bench the interpreter,
      not the kernel; CPU artifacts carry ``measured: null``.

    Resident factor bytes per dtype come from actually quantizing a
    factor pair at the bench shape (scales included), so the int8 ≤ ½
    acceptance line is measured, not asserted.
    """
    import jax

    from predictionio_tpu.obs.devprof import (
        PEAKS, fused_score_cost, score_cost,
    )
    from predictionio_tpu.ops.quantize import quantize_factors
    from predictionio_tpu.ops.topk import gather_score_topk

    batch = int(os.environ.get("BENCH_KERNEL_BATCH", 256))
    top_k = int(os.environ.get("BENCH_KERNEL_TOPK", 100))
    peak = PEAKS["tpu"]  # roofline projection is against the TPU target

    rng = np.random.default_rng(11)
    n_users = max(batch * 4, 1024)
    U = rng.standard_normal((n_users, rank)).astype(np.float32)
    V = rng.standard_normal((n_items, rank)).astype(np.float32)

    def roofline(flops: float, nbytes: float) -> dict:
        intensity = flops / nbytes
        attainable = min(peak["flops"], intensity * peak["hbm_gbps"])
        return {
            "intensity_flops_per_byte": round(intensity, 3),
            "roofline_mfu": round(attainable / peak["flops"], 4),
        }

    on_tpu = platform == "tpu"
    out: dict = {
        "shape": {
            "batch": batch, "items": n_items, "rank": rank, "top_k": top_k,
        },
        "measured_backend": platform if on_tpu else None,
        "dtypes": {},
    }
    f32_bytes = None
    for dtype in ("f32", "bf16", "int8"):
        Uq, us = quantize_factors(U, dtype)
        Vq, vs = quantize_factors(V, dtype)
        resident = sum(
            int(a.nbytes) for a in (Uq, Vq, us, vs) if a is not None
        )
        if dtype == "f32":
            f32_bytes = resident
        ref = roofline(*score_cost(batch, n_items, rank, dtype=dtype))
        fused = roofline(
            *fused_score_cost(batch, n_items, rank, top_k, dtype=dtype)
        )
        cell = {
            "reference": ref,
            "fused": fused,
            "intensity_gain": round(
                fused["intensity_flops_per_byte"]
                / ref["intensity_flops_per_byte"], 2
            ),
            "resident_factor_bytes": resident,
            "resident_vs_f32": round(resident / f32_bytes, 4),
        }
        if on_tpu:
            # measured A/B: same inputs, both backends, scores/s
            u_idx = rng.integers(0, n_users, batch).astype(np.int32)
            measured = {}
            for backend in ("reference", "fused"):
                fn = jax.jit(
                    lambda U_, V_, us_, vs_, idx_, _b=backend:
                    gather_score_topk(
                        U_, V_, idx_, top_k, u_scale=us_, v_scale=vs_,
                        backend=_b,
                    )
                )
                r = fn(Uq, Vq, us, vs, u_idx)
                jax.block_until_ready(r)  # compile + warm
                iters = int(os.environ.get("BENCH_KERNEL_ITERS", 30))
                t0 = time.perf_counter()
                for _ in range(iters):
                    r = fn(Uq, Vq, us, vs, u_idx)
                jax.block_until_ready(r)
                dt = time.perf_counter() - t0
                measured[backend] = round(batch * n_items * iters / dt, 1)
            cell["measured_scores_per_sec"] = measured
            cell["measured_gain"] = round(
                measured["fused"] / measured["reference"], 2
            )
        out["dtypes"][dtype] = cell

    f32 = out["dtypes"]["f32"]
    int8 = out["dtypes"]["int8"]
    # matrix gates: the fused kernel must not be below the reference on
    # the analytic model (and on silicon when measured), and int8 must at
    # least halve the resident factor footprint
    out["intensity_gain_f32"] = f32["intensity_gain"]
    out["int8_resident_vs_f32"] = int8["resident_vs_f32"]
    gate = f32["intensity_gain"] >= 1.0 and int8["resident_vs_f32"] <= 0.5
    if on_tpu:
        gate = gate and f32.get("measured_gain", 0.0) >= 1.0
    out["gate_pass"] = bool(gate)
    return out


def _train_kernel_bench(
    ctx, platform: str, n_users: int, n_items: int, n_ratings: int,
    rank: int,
) -> dict:
    """Training-kernel block: fused Pallas vs XLA reference, per COMPUTE
    dtype (``PIO_ALS_COMPUTE_DTYPE``).

    Three kinds of evidence per dtype (f32/bf16/int8):

    * **Analytic roofline** at the artifact's training shape — the
      reference backend priced with the gather term XLA actually pays
      (~512 B sector per factor row, ``als_train_cost_amplified``)
      against the fused kernel's one-sequential-V-read model
      (``fused_train_cost``), plus the expected ms/iteration each
      implies (max of compute time and HBM time at TPU peaks).  The
      matrix gate holds fused intensity STRICTLY above the reference
      for every dtype and the int8 one-pass V read to ≤ ½ the f32
      bytes.
    * **Equivalence** — a small train on the live mesh, fused (the real
      kernel body, interpret off-TPU) vs reference, per dtype; the f32
      factors must be BIT-equal, bf16/int8 within documented tolerance.
    * **Measured rating-updates/s**, TPU only — on CPU the fused path
      would bench the Pallas *interpreter*, so CPU artifacts carry
      ``measured: null``.
    """
    from predictionio_tpu.models.als import ALSConfig, train_als
    from predictionio_tpu.obs.devprof import (
        PEAKS,
        als_train_cost_amplified,
        fused_train_cost,
        fused_train_vread_bytes,
    )

    peak = PEAKS["tpu"]
    on_tpu = platform == "tpu"
    out: dict = {
        "shape": {
            "users": n_users, "items": n_items, "ratings": n_ratings,
            "rank": rank,
        },
        "measured_backend": platform if on_tpu else None,
        "dtypes": {},
    }

    def roofline(flops: float, nbytes: float) -> dict:
        intensity = flops / nbytes
        attainable = min(peak["flops"], intensity * peak["hbm_gbps"])
        return {
            "intensity_flops_per_byte": round(intensity, 3),
            "roofline_mfu": round(attainable / peak["flops"], 4),
            "expected_ms_per_iter": round(
                max(flops / peak["flops"], nbytes / peak["hbm_gbps"]) * 1e3,
                3,
            ),
        }

    # equivalence workload: small enough to train on any mesh in seconds,
    # ragged enough (Zipf) to hit multi-bucket dense shapes
    eq_inter = _make_interactions(
        "zipf", min(n_users, 384), min(n_items, 256), min(n_ratings, 6000)
    )
    f32_vread = fused_train_vread_bytes(n_users, n_items, rank, "f32")
    for cd in ("f32", "bf16", "int8"):
        ref = roofline(
            *als_train_cost_amplified(n_ratings, n_users, n_items, rank)
        )
        fused = roofline(
            *fused_train_cost(n_ratings, n_users, n_items, rank, cd)
        )
        vread = fused_train_vread_bytes(n_users, n_items, rank, cd)
        factors = {}
        for backend in ("reference", "fused"):
            m = train_als(ctx, eq_inter, ALSConfig(
                rank=rank, iterations=2, seed=7, compute_dtype=cd,
                train_kernel=backend,
            ))
            factors[backend] = (m.user_factors, m.item_factors)
        bit_equal = bool(
            np.array_equal(factors["fused"][0], factors["reference"][0])
            and np.array_equal(factors["fused"][1], factors["reference"][1])
        )
        cell = {
            "reference": ref,
            "fused": fused,
            "intensity_gain": round(
                fused["intensity_flops_per_byte"]
                / ref["intensity_flops_per_byte"], 2
            ),
            "vread_bytes": vread,
            "vread_vs_f32": round(vread / f32_vread, 4),
            "factors_bit_equal": bit_equal,
        }
        if on_tpu:
            # measured A/B on the full bench workload, rating-updates/s
            # (each rating is touched twice per iteration — both sides)
            iters = int(os.environ.get("BENCH_TRAIN_KERNEL_ITERS", 3))
            inter = _make_interactions(
                "uniform", n_users, n_items, n_ratings
            )
            measured = {}
            for backend in ("reference", "fused"):
                cfg = ALSConfig(
                    rank=rank, iterations=iters, seed=7,
                    compute_dtype=cd, train_kernel=backend,
                )
                train_als(ctx, inter, ALSConfig(  # compile + warm
                    rank=rank, iterations=1, seed=7, compute_dtype=cd,
                    train_kernel=backend,
                ))
                t0 = time.perf_counter()
                train_als(ctx, inter, cfg)
                dt = time.perf_counter() - t0
                measured[backend] = round(n_ratings * 2 * iters / dt, 1)
            cell["measured_updates_per_sec"] = measured
            cell["measured_gain"] = round(
                measured["fused"] / measured["reference"], 2
            )
        out["dtypes"][cd] = cell

    # matrix gates: fused analytic intensity STRICTLY above the
    # sector-amplified reference for EVERY compute dtype, the int8
    # one-pass V read ≤ ½ the f32 bytes, and f32 factors bit-equal
    # across backends (bf16/int8 ride the documented-tolerance suite)
    gate = all(
        c["fused"]["intensity_flops_per_byte"]
        > c["reference"]["intensity_flops_per_byte"]
        for c in out["dtypes"].values()
    )
    gate = gate and out["dtypes"]["int8"]["vread_vs_f32"] <= 0.5
    gate = gate and out["dtypes"]["f32"]["factors_bit_equal"]
    if on_tpu:
        gate = gate and all(
            c.get("measured_gain", 0.0) >= 1.0
            for c in out["dtypes"].values()
        )
    out["intensity_gain_f32"] = out["dtypes"]["f32"]["intensity_gain"]
    out["int8_vread_vs_f32"] = out["dtypes"]["int8"]["vread_vs_f32"]
    out["factors_bit_equal_f32"] = out["dtypes"]["f32"]["factors_bit_equal"]
    out["gate_pass"] = bool(gate)
    return out


_FLEET_CHILD = """
import os
from predictionio_tpu.data import store as store_mod
from predictionio_tpu.data.storage.registry import Storage
from predictionio_tpu.parallel.mesh import MeshContext
from predictionio_tpu.serving.query_server import QueryServer
from predictionio_tpu.templates.recommendation import RecommendationEngine

storage = Storage()
store_mod.set_storage(storage)
qs = QueryServer(
    RecommendationEngine.apply(), storage=storage,
    ctx=MeshContext.create(), telemetry=False,
)
qs.start("127.0.0.1", int(os.environ["FLEET_CHILD_PORT"]))
qs.service.serve_forever()
"""


def _fleet_bench(ctx) -> dict:
    """Fleet routing evidence (ISSUE 10): replica scaling (1 vs 3 replica
    qps through the router), hedged vs unhedged p99 with one injected
    slow replica, and a rolling deploy under load.

    The two acceptance numbers are ``hedged_vs_unhedged_p99`` — the hedge
    must at least halve the slow-replica tail — and
    ``roll.client_errors`` — a roll must be invisible to clients (zero
    non-200s).  The slow replica is made slow via the seeded fault shim
    in its own process (``PIO_FAULT_SPEC`` latency on the query path), so
    /readyz stays green and the routers see a wedged-but-listening
    replica, not a dead one.
    """
    import shutil
    import socket
    import tempfile
    import threading
    import urllib.request

    import predictionio_tpu
    from predictionio_tpu.core.workflow import run_train
    from predictionio_tpu.data import Event
    from predictionio_tpu.data import store as store_mod
    from predictionio_tpu.data.storage import App
    from predictionio_tpu.data.storage.registry import Storage
    from predictionio_tpu.data.storage.sqlite import close_db
    from predictionio_tpu.serving.fleet import FleetSupervisor
    from predictionio_tpu.serving.router import ADMITTED, Router
    from predictionio_tpu.templates.recommendation import (
        RecommendationEngine,
    )
    from predictionio_tpu.tools.loadtest import run_loadtest

    n_req = int(os.environ.get("BENCH_FLEET_REQUESTS", 200))
    slow_ms = float(os.environ.get("BENCH_FLEET_SLOW_MS", 250.0))
    slow_p = float(os.environ.get("BENCH_FLEET_SLOW_P", 0.1))
    tmp = tempfile.mkdtemp(prefix="pio-fleet-bench-")
    src = "FLEETB"
    storage_env = {
        f"PIO_STORAGE_SOURCES_{src}_TYPE": "sqlite",
        f"PIO_STORAGE_SOURCES_{src}_PATH": os.path.join(
            tmp, "events.sqlite"
        ),
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": src,
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": src,
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": src,
    }
    old_basedir = os.environ.get("PIO_FS_BASEDIR")
    os.environ["PIO_FS_BASEDIR"] = os.path.join(tmp, "fs")
    routers: list = []
    fleets: list = []
    out: dict = {}
    try:
        storage = Storage(env=storage_env)
        store_mod.set_storage(storage)
        app_id = storage.get_meta_data_apps().insert(App(0, "fleetbench"))
        le = storage.get_l_events()
        le.init(app_id)
        rng = np.random.default_rng(23)
        events = []
        for u in range(20):
            for i in rng.choice(16, size=6, replace=False):
                events.append(Event(
                    event="rate", entity_type="user", entity_id=f"u{u}",
                    target_entity_type="item", target_entity_id=f"i{i}",
                    properties={"rating": float(rng.integers(1, 6))},
                ))
        le.batch_insert(events, app_id)
        engine = RecommendationEngine.apply()
        ep = engine.params_from_variant({
            "datasource": {"params": {"appName": "fleetbench"}},
            "algorithms": [
                {"name": "als", "params": {"rank": 4, "numIterations": 3}}
            ],
        })
        run_train(engine, ep, "f", storage=storage, ctx=ctx)

        repo_root = os.path.dirname(
            os.path.dirname(os.path.abspath(predictionio_tpu.__file__))
        )
        child_env = dict(os.environ)
        child_env.pop("PIO_FAULT_SPEC", None)
        child_env.update(storage_env)
        child_env["JAX_PLATFORMS"] = "cpu"
        child_env["PYTHONPATH"] = os.pathsep.join(
            [repo_root] + ([child_env["PYTHONPATH"]]
                           if child_env.get("PYTHONPATH") else [])
        )

        def spawn_with(extra):
            def spawn(port):
                cenv = dict(child_env)
                cenv.update(extra)
                cenv["FLEET_CHILD_PORT"] = str(port)
                return subprocess.Popen(
                    [sys.executable, "-c", _FLEET_CHILD], env=cenv,
                )
            return spawn

        socks = [socket.socket() for _ in range(4)]
        for s in socks:
            s.bind(("127.0.0.1", 0))
        ports = [s.getsockname()[1] for s in socks]
        for s in socks:
            s.close()
        fast_ports, slow_port = ports[:3], ports[3]
        fleet = FleetSupervisor(spawn_with({}), fast_ports)
        slow_spec = (
            f"site=server:queryserver:/queries.json,kind=latency,"
            f"latency_ms={slow_ms:g},p={slow_p:g}"
        )
        slow_fleet = FleetSupervisor(
            spawn_with({"PIO_FAULT_SPEC": slow_spec}), [slow_port]
        )
        fleets = [fleet, slow_fleet]
        fleet.start()
        slow_fleet.start()
        fast_urls = fleet.urls()
        slow_url = slow_fleet.urls()[0]

        def mk_router(urls, hedge):
            r = Router(urls, hedge_enabled=hedge, telemetry=False)
            r.health_interval_ms = 100.0
            r.outlier_ratio = 1e9  # isolate hedging from outlier ejection
            routers.append(r)
            port = r.start("127.0.0.1", 0)
            return r, f"http://127.0.0.1:{port}"

        def wait_proven(r, timeout=180.0):
            t_end = time.time() + timeout
            while time.time() < t_end:
                reps = r.stats()["replicas"]
                if all(x["state"] == ADMITTED
                       and x["generation"] is not None for x in reps):
                    return
                time.sleep(0.1)
            raise TimeoutError("fleet bench replicas never became ready")

        users = [f"u{i}" for i in range(20)]

        def measure(base):
            # run_loadtest appends /queries.json itself
            return run_loadtest(
                base, {"user": "u1", "num": 3},
                requests=n_req, concurrency=8, samples={"user": users},
            )

        r1, b1 = mk_router([fast_urls[0]], hedge=False)
        r3, b3 = mk_router(list(fast_urls), hedge=False)
        mixed = [fast_urls[0], fast_urls[1], slow_url]
        ru, bu = mk_router(mixed, hedge=False)
        rh, bh = mk_router(mixed, hedge=True)
        for r in (r1, r3, ru, rh):
            wait_proven(r)

        one = measure(b1)
        three = measure(b3)
        out["qps_1_replica"] = one["qps"]
        out["qps_3_replicas"] = three["qps"]
        out["scaling_3_over_1"] = (
            round(three["qps"] / one["qps"], 3) if one["qps"] else None
        )
        unhedged = measure(bu)
        hedged = measure(bh)
        out["p99_unhedged_slow_ms"] = unhedged["p99Ms"]
        out["p99_hedged_ms"] = hedged["p99Ms"]
        out["p50_unhedged_slow_ms"] = unhedged["p50Ms"]
        out["p50_hedged_ms"] = hedged["p50Ms"]
        out["hedged_vs_unhedged_p99"] = (
            round(hedged["p99Ms"] / unhedged["p99Ms"], 4)
            if unhedged["p99Ms"] else None
        )
        out["hedges"] = {
            "fired": rh.counters.get("hedges_fired"),
            "won": rh.counters.get("hedges_won"),
            "denied": rh.counters.get("hedges_denied"),
            "delay_ms": round(rh.hedge_delay_ms(), 1),
        }
        out["load_errors"] = (
            one["errors"] + three["errors"]
            + unhedged["errors"] + hedged["errors"]
        )

        # rolling deploy under load: retrain, roll the 3-replica fleet
        # through r3, count every client-visible non-200
        run_train(engine, ep, "f", storage=storage, ctx=ctx)
        fleet.router = r3
        r3.attach_fleet(fleet)
        stop_evt = threading.Event()
        lock = threading.Lock()
        tally = {"ok": 0, "errors": 0}

        def pound(idx):
            i = 0
            while not stop_evt.is_set():
                body = json.dumps(
                    {"user": f"u{(i * 7 + idx) % 20}", "num": 3}
                ).encode()
                req = urllib.request.Request(
                    b3 + "/queries.json", data=body, method="POST",
                    headers={"Content-Type": "application/json"},
                )
                try:
                    with urllib.request.urlopen(req, timeout=30) as resp:
                        resp.read()
                        ok = resp.status == 200
                except Exception:
                    ok = False
                with lock:
                    tally["ok" if ok else "errors"] += 1
                i += 1

        workers = [
            threading.Thread(target=pound, args=(i,), daemon=True)
            for i in range(4)
        ]
        for w in workers:
            w.start()
        t0 = time.time()
        report = fleet.roll()
        wall = time.time() - t0
        stop_evt.set()
        for w in workers:
            w.join(30.0)
        out["roll"] = {
            "wall_sec": round(wall, 1),
            "ok": tally["ok"],
            "client_errors": tally["errors"],
            "replicas_ok": report["ok"],
        }
    finally:
        for r in routers:
            r.stop()
        for f in fleets:
            f.stop()
        store_mod.set_storage(None)
        close_db(os.path.join(tmp, "events.sqlite"))
        if old_basedir is None:
            os.environ.pop("PIO_FS_BASEDIR", None)
        else:
            os.environ["PIO_FS_BASEDIR"] = old_basedir
        shutil.rmtree(tmp, ignore_errors=True)
    return out


def _canary_bench(ctx) -> dict:
    """Progressive-delivery evidence (ISSUE 20): a deliberately BAD
    candidate generation (fault-injected latency on exactly that
    generation's serving path) is canaried onto one replica of a
    three-replica fleet under client load.  The controller must detect
    the SLO breach online, auto-roll the canary back to the baseline,
    and write a durable quarantine receipt.

    The gates are: ``rolled_back`` (the candidate was quarantined, not
    promoted), ``client_errors == 0`` (the whole experiment is invisible
    to clients), ``blast_radius`` ≤ the canary fraction plus slack (only
    the one canaried replica's share of traffic ever saw the bad
    generation), and ``receipt_blocks_redeploy`` (after the rollback,
    newest-COMPLETED selection — what every restarted replica runs —
    resolves to the baseline, and a second canary attempt refuses for
    want of a candidate).
    """
    import shutil
    import socket
    import tempfile
    import threading
    import urllib.request

    import predictionio_tpu
    from predictionio_tpu.core import persistence
    from predictionio_tpu.core.workflow import (
        get_latest_completed_instance,
        run_train,
    )
    from predictionio_tpu.data import Event
    from predictionio_tpu.data import store as store_mod
    from predictionio_tpu.data.storage import App
    from predictionio_tpu.data.storage.registry import Storage
    from predictionio_tpu.data.storage.sqlite import close_db
    from predictionio_tpu.serving.canary import CanaryController
    from predictionio_tpu.serving.fleet import FleetSupervisor
    from predictionio_tpu.serving.router import ADMITTED, Router
    from predictionio_tpu.templates.recommendation import (
        RecommendationEngine,
    )

    slow_ms = float(os.environ.get("BENCH_CANARY_SLOW_MS", 300.0))
    slo_ms = float(os.environ.get("BENCH_CANARY_SLO_MS", 120.0))
    tmp = tempfile.mkdtemp(prefix="pio-canary-bench-")
    src = "CANARYB"
    storage_env = {
        f"PIO_STORAGE_SOURCES_{src}_TYPE": "sqlite",
        f"PIO_STORAGE_SOURCES_{src}_PATH": os.path.join(
            tmp, "events.sqlite"
        ),
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": src,
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": src,
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": src,
    }
    old_basedir = os.environ.get("PIO_FS_BASEDIR")
    os.environ["PIO_FS_BASEDIR"] = os.path.join(tmp, "fs")
    # canary knobs: a short, aggressive window so the bench converges in
    # seconds; the absolute-p99 SLO mode makes the verdict deterministic
    knob_env = {
        "PIO_CANARY_TICK_MS": "100",
        "PIO_CANARY_MIN_SAMPLES": "30",
        "PIO_CANARY_WINDOW_S": "15",
        "PIO_CANARY_P99_SLO_MS": f"{slo_ms:g}",
        "PIO_CANARY_SHADOW_BUDGET": "16",
        "PIO_CANARY_SOAK_S": "2",
    }
    old_knobs = {k: os.environ.get(k) for k in knob_env}
    os.environ.update(knob_env)
    routers: list = []
    fleets: list = []
    canary = None
    out: dict = {}
    try:
        storage = Storage(env=storage_env)
        store_mod.set_storage(storage)
        app_id = storage.get_meta_data_apps().insert(App(0, "canarybench"))
        le = storage.get_l_events()
        le.init(app_id)
        rng = np.random.default_rng(29)
        events = []
        for u in range(20):
            for i in rng.choice(16, size=6, replace=False):
                events.append(Event(
                    event="rate", entity_type="user", entity_id=f"u{u}",
                    target_entity_type="item", target_entity_id=f"i{i}",
                    properties={"rating": float(rng.integers(1, 6))},
                ))
        le.batch_insert(events, app_id)
        engine = RecommendationEngine.apply()
        ep = engine.params_from_variant({
            "datasource": {"params": {"appName": "canarybench"}},
            "algorithms": [
                {"name": "als", "params": {"rank": 4, "numIterations": 3}}
            ],
        })
        baseline_id = run_train(engine, ep, "f", storage=storage, ctx=ctx)
        candidate_id = run_train(engine, ep, "f", storage=storage, ctx=ctx)

        repo_root = os.path.dirname(
            os.path.dirname(os.path.abspath(predictionio_tpu.__file__))
        )
        child_env = dict(os.environ)
        child_env.pop("PIO_FAULT_SPEC", None)
        child_env.update(storage_env)
        child_env["JAX_PLATFORMS"] = "cpu"
        child_env["PYTHONPATH"] = os.pathsep.join(
            [repo_root] + ([child_env["PYTHONPATH"]]
                           if child_env.get("PYTHONPATH") else [])
        )
        # every child: cold-start pinned to the BASELINE (the candidate
        # is newer, so unpinned children would boot straight onto the
        # unverified generation) and carrying the generation-targeted
        # fault — the candidate generation is slow IN WHICHEVER PROCESS
        # serves it, exactly like a model with a real latency regression
        child_env["PIO_PIN_INSTANCE"] = baseline_id
        child_env["PIO_FAULT_SPEC"] = (
            f"site=server:generation:{candidate_id},kind=latency,"
            f"latency_ms={slow_ms:g},p=0.9"
        )

        def spawn(port):
            cenv = dict(child_env)
            cenv["FLEET_CHILD_PORT"] = str(port)
            return subprocess.Popen(
                [sys.executable, "-c", _FLEET_CHILD], env=cenv,
            )

        socks = [socket.socket() for _ in range(3)]
        for s in socks:
            s.bind(("127.0.0.1", 0))
        ports = [s.getsockname()[1] for s in socks]
        for s in socks:
            s.close()
        fleet = FleetSupervisor(spawn, ports)
        fleets = [fleet]
        fleet.start()
        router = Router(fleet.urls(), telemetry=False)
        router.health_interval_ms = 100.0
        # the canary controller is the intended responder to a slow
        # generation — don't let latency-outlier ejection race it
        router.outlier_ratio = 1e9
        routers.append(router)
        fleet.router = router
        router.attach_fleet(fleet)
        canary = CanaryController(
            router, fleet=fleet, storage=storage
        )
        router.attach_canary(canary)
        rport = router.start("127.0.0.1", 0)
        base = f"http://127.0.0.1:{rport}"

        t_end = time.time() + 180.0
        while time.time() < t_end:
            reps = router.stats()["replicas"]
            if reps and all(
                x["state"] == ADMITTED and x["instanceId"] == baseline_id
                for x in reps
            ):
                break
            time.sleep(0.1)
        else:
            raise TimeoutError("canary bench replicas never became ready")

        stop_evt = threading.Event()
        lock = threading.Lock()
        tally = {"ok": 0, "errors": 0}

        def pound(idx):
            i = 0
            while not stop_evt.is_set():
                body = json.dumps(
                    {"user": f"u{(i * 7 + idx) % 20}", "num": 3}
                ).encode()
                req = urllib.request.Request(
                    base + "/queries.json", data=body, method="POST",
                    headers={"Content-Type": "application/json"},
                )
                try:
                    with urllib.request.urlopen(req, timeout=30) as resp:
                        resp.read()
                        ok = resp.status == 200
                except Exception:
                    ok = False
                with lock:
                    tally["ok" if ok else "errors"] += 1
                i += 1

        workers = [
            threading.Thread(target=pound, args=(i,), daemon=True)
            for i in range(4)
        ]
        for w in workers:
            w.start()
        t0 = time.time()
        canary.start_canary()
        while canary.active() and time.time() - t0 < 120.0:
            time.sleep(0.2)
        wall = time.time() - t0
        stop_evt.set()
        for w in workers:
            w.join(30.0)

        stats = canary.stats()
        outcome = stats.get("lastOutcome") or {}
        gens = router.generation_stats()
        cand = gens.get(candidate_id) or {}
        attributed = sum(
            g.get("requests", 0) for g in gens.values()
        )
        blast = (
            cand.get("requests", 0) / attributed if attributed else None
        )
        blocks = get_latest_completed_instance(storage).id == baseline_id
        try:
            canary.start_canary()
            second_refused = False
            canary.request_abort()
        except ValueError:
            second_refused = True
        out = {
            "baseline": baseline_id,
            "candidate": candidate_id,
            "wall_sec": round(wall, 1),
            "rolled_back": outcome.get("outcome") == "quarantined"
            and outcome.get("candidate") == candidate_id,
            "rollback_reason": outcome.get("reason"),
            "client_ok": tally["ok"],
            "client_errors": tally["errors"],
            "blast_radius": round(blast, 4) if blast is not None else None,
            "candidate_requests": cand.get("requests", 0),
            "candidate_p99_ms": cand.get("p99Ms"),
            "shadow_pairs": (stats.get("shadow") or {}).get("pairs", 0),
            "quarantined": stats.get("quarantined"),
            "receipt_on_disk": persistence.is_quarantined(candidate_id),
            "selection_resolves_baseline": blocks,
            "second_canary_refused": second_refused,
            "receipt_blocks_redeploy": blocks and second_refused,
        }
    finally:
        if canary is not None:
            canary.stop()
        for r in routers:
            r.stop()
        for f in fleets:
            f.stop()
        for k, v in old_knobs.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        store_mod.set_storage(None)
        close_db(os.path.join(tmp, "events.sqlite"))
        if old_basedir is None:
            os.environ.pop("PIO_FS_BASEDIR", None)
        else:
            os.environ["PIO_FS_BASEDIR"] = old_basedir
        shutil.rmtree(tmp, ignore_errors=True)
    return out


def _elastic_bench(ctx) -> dict:
    """Elastic fleet evidence (ISSUE 11): a flash-crowd scenario (10x
    step) replayed against an autoscaled two-replica fleet, with a
    seeded ``crash:fleet:replica`` preemption fired mid-surge and a
    scale-down drain after the crowd passes.

    The gate is "SLO held while scaling": zero client-visible errors
    across the whole program (shed 503s are the backpressure contract,
    not errors), flash-phase p99 within ``BENCH_ELASTIC_SLO_P99_MS``,
    at least one scale-up AND one scale-down actually executed, and the
    preemption plan actually fired (a chaos run where the kill never
    landed proves nothing).
    """
    import shutil
    import socket
    import tempfile
    import threading

    import predictionio_tpu
    from predictionio_tpu.common import faults as _faults
    from predictionio_tpu.core.workflow import run_train
    from predictionio_tpu.data import Event
    from predictionio_tpu.data import store as store_mod
    from predictionio_tpu.data.storage import App
    from predictionio_tpu.data.storage.registry import Storage
    from predictionio_tpu.data.storage.sqlite import close_db
    from predictionio_tpu.serving.autoscaler import Autoscaler
    from predictionio_tpu.serving.fleet import (
        PREEMPT_SITE, FleetSupervisor,
    )
    from predictionio_tpu.serving.router import ADMITTED, Router
    from predictionio_tpu.templates.recommendation import (
        RecommendationEngine,
    )
    from predictionio_tpu.tools.scenarios import (
        parse_scenario, run_scenario,
    )

    rate = float(os.environ.get("BENCH_ELASTIC_RATE", 25.0))
    slo_p99_ms = float(os.environ.get("BENCH_ELASTIC_SLO_P99_MS", 1500.0))
    slow_ms = float(os.environ.get("BENCH_ELASTIC_SLOW_MS", 40.0))
    tmp = tempfile.mkdtemp(prefix="pio-elastic-bench-")
    src = "ELASTB"
    storage_env = {
        f"PIO_STORAGE_SOURCES_{src}_TYPE": "sqlite",
        f"PIO_STORAGE_SOURCES_{src}_PATH": os.path.join(
            tmp, "events.sqlite"
        ),
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": src,
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": src,
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": src,
    }
    old_basedir = os.environ.get("PIO_FS_BASEDIR")
    os.environ["PIO_FS_BASEDIR"] = os.path.join(tmp, "fs")
    routers: list = []
    fleets: list = []
    scalers: list = []
    timers: list = []
    out: dict = {}
    try:
        storage = Storage(env=storage_env)
        store_mod.set_storage(storage)
        app_id = storage.get_meta_data_apps().insert(App(0, "elasticbench"))
        le = storage.get_l_events()
        le.init(app_id)
        rng = np.random.default_rng(29)
        events = []
        for u in range(20):
            for i in rng.choice(16, size=6, replace=False):
                events.append(Event(
                    event="rate", entity_type="user", entity_id=f"u{u}",
                    target_entity_type="item", target_entity_id=f"i{i}",
                    properties={"rating": float(rng.integers(1, 6))},
                ))
        le.batch_insert(events, app_id)
        engine = RecommendationEngine.apply()
        ep = engine.params_from_variant({
            "datasource": {"params": {"appName": "elasticbench"}},
            "algorithms": [
                {"name": "als", "params": {"rank": 4, "numIterations": 3}}
            ],
        })
        run_train(engine, ep, "e", storage=storage, ctx=ctx)

        repo_root = os.path.dirname(
            os.path.dirname(os.path.abspath(predictionio_tpu.__file__))
        )
        child_env = dict(os.environ)
        child_env.pop("PIO_FAULT_SPEC", None)
        child_env.update(storage_env)
        child_env["JAX_PLATFORMS"] = "cpu"
        child_env["PYTHONPATH"] = os.pathsep.join(
            [repo_root] + ([child_env["PYTHONPATH"]]
                           if child_env.get("PYTHONPATH") else [])
        )
        # a touch of injected latency so in-flight pressure accumulates
        # at flash rates (a rank-4 CPU model otherwise answers too fast
        # for inflight utilization to register)
        child_env["PIO_FAULT_SPEC"] = (
            f"site=server:queryserver:/queries.json,kind=latency,"
            f"latency_ms={slow_ms:g},p=1"
        )

        def spawn(port):
            cenv = dict(child_env)
            cenv["FLEET_CHILD_PORT"] = str(port)
            return subprocess.Popen(
                [sys.executable, "-c", _FLEET_CHILD], env=cenv,
            )

        socks = [socket.socket() for _ in range(2)]
        for s in socks:
            s.bind(("127.0.0.1", 0))
        ports = [s.getsockname()[1] for s in socks]
        for s in socks:
            s.close()

        r = Router(
            [f"http://127.0.0.1:{p}" for p in ports],
            hedge_enabled=False, telemetry=False,
        )
        r.health_interval_ms = 100.0
        r.outlier_ratio = 1e9
        # 24 open-loop workers against a 24-per-replica cap: one healthy
        # replica can absorb the whole crowd at the cap boundary, so a
        # mid-surge preemption retries cleanly instead of 502ing
        r.replica_max_inflight = 24
        routers.append(r)
        rport = r.start("127.0.0.1", 0)
        base = f"http://127.0.0.1:{rport}"

        fleet = FleetSupervisor(spawn, ports, router=r)
        fleets.append(fleet)
        r.attach_fleet(fleet)
        fleet.start()

        t_end = time.time() + 180.0
        while time.time() < t_end:
            reps = r.stats()["replicas"]
            if reps and all(x["state"] == ADMITTED
                            and x["generation"] is not None for x in reps):
                break
            time.sleep(0.1)
        else:
            raise TimeoutError("elastic bench replicas never became ready")

        scaler = Autoscaler(r, fleet)
        scaler.interval_ms = 300.0
        scaler.min_replicas = 2
        scaler.max_replicas = 3
        scaler.up_threshold = 0.2
        scaler.down_threshold = 0.1
        scaler.up_cooldown_s = 1.0
        scaler.down_cooldown_s = 2.0
        scaler.down_after = 3
        scaler.busy_enabled = False  # telemetry=False children: no /metrics
        scalers.append(scaler)
        r.attach_autoscaler(scaler)
        scaler.start()

        program = parse_scenario(
            f"steady:name=calm,rate={rate:g},duration=6;"
            f"flash:name=flash,base={rate:g},peak={rate * 10:g},"
            f"at=2,hold=8,duration=12;"
            f"steady:name=cooldown,rate={rate:g},duration=8"
        )
        # the preemption: a seeded kill -9 of one replica, installed on
        # a timer so it lands mid-flash while the scaler is growing the
        # fleet (the supervisor's monitor consults the site every 0.25s)
        plan = _faults.FaultPlan(
            [_faults.FaultRule(site=PREEMPT_SITE, kind="crash", times=1)],
            seed=7,
        )
        preempt_timer = threading.Timer(10.0, _faults.install, args=(plan,))
        preempt_timer.daemon = True
        timers.append(preempt_timer)
        preempt_timer.start()

        users = [f"u{i}" for i in range(20)]
        res = run_scenario(
            base, {"user": "u1", "num": 3}, program,
            samples={"user": users}, concurrency=24,
            slo_p99_ms=slo_p99_ms,
        )

        # the crowd has passed: give the scaler a moment to drain the
        # surge replica back out (down_after low ticks + cooldown)
        t_end = time.time() + 30.0
        while time.time() < t_end:
            if scaler.stats()["scaleDowns"] >= 1:
                break
            time.sleep(0.25)

        stats = scaler.stats()
        fired = sum(x["fired"] for x in plan.stats()["rules"])
        flash = next(
            (p for p in res["phases"] if p["name"] == "flash"),
            res["phases"][1],
        )
        out["phases"] = [
            {
                "name": p["name"],
                "offered": p["offered"],
                "ok": p["ok"],
                "errors": p["errors"],
                "shed": p["shed"],
                "p50_ms": p["p50Ms"],
                "p99_ms": p["p99Ms"],
            }
            for p in res["phases"]
        ]
        out["client_errors"] = res["errors"]
        out["shed"] = res["shed"]
        out["p99_while_scaling_ms"] = flash["p99Ms"]
        out["slo_p99_ms"] = slo_p99_ms
        out["worst_lag_s"] = res["worstLagS"]
        out["scale_ups"] = stats["scaleUps"]
        out["scale_downs"] = stats["scaleDowns"]
        out["preemptions"] = fired
        out["fleet_transitions"] = fleet.status()["transitions"]
        out["gate_pass"] = bool(
            res["errors"] == 0
            and (flash["p99Ms"] or 0.0) <= slo_p99_ms
            and stats["scaleUps"] >= 1
            and stats["scaleDowns"] >= 1
            and fired >= 1
        )
    finally:
        for t in timers:
            t.cancel()
        _faults.clear()
        for sc in scalers:
            sc.stop()
        for r in routers:
            r.stop()
        for f in fleets:
            f.stop()
        store_mod.set_storage(None)
        close_db(os.path.join(tmp, "events.sqlite"))
        if old_basedir is None:
            os.environ.pop("PIO_FS_BASEDIR", None)
        else:
            os.environ["PIO_FS_BASEDIR"] = old_basedir
        shutil.rmtree(tmp, ignore_errors=True)
    return out


def _freshness_bench(ctx) -> dict:
    """Streaming-freshness evidence: sustained query load against an
    autoscaled two-replica fleet while the in-process event plane folds
    committed events into sealed micro-generation deltas and the router
    propagates each one to every replica.

    Three numbers matter: ``visible_p99_ms`` (event submitted →
    prediction-visible on every replica, i.e. WAL ack + group-commit +
    fold-in + seal + router push + in-place apply), ``apply_wall_ms``
    (the router→fleet propagation round-trip alone), and
    ``lost_acked_events`` (must be zero — every fast-acked event id is
    found back in storage after the run).  The gate is all of: every
    batch sealed, every push acked by the full fleet, visible p99 within
    ``PIO_FRESHNESS_SLO_MS``, zero lost acked events, zero client-visible
    query errors while the deltas landed.
    """
    import copy as _copy
    import shutil
    import socket
    import tempfile
    import threading
    import urllib.request as _urlreq

    import predictionio_tpu
    from predictionio_tpu.core import delta as _delta
    from predictionio_tpu.core.workflow import run_train
    from predictionio_tpu.data import Event
    from predictionio_tpu.data import store as store_mod
    from predictionio_tpu.data.api.event_server import EventServer
    from predictionio_tpu.data.storage import App
    from predictionio_tpu.data.storage.registry import Storage
    from predictionio_tpu.data.storage.sqlite import close_db
    from predictionio_tpu.serving.autoscaler import Autoscaler
    from predictionio_tpu.serving.fleet import FleetSupervisor
    from predictionio_tpu.serving.query_server import QueryServer
    from predictionio_tpu.serving.router import ADMITTED, Router
    from predictionio_tpu.templates.recommendation import (
        RecommendationEngine,
    )

    batches = int(os.environ.get("BENCH_FRESHNESS_BATCHES", 10))
    per_batch = int(os.environ.get("BENCH_FRESHNESS_EVENTS", 24))
    slo_ms = float(os.environ.get("PIO_FRESHNESS_SLO_MS", "5000"))
    tmp = tempfile.mkdtemp(prefix="pio-freshness-bench-")
    src = "FRESHB"
    storage_env = {
        f"PIO_STORAGE_SOURCES_{src}_TYPE": "sqlite",
        f"PIO_STORAGE_SOURCES_{src}_PATH": os.path.join(
            tmp, "events.sqlite"
        ),
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": src,
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": src,
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": src,
    }
    saved_env = {
        k: os.environ.get(k)
        for k in ("PIO_FS_BASEDIR", "PIO_STREAMING", "PIO_DELTA_DIR",
                  "PIO_DELTA_CATCHUP_MS")
    }
    os.environ["PIO_FS_BASEDIR"] = os.path.join(tmp, "fs")
    os.environ["PIO_STREAMING"] = "1"
    os.environ["PIO_DELTA_DIR"] = os.path.join(tmp, "deltas")
    # visibility is router-push driven here; park the replica poll pace
    # so catch-up slack never flatters the measurement
    os.environ["PIO_DELTA_CATCHUP_MS"] = "60000"
    routers: list = []
    fleets: list = []
    scalers: list = []
    event_servers: list = []
    stop_load = threading.Event()
    load_threads: list = []
    out: dict = {}
    try:
        storage = Storage(env=storage_env)
        store_mod.set_storage(storage)
        app_id = storage.get_meta_data_apps().insert(App(0, "freshbench"))
        le = storage.get_l_events()
        le.init(app_id)
        rng = np.random.default_rng(31)
        events = []
        for u in range(20):
            for i in rng.choice(16, size=6, replace=False):
                events.append(Event(
                    event="rate", entity_type="user", entity_id=f"u{u}",
                    target_entity_type="item", target_entity_id=f"i{i}",
                    properties={"rating": float(rng.integers(1, 6))},
                ))
        le.batch_insert(events, app_id)
        engine = RecommendationEngine.apply()
        ep = engine.params_from_variant({
            "datasource": {"params": {"appName": "freshbench"}},
            "algorithms": [
                {"name": "als", "params": {"rank": 4, "numIterations": 3}}
            ],
        })
        run_train(engine, ep, "fresh", storage=storage, ctx=ctx)

        repo_root = os.path.dirname(
            os.path.dirname(os.path.abspath(predictionio_tpu.__file__))
        )
        child_env = dict(os.environ)
        child_env.pop("PIO_FAULT_SPEC", None)
        child_env.update(storage_env)
        child_env["JAX_PLATFORMS"] = "cpu"
        child_env["PYTHONPATH"] = os.pathsep.join(
            [repo_root] + ([child_env["PYTHONPATH"]]
                           if child_env.get("PYTHONPATH") else [])
        )

        def spawn(port):
            cenv = dict(child_env)
            cenv["FLEET_CHILD_PORT"] = str(port)
            return subprocess.Popen(
                [sys.executable, "-c", _FLEET_CHILD], env=cenv,
            )

        socks = [socket.socket() for _ in range(2)]
        for s in socks:
            s.bind(("127.0.0.1", 0))
        ports = [s.getsockname()[1] for s in socks]
        for s in socks:
            s.close()

        r = Router(
            [f"http://127.0.0.1:{p}" for p in ports],
            hedge_enabled=False, telemetry=False,
        )
        r.health_interval_ms = 100.0
        r.outlier_ratio = 1e9
        routers.append(r)
        rport = r.start("127.0.0.1", 0)
        base = f"http://127.0.0.1:{rport}"

        fleet = FleetSupervisor(spawn, ports, router=r)
        fleets.append(fleet)
        r.attach_fleet(fleet)
        fleet.start()

        t_end = time.time() + 180.0
        while time.time() < t_end:
            reps = r.stats()["replicas"]
            if reps and all(x["state"] == ADMITTED
                            and x["generation"] is not None for x in reps):
                break
            time.sleep(0.1)
        else:
            raise TimeoutError("freshness bench replicas never became ready")

        # the scaler runs for real (evaluates every tick) but the rank-4
        # CPU workload keeps utilization far under the threshold, so the
        # fleet holds steady and every push can be gated on full-fleet
        # acknowledgement
        scaler = Autoscaler(r, fleet)
        scaler.interval_ms = 300.0
        scaler.min_replicas = 2
        scaler.max_replicas = 3
        scaler.up_threshold = 0.9
        scaler.busy_enabled = False  # telemetry=False children: no /metrics
        scalers.append(scaler)
        r.attach_autoscaler(scaler)
        scaler.start()

        # event plane: its own copy of the SAME deployed base generation
        # the children serve, loaded through the identical deploy path so
        # the delta fence (base fingerprint) matches across processes
        qs_local = QueryServer(
            engine, storage=storage, ctx=ctx, telemetry=False,
        )
        st_local = qs_local._streaming
        if st_local is None:
            raise RuntimeError("PIO_STREAMING=1 but streaming not enabled")
        pub_model = _copy.deepcopy(st_local["model"])
        delta_dir = st_local["dir"]
        qs_local.stop()

        es = EventServer(
            storage=storage, ingest_mode="fast",
            wal_dir=os.path.join(tmp, "wal"),
            ingest_flush_ms=5.0, telemetry=False,
        )
        event_servers.append(es)
        # gate off: this bench measures the pipeline's latency, not
        # fold-in quality (the quality gate has its own chaos coverage)
        pub = es.enable_delta_publisher(pub_model, min_overlap=0.0)
        if pub is None:
            raise RuntimeError("delta publisher did not enable")

        load_counts = {"ok": 0, "errors": 0}
        count_lock = threading.Lock()

        def _load(worker):
            i = worker
            while not stop_load.is_set():
                i += 1
                body = json.dumps(
                    {"user": f"u{i % 20}", "num": 3}
                ).encode()
                req = _urlreq.Request(
                    base + "/queries.json", data=body,
                    headers={"Content-Type": "application/json"},
                )
                try:
                    with _urlreq.urlopen(req, timeout=10) as resp:
                        resp.read()
                        ok = resp.status == 200
                except Exception:
                    ok = False
                with count_lock:
                    load_counts["ok" if ok else "errors"] += 1
                time.sleep(0.01)

        for w in range(4):
            t = threading.Thread(target=_load, args=(w,), daemon=True)
            load_threads.append(t)
            t.start()

        log = _delta.DeltaLog(delta_dir)
        acked_ids: list = []
        visible_ms: list = []
        apply_ms: list = []
        seal_failures = 0
        partial_pushes = 0
        seq = 0
        erng = np.random.default_rng(41)
        for _ in range(batches):
            t0 = time.time()
            for _e in range(per_batch):
                seq += 1
                ev = Event(
                    event="rate", entity_type="user",
                    entity_id=f"u{erng.integers(20)}",
                    target_entity_type="item",
                    target_entity_id=f"i{erng.integers(16)}",
                    properties={"rating": float(erng.integers(1, 6))},
                    event_id=f"fresh-{seq:05d}",
                )
                es.ingest_buffer.submit(ev, app_id)  # WAL fast-ack
                acked_ids.append(ev.event_id)
            # the group-commit flush feeds the publisher within ~flush_ms
            t_wait = time.time() + 30.0
            while pub.pending() < per_batch and time.time() < t_wait:
                time.sleep(0.002)
            receipt = pub.flush()
            if not (receipt and receipt.get("sealed")):
                seal_failures += 1
                continue
            blob = open(log.path(receipt["epoch"]), "rb").read()
            t_push = time.time()
            acks = r.push_delta(blob)
            now = time.time()
            apply_ms.append((now - t_push) * 1000.0)
            visible_ms.append((now - t0) * 1000.0)
            if acks["acked"] != acks["replicas"]:
                partial_pushes += 1
        stop_load.set()
        for t in load_threads:
            t.join(timeout=15.0)

        # zero-loss audit: every fast-acked event id must be in storage
        stored = {e.event_id for e in le.find(app_id)}
        lost = [i for i in acked_ids if i not in stored]
        vis = sorted(visible_ms)
        p99 = vis[min(len(vis) - 1, int(len(vis) * 0.99))] if vis else None
        pstats = pub.stats()
        out = {
            "batches": batches,
            "events_per_batch": per_batch,
            "sealed": pstats["sealed"],
            "seal_failures": seal_failures,
            "partial_pushes": partial_pushes,
            "visible_p99_ms": round(p99, 2) if p99 is not None else None,
            "visible_max_ms": round(vis[-1], 2) if vis else None,
            "apply_wall_ms": (
                round(sorted(apply_ms)[len(apply_ms) // 2], 2)
                if apply_ms else None
            ),
            "slo_ms": slo_ms,
            "lost_acked_events": len(lost),
            "query_ok": load_counts["ok"],
            "query_errors": load_counts["errors"],
            "scale_ups": scaler.stats()["scaleUps"],
            "gate_pass": bool(
                pstats["sealed"] == batches
                and seal_failures == 0
                and partial_pushes == 0
                and p99 is not None
                and p99 <= slo_ms
                and not lost
                and load_counts["errors"] == 0
            ),
        }
    finally:
        stop_load.set()
        for t in load_threads:
            t.join(timeout=5.0)
        for es in event_servers:
            es.stop()
        for sc in scalers:
            sc.stop()
        for r in routers:
            r.stop()
        for f in fleets:
            f.stop()
        store_mod.set_storage(None)
        close_db(os.path.join(tmp, "events.sqlite"))
        for k, v in saved_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        shutil.rmtree(tmp, ignore_errors=True)
    return out


def _sharded_serving_bench(ctx) -> dict:
    """Sharded-serving evidence (ISSUE 12): on the multi-device mesh, a
    catalog deliberately sized past one device's (simulated) HBM budget is
    served through the :class:`ShardingPlan` partitioned fast path under a
    Zipf workload.

    Gates: (a) the catalog really overflows the per-device budget while
    every shard's resident block fits it, (b) sharded answers are
    BIT-IDENTICAL to the replicated reference (indices and values), (c)
    per-shard utilization is non-null, and (d) the popularity-aware plan's
    max/min attributed busy-fraction balance stays ≤ 1.5.  The naive
    round-robin plan serves the same workload and reports its balance for
    comparison, ungated — with hot items at contiguous low ids it can land
    anywhere; the LPT plan cannot.
    """
    from predictionio_tpu.serving import sharding as sharding_mod
    from predictionio_tpu.serving.fastpath import BucketedScorer

    n_items = int(os.environ.get("BENCH_SHARD_ITEMS", 4096))
    rank = int(os.environ.get("BENCH_SHARD_RANK", 16))
    budget = int(os.environ.get("BENCH_SHARD_BUDGET", 70_000))
    n_req = int(os.environ.get("BENCH_SHARD_REQUESTS", 640))
    n_users = 512
    k = 20
    rng = np.random.default_rng(12)
    U = rng.normal(size=(n_users, rank)).astype(np.float32)
    V = rng.normal(size=(n_items, rank)).astype(np.float32)
    catalog_bytes = int(V.nbytes)
    users = _sample_ids(rng, n_users, n_req, "zipf", s=1.1)

    # replicated reference: the ground truth answers AND the measured
    # per-item win counts the popularity plan balances (the live analogue
    # of the publish-time factor-norm proxy)
    repl = BucketedScorer(ctx, U, V, max_k=k, sharding="replicated")
    ref_idx, ref_val = repl.score_topk(users, k)
    wins = np.bincount(
        ref_idx.reshape(-1), minlength=n_items
    ).astype(np.float64)

    n_shards = sharding_mod.shard_count_for_budget(
        n_items, rank * 4.0, budget
    )
    plans = {
        name: sharding_mod.build_plan(
            n_items, n_shards, weights=wins, strategy=name,
            capacity_budget_bytes=budget,
        )
        for name in ("popularity", "round_robin")
    }
    per_plan: dict = {}
    exact = True
    busy_ok = True
    resident_fits = True
    for name, plan in plans.items():
        sc = BucketedScorer(ctx, U, V, max_k=k, plan=plan, sharding="sharded")
        idx, vals = sc.score_topk(users, k)
        eq = bool(
            np.array_equal(idx, ref_idx) and np.array_equal(vals, ref_val)
        )
        exact = exact and eq
        st = (sc.stats() or {}).get("sharding") or {}
        busy = st.get("busy_fraction")
        busy_ok = busy_ok and bool(
            busy and all(b is not None for b in busy)
        )
        resident = st.get("resident_bytes") or []
        resident_fits = resident_fits and bool(
            resident and max(resident) <= budget
        )
        balance = (
            round(max(busy) / min(busy), 4)
            if busy and min(busy) > 0 else None
        )
        per_plan[name] = {
            "fingerprint": plan.fingerprint,
            "exact_match": eq,
            "busy_fraction": busy,
            "busy_balance": balance,
            "result_share": st.get("result_share"),
            "resident_bytes_per_shard": resident,
            "merge_bytes": st.get("merge_bytes"),
        }
    pop_balance = per_plan["popularity"]["busy_balance"]
    return {
        "n_items": n_items,
        "rank": rank,
        "k": k,
        "requests": int(n_req),
        "distribution": "zipf",
        "catalog_bytes": catalog_bytes,
        "per_device_budget_bytes": budget,
        "n_shards": n_shards,
        "plans": per_plan,
        "gate_pass": bool(
            catalog_bytes > budget
            and n_shards > 1
            and resident_fits
            and exact
            and busy_ok
            and pop_balance is not None
            and pop_balance <= 1.5
        ),
    }


_POD_BENCH_WORKER = """
import os, sys
sys.path.insert(0, {repo!r})
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
jax.config.update("jax_platforms", "cpu")
import json
import numpy as np
from predictionio_tpu.parallel import distributed

assert distributed.initialize()
from predictionio_tpu.parallel.mesh import MeshContext
from predictionio_tpu.serving import sharding as _sharding
from predictionio_tpu.serving.fastpath import BucketedScorer

ctx = MeshContext.create()
rng = np.random.default_rng({seed})
U = rng.standard_normal(({n_users}, {rank})).astype(np.float32)
V = rng.standard_normal(({n_items}, {rank})).astype(np.float32)
batches = [rng.integers(0, {n_users}, n).astype(np.int32) for n in (1, 13)]
plan = _sharding.build_plan({n_items}, 4, host_groups=2)
sc = BucketedScorer(ctx, U, V, max_k={k}, buckets=(1, 8),
                    sharding="sharded", plan=plan)
cells = []
for users in batches:
    idx, vals = sc.score_topk(users, {k})
    cells.append({{"idx": np.asarray(idx).tolist(),
                  "vals": np.asarray(vals, np.float64).tolist()}})
st = sc.stats()
pod = st["pod"]
shard = (st.get("sharding") or {{}})
print("POD_RESULT " + json.dumps({{
    "cells": cells,
    "pod_bytes": pod["cross_host_merge_bytes"],
    "pod_seconds": pod["cross_host_merge_seconds"],
    "dispatches": pod["dispatches"],
    "on_host_merge_bytes": shard.get("merge_bytes"),
    "host_groups": pod["host_groups"],
    "process_count": pod["process_count"],
}}))
"""


def _pod_serving_bench() -> dict:
    """Pod-scale serving gate (ISSUE 18): a REAL 2-process
    ``jax.distributed`` CPU mesh (Gloo collectives, 2 virtual devices per
    process) serves a 4-shard / 2-host-group plan through the two-tier
    merge, and the parent replays the same workload on a single-process
    replicated scorer.

    Gates: (a) the pod answers are BIT-identical to the replicated
    reference — indices and values, every dispatch; (b) the measured
    cross-host merge traffic is <= the ``H*B*k*8`` two-tier derivation in
    docs/perf_roofline.md (it lands exactly on it; the bound keeps the
    gate honest if accounting grows).  The flat single-tier collective
    would have moved ``S*B*local_k*8`` across hosts — the reduction
    factor is reported alongside.
    """
    import socket
    import subprocess
    import tempfile

    from predictionio_tpu.parallel.mesh import MeshContext
    from predictionio_tpu.serving.fastpath import BucketedScorer

    n_users, n_items, rank, k, seed = 40, 320, 8, 10, 11
    script = _POD_BENCH_WORKER.format(
        repo=os.path.dirname(os.path.abspath(__file__)),
        seed=seed, n_users=n_users, n_items=n_items, rank=rank, k=k,
    )
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        coord_port = s.getsockname()[1]
    with tempfile.TemporaryDirectory(prefix="pio-pod-bench-") as tmp:
        path = os.path.join(tmp, "pod_worker.py")
        with open(path, "w") as f:
            f.write(script)
        procs = []
        for pid in (0, 1):
            env = dict(os.environ)
            env.update(
                PIO_COORDINATOR=f"127.0.0.1:{coord_port}",
                PIO_NUM_PROCESSES="2",
                PIO_PROCESS_ID=str(pid),
            )
            procs.append(subprocess.Popen(
                [sys.executable, path], env=env,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            ))
        outs = []
        try:
            for p in procs:
                out, _ = p.communicate(timeout=240)
                outs.append(out)
                if p.returncode != 0:
                    raise RuntimeError(f"pod bench worker failed:\n{out}")
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
    got = json.loads(next(
        ln for ln in outs[0].splitlines() if ln.startswith("POD_RESULT ")
    )[len("POD_RESULT "):])

    # replicated reference on the parent's own mesh, same seeded workload
    rng = np.random.default_rng(seed)
    U = rng.standard_normal((n_users, rank)).astype(np.float32)
    V = rng.standard_normal((n_items, rank)).astype(np.float32)
    batches = [rng.integers(0, n_users, n).astype(np.int32) for n in (1, 13)]
    repl = BucketedScorer(
        MeshContext.create(), U, V, max_k=k, buckets=(1, 8),
        sharding="replicated",
    )
    exact = True
    for cell, users in zip(got["cells"], batches):
        ref_idx, ref_val = repl.score_topk(users, k)
        exact = exact and bool(
            np.array_equal(np.asarray(cell["idx"], np.int32), ref_idx)
            and np.array_equal(
                np.asarray(cell["vals"], np.float64),
                np.asarray(ref_val, np.float64),
            )
        )
    # two-tier derivation (docs/perf_roofline.md): H*b*k*8 per dispatch
    # over the padded rungs b = 1, 8, 8; flat would be S*b*local_k*8
    h, s_shards, local_k = 2, 4, k
    rungs = (1, 8, 8)
    derived = float(sum(h * b * k * 8 for b in rungs))
    flat = float(sum(s_shards * b * local_k * 8 for b in rungs))
    measured = float(got["pod_bytes"])
    return {
        "processes": int(got["process_count"]),
        "host_groups": int(got["host_groups"]),
        "n_shards": s_shards,
        "k": k,
        "dispatches": int(got["dispatches"]),
        "exact_match": exact,
        "cross_host_merge_bytes": measured,
        "cross_host_merge_bytes_derived": derived,
        "flat_merge_bytes": flat,
        "reduction_factor": round(flat / measured, 4) if measured else None,
        "cross_host_merge_seconds": got["pod_seconds"],
        "on_host_merge_bytes": got["on_host_merge_bytes"],
        "gate_pass": bool(exact and measured and measured <= derived),
    }


def _retrieval_bench(ctx, platform) -> dict:
    """IVF retrieval gate (ISSUE 16): serve a clustered catalog through the
    coarse-partition fast path at the DEFAULT nprobe and prove the two
    halves of the trade hold at once — recall@10 >= 0.95 against the exact
    scorer AND mean scanned fraction <= 0.2 of the catalog's padded rows.

    The catalog is a Gaussian mixture, not white noise: IVF prunes
    *structure*, and a structureless catalog has nothing to prune (every
    cluster holds someone's top-k, so recall collapses at any scanned
    fraction < 1).  Real item-factor matrices cluster — genre, popularity
    band, co-consumption — and the mixture encodes that regime.

    Recall is measured over b=1 dispatches, where the probe budget is the
    per-query ``nprobe`` itself (no batch widening) — the same regime the
    publish-time refusal gate measures.  The scanned fraction comes from
    the scorer's own accounting (``stats()['retrieval']``), read BEFORE
    any batched timing dispatches so wide rungs' widened probe budgets
    don't dilute it.  Wall-clock scores/s is recorded on TPU only: off
    TPU the fused kernel runs under the Pallas interpreter, whose timings
    are meaningless.
    """
    from predictionio_tpu.core.evaluation import recall_at_k
    from predictionio_tpu.ops import ivf as ivf_mod
    from predictionio_tpu.serving.fastpath import BucketedScorer

    n_items = int(os.environ.get("BENCH_IVF_ITEMS", 8192))
    rank = int(os.environ.get("BENCH_IVF_RANK", 16))
    nlist = int(os.environ.get("BENCH_IVF_NLIST", 64))
    n_queries = int(os.environ.get("BENCH_IVF_QUERIES", 96))
    k = 10
    rng = np.random.default_rng(16)
    centers = (rng.normal(size=(nlist, rank)) * 4.0).astype(np.float32)
    item_cluster = rng.integers(0, nlist, size=n_items)
    V = (
        centers[item_cluster] + rng.normal(size=(n_items, rank)) * 0.25
    ).astype(np.float32)
    # queries live near the same centers: each user's top-k concentrates
    # in a handful of clusters, the regime the nprobe default targets
    q_cluster = rng.integers(0, nlist, size=n_queries)
    U = (
        centers[q_cluster] + rng.normal(size=(n_queries, rank)) * 0.25
    ).astype(np.float32)

    index = ivf_mod.build_index(V, nlist)  # default nprobe = nlist // 8
    exact_sc = BucketedScorer(ctx, U, V, max_k=k)
    ivf_sc = BucketedScorer(
        ctx, U, V, max_k=k, ivf_index=index, retrieval="ivf"
    )
    exact_rows = []
    approx_rows = []
    for u in range(n_queries):
        one = np.array([u])
        exact_rows.append(exact_sc.score_topk(one, k)[0][0])
        approx_rows.append(ivf_sc.score_topk(one, k)[0][0])
    recall = recall_at_k(np.stack(exact_rows), np.stack(approx_rows), k)
    st = (ivf_sc.stats() or {}).get("retrieval") or {}
    frac = st.get("scanned_fraction")

    measured = None
    if platform == "tpu":  # never time the Pallas interpreter
        users_all = np.arange(n_queries)
        exact_sc.score_topk(users_all, k)  # warm the wide rung
        ivf_sc.score_topk(users_all, k)
        reps = int(os.environ.get("BENCH_IVF_REPS", 20))
        t0 = time.perf_counter()
        for _ in range(reps):
            exact_sc.score_topk(users_all, k)
        t_exact = time.perf_counter() - t0
        t0 = time.perf_counter()
        for _ in range(reps):
            ivf_sc.score_topk(users_all, k)
        t_ivf = time.perf_counter() - t0
        scored = reps * n_queries * n_items
        measured = {
            "reps": reps,
            "exact_scores_per_s": round(scored / t_exact, 1),
            "ivf_requests_per_s": round(reps * n_queries / t_ivf, 1),
            "speedup_vs_exact": round(t_exact / t_ivf, 4),
        }
    return {
        "n_items": n_items,
        "rank": rank,
        "k": k,
        "queries": n_queries,
        "nlist": int(st.get("nlist") or index.nlist),
        "nprobe": int(st.get("nprobe") or index.nprobe),
        "min_probes": st.get("min_probes"),
        "cap_pad": st.get("cap_pad"),
        "recall_at_10": round(float(recall), 4),
        "scanned_fraction": frac,
        "analytic_scan_speedup": (
            round(1.0 / frac, 2) if frac else None
        ),
        "fingerprint": st.get("fingerprint"),
        "measured": measured,
        "gate_pass": bool(
            recall >= 0.95 and frac is not None and frac <= 0.2
        ),
    }


def _tenant_bench(ctx) -> dict:
    """Multi-tenant QoS + composed-pipeline evidence (ISSUE 19).

    Two gates in one block:

    * ``noisy_neighbor`` — two tenants behind one query server; tenant
      ``alpha`` drives far past its qps quota while ``beta`` sends a
      modest stream.  The contract: alpha's overage is shed with 503s
      ATTRIBUTED to its quota (token bucket, ``Retry-After``), alpha's
      admitted requests all succeed, and beta sees zero errors, zero
      sheds, and a p99 inside its SLO — one tenant's saturation must
      not tax another's latency.
    * ``pipeline`` — the same query answered two ways on a bench-sized
      clustered catalog: single-stage exact ALS (full-catalog matvec +
      top-k) vs the composed IVF-retrieval → fused-ALS-ranking
      pipeline.  The gate is the ISSUE's bar: the pipeline beats exact
      on scores/s (catalog rows ranked per wall-second) at <= 1.5x the
      exact path's p99.
    """
    import shutil
    import tempfile
    import threading
    import types

    from predictionio_tpu.core.workflow import run_train
    from predictionio_tpu.data import Event
    from predictionio_tpu.data import store as store_mod
    from predictionio_tpu.data.bimap import BiMap
    from predictionio_tpu.data.storage import App
    from predictionio_tpu.data.storage.registry import Storage
    from predictionio_tpu.data.storage.sqlite import close_db
    from predictionio_tpu.models.als import ALSModel, ALSScorer
    from predictionio_tpu.ops import ivf as ivf_mod
    from predictionio_tpu.serving.pipeline import (
        PipelineConfig, StageSpec, build_recommendation_stages,
    )
    from predictionio_tpu.serving.query_server import QueryServer
    from predictionio_tpu.serving.tenancy import TenantRegistry, TenantSpec
    from predictionio_tpu.templates.recommendation import (
        Query, RecommendationEngine,
    )
    from predictionio_tpu.tools.loadtest import run_loadtest

    quota_qps = float(os.environ.get("BENCH_TENANT_QUOTA_QPS", 25.0))
    slo_ms = float(os.environ.get("BENCH_TENANT_SLO_MS", 500.0))
    out: dict = {}

    # -- noisy neighbor: quota shed + isolation ---------------------------
    tmp = tempfile.mkdtemp(prefix="pio-tenant-bench-")
    src = "TENB"
    storage_env = {
        f"PIO_STORAGE_SOURCES_{src}_TYPE": "sqlite",
        f"PIO_STORAGE_SOURCES_{src}_PATH": os.path.join(
            tmp, "events.sqlite"
        ),
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": src,
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": src,
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": src,
    }
    old_basedir = os.environ.get("PIO_FS_BASEDIR")
    os.environ["PIO_FS_BASEDIR"] = os.path.join(tmp, "fs")
    qs = None
    try:
        storage = Storage(env=storage_env)
        store_mod.set_storage(storage)
        app_id = storage.get_meta_data_apps().insert(App(0, "tenantbench"))
        le = storage.get_l_events()
        le.init(app_id)
        rng = np.random.default_rng(19)
        events = []
        for u in range(20):
            for i in rng.choice(16, size=6, replace=False):
                events.append(Event(
                    event="rate", entity_type="user", entity_id=f"u{u}",
                    target_entity_type="item", target_entity_id=f"i{i}",
                    properties={"rating": float(rng.integers(1, 6))},
                ))
        le.batch_insert(events, app_id)
        engine = RecommendationEngine.apply()
        ep = engine.params_from_variant({
            "datasource": {"params": {"appName": "tenantbench"}},
            "algorithms": [
                {"name": "als", "params": {"rank": 4, "numIterations": 3}}
            ],
        })
        run_train(engine, ep, "e", storage=storage, ctx=ctx)

        registry = TenantRegistry(
            [
                TenantSpec("alpha", "bench-key-alpha", weight=1.0,
                           quota_qps=quota_qps, slo_ms=slo_ms),
                TenantSpec("beta", "bench-key-beta", weight=1.0,
                           slo_ms=slo_ms),
            ],
            total_inflight=64,
        )
        qs = QueryServer(
            engine, storage=storage, ctx=ctx, telemetry=False,
            tenants=registry,
        )
        port = qs.start("127.0.0.1", 0)
        url = f"http://127.0.0.1:{port}"
        users = [f"u{i}" for i in range(20)]

        results: dict = {}

        def drive(name, key, requests, concurrency):
            results[name] = run_loadtest(
                url, {"num": 3, "accessKey": key},
                requests=requests, concurrency=concurrency,
                samples={"user": users},
            )

        # alpha floods (8 closed-loop workers against a ~sub-ms model
        # burn the banked burst tokens in well under a second); beta
        # keeps a polite trickle going the whole time
        ta = threading.Thread(
            target=drive, args=("alpha", "bench-key-alpha", 400, 8),
        )
        tb = threading.Thread(
            target=drive, args=("beta", "bench-key-beta", 120, 2),
        )
        ta.start()
        tb.start()
        ta.join()
        tb.join()

        tstats = registry.stats()
        alpha, beta = results["alpha"], results["beta"]
        noisy = {
            "quota_qps": quota_qps,
            "slo_ms": slo_ms,
            "alpha": {
                "ok": alpha["ok"], "errors": alpha["errors"],
                "shed": alpha["shed"],
                "shed_reasons": tstats["alpha"]["shed"],
                "admitted": tstats["alpha"]["admitted"],
            },
            "beta": {
                "ok": beta["ok"], "errors": beta["errors"],
                "shed": beta["shed"], "p99_ms": beta["p99Ms"],
                "slo_violations": tstats["beta"]["slo_violations"],
            },
            "gate_pass": bool(
                alpha["shed"] > 0
                and tstats["alpha"]["shed"]["quota"] > 0
                and alpha["errors"] == 0
                and beta["errors"] == 0
                and beta["shed"] == 0
                and (beta["p99Ms"] or 0.0) <= slo_ms
            ),
        }
    finally:
        if qs is not None:
            qs.stop()
        store_mod.set_storage(None)
        close_db(os.path.join(tmp, "events.sqlite"))
        if old_basedir is None:
            os.environ.pop("PIO_FS_BASEDIR", None)
        else:
            os.environ["PIO_FS_BASEDIR"] = old_basedir
        shutil.rmtree(tmp, ignore_errors=True)
    out["noisy_neighbor"] = noisy

    # -- composed pipeline vs single-stage exact --------------------------
    n_items = int(os.environ.get("BENCH_TENANT_ITEMS", 32768))
    rank = int(os.environ.get("BENCH_TENANT_RANK", 16))
    n_queries = int(os.environ.get("BENCH_TENANT_QUERIES", 300))
    n_users = 64
    nlist = 64
    rng = np.random.default_rng(23)
    # clustered catalog (same regime as the IVF gate): retrieval prunes
    # structure, and real item-factor matrices have it
    centers = (rng.normal(size=(nlist, rank)) * 4.0).astype(np.float32)
    item_cluster = rng.integers(0, nlist, size=n_items)
    V = (
        centers[item_cluster] + rng.normal(size=(n_items, rank)) * 0.25
    ).astype(np.float32)
    u_cluster = rng.integers(0, nlist, size=n_users)
    U = (
        centers[u_cluster] + rng.normal(size=(n_users, rank)) * 0.25
    ).astype(np.float32)
    model = ALSModel(
        user_factors=U,
        item_factors=V,
        user_map=BiMap({f"u{i}": i for i in range(n_users)}),
        item_map=BiMap({f"i{i}": i for i in range(n_items)}),
        ivf_index=ivf_mod.build_index(V, nlist),
    )
    scorer = ALSScorer(ctx, model)  # bench catalog < HOST_THRESHOLD: host path
    config = PipelineConfig(
        name="bench-two-stage",
        stages=(
            StageSpec("retrieve", "retrieval", 0.4,
                      params=(("candidates", 512),)),
            StageSpec("rank", "ranking", 0.6),
        ),
    )
    pipe = build_recommendation_stages(
        config, types.SimpleNamespace(_scorer=lambda m: scorer), model,
    )
    if pipe is None:
        raise RuntimeError("pipeline failed to bind the bench model")

    def drive_exact(i: int) -> None:
        scorer.recommend(i % n_users, 10)

    def drive_pipeline(i: int) -> None:
        pred, meta = pipe.run_pipeline(Query(user=f"u{i % n_users}", num=10))
        if meta.get("degraded"):
            raise RuntimeError("pipeline degraded with no deadline set")

    def timed(fn) -> tuple:
        for i in range(20):  # warm caches / lazy allocations
            fn(i)
        lats = []
        t0 = time.perf_counter()
        for i in range(n_queries):
            t1 = time.perf_counter()
            fn(i)
            lats.append(time.perf_counter() - t1)
        total = time.perf_counter() - t0
        lats.sort()
        p99 = lats[min(int(0.99 * len(lats)), len(lats) - 1)] * 1e3
        return n_queries / total, p99

    exact_qps, exact_p99 = timed(drive_exact)
    pipe_qps, pipe_p99 = timed(drive_pipeline)
    out["pipeline"] = {
        "n_items": n_items,
        "rank": rank,
        "queries": n_queries,
        "fingerprint": config.fingerprint,
        "exact_qps": round(exact_qps, 1),
        "exact_scores_per_s": round(exact_qps * n_items, 1),
        "exact_p99_ms": round(exact_p99, 3),
        "pipeline_qps": round(pipe_qps, 1),
        "pipeline_scores_per_s": round(pipe_qps * n_items, 1),
        "pipeline_p99_ms": round(pipe_p99, 3),
        "speedup": round(pipe_qps / exact_qps, 3),
        "stage_stats": pipe.stats()["stages"],
        "gate_pass": bool(
            pipe_qps > exact_qps and pipe_p99 <= 1.5 * exact_p99
        ),
    }
    out["gate_pass"] = bool(
        out["noisy_neighbor"]["gate_pass"] and out["pipeline"]["gate_pass"]
    )
    return out


def main() -> None:
    # BENCH_PLATFORM=cpu skips the (slow) tunnel probe for local iteration
    forced_cpu = os.environ.get("BENCH_PLATFORM") == "cpu"
    fallback = forced_cpu or not _device_backend_alive()
    if fallback:
        print(
            "INFO: CPU requested via BENCH_PLATFORM; benchmarking on CPU "
            "(vs_baseline will be null)"
            if forced_cpu
            else "WARNING: device backend unresponsive; benchmarking on CPU "
            "(vs_baseline will be null)",
            file=sys.stderr,
        )
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        jax.config.update("jax_platforms", "cpu")
        # CPU cannot chew 25M ratings in reasonable time; shrink unless set
        os.environ.setdefault("BENCH_RATINGS", "1000000")
        os.environ.setdefault("BENCH_ITERS", "3")
        os.environ.setdefault("BENCH_USERS", "50000")
        os.environ.setdefault("BENCH_ITEMS", "10000")
    import jax

    from predictionio_tpu.parallel.mesh import MeshContext

    # MovieLens-25M scale (the reference's largest workload config) with the
    # recommendation template's default rank/iterations (BASELINE.md)
    n_users = int(os.environ.get("BENCH_USERS", 162_000))
    n_items = int(os.environ.get("BENCH_ITEMS", 59_000))
    n_ratings = int(os.environ.get("BENCH_RATINGS", 25_000_000))
    rank = int(os.environ.get("BENCH_RANK", 10))
    iterations = int(os.environ.get("BENCH_ITERS", 20))
    # BENCH_DTYPE=bf16 benches the bf16 gather/all-gather path (f32 solve
    # accumulation either way); default stays f32
    dtype = os.environ.get("BENCH_DTYPE", "f32")
    dist = os.environ.get("BENCH_DIST", "both")
    if dist not in ("uniform", "zipf", "both"):
        raise SystemExit(f"BENCH_DIST must be uniform|zipf|both, got {dist!r}")
    # parsed ONCE: the benched layout and the recorded workload flag must
    # come from the same read (BENCH_REBALANCE=0 = the no-LPT cell)
    rebalance = os.environ.get("BENCH_REBALANCE", "1") != "0"

    ctx = MeshContext.create()
    n_chips = ctx.n_devices
    platform = jax.devices()[0].platform

    results: dict[str, float] = {}
    models: dict[str, object] = {}
    times: dict[str, float] = {}
    for d in ("uniform", "zipf") if dist == "both" else (dist,):
        inter = _make_interactions(d, n_users, n_items, n_ratings)
        results[d], models[d], times[d] = _timed_run(
            ctx, inter, rank, iterations, dtype, n_chips, rebalance=rebalance
        )
        print(
            f"INFO: {d} distribution: {results[d]:.1f} events/s/chip",
            file=sys.stderr,
        )

    primary_dist = "uniform" if "uniform" in results else dist
    value = results[primary_dist]
    on_tpu = platform == "tpu" and not fallback

    utilization = _utilization(
        n_ratings, n_users, n_items, rank, iterations, dtype,
        times[primary_dist], n_chips, platform,
    )
    if os.environ.get("BENCH_MEASURED", "1") != "0":
        # measured fields must never kill the artifact (tensorflow proto
        # parse, profiler trace — both environment-sensitive)
        try:
            inter_m = _make_interactions(
                primary_dist, n_users, n_items,
                min(n_ratings, int(os.environ.get("BENCH_MEASURED_RATINGS",
                                                  4_000_000))),
            )
            utilization.update(
                _measured_utilization(ctx, inter_m, rank, dtype, platform,
                                      rebalance=rebalance)
            )
        except Exception as e:
            print(f"WARNING: measured utilization failed: {e}",
                  file=sys.stderr)
            utilization["measured_error"] = str(e)
    print(f"INFO: utilization: {utilization}", file=sys.stderr)

    solver_ab = None
    if on_tpu and os.environ.get("BENCH_SOLVER_AB", "1") != "0":
        # on real hardware, also time the scatter-based segment solver at a
        # REDUCED workload (it is ~orders slower there — docs/perf_roofline
        # .md) so every TPU artifact carries the dense-vs-segment evidence
        import predictionio_tpu.models.als as als_mod

        ab_ratings = min(n_ratings, 2_000_000)
        ab_iters = 2
        try:
            inter_ab = _make_interactions(
                primary_dist, n_users, n_items, ab_ratings
            )
            results_ab = {}
            for solver in ("dense", "segment"):
                cfg = als_mod.ALSConfig(
                    rank=rank, iterations=1, compute_dtype=dtype,
                    solver=solver,
                )
                als_mod.train_als(ctx, inter_ab, cfg)  # compile
                t0 = time.perf_counter()
                als_mod.train_als(
                    ctx, inter_ab,
                    als_mod.ALSConfig(
                        rank=rank, iterations=ab_iters,
                        compute_dtype=dtype, solver=solver,
                    ),
                )
                dt = time.perf_counter() - t0
                results_ab[solver] = round(
                    ab_ratings * ab_iters / dt / n_chips, 1
                )
            solver_ab = {
                **results_ab,
                "speedup_dense_vs_segment": round(
                    results_ab["dense"] / results_ab["segment"], 2
                ),
                "workload_ratings": ab_ratings,
                "iterations": ab_iters,
            }
            print(f"INFO: solver A/B: {solver_ab}", file=sys.stderr)
        except Exception as e:  # the A/B must never kill the artifact
            print(f"WARNING: solver A/B failed: {e}", file=sys.stderr)
            solver_ab = {"error": str(e)}

    latency = None
    if os.environ.get("BENCH_SERVING", "1") != "0":
        # serving benches must never kill the artifact: the training number
        # above is already earned, so failures degrade to an error field
        try:
            scorer_lat = _scorer_latency(
                ctx, models[primary_dist], on_device=True if on_tpu else None
            )
        except Exception as e:
            print(f"WARNING: scorer latency bench failed: {e}", file=sys.stderr)
            scorer_lat = {"error": str(e)}
        print(f"INFO: scorer latency: {scorer_lat}", file=sys.stderr)
        try:
            http_lat = _http_latency(ctx, primary_dist, n_users, n_items)
        except Exception as e:
            print(f"WARNING: http latency bench failed: {e}", file=sys.stderr)
            http_lat = {"error": str(e)}
        print(f"INFO: http latency: {http_lat}", file=sys.stderr)
        latency = {"scorer": scorer_lat, "http": http_lat}
    ingest = None
    if os.environ.get("BENCH_INGEST", "1") != "0":
        try:
            ingest = _ingest_bench()
        except Exception as e:  # ingest bench must never kill the artifact
            print(f"WARNING: ingest bench failed: {e}", file=sys.stderr)
            ingest = {"error": str(e)}
        print(f"INFO: ingest: {ingest}", file=sys.stderr)
    durability = None
    if os.environ.get("BENCH_DURABILITY", "1") != "0":
        try:
            durability = _durability_bench()
        except Exception as e:  # durability bench must never kill the artifact
            print(f"WARNING: durability bench failed: {e}", file=sys.stderr)
            durability = {"error": str(e)}
        print(f"INFO: durability: {durability}", file=sys.stderr)
    observability = None
    if os.environ.get("BENCH_OBS", "1") != "0":
        try:
            observability = _observability_bench(ctx)
        except Exception as e:  # the obs gate must never kill the artifact
            print(f"WARNING: observability bench failed: {e}", file=sys.stderr)
            observability = {"error": str(e)}
        print(f"INFO: observability: {observability}", file=sys.stderr)
    kernel = None
    if os.environ.get("BENCH_KERNEL", "1") != "0":
        try:
            kernel = _kernel_bench(
                platform,
                int(os.environ.get("BENCH_KERNEL_ITEMS", n_items)),
                rank,
            )
        except Exception as e:  # the kernel A/B must never kill the artifact
            print(f"WARNING: kernel bench failed: {e}", file=sys.stderr)
            kernel = {"error": str(e)}
        print(f"INFO: kernel: {kernel}", file=sys.stderr)
    train_kernel = None
    if os.environ.get("BENCH_TRAIN_KERNEL", "1") != "0":
        try:
            train_kernel = _train_kernel_bench(
                ctx, platform, n_users, n_items, n_ratings, rank,
            )
        except Exception as e:  # the train A/B must never kill the artifact
            print(f"WARNING: train-kernel bench failed: {e}", file=sys.stderr)
            train_kernel = {"error": str(e)}
        print(f"INFO: train_kernel: {train_kernel}", file=sys.stderr)
    fleet = None
    if os.environ.get("BENCH_FLEET", "1") != "0":
        try:
            fleet = _fleet_bench(ctx)
        except Exception as e:  # the fleet bench must never kill the artifact
            print(f"WARNING: fleet bench failed: {e}", file=sys.stderr)
            fleet = {"error": str(e)}
        print(f"INFO: fleet: {fleet}", file=sys.stderr)
    elastic = None
    if os.environ.get("BENCH_ELASTIC", "1") != "0":
        try:
            elastic = _elastic_bench(ctx)
        except Exception as e:  # the elastic bench must never kill the artifact
            print(f"WARNING: elastic bench failed: {e}", file=sys.stderr)
            elastic = {"error": str(e)}
        print(f"INFO: elastic: {elastic}", file=sys.stderr)
    freshness = None
    if os.environ.get("BENCH_FRESHNESS", "1") != "0":
        try:
            freshness = _freshness_bench(ctx)
        except Exception as e:  # the freshness bench must never kill the artifact
            print(f"WARNING: freshness bench failed: {e}", file=sys.stderr)
            freshness = {"error": str(e)}
        print(f"INFO: freshness: {freshness}", file=sys.stderr)
    sharded = None
    if os.environ.get("BENCH_SHARDED", "1") != "0":
        try:
            sharded = _sharded_serving_bench(ctx)
        except Exception as e:  # the sharding bench must never kill the artifact
            print(f"WARNING: sharded serving bench failed: {e}",
                  file=sys.stderr)
            sharded = {"error": str(e)}
        print(f"INFO: sharded_serving: {sharded}", file=sys.stderr)
    pod = None
    if os.environ.get("BENCH_POD", "1") != "0":
        try:
            pod = _pod_serving_bench()
        except Exception as e:  # the pod bench must never kill the artifact
            print(f"WARNING: pod serving bench failed: {e}", file=sys.stderr)
            pod = {"error": str(e)}
        print(f"INFO: pod_serving: {pod}", file=sys.stderr)
    retrieval = None
    if os.environ.get("BENCH_RETRIEVAL", "1") != "0":
        try:
            retrieval = _retrieval_bench(ctx, platform)
        except Exception as e:  # the IVF gate must never kill the artifact
            print(f"WARNING: retrieval bench failed: {e}", file=sys.stderr)
            retrieval = {"error": str(e)}
        print(f"INFO: retrieval: {retrieval}", file=sys.stderr)
    tenant = None
    if os.environ.get("BENCH_TENANT", "1") != "0":
        try:
            tenant = _tenant_bench(ctx)
        except Exception as e:  # the tenancy gate must never kill the artifact
            print(f"WARNING: tenant bench failed: {e}", file=sys.stderr)
            tenant = {"error": str(e)}
        print(f"INFO: tenant: {tenant}", file=sys.stderr)
    canary = None
    if os.environ.get("BENCH_CANARY", "1") != "0":
        try:
            canary = _canary_bench(ctx)
        except Exception as e:  # the canary gate must never kill the artifact
            print(f"WARNING: canary bench failed: {e}", file=sys.stderr)
            canary = {"error": str(e)}
        print(f"INFO: canary: {canary}", file=sys.stderr)
    record = {
        "metric": "als_train_events_per_sec_per_chip",
        "value": round(value, 1),
        "unit": "events/s/chip",
        "vs_baseline": (
            round(value / NORTH_STAR_EVENTS_PER_SEC_PER_CHIP, 4) if on_tpu else None
        ),
        "platform": platform,
        "fallback": fallback,
        "n_devices": n_chips,
        "workload": {
            "users": n_users,
            "items": n_items,
            "ratings": n_ratings,
            "rank": rank,
            "iterations": iterations,
            "dtype": dtype,
            "distribution": primary_dist,
            "rebalance": rebalance,
        },
    }
    record["utilization"] = utilization
    record["solver"] = os.environ.get("PIO_ALS_SOLVER", "dense")
    if solver_ab is not None:
        record["solver_ab"] = solver_ab
    if latency is not None:
        record["predict_latency_ms"] = latency
        http_res = (latency.get("http") or {}).get("resilience")
        if http_res is not None:
            record["resilience"] = http_res
    if ingest is not None:
        record["ingest"] = ingest
    if durability is not None:
        record["durability"] = durability
    if observability is not None:
        record["observability"] = observability
    if kernel is not None:
        record["kernel"] = kernel
    if train_kernel is not None:
        record["train_kernel"] = train_kernel
    if fleet is not None:
        record["fleet"] = fleet
    if elastic is not None:
        record["elastic"] = elastic
    if freshness is not None:
        record["freshness"] = freshness
    if sharded is not None or pod is not None:
        record["multichip"] = {}
        if sharded is not None:
            record["multichip"]["sharded_serving"] = sharded
        if pod is not None:
            record["multichip"]["pod_serving"] = pod
    if retrieval is not None:
        record["retrieval"] = retrieval
    if tenant is not None:
        record["tenant"] = tenant
    if canary is not None:
        record["canary"] = canary
    if "zipf" in results and primary_dist != "zipf":
        record["zipf"] = {
            "value": round(results["zipf"], 1),
            "ratio_vs_uniform": round(results["zipf"] / value, 4),
        }
    print(json.dumps(record))


if __name__ == "__main__":
    main()
