"""Benchmark: ALS training throughput (events/sec/chip) on the local device.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The reference publishes no numbers (BASELINE.md); vs_baseline is measured
against the driver-set north star: MovieLens-25M × 20 iterations on v5e-16
in 60 s ⇒ ~520,833 events/sec/chip.  vs_baseline = value / north_star.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

NORTH_STAR_EVENTS_PER_SEC_PER_CHIP = 25_000_000 * 20 / (60 * 16)


def _device_backend_alive(timeout_s: int = 120, attempts: int = 3) -> bool:
    """Probe device init in a SUBPROCESS: the axon TPU tunnel can hang
    jax.devices() indefinitely; a hung probe must not hang the bench.

    The tunnel also flaps — retry a few times (with a pause) before
    concluding the chip is gone, so a transient outage doesn't turn the
    round's perf artifact into a CPU number.
    """
    for attempt in range(attempts):
        try:
            r = subprocess.run(
                [sys.executable, "-c", "import jax; jax.devices()"],
                timeout=timeout_s,
                capture_output=True,
            )
            if r.returncode == 0:
                return True
        except subprocess.TimeoutExpired:
            pass
        if attempt + 1 < attempts:
            print(
                f"WARNING: device probe {attempt + 1}/{attempts} failed; retrying",
                file=sys.stderr,
            )
            time.sleep(60)
    return False


def main() -> None:
    if not _device_backend_alive():
        print(
            "WARNING: device backend unresponsive; benchmarking on CPU",
            file=sys.stderr,
        )
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        jax.config.update("jax_platforms", "cpu")
        # CPU cannot chew 25M ratings in reasonable time; shrink unless set
        os.environ.setdefault("BENCH_RATINGS", "1000000")
        os.environ.setdefault("BENCH_ITERS", "3")
        os.environ.setdefault("BENCH_USERS", "50000")
        os.environ.setdefault("BENCH_ITEMS", "10000")
    import jax

    from predictionio_tpu.data.batch import Interactions
    from predictionio_tpu.data.bimap import BiMap
    from predictionio_tpu.models import als
    from predictionio_tpu.parallel.mesh import MeshContext

    # MovieLens-25M scale (the reference's largest workload config) with the
    # recommendation template's default rank/iterations (BASELINE.md)
    n_users = int(os.environ.get("BENCH_USERS", 162_000))
    n_items = int(os.environ.get("BENCH_ITEMS", 59_000))
    n_ratings = int(os.environ.get("BENCH_RATINGS", 25_000_000))
    rank = int(os.environ.get("BENCH_RANK", 10))
    iterations = int(os.environ.get("BENCH_ITERS", 20))

    rng = np.random.default_rng(0)
    inter = Interactions(
        user=rng.integers(0, n_users, n_ratings).astype(np.int32),
        item=rng.integers(0, n_items, n_ratings).astype(np.int32),
        rating=rng.uniform(1.0, 5.0, n_ratings).astype(np.float32),
        t=np.zeros(n_ratings),
        user_map=None,
        item_map=None,
    )
    inter.user_map = BiMap({f"u{i}": i for i in range(n_users)})
    inter.item_map = BiMap({f"i{i}": i for i in range(n_items)})

    ctx = MeshContext.create()
    n_chips = ctx.n_devices

    # BENCH_DTYPE=bf16 benches the bf16 gather/all-gather path (f32 solve
    # accumulation either way); default stays f32
    dtype = os.environ.get("BENCH_DTYPE", "f32")

    # warm-up: compile the step (first TPU compile is slow, cached after)
    als.train_als(
        ctx, inter,
        als.ALSConfig(rank=rank, iterations=1, compute_dtype=dtype),
    )

    t0 = time.perf_counter()
    als.train_als(
        ctx, inter,
        als.ALSConfig(rank=rank, iterations=iterations, compute_dtype=dtype),
    )
    dt = time.perf_counter() - t0

    events_per_sec_per_chip = n_ratings * iterations / dt / n_chips
    print(
        json.dumps(
            {
                "metric": "als_train_events_per_sec_per_chip",
                "value": round(events_per_sec_per_chip, 1),
                "unit": "events/s/chip",
                "vs_baseline": round(
                    events_per_sec_per_chip / NORTH_STAR_EVENTS_PER_SEC_PER_CHIP, 4
                ),
            }
        )
    )


if __name__ == "__main__":
    main()
