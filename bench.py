"""Benchmark: ALS training throughput (events/sec/chip) on the local device.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

The reference publishes no numbers (BASELINE.md); vs_baseline is measured
against the driver-set north star: MovieLens-25M × 20 iterations on v5e-16
in 60 s ⇒ ~520,833 events/sec/chip.  vs_baseline = value / north_star.

Honesty contract (VERDICT round 2, item 1): the JSON line always carries
``platform``, ``n_devices``, and the actual ``workload`` dims; when the
device backend is unreachable and the bench falls back to CPU, it reports
``"fallback": true`` and ``"vs_baseline": null`` — a CPU number must never
be readable as progress against the TPU north star.

Workload distributions (VERDICT item 2): by default the bench runs the
uniform workload (primary metric) AND a Zipf-skewed workload whose item
popularity follows a power law like MovieLens-25M's catalog (hot ids
contiguous — the worst case for range-blocking).  ``BENCH_DIST`` narrows to
``uniform`` or ``zipf``.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

NORTH_STAR_EVENTS_PER_SEC_PER_CHIP = 25_000_000 * 20 / (60 * 16)


def _device_backend_alive(timeout_s: int = 120, attempts: int = 4) -> bool:
    """Probe device init in a SUBPROCESS: the axon TPU tunnel can hang
    jax.devices() indefinitely; a hung probe must not hang the bench.

    The tunnel also flaps — retry with a growing pause before concluding
    the chip is gone, so a transient outage doesn't turn the round's perf
    artifact into a CPU number.
    """
    for attempt in range(attempts):
        try:
            r = subprocess.run(
                [sys.executable, "-c", "import jax; jax.devices()"],
                timeout=timeout_s,
                capture_output=True,
            )
            if r.returncode == 0:
                return True
        except subprocess.TimeoutExpired:
            pass
        if attempt + 1 < attempts:
            pause = 30 * (attempt + 1)
            print(
                f"WARNING: device probe {attempt + 1}/{attempts} failed; "
                f"retrying in {pause}s",
                file=sys.stderr,
            )
            time.sleep(pause)
    return False


def _sample_ids(rng, n: int, size: int, dist: str, s: float, q: float = 50.0) -> np.ndarray:
    """Entity ids from the named distribution.

    ``zipf``: Zipf-Mandelbrot P(id=k) ∝ (k+q)^-s over [0, n) with hot ids
    CONTIGUOUS at the low end — the adversarial layout for contiguous-range
    blocking.  The q shift matches real catalogs: at s=1.1, q=50 over 59k
    items the hottest item draws ~0.4% of ratings, like ML-25M's ~0.32%
    (a pure Zipf head would take ~10%, which no real catalog does).
    """
    if dist == "uniform":
        return rng.integers(0, n, size).astype(np.int32)
    ranks = np.arange(1, n + 1, dtype=np.float64)
    p = (ranks + q) ** -s
    p /= p.sum()
    return rng.choice(n, size=size, p=p).astype(np.int32)


def _make_interactions(dist: str, n_users: int, n_items: int, n_ratings: int):
    from predictionio_tpu.data.batch import Interactions
    from predictionio_tpu.data.bimap import BiMap

    rng = np.random.default_rng(0)
    inter = Interactions(
        user=_sample_ids(rng, n_users, n_ratings, dist, s=0.7),
        item=_sample_ids(rng, n_items, n_ratings, dist, s=1.1),
        rating=rng.uniform(1.0, 5.0, n_ratings).astype(np.float32),
        t=np.zeros(n_ratings),
        user_map=None,
        item_map=None,
    )
    inter.user_map = BiMap({f"u{i}": i for i in range(n_users)})
    inter.item_map = BiMap({f"i{i}": i for i in range(n_items)})
    return inter


def _timed_run(ctx, inter, rank, iterations, dtype, n_chips) -> float:
    from predictionio_tpu.models import als

    # warm-up: compile the step (first TPU compile is slow, cached after)
    als.train_als(
        ctx, inter, als.ALSConfig(rank=rank, iterations=1, compute_dtype=dtype)
    )
    t0 = time.perf_counter()
    als.train_als(
        ctx,
        inter,
        als.ALSConfig(rank=rank, iterations=iterations, compute_dtype=dtype),
    )
    dt = time.perf_counter() - t0
    return len(inter.rating) * iterations / dt / n_chips


def main() -> None:
    # BENCH_PLATFORM=cpu skips the (slow) tunnel probe for local iteration
    forced_cpu = os.environ.get("BENCH_PLATFORM") == "cpu"
    fallback = forced_cpu or not _device_backend_alive()
    if fallback:
        print(
            "INFO: CPU requested via BENCH_PLATFORM; benchmarking on CPU "
            "(vs_baseline will be null)"
            if forced_cpu
            else "WARNING: device backend unresponsive; benchmarking on CPU "
            "(vs_baseline will be null)",
            file=sys.stderr,
        )
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        jax.config.update("jax_platforms", "cpu")
        # CPU cannot chew 25M ratings in reasonable time; shrink unless set
        os.environ.setdefault("BENCH_RATINGS", "1000000")
        os.environ.setdefault("BENCH_ITERS", "3")
        os.environ.setdefault("BENCH_USERS", "50000")
        os.environ.setdefault("BENCH_ITEMS", "10000")
    import jax

    from predictionio_tpu.parallel.mesh import MeshContext

    # MovieLens-25M scale (the reference's largest workload config) with the
    # recommendation template's default rank/iterations (BASELINE.md)
    n_users = int(os.environ.get("BENCH_USERS", 162_000))
    n_items = int(os.environ.get("BENCH_ITEMS", 59_000))
    n_ratings = int(os.environ.get("BENCH_RATINGS", 25_000_000))
    rank = int(os.environ.get("BENCH_RANK", 10))
    iterations = int(os.environ.get("BENCH_ITERS", 20))
    # BENCH_DTYPE=bf16 benches the bf16 gather/all-gather path (f32 solve
    # accumulation either way); default stays f32
    dtype = os.environ.get("BENCH_DTYPE", "f32")
    dist = os.environ.get("BENCH_DIST", "both")
    if dist not in ("uniform", "zipf", "both"):
        raise SystemExit(f"BENCH_DIST must be uniform|zipf|both, got {dist!r}")

    ctx = MeshContext.create()
    n_chips = ctx.n_devices
    platform = jax.devices()[0].platform

    results: dict[str, float] = {}
    for d in ("uniform", "zipf") if dist == "both" else (dist,):
        inter = _make_interactions(d, n_users, n_items, n_ratings)
        results[d] = _timed_run(ctx, inter, rank, iterations, dtype, n_chips)
        print(
            f"INFO: {d} distribution: {results[d]:.1f} events/s/chip",
            file=sys.stderr,
        )

    primary_dist = "uniform" if "uniform" in results else dist
    value = results[primary_dist]
    on_tpu = platform == "tpu" and not fallback
    record = {
        "metric": "als_train_events_per_sec_per_chip",
        "value": round(value, 1),
        "unit": "events/s/chip",
        "vs_baseline": (
            round(value / NORTH_STAR_EVENTS_PER_SEC_PER_CHIP, 4) if on_tpu else None
        ),
        "platform": platform,
        "fallback": fallback,
        "n_devices": n_chips,
        "workload": {
            "users": n_users,
            "items": n_items,
            "ratings": n_ratings,
            "rank": rank,
            "iterations": iterations,
            "dtype": dtype,
            "distribution": primary_dist,
        },
    }
    if "zipf" in results and primary_dist != "zipf":
        record["zipf"] = {
            "value": round(results["zipf"], 1),
            "ratio_vs_uniform": round(results["zipf"] / value, 4),
        }
    print(json.dumps(record))


if __name__ == "__main__":
    main()
