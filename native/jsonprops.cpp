// Columnar JSON property scanner — the native data-plane kernel behind
// predictionio_tpu's numeric-property promotion (parquet compaction) and
// bulk property scans.
//
// Role parity: the reference's equivalent tier is JVM-native JSON handling
// (json4s/Jackson) under its storage drivers; here the hot path is the
// parquet driver's promote_numeric over tens of millions of small JSON
// objects, where a per-row Python json.loads costs minutes. This kernel
// makes one pass over a concatenated buffer of JSON objects and reports,
// per top-level key:
//   - a per-row float64 column (NaN where the key is absent) for keys whose
//     present values are ONLY JSON numbers or booleans (the unambiguous
//     subset where C and Python coercion agree bit-for-bit), and
//   - flags: "saw_other" marks keys with null/object/array values or
//     strings provably not float()-coercible — rejected, exactly as the
//     Python path rejects them; "saw_string" marks keys with a string that
//     MIGHT coerce (e.g. "3"), which makes the Python side decline the
//     whole batch so Python's float() semantics decide.
//
// Any malformed line aborts the whole scan (returns NULL) — callers fall
// back to the Python implementation, so this kernel can be strict.
//
// C ABI only (loaded via ctypes; no pybind11 in this image).

#include <cmath>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <locale.h>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct KeyInfo {
    std::string name;
    // allocated lazily on the first numeric value — rejected keys (string
    // labels etc.) must not cost nrows×8B each at 25M-row scale
    std::vector<double> column;
    int64_t last_row = -1;    // duplicate-key-in-one-object detection
    bool saw_string = false;  // a maybe-coercible string value
    bool saw_other = false;   // null/object/array/never-coercible string
};

struct Scan {
    std::vector<KeyInfo> keys;
    std::unordered_map<std::string, size_t> index;
    int64_t nrows = 0;
};

struct Cursor {
    const char* p;
    const char* end;
    bool ok = true;

    void ws() {
        while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r'))
            ++p;
    }
    bool eat(char c) {
        ws();
        if (p < end && *p == c) {
            ++p;
            return true;
        }
        ok = false;
        return false;
    }
    bool peek(char c) {
        ws();
        return p < end && *p == c;
    }
};

// Scan past a JSON string (opening quote consumed), appending the raw
// (still-escaped) contents to *out when non-null. Returns false on error.
bool skip_string(Cursor& c, std::string* out) {
    while (c.p < c.end) {
        char ch = *c.p++;
        if (ch == '"') return true;
        if (ch == '\\') {
            if (c.p >= c.end) return false;
            if (out) {
                out->push_back('\\');
                out->push_back(*c.p);
            }
            ++c.p;
            continue;
        }
        if (out) out->push_back(ch);
    }
    return false;
}

// Minimal unescape for object KEYS (values never need their text here).
// json.dumps(ensure_ascii=True) emits \uXXXX for non-ASCII; decode the BMP
// cases to UTF-8 so key names match Python's. Surrogate pairs are rare in
// keys — on encountering one, fail the scan (Python fallback handles it).
bool unescape_key(const std::string& raw, std::string& out) {
    out.clear();
    for (size_t i = 0; i < raw.size(); ++i) {
        char ch = raw[i];
        if (ch != '\\') {
            out.push_back(ch);
            continue;
        }
        if (++i >= raw.size()) return false;
        switch (raw[i]) {
            case '"': out.push_back('"'); break;
            case '\\': out.push_back('\\'); break;
            case '/': out.push_back('/'); break;
            case 'b': out.push_back('\b'); break;
            case 'f': out.push_back('\f'); break;
            case 'n': out.push_back('\n'); break;
            case 'r': out.push_back('\r'); break;
            case 't': out.push_back('\t'); break;
            case 'u': {
                if (i + 4 >= raw.size()) return false;
                unsigned cp = 0;
                for (int k = 1; k <= 4; ++k) {
                    char h = raw[i + k];
                    cp <<= 4;
                    if (h >= '0' && h <= '9') cp |= h - '0';
                    else if (h >= 'a' && h <= 'f') cp |= h - 'a' + 10;
                    else if (h >= 'A' && h <= 'F') cp |= h - 'A' + 10;
                    else return false;
                }
                i += 4;
                if (cp == 0) return false;  // NUL would truncate the C name
                if (cp >= 0xD800 && cp <= 0xDFFF) return false;  // surrogate
                if (cp < 0x80) {
                    out.push_back(static_cast<char>(cp));
                } else if (cp < 0x800) {
                    out.push_back(static_cast<char>(0xC0 | (cp >> 6)));
                    out.push_back(static_cast<char>(0x80 | (cp & 0x3F)));
                } else {
                    out.push_back(static_cast<char>(0xE0 | (cp >> 12)));
                    out.push_back(static_cast<char>(0x80 | ((cp >> 6) & 0x3F)));
                    out.push_back(static_cast<char>(0x80 | (cp & 0x3F)));
                }
                break;
            }
            default:
                return false;
        }
    }
    return true;
}

// Could this raw (escaped) string content be float()-coercible in Python?
// Conservative: any escape sequence, or any character that can appear in a
// Python float literal (digits, sign, '.', exponent, underscores, the
// letters of inf/infinity/nan, whitespace) keeps it "maybe"; one character
// outside that alphabet (most labels/categories/ids have one) proves it can
// never coerce — Python would reject the key, and so can we.
bool string_maybe_coercible(const std::string& raw) {
    if (raw.empty()) return true;  // float("") raises, but stay conservative
    for (char ch : raw) {
        if (static_cast<unsigned char>(ch) >= 0x80)
            return true;  // Python float() accepts non-ASCII digits/spaces
        if (ch == '\\') return true;  // escaped char: don't reason about it
        if ((ch >= '0' && ch <= '9') || ch == '+' || ch == '-' || ch == '.' ||
            ch == '_' || ch == ' ' || ch == '\t' || ch == '\n' || ch == '\r')
            continue;
        switch (ch) {  // i n f a t y (inf / infinity / nan), either case
            case 'i': case 'n': case 'f': case 'a': case 't': case 'y':
            case 'e': case 'E':
            case 'I': case 'N': case 'F': case 'A': case 'T': case 'Y':
                continue;
            default:
                return false;
        }
    }
    return true;
}

// Skip a JSON value of any type. When the value is a number or boolean,
// set *num and return kind 1; possibly-float-coercible string → kind 2;
// null/object/array or never-coercible string → kind 3 (key rejected,
// matching Python). Returns 0 on parse error.
int skip_value(Cursor& c, double* num) {
    c.ws();
    if (c.p >= c.end) return 0;
    char ch = *c.p;
    if (ch == '"') {
        ++c.p;
        static thread_local std::string content;
        content.clear();
        if (!skip_string(c, &content)) return 0;
        return string_maybe_coercible(content) ? 2 : 3;
    }
    if (ch == 't') {
        if (c.end - c.p >= 4 && std::memcmp(c.p, "true", 4) == 0) {
            c.p += 4;
            *num = 1.0;
            return 1;
        }
        return 0;
    }
    if (ch == 'f') {
        if (c.end - c.p >= 5 && std::memcmp(c.p, "false", 5) == 0) {
            c.p += 5;
            *num = 0.0;
            return 1;
        }
        return 0;
    }
    if (ch == 'n') {
        if (c.end - c.p >= 4 && std::memcmp(c.p, "null", 4) == 0) {
            c.p += 4;
            return 3;
        }
        return 0;
    }
    if (ch == '{' || ch == '[') {
        int depth = 0;
        while (c.p < c.end) {
            char d = *c.p++;
            if (d == '"') {
                if (!skip_string(c, nullptr)) return 0;
            } else if (d == '{' || d == '[') {
                ++depth;
            } else if (d == '}' || d == ']') {
                if (--depth == 0) return 3;
            }
        }
        return 0;
    }
    // number: validate the token against the JSON grammar FIRST — strtod
    // alone also accepts hex, 'inf', '1.' etc., forms json.loads rejects,
    // and accepting them would serve corrupted rows as data instead of
    // surfacing the error the Python path raises.
    if (ch == '-' || (ch >= '0' && ch <= '9')) {
        const char* s = c.p;
        bool is_int = true;
        if (*s == '-') ++s;
        if (s >= c.end || *s < '0' || *s > '9') return 0;
        if (*s == '0') {
            ++s;
        } else {
            while (s < c.end && *s >= '0' && *s <= '9') ++s;
        }
        if (s < c.end && *s == '.') {
            is_int = false;
            ++s;
            if (s >= c.end || *s < '0' || *s > '9') return 0;
            while (s < c.end && *s >= '0' && *s <= '9') ++s;
        }
        if (s < c.end && (*s == 'e' || *s == 'E')) {
            is_int = false;
            ++s;
            if (s < c.end && (*s == '+' || *s == '-')) ++s;
            if (s >= c.end || *s < '0' || *s > '9') return 0;
            while (s < c.end && *s >= '0' && *s <= '9') ++s;
        }
        // strtod on the validated span, pinned to the C locale — a host app
        // that setlocale()s to a ','-decimal locale must not change results
        static locale_t c_locale = newlocale(LC_ALL_MASK, "C", nullptr);
        static thread_local std::string token;
        token.assign(c.p, s);  // NUL-terminated copy of just the literal
        char* endp = nullptr;
        *num = strtod_l(token.c_str(), &endp, c_locale);
        if (endp != token.c_str() + token.size()) return 0;
        // an INTEGER literal overflowing double: json.loads gives a Python
        // int and float(int) raises OverflowError on the Python path —
        // decline rather than silently serving inf. (Float literals like
        // 1e999 become inf in BOTH paths, so those stay.)
        if (is_int && !std::isfinite(*num)) return 0;
        c.p = s;
        return 1;
    }
    return 0;
}

}  // namespace

extern "C" {

// Scan `nrows` JSON objects laid out back-to-back in buf; offsets[i] /
// offsets[i+1] delimit row i (offsets has nrows+1 entries — exactly an
// Arrow string column's layout). Returns an opaque handle, or NULL if any
// row fails to parse (caller uses its fallback).
void* pio_props_scan(const char* buf, const int64_t* offsets, int64_t nrows) {
    auto* scan = new Scan();
    scan->nrows = nrows;
    std::string raw_key, key;
    for (int64_t row = 0; row < nrows; ++row) {
        Cursor c{buf + offsets[row], buf + offsets[row + 1]};
        if (c.p == c.end) continue;  // empty cell: json path treats as {}
        // whitespace-ONLY cells are a json.loads error, not {} — decline
        if (!c.eat('{')) {
            delete scan;
            return nullptr;
        }
        if (c.peek('}')) {
            ++c.p;
            c.ws();
            if (c.p != c.end) {  // '{}garbage' is a json.loads error
                delete scan;
                return nullptr;
            }
            continue;
        }
        while (true) {
            if (!c.eat('"')) {
                delete scan;
                return nullptr;
            }
            raw_key.clear();
            if (!skip_string(c, &raw_key) || !unescape_key(raw_key, key)) {
                delete scan;
                return nullptr;
            }
            if (!c.eat(':')) {
                delete scan;
                return nullptr;
            }
            double num = 0.0;
            int kind = skip_value(c, &num);
            if (kind == 0) {
                delete scan;
                return nullptr;
            }
            auto it = scan->index.find(key);
            size_t ki;
            if (it == scan->index.end()) {
                ki = scan->keys.size();
                scan->index.emplace(key, ki);
                scan->keys.emplace_back();
                scan->keys[ki].name = key;
            } else {
                ki = it->second;
            }
            KeyInfo& info = scan->keys[ki];
            if (info.last_row == row) {
                // duplicate key in one object: json.loads keeps only the
                // LAST value; replicating that for the flags is subtle, so
                // decline — Python's semantics decide
                delete scan;
                return nullptr;
            }
            info.last_row = row;
            if (kind == 1) {
                if (!info.saw_other) {
                    if (info.column.empty())
                        info.column.assign(
                            static_cast<size_t>(nrows), std::nan(""));
                    info.column[static_cast<size_t>(row)] = num;
                }
            } else if (kind == 2) {
                info.saw_string = true;
            } else {
                info.saw_other = true;
                info.column.clear();  // rejected: release, never read again
                info.column.shrink_to_fit();
            }
            if (c.peek(',')) {
                ++c.p;
                continue;
            }
            if (c.eat('}')) break;
            delete scan;
            return nullptr;
        }
        c.ws();
        if (c.p != c.end) {  // trailing garbage in the row
            delete scan;
            return nullptr;
        }
    }
    return scan;
}

int64_t pio_props_nkeys(void* h) {
    return static_cast<int64_t>(static_cast<Scan*>(h)->keys.size());
}

const char* pio_props_key_name(void* h, int64_t i) {
    return static_cast<Scan*>(h)->keys[static_cast<size_t>(i)].name.c_str();
}

// Bit 0: saw_string (a maybe-coercible string → caller must decline),
// bit 1: saw_other (null/object/array/never-coercible string → key rejected).
int32_t pio_props_key_flags(void* h, int64_t i) {
    const KeyInfo& k = static_cast<Scan*>(h)->keys[static_cast<size_t>(i)];
    return (k.saw_string ? 1 : 0) | (k.saw_other ? 2 : 0);
}

// Pointer to the per-row float64 column for key i (length = nrows).
const double* pio_props_key_column(void* h, int64_t i) {
    return static_cast<Scan*>(h)->keys[static_cast<size_t>(i)].column.data();
}

void pio_props_free(void* h) { delete static_cast<Scan*>(h); }

}  // extern "C"
