"""Request-scoped tracing: per-stage breakdown, sampling, bounded ring.

A :class:`Trace` is born at HTTP accept (``common/http.py``), rides the
request through the serving pipeline, and lands in a bounded in-memory
ring exposed at ``GET /trace/recent.json``.  Stages recorded on the query
path:

``decode`` → ``queue_wait`` (MicroBatcher) → ``batch_assembly`` → ``h2d``
→ ``device_compute`` (via the :func:`utils.profiling.trace` hook) →
``serialize``; whatever wall time the named stages don't cover lands in
an explicit ``other`` remainder so the stage sum always reconciles with
wall time.

Propagation contract (documented in docs/observability.md):

* The ``X-Request-Id`` header carries the trace id.  A request that
  ARRIVES with one is always sampled (upstream already decided), and the
  id is propagated by the NetworkStorage client on every outgoing call so
  a query's storage round-trips correlate across services.  The response
  echoes the id back.
* Requests without the header are head-sampled at ``PIO_TRACE_SAMPLE``
  (deterministic every-Nth admission — no RNG in the hot path).
* Finished traces are additionally TAIL-sampled: walls above a rolling
  quantile (``PIO_SLOW_TRACE_QUANTILE``) land in a second bounded ring
  (``PIO_SLOW_TRACE_RING``) at ``GET /trace/slow.json`` — the flight
  recorder that explains the p99 instead of merely counting it.

Cross-thread attribution: the micro-batcher executes ONE batch for many
requests, so the worker thread installs every batch member's trace as
"active" (:func:`scope`) and shared stages (``h2d``, ``device_compute``)
are charged to each of them — the per-request view stays truthful about
where its wall time went even when the work was amortized.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
import uuid
from collections import deque
from typing import Optional, Sequence

TRACE_HEADER = "X-Request-Id"

DEFAULT_SAMPLE_RATE = 0.1
DEFAULT_RING_SIZE = 256
# flight recorder (tail sampling): retain traces whose wall exceeds this
# rolling quantile of recent request walls, in a ring of this size
DEFAULT_SLOW_QUANTILE = 0.99
DEFAULT_SLOW_RING_SIZE = 64
# wall-time reservoir backing the rolling quantile; threshold is
# recomputed every _SLOW_RECOMPUTE records so the hot path stays O(1)
_SLOW_RESERVOIR = 512
_SLOW_RECOMPUTE = 16
# tail sampling stays off until the reservoir has seen this many walls —
# with two data points "the 99th percentile" would just be the max
_SLOW_MIN_SAMPLES = 16


class Trace:
    """One sampled request: stage durations + identity. Thread-safe."""

    __slots__ = (
        "request_id", "name", "start_unix", "_t0", "stages", "meta",
        "wall_s", "status", "_lock",
    )

    def __init__(self, request_id: str, name: str = ""):
        self.request_id = request_id
        self.name = name
        self.start_unix = time.time()
        self._t0 = time.perf_counter()
        self.stages: dict[str, float] = {}
        self.meta: dict = {}
        self.wall_s: Optional[float] = None
        self.status: Optional[int] = None
        self._lock = threading.Lock()

    def add_stage(self, stage: str, seconds: float) -> None:
        """Accumulate time into a named stage (re-entry adds, not replaces)."""
        if seconds < 0:
            seconds = 0.0
        with self._lock:
            self.stages[stage] = self.stages.get(stage, 0.0) + seconds

    @contextlib.contextmanager
    def stage(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add_stage(name, time.perf_counter() - t0)

    def annotate(self, **kv) -> None:
        """Attach request context (bucket, batch size, cache disposition…)
        to the trace — the flight recorder's "why was this slow" fields."""
        with self._lock:
            self.meta.update(kv)

    def finish(self, status: Optional[int] = None) -> None:
        wall = time.perf_counter() - self._t0
        with self._lock:
            self.wall_s = wall
            self.status = status
            # the explicit remainder: stage sum ≡ wall by construction, so
            # a reader never wonders whether missing time means missing
            # instrumentation or missing truth
            covered = sum(self.stages.values())
            self.stages["other"] = max(0.0, wall - covered)

    def to_dict(self) -> dict:
        with self._lock:
            return {
                "requestId": self.request_id,
                "name": self.name,
                "startUnix": round(self.start_unix, 6),
                "wallMs": (
                    None if self.wall_s is None
                    else round(self.wall_s * 1e3, 4)
                ),
                "status": self.status,
                "stagesMs": {
                    k: round(v * 1e3, 4) for k, v in self.stages.items()
                },
                **({"meta": dict(self.meta)} if self.meta else {}),
            }


# -- active-trace propagation (thread-local) ---------------------------------

_active = threading.local()


def active_traces() -> Sequence[Trace]:
    return getattr(_active, "traces", ())


@contextlib.contextmanager
def scope(traces: Sequence[Optional[Trace]]):
    """Install traces as this thread's active set for the duration.

    The HTTP thread scopes its single request trace around dispatch; the
    micro-batcher worker scopes the whole batch's traces around execute.
    """
    prev = getattr(_active, "traces", ())
    _active.traces = tuple(t for t in traces if t is not None)
    try:
        yield
    finally:
        _active.traces = prev


@contextlib.contextmanager
def stage(name: str):
    """Charge the enclosed wall time to ``name`` on every active trace.

    The no-trace case is two attribute lookups — cheap enough to leave in
    hot loops permanently.
    """
    traces = getattr(_active, "traces", ())
    if not traces:
        yield
        return
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dt = time.perf_counter() - t0
        for t in traces:
            t.add_stage(name, dt)


def add_stage(name: str, seconds: float) -> None:
    """Charge an externally-measured duration to every active trace."""
    for t in getattr(_active, "traces", ()):
        t.add_stage(name, seconds)


def new_request_id() -> str:
    return uuid.uuid4().hex[:16]


class Tracer:
    """Head sampler + bounded ring of finished traces + flight recorder.

    The flight recorder is TAIL-based: after a sampled trace finishes,
    its wall time is compared against a rolling quantile
    (``PIO_SLOW_TRACE_QUANTILE``) of recent walls, and outliers are
    retained — with their full stage breakdown and meta — in a second
    bounded ring (``PIO_SLOW_TRACE_RING``) served at
    ``GET /trace/slow.json``.  The p99 is explained, not just counted.
    """

    def __init__(
        self,
        sample_rate: Optional[float] = None,
        ring_size: Optional[int] = None,
        slow_quantile: Optional[float] = None,
        slow_ring_size: Optional[int] = None,
    ):
        if sample_rate is None:
            sample_rate = float(
                os.environ.get("PIO_TRACE_SAMPLE", DEFAULT_SAMPLE_RATE)
            )
        if ring_size is None:
            ring_size = int(
                os.environ.get("PIO_TRACE_RING", DEFAULT_RING_SIZE)
            )
        if slow_quantile is None:
            slow_quantile = float(
                os.environ.get(
                    "PIO_SLOW_TRACE_QUANTILE", DEFAULT_SLOW_QUANTILE
                )
            )
        if slow_ring_size is None:
            slow_ring_size = int(
                os.environ.get(
                    "PIO_SLOW_TRACE_RING", DEFAULT_SLOW_RING_SIZE
                )
            )
        self.sample_rate = min(1.0, max(0.0, float(sample_rate)))
        self.ring_max = max(1, int(ring_size))
        self.ring: deque = deque(maxlen=self.ring_max)
        self.seen = 0
        self.sampled = 0
        self._acc = 0.0
        self._lock = threading.Lock()
        # flight recorder state (slow_quantile <= 0 disables retention)
        self.slow_quantile = min(1.0, float(slow_quantile))
        self.slow_ring_max = max(1, int(slow_ring_size))
        self.slow_ring: deque = deque(maxlen=self.slow_ring_max)
        self.slow_retained = 0
        self._walls: deque = deque(maxlen=_SLOW_RESERVOIR)
        self._slow_threshold: Optional[float] = None
        self._since_recompute = 0

    def begin(
        self,
        request_id: Optional[str] = None,
        name: str = "",
    ) -> Optional[Trace]:
        """Head-sampling decision; returns a live Trace or None.

        An explicit ``request_id`` (the header arrived) always samples —
        upstream made the decision and cross-service stitching needs the
        downstream half.  Otherwise a deterministic every-Nth accumulator
        admits ``sample_rate`` of traffic with zero RNG cost.
        """
        with self._lock:
            self.seen += 1
            if request_id is None:
                self._acc += self.sample_rate
                if self._acc < 1.0:
                    return None
                self._acc -= 1.0
            self.sampled += 1
        return Trace(request_id or new_request_id(), name=name)

    def record(self, trace: Trace) -> None:
        self.ring.append(trace)  # deque append is atomic
        wall = trace.wall_s
        if wall is None or self.slow_quantile <= 0.0:
            return
        with self._lock:
            # threshold from the reservoir BEFORE admitting this wall, so
            # a request is never judged against a sample that includes it
            thr = self._slow_threshold
            retain = (
                thr is not None
                and len(self._walls) >= _SLOW_MIN_SAMPLES
                and wall > thr
            )
            self._walls.append(wall)
            self._since_recompute += 1
            if (
                self._slow_threshold is None
                or self._since_recompute >= _SLOW_RECOMPUTE
            ):
                self._since_recompute = 0
                ordered = sorted(self._walls)
                i = min(
                    len(ordered) - 1,
                    int(self.slow_quantile * len(ordered)),
                )
                self._slow_threshold = ordered[i]
            if retain:
                self.slow_retained += 1
                self.slow_ring.append(trace)

    def slow_threshold_s(self) -> Optional[float]:
        """Current rolling-quantile wall threshold (None until warmed)."""
        with self._lock:
            if len(self._walls) < _SLOW_MIN_SAMPLES:
                return None
            return self._slow_threshold

    def recent(self, limit: Optional[int] = None) -> list:
        traces = list(self.ring)
        if limit:
            traces = traces[-limit:]
        return [t.to_dict() for t in reversed(traces)]

    def slow_recent(self, limit: Optional[int] = None) -> list:
        """Retained slow-request exemplars, newest first."""
        traces = list(self.slow_ring)
        if limit:
            traces = traces[-limit:]
        return [t.to_dict() for t in reversed(traces)]
