"""Bridges: existing component stats → registry Families at scrape time.

Every load-bearing runtime layer predates the registry and already keeps
its own thread-safe counters (``MicroBatcher.stats()``, fastpath
``serving_stats``, ``ErrorCounters``, the ingest buffer, the storage
client's breakers, the event-server ``Stats``).  Rather than re-homing
those counters — and adding a second lock acquisition to every hot-path
event — each bridge snapshots the component's existing ``stats()`` dict
when ``/metrics`` is scraped and reshapes it into
:class:`~predictionio_tpu.obs.metrics.Family` samples.  ``/metrics`` is
the single source of truth; the components keep their single lock.

All bridges tolerate missing keys (``.get`` with defaults) so a component
evolving its stats dict degrades a series to 0 instead of breaking the
exposition.
"""

from __future__ import annotations

from typing import Callable, Optional

from predictionio_tpu.obs.metrics import Family, MetricsRegistry

BREAKER_STATE_VALUES = {"closed": 0.0, "open": 1.0, "half_open": 2.0}


def _fam(name: str, kind: str, help: str, samples: list) -> Family:
    return Family(name, kind, help, samples)


def _num(v, default=0.0) -> float:
    try:
        return float(v)
    except (TypeError, ValueError):
        return float(default)


# -- serving: micro-batcher --------------------------------------------------

def bridge_batcher(
    registry: MetricsRegistry, stats_fn: Callable[[], Optional[dict]]
) -> None:
    """MicroBatcher occupancy/EWMA/drop stats → pio_batcher_* series."""

    def collect():
        s = stats_fn()
        if not s:
            return []
        fams = [
            _fam(
                "pio_batcher_batches_total", "counter",
                "Batches executed, split by formation kind.",
                [
                    ("", (("kind", "window"),),
                     _num(s.get("batches")) - _num(s.get("inline_batches"))),
                    ("", (("kind", "inline"),),
                     _num(s.get("inline_batches"))),
                ],
            ),
            _fam(
                "pio_batcher_queries_total", "counter",
                "Queries that passed through the micro-batcher.",
                [("", (), _num(s.get("queries")))],
            ),
            _fam(
                "pio_batcher_coalesced_total", "counter",
                "Single-flight followers served by another identical "
                "query's device slot.",
                [("", (), _num(s.get("coalesced")))],
            ),
            _fam(
                "pio_batcher_expired_dropped_total", "counter",
                "Pendings dropped at dispatch because their deadline "
                "expired while queued.",
                [("", (), _num(s.get("expired_dropped")))],
            ),
            _fam(
                "pio_batcher_depth", "gauge",
                "Queries currently waiting in the batch queue.",
                [("", (), _num(s.get("depth")))],
            ),
            _fam(
                "pio_batcher_avg_batch", "gauge",
                "Mean formed batch size (occupancy) since start.",
                [("", (), _num(s.get("avg_batch")))],
            ),
            _fam(
                "pio_batcher_window_wait_ms", "gauge",
                "Mean window wait per batched query, milliseconds.",
                [("", (), _num(s.get("avg_window_wait_ms")))],
            ),
            _fam(
                "pio_batcher_ewma_gap_ms", "gauge",
                "EWMA of inter-arrival gap driving the adaptive window.",
                [("", (), _num(s.get("ewma_gap_ms")))],
            ),
            _fam(
                "pio_batcher_ewma_run_ms", "gauge",
                "EWMA of batch execution time driving the adaptive window.",
                [("", (), _num(s.get("ewma_run_ms")))],
            ),
        ]
        sizes = s.get("batch_sizes")
        if isinstance(sizes, dict) and sizes:
            fams.append(
                _fam(
                    "pio_batcher_batch_size_total", "counter",
                    "Formed batches by size bucket.",
                    [
                        ("", (("size", str(k)),), _num(v))
                        for k, v in sorted(
                            sizes.items(), key=lambda kv: str(kv[0])
                        )
                    ],
                )
            )
        return fams

    registry.register_collector(collect)


# -- serving: AOT fastpath ---------------------------------------------------

def bridge_fastpath(
    registry: MetricsRegistry, stats_fn: Callable[[], Optional[dict]]
) -> None:
    """BucketedScorer stats → pio_fastpath_* (compiles, bucket hits)."""

    def collect():
        s = stats_fn()
        if not s:
            return []
        fams = [
            _fam(
                "pio_fastpath_compiles_total", "counter",
                "XLA compilations performed by the bucketed scorer; flat "
                "under traffic == the AOT warmup contract holds.",
                [("", (), _num(s.get("compile_count")))],
            ),
            _fam(
                "pio_fastpath_calls_total", "counter",
                "score_topk invocations (one per formed batch).",
                [("", (), _num(s.get("calls")))],
            ),
            _fam(
                "pio_fastpath_queries_total", "counter",
                "User rows scored through the fastpath.",
                [("", (), _num(s.get("queries")))],
            ),
            _fam(
                "pio_fastpath_padded_rows_total", "counter",
                "Padding rows wasted by bucket rounding.",
                [("", (), _num(s.get("padded_rows")))],
            ),
            _fam(
                "pio_fastpath_row_occupancy", "gauge",
                "Real rows / padded rows since start (1.0 = no waste).",
                [("", (), _num(s.get("row_occupancy")))],
            ),
        ]
        hits = s.get("bucket_hits")
        if isinstance(hits, dict) and hits:
            fams.append(
                _fam(
                    "pio_fastpath_bucket_hits_total", "counter",
                    "Batches served per compiled bucket rung.",
                    [
                        ("", (("bucket", str(k)),), _num(v))
                        for k, v in sorted(
                            hits.items(), key=lambda kv: _num(kv[0])
                        )
                    ],
                )
            )
        hot = s.get("hotset")
        if isinstance(hot, dict):
            fams.extend([
                _fam(
                    "pio_hotset_lookups_total", "counter",
                    "Fastpath rows answered from the materialized hot-set "
                    "table (hit) vs the bucketed device path (miss).",
                    [
                        ("", (("outcome", "hit"),), _num(hot.get("hits"))),
                        ("", (("outcome", "miss"),), _num(hot.get("misses"))),
                    ],
                ),
                _fam(
                    "pio_hotset_refreshes_total", "counter",
                    "Hot-set re-rank + table materialization passes.",
                    [("", (), _num(hot.get("refreshes")))],
                ),
                _fam(
                    "pio_hotset_size", "gauge",
                    "Configured hot-set working-set bound.",
                    [("", (), _num(hot.get("size")))],
                ),
                _fam(
                    "pio_hotset_resident", "gauge",
                    "Users currently materialized in the hot-set table.",
                    [("", (), _num(hot.get("resident")))],
                ),
            ])
        kern = s.get("kernel")
        if isinstance(kern, dict):
            fams.extend([
                _fam(
                    "pio_kernel_info", "gauge",
                    "Active score-kernel backend and factor dtype "
                    "(info gauge, constant 1; the labels are the signal).",
                    [(
                        "",
                        (
                            ("backend", str(kern.get("backend", ""))),
                            ("dtype", str(kern.get("factor_dtype", ""))),
                        ),
                        1.0,
                    )],
                ),
                _fam(
                    "pio_kernel_resident_factor_bytes", "gauge",
                    "Device-resident factor storage (quantized when a "
                    "bf16/int8 variant is live; int8 ≈ ¼ of fp32).",
                    [("", (), _num(kern.get("resident_factor_bytes")))],
                ),
                _fam(
                    "pio_kernel_intensity_flops_per_byte", "gauge",
                    "Analytic arithmetic intensity of the top scoring "
                    "rung; fused ≫ reference because scores never round-"
                    "trip through HBM.",
                    [("", (), _num(kern.get("intensity_flops_per_byte")))],
                ),
                _fam(
                    "pio_kernel_warmup_executions_total", "counter",
                    "Bucket rungs executed at deploy-time warmup (each "
                    "rung runs once so no compile happens under load).",
                    [("", (), _num(kern.get("warmup_executions")))],
                ),
            ])
        return fams

    registry.register_collector(collect)


# -- training: fused gather-contract kernel ----------------------------------

def bridge_train_kernel(
    registry: MetricsRegistry, stats_fn: Callable[[], Optional[dict]]
) -> None:
    """``ops/train_kernel.stats()`` → pio_train_kernel_* series.

    Training records its resolved dispatch (backend, compute dtype,
    resident opposite-factor bytes, analytic intensity) into the kernel
    module's stats dict at step-build time; this bridge snapshots it at
    scrape so an in-process train (the template train-then-serve flow)
    is visible on the same ``/metrics`` the serving kernel reports to.
    Emits nothing before the first train in this process.
    """

    def collect():
        s = stats_fn()
        if not s:
            return []
        fams = [
            _fam(
                "pio_train_kernel_info", "gauge",
                "Active training-kernel backend and compute dtype "
                "(info gauge, constant 1; the labels are the signal).",
                [(
                    "",
                    (
                        ("backend", str(s.get("backend", ""))),
                        ("compute_dtype", str(s.get("compute_dtype", ""))),
                    ),
                    1.0,
                )],
            ),
            _fam(
                "pio_train_kernel_resident_bytes", "gauge",
                "VMEM-resident opposite-factor bytes per half-step (the "
                "one sequential V read; narrowed by the compute dtype).",
                [("", (), _num(s.get("resident_bytes")))],
            ),
        ]
        if s.get("intensity_flop_per_byte") is not None:
            fams.append(
                _fam(
                    "pio_train_kernel_intensity_flop_per_byte", "gauge",
                    "Analytic arithmetic intensity of one training "
                    "iteration under the resolved backend; fused ≫ "
                    "reference because the gather never touches HBM.",
                    [("", (), _num(s.get("intensity_flop_per_byte")))],
                )
            )
        return fams

    registry.register_collector(collect)


# -- serving: sharded factor placement ---------------------------------------

def bridge_sharding(
    registry: MetricsRegistry, stats_fn: Callable[[], Optional[dict]]
) -> None:
    """Sharded-serving accounting → pio_shard_* series.

    Emits nothing while the scorer serves replicated (no ``sharding``
    block in its stats), so the family set appears exactly when a
    ShardingPlan is live.  ``pio_shard_busy_fraction`` is an ATTRIBUTED
    quantity — the measured whole-mesh busy fraction apportioned across
    shards by realized result-load share (docs/operations.md, "Sharded
    serving") — because one SPMD dispatch keeps every shard busy
    simultaneously; the max/min balance alerts care about is exactly the
    share imbalance this preserves.
    """

    def collect():
        s = stats_fn()
        sh = (s or {}).get("sharding")
        if not isinstance(sh, dict):
            return []
        plan = sh.get("plan") or {}
        n = int(_num(plan.get("n_shards")))

        def per_shard(values, cast=_num):
            vals = values if isinstance(values, list) else []
            return [
                ("", (("shard", str(i)),), cast(v))
                for i, v in enumerate(vals[:n])
            ]

        fams = [
            _fam(
                "pio_shard_info", "gauge",
                "Active sharding plan (info gauge; value is the shard "
                "count, labels carry the plan identity).",
                [(
                    "",
                    (
                        ("fingerprint", str(plan.get("fingerprint", ""))),
                        ("strategy", str(plan.get("strategy", ""))),
                    ),
                    float(n),
                )],
            ),
            _fam(
                "pio_shard_items", "gauge",
                "Catalog items assigned to each shard by the plan.",
                per_shard(plan.get("items_per_shard")),
            ),
            _fam(
                "pio_shard_resident_bytes", "gauge",
                "Device-resident item-factor bytes per shard (padded "
                "block; must fit the per-shard HBM budget).",
                per_shard(sh.get("resident_bytes")),
            ),
            _fam(
                "pio_shard_queries_routed_total", "counter",
                "Query rows fanned out to each shard (every shard scores "
                "every row of every dispatch).",
                per_shard(sh.get("queries_routed")),
            ),
            _fam(
                "pio_shard_result_wins_total", "counter",
                "Top-k result slots won by each shard's items — the "
                "realized popularity load the plan balances.",
                per_shard(sh.get("result_wins")),
            ),
            _fam(
                "pio_shard_load_share", "gauge",
                "Expected per-shard traffic share the plan was balanced "
                "with (build-time weights).",
                per_shard(plan.get("load_share")),
            ),
            _fam(
                "pio_shard_result_share", "gauge",
                "Realized per-shard share of returned top-k slots.",
                per_shard(sh.get("result_share")),
            ),
            _fam(
                "pio_shard_merge_bytes_total", "counter",
                "Cumulative cross-shard merge collective payload "
                "(all-gathered leaderboard bytes; see perf_roofline.md).",
                [("", (), _num(sh.get("merge_bytes")))],
            ),
            _fam(
                "pio_shard_merge_seconds_total", "counter",
                "Device wall attributed to the merge collective (modeled "
                "as the merge-byte share of each dispatch).",
                [("", (), _num(sh.get("merge_seconds")))],
            ),
        ]
        busy = sh.get("busy_fraction")
        if isinstance(busy, list):
            fams.append(
                _fam(
                    "pio_shard_busy_fraction", "gauge",
                    "Measured window busy fraction attributed across "
                    "shards by realized result-load share; max/min is "
                    "the balance the bench gates on.",
                    per_shard(busy),
                )
            )
        return fams

    registry.register_collector(collect)


def bridge_pod(
    registry: MetricsRegistry, stats_fn: Callable[[], Optional[dict]]
) -> None:
    """Pod-scale serving accounting → pio_pod_* series.

    One bridge, two emitters: a query server's fastpath exposes its
    ``pod`` stats block (host-group topology, process slot, cross-host
    merge traffic), a router exposes its shard-aware fan-out counters
    (per-group queries routed, fleet-wide fallback broadcasts).  Each
    family appears exactly when its source key is present — no pod plan,
    no series (the ``pio_shard_*`` presence contract).
    """

    def collect():
        pod = stats_fn()
        if not isinstance(pod, dict):
            return []
        fams = []
        hg = pod.get("host_groups")
        if hg:
            fams.append(_fam(
                "pio_pod_host_groups", "gauge",
                "Host groups in the active pod serving mesh.",
                [("", (), _num(hg))],
            ))
        routed = pod.get("queries_routed")
        if isinstance(routed, dict):
            fams.append(_fam(
                "pio_pod_queries_routed_total", "counter",
                "Attempts the router fanned to their owning host group "
                "(shard-aware routing; primaries, retries, and hedges "
                "all keep — and count against — the query's affinity).",
                [("", (("group", str(g)),), _num(n))
                 for g, n in sorted(routed.items(), key=lambda kv:
                                    str(kv[0]))],
            ))
        if "fallback_broadcasts" in pod:
            fams.append(_fam(
                "pio_pod_fallback_broadcasts_total", "counter",
                "Attempts routed fleet-wide because the owning group had "
                "no eligible replica — the documented degrade path "
                "(retried and hedged attempts included).",
                [("", (), _num(pod.get("fallback_broadcasts")))],
            ))
        if "cross_host_merge_bytes" in pod:
            fams.append(_fam(
                "pio_pod_cross_host_merge_bytes_total", "counter",
                "Cumulative cross-host leaderboard payload: the (H, B, "
                "k) tier-2 gather only (tier-1 stays on-host; "
                "perf_roofline.md derives the S/H reduction).",
                [("", (), _num(pod.get("cross_host_merge_bytes")))],
            ))
        if "cross_host_merge_seconds" in pod:
            fams.append(_fam(
                "pio_pod_cross_host_merge_seconds_total", "counter",
                "Device wall attributed to the cross-host merge tier "
                "(its byte share of each dispatch).",
                [("", (), _num(pod.get("cross_host_merge_seconds")))],
            ))
        if "dispatches" in pod:
            fams.append(_fam(
                "pio_pod_merge_dispatches_total", "counter",
                "Device dispatches that ran the two-tier pod merge.",
                [("", (), _num(pod.get("dispatches")))],
            ))
        if "process_count" in pod:
            fams.append(_fam(
                "pio_pod_process_info", "gauge",
                "This process's slot in the pod launch (info gauge; "
                "labels carry index/count).",
                [(
                    "",
                    (
                        ("index", str(int(_num(pod.get("process_index"))))),
                        ("count", str(int(_num(pod.get("process_count"))))),
                    ),
                    1.0,
                )],
            ))
        return fams

    registry.register_collector(collect)


def bridge_ivf(
    registry: MetricsRegistry, stats_fn: Callable[[], Optional[dict]]
) -> None:
    """IVF retrieval accounting → pio_ivf_* series.

    Emits nothing while the scorer serves the exact scan (no
    ``retrieval`` block in its stats), so the family set appears exactly
    when an IVF index is live — the same presence contract as
    ``pio_shard_*``.  ``pio_ivf_scanned_fraction`` is the realized
    HBM-bytes ratio of the probe scans vs the exact full scans the same
    dispatches would have run; the bench gates it at ≤ 0.2.
    """

    def collect():
        s = stats_fn()
        rv = (s or {}).get("retrieval")
        if not isinstance(rv, dict):
            return []
        fams = [
            _fam(
                "pio_ivf_info", "gauge",
                "Active IVF index (info gauge; value is the cluster "
                "count, labels carry the index identity).",
                [(
                    "",
                    (("fingerprint", str(rv.get("fingerprint", ""))),),
                    _num(rv.get("nlist")),
                )],
            ),
            _fam(
                "pio_ivf_nprobe", "gauge",
                "Serving-time probe budget per query (PIO_IVF_NPROBE "
                "override, else the publish-time default).",
                [("", (), _num(rv.get("nprobe")))],
            ),
            _fam(
                "pio_ivf_probed_blocks_total", "counter",
                "Cluster blocks scanned across all dispatches (rung "
                "probe budgets summed).",
                [("", (), _num(rv.get("probed_blocks")))],
            ),
            _fam(
                "pio_ivf_scanned_fraction", "gauge",
                "Realized scan-bytes fraction vs the exact path for the "
                "same dispatches (probe rows / full-catalog rows).",
                [("", (), _num(rv.get("scanned_fraction")))],
            ),
            _fam(
                "pio_ivf_recall_at_publish", "gauge",
                "Recall@k the sealed index measured at its publish gate "
                "(PIO_IVF_MIN_RECALL receipt).",
                [("", (), _num(rv.get("recall_at_publish")))],
            ),
            _fam(
                "pio_ivf_resident_extra_bytes", "gauge",
                "Device-resident bytes the IVF layout adds over the "
                "replicated exact placement (centroids, id map, pad "
                "mask).",
                [("", (), _num(rv.get("resident_extra_bytes")))],
            ),
        ]
        return fams

    registry.register_collector(collect)


# -- serving: device-utilization accountant ----------------------------------

def bridge_devprof(
    registry: MetricsRegistry,
    snapshot_fn: Callable[[], Optional[dict]],
    generation_fn: Optional[Callable[[], int]] = None,
) -> None:
    """A :class:`~predictionio_tpu.obs.devprof.DeviceUtilization`
    snapshot → the live pio_device_* utilization gauges.

    ``generation_fn`` labels every sample with the model generation the
    live scorer belongs to (the accountant is rebuilt with the scorer on
    reload, so one accountant == one generation). mfu / hbm_util are
    omitted when the platform has no peak-table entry — absent beats a
    fabricated zero.
    """

    def collect():
        s = snapshot_fn()
        if not s:
            return []
        gen = str(generation_fn() if generation_fn is not None else 0)
        lbl = (("generation", gen),)
        fams = [
            _fam(
                "pio_device_busy_fraction", "gauge",
                "Fraction of the rolling window the device spent inside "
                "cost-annotated dispatches.",
                [("", lbl, _num(s.get("busy_fraction")))],
            ),
            _fam(
                "pio_device_flops_per_s", "gauge",
                "Achieved FLOP/s over the rolling window (per-dispatch "
                "cost from XLA cost_analysis or the analytic model).",
                [("", lbl, _num(s.get("flops_per_s")))],
            ),
            _fam(
                "pio_device_hbm_gbps", "gauge",
                "Achieved HBM GB/s over the rolling window.",
                [("", lbl, _num(s.get("hbm_gbps")))],
            ),
            _fam(
                "pio_device_dispatches_total", "counter",
                "Cost-annotated device dispatches since this accountant "
                "(== model generation) went live.",
                [("", lbl, _num(s.get("dispatches_total")))],
            ),
            _fam(
                "pio_device_busy_seconds", "gauge",
                "Device seconds spent in dispatches within the window.",
                [("", lbl, _num(s.get("busy_s")))],
            ),
        ]
        if s.get("mfu") is not None:
            fams.append(
                _fam(
                    "pio_device_mfu", "gauge",
                    "Model FLOP utilization: achieved FLOP/s over the "
                    "per-chip peak (devprof.PEAKS).",
                    [("", lbl, _num(s.get("mfu")))],
                )
            )
        if s.get("hbm_util") is not None:
            fams.append(
                _fam(
                    "pio_device_hbm_util", "gauge",
                    "Achieved HBM bandwidth over the per-chip peak.",
                    [("", lbl, _num(s.get("hbm_util")))],
                )
            )
        return fams

    registry.register_collector(collect)


# -- serving: result cache + event cache (one cache idiom, one surface) ------

def bridge_result_cache(
    registry: MetricsRegistry, stats_fn: Callable[[], Optional[dict]]
) -> None:
    """ResultCache stats → pio_result_cache_* (hits, invalidation split
    by reason, occupancy)."""

    def collect():
        s = stats_fn()
        if not s:
            return []
        return [
            _fam(
                "pio_result_cache_lookups_total", "counter",
                "Result-cache lookups by outcome.",
                [
                    ("", (("outcome", "hit"),), _num(s.get("hits"))),
                    ("", (("outcome", "miss"),), _num(s.get("misses"))),
                ],
            ),
            _fam(
                "pio_result_cache_invalidated_total", "counter",
                "Cached answers dropped at lookup, by reason: event (an "
                "ingest bump), ttl (backstop lapsed), model (generation "
                "swapped).",
                [
                    ("", (("reason", "event"),),
                     _num(s.get("invalidated_event"))),
                    ("", (("reason", "ttl"),),
                     _num(s.get("invalidated_ttl"))),
                    ("", (("reason", "model"),),
                     _num(s.get("invalidated_model"))),
                ],
            ),
            _fam(
                "pio_result_cache_stores_total", "counter",
                "Answers written into the result cache.",
                [("", (), _num(s.get("stores")))],
            ),
            _fam(
                "pio_result_cache_evictions_total", "counter",
                "LRU evictions under the entry bound.",
                [("", (), _num(s.get("evictions")))],
            ),
            _fam(
                "pio_result_cache_entries", "gauge",
                "Entries currently resident.",
                [("", (), _num(s.get("entries")))],
            ),
            _fam(
                "pio_result_cache_hit_rate", "gauge",
                "Hits / lookups since start.",
                [("", (), _num(s.get("hit_rate")))],
            ),
        ]

    registry.register_collector(collect)


def bridge_tenancy(
    registry: MetricsRegistry, stats_fn: Callable[[], Optional[dict]]
) -> None:
    """TenantRegistry ``stats()`` → pio_tenant_* families, labeled by
    tenant (and variant for the A/B comparison series).  Emits nothing
    when no registry is installed; label cardinality is bounded by the
    registry's tenant/variant config, under PIO_METRICS_MAX_SERIES."""

    def collect():
        s = stats_fn()
        if not s:
            return []
        req_samples, err_samples, lat_samples = [], [], []
        shed_samples, inflight, caps, tokens = [], [], [], []
        slo, brk, pressure = [], [], []
        for tid, t in sorted(s.items()):
            lab = (("tenant", tid),)
            inflight.append(("", lab, _num(t.get("inflight"))))
            caps.append(("", lab, _num(t.get("cap"))))
            if t.get("tokens") is not None:
                tokens.append(("", lab, _num(t.get("tokens"))))
            slo.append(("", lab, _num(t.get("slo_violations"))))
            brk.append((
                "", lab,
                BREAKER_STATE_VALUES.get(str(t.get("breaker")), 0.0),
            ))
            cap = max(1.0, _num(t.get("cap"), 1.0))
            pressure.append(
                ("", lab, min(1.0, _num(t.get("inflight")) / cap))
            )
            for reason, n in sorted((t.get("shed") or {}).items()):
                shed_samples.append(
                    ("", (("tenant", tid), ("reason", reason)), _num(n))
                )
            for vname, v in sorted((t.get("variants") or {}).items()):
                vlab = (("tenant", tid), ("variant", vname))
                req_samples.append(("", vlab, _num(v.get("requests"))))
                err_samples.append(("", vlab, _num(v.get("errors"))))
                for q in ("p50", "p99"):
                    lat_samples.append((
                        "", vlab + (("quantile", q),),
                        _num(v.get(f"{q}_ms")),
                    ))
        return [
            _fam("pio_tenant_requests_total", "counter",
                 "Requests accounted per tenant and A/B variant.",
                 req_samples),
            _fam("pio_tenant_errors_total", "counter",
                 "Server-error (5xx) responses per tenant and variant — "
                 "the same events that feed the tenant's breaker.",
                 err_samples),
            _fam("pio_tenant_latency_ms", "gauge",
                 "Per-tenant, per-variant latency quantiles (the online "
                 "A/B comparison surface).", lat_samples),
            _fam("pio_tenant_shed_total", "counter",
                 "Per-tenant sheds by reason: quota (token bucket dry), "
                 "inflight (fair-share cap), breaker (tenant breaker "
                 "open).", shed_samples),
            _fam("pio_tenant_inflight", "gauge",
                 "Requests currently inside this tenant's admission "
                 "slice.", inflight),
            _fam("pio_tenant_inflight_cap", "gauge",
                 "Fair-share inflight cap (weight-proportional share of "
                 "the server gate, x PIO_TENANT_BURST).", caps),
            _fam("pio_tenant_quota_tokens", "gauge",
                 "Token-bucket balance for quota'd tenants (absent when "
                 "no quota_qps is set).", tokens),
            _fam("pio_tenant_slo_violations_total", "counter",
                 "Successful answers that exceeded the tenant's slo_ms.",
                 slo),
            _fam("pio_tenant_breaker_state", "gauge",
                 "Tenant circuit-breaker state (0 closed / 1 open / 2 "
                 "half-open).", brk),
            _fam("pio_tenant_pressure", "gauge",
                 "Inflight saturation against the fair-share cap — the "
                 "autoscaler's per-tenant signal.", pressure),
        ]

    registry.register_collector(collect)


def bridge_pipeline(
    registry: MetricsRegistry, stats_fn: Callable[[], Optional[dict]]
) -> None:
    """PipelineEngine ``stats()`` → pio_pipeline_* families, labeled by
    stage.  Emits nothing while no pipeline is bound."""

    def collect():
        s = stats_fn()
        if not s:
            return []
        runs, overruns, errors, lat, frac = [], [], [], [], []
        for name, st in sorted((s.get("stages") or {}).items()):
            lab = (("stage", name),)
            runs.append(("", lab, _num(st.get("runs"))))
            overruns.append(("", lab, _num(st.get("overruns"))))
            errors.append(("", lab, _num(st.get("errors"))))
            frac.append(("", lab, _num(st.get("budget_fraction"))))
            for q in ("p50", "p99"):
                lat.append((
                    "", lab + (("quantile", q),), _num(st.get(f"{q}_ms")),
                ))
        return [
            _fam("pio_pipeline_stage_runs_total", "counter",
                 "Completed runs per pipeline stage.", runs),
            _fam("pio_pipeline_stage_overruns_total", "counter",
                 "Stage executions that exceeded their share of the "
                 "request deadline.", overruns),
            _fam("pio_pipeline_stage_errors_total", "counter",
                 "Stage executions that raised.", errors),
            _fam("pio_pipeline_stage_latency_ms", "gauge",
                 "Per-stage latency quantiles.", lat),
            _fam("pio_pipeline_stage_budget_fraction", "gauge",
                 "Configured share of the request deadline per stage.",
                 frac),
            _fam("pio_pipeline_degraded_total", "counter",
                 "Answers degraded to the retrieval-only result after a "
                 "later stage overran or failed.",
                 [("", (), _num(s.get("degraded_total")))]),
        ]

    registry.register_collector(collect)


def bridge_event_cache(
    registry: MetricsRegistry, stats_fn: Callable[[], Optional[dict]]
) -> None:
    """ServingEventCache ``stats_dict()`` → pio_event_cache_* families
    (the template-level TTL cache for predict-time storage lookups)."""

    def collect():
        s = stats_fn()
        if not s:
            return []
        return [
            _fam(
                "pio_event_cache_lookups_total", "counter",
                "Event-cache lookups by outcome.",
                [
                    ("", (("outcome", "hit"),), _num(s.get("hits"))),
                    ("", (("outcome", "miss"),), _num(s.get("misses"))),
                ],
            ),
            _fam(
                "pio_event_cache_refreshes_total", "counter",
                "Background refreshes that replaced a stale value.",
                [("", (), _num(s.get("refreshes")))],
            ),
            _fam(
                "pio_event_cache_invalidated_total", "counter",
                "Entries reloaded synchronously after an invalidation-"
                "token change (event-driven).",
                [("", (), _num(s.get("invalidated")))],
            ),
            _fam(
                "pio_event_cache_evictions_total", "counter",
                "Stalest-first evictions under the entry bound.",
                [("", (), _num(s.get("evictions")))],
            ),
            _fam(
                "pio_event_cache_entries", "gauge",
                "Entries currently resident.",
                [("", (), _num(s.get("entries")))],
            ),
        ]

    registry.register_collector(collect)


# -- resilience: error counters + breakers -----------------------------------

def bridge_error_counters(
    registry: MetricsRegistry,
    name: str,
    help: str,
    counters,
) -> None:
    """An :class:`~predictionio_tpu.common.resilience.ErrorCounters` →
    one counter family labeled by kind (includes shed / deadline 504)."""

    def collect():
        snap = counters.snapshot()
        return [
            _fam(
                name, "counter", help,
                [
                    ("", (("kind", str(k)),), _num(v))
                    for k, v in sorted(snap.items())
                ],
            )
        ]

    registry.register_collector(collect)


def bridge_resilience(
    registry: MetricsRegistry,
    stats_fn: Callable[[], Optional[dict]],
    prefix: str = "pio_storage_client",
) -> None:
    """A storage client's ``resilience_stats()`` → retry counter, retry-
    budget gauge, and per-endpoint breaker-state gauges (closed=0,
    open=1, half_open=2)."""

    def collect():
        s = stats_fn()
        if not s:
            return []
        fams = []
        if "retries" in s:
            fams.append(
                _fam(
                    f"{prefix}_retries_total", "counter",
                    "Calls retried under the resilience policy.",
                    [("", (), _num(s.get("retries")))],
                )
            )
        if s.get("retry_budget_tokens") is not None:
            fams.append(
                _fam(
                    f"{prefix}_retry_budget_tokens", "gauge",
                    "Tokens left in the retry budget (exhausted == 0).",
                    [("", (), _num(s.get("retry_budget_tokens")))],
                )
            )
        breakers = s.get("breakers") or []
        if isinstance(breakers, dict):
            breakers = list(breakers.values())
        state_samples, fail_samples, open_samples = [], [], []
        for b in breakers:
            ep = (("endpoint", str(b.get("endpoint", "?"))),)
            state_samples.append(
                ("", ep, BREAKER_STATE_VALUES.get(b.get("state"), -1.0))
            )
            fail_samples.append(
                ("", ep, _num(b.get("consecutive_failures")))
            )
            open_samples.append(("", ep, _num(b.get("open_count"))))
        if state_samples:
            fams.extend(
                [
                    _fam(
                        f"{prefix}_breaker_state", "gauge",
                        "Circuit state per endpoint: 0 closed, 1 open, "
                        "2 half-open.",
                        state_samples,
                    ),
                    _fam(
                        f"{prefix}_breaker_consecutive_failures", "gauge",
                        "Consecutive failures seen by each breaker.",
                        fail_samples,
                    ),
                    _fam(
                        f"{prefix}_breaker_opens_total", "counter",
                        "Times each breaker tripped open.",
                        open_samples,
                    ),
                ]
            )
        return fams

    registry.register_collector(collect)


# -- serving: fleet supervisor + autoscaler ----------------------------------

def bridge_fleet(
    registry: MetricsRegistry, stats_fn: Callable[[], Optional[dict]]
) -> None:
    """FleetSupervisor ``stats()`` → pio_fleet_* process-lifecycle
    series, so crash-restarts and scale events are visible on the
    router's /metrics instead of only in its logs."""

    def collect():
        s = stats_fn()
        if not s:
            return []
        trans = s.get("transitions") or {}
        fams = [
            _fam(
                "pio_fleet_replicas", "gauge",
                "Replica processes currently under supervision.",
                [("", (), _num(s.get("replicas")))],
            ),
            _fam(
                "pio_fleet_replicas_alive", "gauge",
                "Supervised replica processes currently running.",
                [("", (), _num(s.get("alive")))],
            ),
            _fam(
                "pio_fleet_restarts_total", "counter",
                "Crash-restarts performed by the supervisor.",
                [("", (), _num(s.get("restarts")))],
            ),
            _fam(
                "pio_fleet_transitions_total", "counter",
                "Replica lifecycle transitions: up (process spawned) and "
                "down (crash observed or replica scaled away).",
                [
                    ("", (("direction", "up"),), _num(trans.get("up"))),
                    ("", (("direction", "down"),), _num(trans.get("down"))),
                ],
            ),
        ]
        backoff = s.get("backoffMs")
        if isinstance(backoff, dict) and backoff:
            fams.append(
                _fam(
                    "pio_fleet_replica_backoff_ms", "gauge",
                    "Current crash-restart backoff per replica slot "
                    "(0 after a healthy stretch).",
                    [
                        ("", (("replica", str(url)),), _num(ms))
                        for url, ms in sorted(backoff.items())
                    ],
                )
            )
        return fams

    registry.register_collector(collect)


def bridge_autoscaler(
    registry: MetricsRegistry, stats_fn: Callable[[], Optional[dict]]
) -> None:
    """Autoscaler ``stats()`` → pio_autoscaler_* decision series (the
    composite pressure, its per-signal inputs, and scale events)."""

    def collect():
        s = stats_fn()
        if not s:
            return []
        sigs = s.get("signals") or {}
        decision = {"down": -1.0, "hold": 0.0, "up": 1.0}.get(
            s.get("lastDecision"), 0.0
        )
        return [
            _fam(
                "pio_autoscaler_replicas_target", "gauge",
                "Replica count the autoscaler is currently holding the "
                "fleet at.",
                [("", (), _num(s.get("replicas")))],
            ),
            _fam(
                "pio_autoscaler_pressure", "gauge",
                "Composite load pressure (max of the normalized signals) "
                "driving scale decisions.",
                [("", (), _num(s.get("pressure")))],
            ),
            _fam(
                "pio_autoscaler_signal", "gauge",
                "Normalized [0,1] per-signal pressure feeding the "
                "composite (inflight, shed, hedge, busy).",
                [
                    ("", (("signal", str(k)),), _num(v))
                    for k, v in sorted(sigs.items())
                ],
            ),
            _fam(
                "pio_autoscaler_scale_events_total", "counter",
                "Scale decisions executed, by direction.",
                [
                    ("", (("direction", "up"),), _num(s.get("scaleUps"))),
                    ("", (("direction", "down"),), _num(s.get("scaleDowns"))),
                ],
            ),
            _fam(
                "pio_autoscaler_last_decision", "gauge",
                "Most recent control decision: -1 down, 0 hold, 1 up.",
                [("", (), decision)],
            ),
        ]

    registry.register_collector(collect)


CANARY_STATE_VALUES = {
    "idle": 0.0, "verifying": 1.0, "promoting": 2.0, "soaking": 3.0,
    "rolling_back": 4.0,
}


def bridge_canary(
    registry: MetricsRegistry, stats_fn: Callable[[], Optional[dict]]
) -> None:
    """CanaryController ``stats()`` → pio_canary_* series: the rollout
    state machine, per-generation verdict inputs, shadow-mirror volume,
    and the quarantine ledger depth."""

    def collect():
        s = stats_fn()
        if not s:
            return []
        counters = s.get("counters") or {}
        shadow = s.get("shadow") or {}
        cand = s.get("candidateStats") or {}
        base = s.get("baselineStats") or {}
        state = str(s.get("state") or "idle")
        fams = [
            _fam(
                "pio_canary_state", "gauge",
                "Controller state: 0 idle, 1 verifying, 2 promoting, "
                "3 soaking, 4 rolling_back.",
                [("", (), CANARY_STATE_VALUES.get(state, 0.0))],
            ),
            _fam(
                "pio_canary_epoch", "gauge",
                "Fencing epoch of the journal owner; bumps on every "
                "canary start and every controller resume.",
                [("", (), _num(s.get("epoch")))],
            ),
            _fam(
                "pio_canary_info", "gauge",
                "Constant-1 info series; the labels carry the current "
                "state and candidate/baseline generation ids.",
                [(
                    "", (
                        ("state", state),
                        ("candidate", str(s.get("candidate") or "")),
                        ("baseline", str(s.get("baseline") or "")),
                    ), 1.0,
                )],
            ),
            _fam(
                "pio_canary_shadow_queries_total", "counter",
                "Shadow-mirrored query pairs replayed against candidate "
                "+ baseline (answers discarded), by outcome.",
                [
                    ("", (("outcome", "ok"),), _num(counters.get("shadow_ok"))),
                    ("", (("outcome", "error"),),
                     _num(counters.get("shadow_errors"))),
                ],
            ),
            _fam(
                "pio_canary_shadow_overlap", "gauge",
                "Mean top-k prediction overlap between candidate and "
                "baseline over this window's shadow pairs.",
                [("", (), _num(shadow.get("meanOverlap"), 0.0))],
            ),
            _fam(
                "pio_canary_candidate_error_rate", "gauge",
                "Attributed online error rate of the candidate "
                "generation (real traffic, router-attributed).",
                [("", (), _num(cand.get("errorRate")))],
            ),
            _fam(
                "pio_canary_candidate_p99_ms", "gauge",
                "Attributed online p99 latency of the candidate "
                "generation, milliseconds.",
                [("", (), _num(cand.get("p99Ms")))],
            ),
            _fam(
                "pio_canary_baseline_p99_ms", "gauge",
                "Attributed online p99 latency of the baseline "
                "generation, milliseconds (the ratio-SLO denominator).",
                [("", (), _num(base.get("p99Ms")))],
            ),
            _fam(
                "pio_canary_verifications_total", "counter",
                "Verification windows concluded, by verdict.",
                [
                    ("", (("outcome", "pass"),),
                     _num(counters.get("verifications_pass"))),
                    ("", (("outcome", "fail"),),
                     _num(counters.get("verifications_fail"))),
                ],
            ),
            _fam(
                "pio_canary_rollbacks_total", "counter",
                "Automatic rollbacks executed, by phase (verify = canary "
                "replica only, soak = runtime fleet-wide to LKG).",
                [
                    ("", (("phase", "verify"),),
                     _num(counters.get("rollbacks_verify"))),
                    ("", (("phase", "soak"),),
                     _num(counters.get("rollbacks_soak"))),
                ],
            ),
            _fam(
                "pio_canary_promotions_total", "counter",
                "Canaries promoted to the full fleet.",
                [("", (), _num(counters.get("promotions")))],
            ),
            _fam(
                "pio_canary_quarantined_generations", "gauge",
                "Engine instance ids currently blocked by a durable "
                "quarantine receipt.",
                [("", (), float(len(s.get("quarantined") or [])))],
            ),
        ]
        return fams

    registry.register_collector(collect)


# -- data plane: event-server Stats + ingest buffer --------------------------

def bridge_event_stats(registry: MetricsRegistry, stats) -> None:
    """Event-server :class:`~predictionio_tpu.data.api.stats.Stats` →
    pio_events_ingested_total{app_id,event,status} (cardinality is capped
    at the Stats layer, overflow bucket included)."""

    def collect():
        samples = []
        for app_id, counts in sorted(stats.snapshot_all().items()):
            for (event, status), n in sorted(counts.items()):
                samples.append(
                    (
                        "",
                        (
                            ("app_id", str(app_id)),
                            ("event", str(event)),
                            ("status", str(status)),
                        ),
                        _num(n),
                    )
                )
        return [
            _fam(
                "pio_events_ingested_total", "counter",
                "Events processed per app, event name, and HTTP status.",
                samples,
            )
        ]

    registry.register_collector(collect)


def bridge_ingest_buffer(
    registry: MetricsRegistry, stats_fn: Callable[[], Optional[dict]]
) -> None:
    """Write-behind ingest buffer → depth gauge, flow counters, and the
    flush batch-size histogram."""

    def collect():
        s = stats_fn()
        if not s:
            return []
        fams = [
            _fam(
                "pio_ingest_buffer_depth", "gauge",
                "Events currently buffered awaiting flush.",
                [("", (), _num(s.get("buffered")))],
            ),
            _fam(
                "pio_ingest_buffer_capacity", "gauge",
                "Configured buffer bound (overflow == shed).",
                [("", (), _num(s.get("buffer_max")))],
            ),
            _fam(
                "pio_ingest_events_total", "counter",
                "Buffered-ingest events by outcome.",
                [
                    ("", (("outcome", "accepted"),),
                     _num(s.get("accepted"))),
                    ("", (("outcome", "flushed"),), _num(s.get("flushed"))),
                    ("", (("outcome", "overflow"),),
                     _num(s.get("overflows"))),
                ],
            ),
            _fam(
                "pio_ingest_flushes_total", "counter",
                "Group-commit flushes executed.",
                [("", (), _num(s.get("flushes")))],
            ),
            _fam(
                "pio_ingest_flush_retries_total", "counter",
                "Flush attempts retried under the resilience policy.",
                [("", (), _num(s.get("retries")))],
            ),
            _fam(
                "pio_ingest_flush_errors_total", "counter",
                "Flushes that exhausted retries and failed their tickets.",
                [("", (), _num(s.get("flush_errors")))],
            ),
        ]
        hist = s.get("flush_batch_hist")
        if isinstance(hist, dict) and hist:
            fams.append(
                _fam(
                    "pio_ingest_flush_batch_total", "counter",
                    "Flushes by batch-size bucket.",
                    [
                        ("", (("size", str(k)),), _num(v))
                        for k, v in hist.items()
                    ],
                )
            )
        wal = s.get("wal")
        if isinstance(wal, dict):
            fams.extend([
                _fam(
                    "pio_wal_depth", "gauge",
                    "WAL records journaled but not yet flush-committed.",
                    [("", (), _num(wal.get("depth")))],
                ),
                _fam(
                    "pio_wal_segments", "gauge",
                    "WAL segment files currently on disk.",
                    [("", (), _num(wal.get("segments")))],
                ),
                _fam(
                    "pio_wal_records_total", "counter",
                    "WAL record flow (appended / committed / replayed).",
                    [
                        ("", (("op", "appended"),), _num(wal.get("appended"))),
                        ("", (("op", "committed"),),
                         _num(wal.get("committed"))),
                        ("", (("op", "replayed"),), _num(wal.get("replayed"))),
                    ],
                ),
                _fam(
                    "pio_wal_syncs_total", "counter",
                    "fsync calls issued by the WAL (policy-dependent).",
                    [("", (), _num(wal.get("synced")))],
                ),
                _fam(
                    "pio_wal_truncated_tails_total", "counter",
                    "Torn segment tails truncated during replay.",
                    [("", (), _num(wal.get("truncated_tails")))],
                ),
                _fam(
                    "pio_wal_reclaimed_segments_total", "counter",
                    "Fully-committed segments reclaimed (unlinked).",
                    [("", (), _num(wal.get("reclaimed_segments")))],
                ),
            ])
        return fams

    registry.register_collector(collect)


# -- latency histogram (existing log₂ profiler histogram) --------------------

def bridge_latency_histogram(
    registry: MetricsRegistry, name: str, help: str, hist
) -> None:
    """A :class:`utils.profiling.LatencyHistogram` → Prometheus histogram
    samples (cumulative ``le`` in seconds), without double-observing in
    the hot path."""

    def collect():
        with hist._lock:
            counts = [int(c) for c in hist._counts]
            total = int(hist.total)
        samples = []
        acc = 0
        for b, c in enumerate(counts):
            acc += c
            upper_s = hist._bucket_upper_ms(b) / 1e3
            samples.append(("_bucket", (("le", f"{upper_s:.6g}"),), acc))
        samples.append(("_bucket", (("le", "+Inf"),), total))
        samples.append(("_count", (), total))
        return [_fam(name, "histogram", help, samples)]

    registry.register_collector(collect)
