"""Lock-cheap metrics registry with Prometheus + JSON exposition.

Three instrument kinds — :class:`Counter`, :class:`Gauge`,
:class:`Histogram` (log buckets) — each optionally labeled.  A labeled
instrument is a family; ``labels(v1, v2)`` returns the per-series child,
which callers should cache on the hot path (one dict hit + one short lock
otherwise).  Cardinality is bounded per family: past
``PIO_METRICS_MAX_SERIES`` distinct label sets, new ones collapse into a
single ``__overflow__`` series instead of growing memory without limit.

Existing components keep their own locking and expose themselves through
*collectors* — callbacks returning :class:`Family` snapshots at scrape
time (see :mod:`~predictionio_tpu.obs.bridges`) — so migration onto the
registry never adds a second lock to a hot loop.

Exposition: :meth:`MetricsRegistry.render_prometheus` (text format 0.0.4,
``# HELP``/``# TYPE`` + cumulative ``le`` buckets) and
:meth:`~MetricsRegistry.render_json`.  :func:`parse_prometheus` is the
strict inverse used by the round-trip tests and ``pio loadtest``'s
scraper.
"""

from __future__ import annotations

import math
import os
import re
import threading
from typing import Callable, Iterable, Optional, Sequence

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_NAME_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

OVERFLOW_LABEL = "__overflow__"


def _max_series_default() -> int:
    return int(os.environ.get("PIO_METRICS_MAX_SERIES", "512"))


def log_buckets(start: float, factor: float, count: int) -> tuple:
    """Geometric bucket ladder: ``start * factor**i`` for ``count`` rungs."""
    return tuple(start * factor ** i for i in range(count))


# ~8 µs .. ~16 s in octaves: wide enough for an HTTP request that waits
# on a cold storage call, fine enough to see a 2-vs-3 ms serving shift —
# and a sub-millisecond `device_compute` dispatch no longer collapses
# into the bottom rung (the old 0.5 ms floor put ALL device times there).
# The rungs above 0.5 ms are unchanged from the original ladder.
DEFAULT_LATENCY_BUCKETS = log_buckets(0.0005 / 2**6, 2.0, 22)


def format_value(v: float) -> str:
    if v != v:  # NaN
        return "NaN"
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    f = float(v)
    if f.is_integer() and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _label_str(labels: Sequence[tuple]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{_escape_label(str(v))}"' for k, v in labels
    )
    return "{" + inner + "}"


class Family:
    """One metric family snapshot: what a collector hands the renderer.

    ``samples`` is a list of ``(suffix, labels, value)`` where ``suffix``
    is appended to the family name (``"_bucket"``, ``"_sum"``, ``"_count"``
    for histograms; ``""`` otherwise) and ``labels`` is a tuple of
    ``(name, value)`` pairs in exposition order.
    """

    __slots__ = ("name", "kind", "help", "samples")

    def __init__(self, name: str, kind: str, help: str, samples: list):
        self.name = name
        self.kind = kind
        self.help = help
        self.samples = samples


class _Child:
    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    @property
    def value(self) -> float:
        return self._value


class _CounterChild(_Child):
    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("counters only go up")
        with self._lock:
            self._value += amount


class _GaugeChild(_Child):
    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value -= amount


class _HistogramChild:
    __slots__ = ("_lock", "_buckets", "_counts", "_sum", "_count")

    def __init__(self, buckets: tuple):
        self._lock = threading.Lock()
        self._buckets = buckets
        self._counts = [0] * (len(buckets) + 1)  # last = +Inf
        self._sum = 0.0
        self._count = 0

    def observe(self, value: float) -> None:
        v = float(value)
        i = len(self._buckets)
        for j, bound in enumerate(self._buckets):
            if v <= bound:
                i = j
                break
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1

    def snapshot(self) -> tuple:
        with self._lock:
            return list(self._counts), self._sum, self._count


class _MetricFamily:
    """Shared family machinery: label validation, children, overflow cap."""

    kind = "untyped"

    def __init__(
        self,
        name: str,
        help: str,
        labelnames: Sequence[str] = (),
        max_series: Optional[int] = None,
    ):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name: {name!r}")
        for ln in labelnames:
            if not _LABEL_NAME_RE.match(ln):
                raise ValueError(f"invalid label name: {ln!r}")
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self.max_series = (
            max_series if max_series is not None else _max_series_default()
        )
        self._lock = threading.Lock()
        self._children: dict = {}
        self._default = None  # unlabeled child, created lazily

    def _new_child(self):
        raise NotImplementedError

    def labels(self, *values):
        if len(values) != len(self.labelnames):
            raise ValueError(
                f"{self.name}: expected {len(self.labelnames)} label "
                f"values, got {len(values)}"
            )
        key = tuple(str(v) for v in values)
        child = self._children.get(key)
        if child is not None:
            return child
        with self._lock:
            child = self._children.get(key)
            if child is None:
                if len(self._children) >= self.max_series:
                    # cardinality cap: every novel label set past the cap
                    # shares ONE overflow series — memory stays bounded
                    # and the overflow is visible in the exposition
                    key = (OVERFLOW_LABEL,) * len(self.labelnames)
                    child = self._children.get(key)
                    if child is not None:
                        return child
                child = self._new_child()
                self._children[key] = child
        return child

    def _solo(self):
        if self.labelnames:
            raise ValueError(f"{self.name} is labeled; use .labels(...)")
        child = self._default
        if child is None:
            with self._lock:
                child = self._default
                if child is None:
                    child = self._default = self._new_child()
        return child

    def _sample_items(self) -> list:
        with self._lock:
            items = list(self._children.items())
            if self._default is not None:
                items.append(((), self._default))
        return items

    def collect(self) -> Family:
        samples = []
        for key, child in self._sample_items():
            labels = tuple(zip(self.labelnames, key))
            samples.append(("", labels, child.value))
        return Family(self.name, self.kind, self.help, samples)


class Counter(_MetricFamily):
    kind = "counter"

    def _new_child(self):
        return _CounterChild()

    def inc(self, amount: float = 1.0) -> None:
        self._solo().inc(amount)

    @property
    def value(self) -> float:
        return self._solo().value


class Gauge(_MetricFamily):
    kind = "gauge"

    def _new_child(self):
        return _GaugeChild()

    def set(self, value: float) -> None:
        self._solo().set(value)

    def inc(self, amount: float = 1.0) -> None:
        self._solo().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._solo().dec(amount)

    @property
    def value(self) -> float:
        return self._solo().value


class Histogram(_MetricFamily):
    kind = "histogram"

    def __init__(
        self,
        name: str,
        help: str,
        labelnames: Sequence[str] = (),
        buckets: Optional[Sequence[float]] = None,
        max_series: Optional[int] = None,
    ):
        super().__init__(name, help, labelnames, max_series)
        b = tuple(sorted(buckets or DEFAULT_LATENCY_BUCKETS))
        if not b:
            raise ValueError("histogram needs at least one bucket")
        self.buckets = b

    def _new_child(self):
        return _HistogramChild(self.buckets)

    def observe(self, value: float) -> None:
        self._solo().observe(value)

    def collect(self) -> Family:
        samples = []
        for key, child in self._sample_items():
            labels = tuple(zip(self.labelnames, key))
            counts, total, count = child.snapshot()
            acc = 0
            for bound, c in zip(self.buckets, counts):
                acc += c
                samples.append(
                    ("_bucket", labels + (("le", format_value(bound)),), acc)
                )
            samples.append(("_bucket", labels + (("le", "+Inf"),), count))
            samples.append(("_sum", labels, total))
            samples.append(("_count", labels, count))
        return Family(self.name, self.kind, self.help, samples)


class _CallbackGauge:
    kind = "gauge"

    def __init__(self, name: str, help: str, fn: Callable[[], float]):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name: {name!r}")
        self.name = name
        self.help = help
        self.fn = fn

    def collect(self) -> Family:
        try:
            v = float(self.fn())
        except Exception:
            v = float("nan")
        return Family(self.name, "gauge", self.help, [("", (), v)])


class MetricsRegistry:
    """Per-server metric namespace: instruments + collectors → exposition."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict = {}
        self._collectors: list = []

    def _register(self, name: str, factory):
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                return existing
            metric = factory()
            self._metrics[name] = metric
            return metric

    def counter(
        self, name: str, help: str, labelnames: Sequence[str] = ()
    ) -> Counter:
        m = self._register(name, lambda: Counter(name, help, labelnames))
        if not isinstance(m, Counter):
            raise ValueError(f"{name} already registered as {m.kind}")
        return m

    def gauge(
        self, name: str, help: str, labelnames: Sequence[str] = ()
    ) -> Gauge:
        m = self._register(name, lambda: Gauge(name, help, labelnames))
        if not isinstance(m, Gauge):
            raise ValueError(f"{name} already registered as {m.kind}")
        return m

    def histogram(
        self,
        name: str,
        help: str,
        labelnames: Sequence[str] = (),
        buckets: Optional[Sequence[float]] = None,
    ) -> Histogram:
        m = self._register(
            name, lambda: Histogram(name, help, labelnames, buckets)
        )
        if not isinstance(m, Histogram):
            raise ValueError(f"{name} already registered as {m.kind}")
        return m

    def gauge_fn(
        self, name: str, help: str, fn: Callable[[], float]
    ) -> None:
        """A gauge computed at scrape time (uptime, queue depth, …)."""
        self._register(name, lambda: _CallbackGauge(name, help, fn))

    def register_collector(
        self, fn: Callable[[], Iterable[Family]]
    ) -> None:
        """Bridge hook: ``fn()`` returns Family snapshots at scrape time.

        This is how pre-existing components (batcher stats dicts, breaker
        state, ingest buffer) join the exposition without re-homing their
        counters or taking a second lock per event.
        """
        with self._lock:
            self._collectors.append(fn)

    # -- exposition ----------------------------------------------------------
    def collect(self) -> list:
        with self._lock:
            metrics = list(self._metrics.values())
            collectors = list(self._collectors)
        families = [m.collect() for m in metrics]
        for fn in collectors:
            try:
                families.extend(fn())
            except Exception:
                # a broken bridge must never take /metrics down with it
                continue
        return families

    def render_prometheus(self) -> str:
        out = []
        for fam in sorted(self.collect(), key=lambda f: f.name):
            if fam.help:
                out.append(f"# HELP {fam.name} {_escape_help(fam.help)}\n")
            out.append(f"# TYPE {fam.name} {fam.kind}\n")
            for suffix, labels, value in fam.samples:
                out.append(
                    f"{fam.name}{suffix}{_label_str(labels)} "
                    f"{format_value(value)}\n"
                )
        return "".join(out)

    def render_json(self) -> dict:
        metrics = []
        for fam in sorted(self.collect(), key=lambda f: f.name):
            metrics.append(
                {
                    "name": fam.name,
                    "type": fam.kind,
                    "help": fam.help,
                    "samples": [
                        {
                            "name": fam.name + suffix,
                            "labels": dict(labels),
                            "value": None if value != value else value,
                        }
                        for suffix, labels, value in fam.samples
                    ],
                }
            )
        return {"metrics": metrics}


def _escape_help(h: str) -> str:
    return h.replace("\\", "\\\\").replace("\n", "\\n")


# -- parser (round-trip tests + loadtest scraping) ---------------------------

_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"       # metric name
    r"(?:\{(.*)\})?"                      # optional label body
    r" "
    r"(NaN|[+-]Inf|[+-]?[0-9]*\.?[0-9]+(?:[eE][+-]?[0-9]+)?)"
    r"(?: [0-9]+)?$"                      # optional timestamp
)
_LABEL_PAIR_RE = re.compile(
    r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"(?:,|$)'
)


def _parse_value(s: str) -> float:
    if s == "NaN":
        return float("nan")
    if s == "+Inf":
        return math.inf
    if s == "-Inf":
        return -math.inf
    return float(s)


def _unescape_label(v: str) -> str:
    return (
        v.replace("\\n", "\n").replace('\\"', '"').replace("\\\\", "\\")
    )


def parse_prometheus(text: str) -> dict:
    """Strict parse of text-format exposition.

    Returns ``{(name, ((label, value), ...)): value}`` with labels sorted,
    raising :class:`ValueError` on any malformed line — the round-trip
    test leans on that strictness.
    """
    series: dict = {}
    for lineno, raw in enumerate(text.splitlines(), 1):
        line = raw.rstrip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ValueError(f"line {lineno}: malformed sample: {raw!r}")
        name, label_body, value = m.group(1), m.group(2), m.group(3)
        labels = []
        if label_body:
            pos = 0
            while pos < len(label_body):
                pm = _LABEL_PAIR_RE.match(label_body, pos)
                if pm is None:
                    raise ValueError(
                        f"line {lineno}: malformed labels: {label_body!r}"
                    )
                labels.append((pm.group(1), _unescape_label(pm.group(2))))
                pos = pm.end()
        key = (name, tuple(sorted(labels)))
        if key in series:
            raise ValueError(f"line {lineno}: duplicate series {key}")
        series[key] = _parse_value(value)
    return series
