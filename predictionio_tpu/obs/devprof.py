"""Device-utilization accounting: cost models, peaks, live rates, capture.

ROADMAP item 4 is gated on measurement — "MFU is effectively unmeasured" —
and before this module the only utilization numbers lived in offline bench
runs (``bench.py``).  This module makes utilization a RUNTIME fact:

* **One cost model, one peak table.**  The analytic ALS iteration cost and
  the per-chip peak table previously private to ``bench.py`` live here, so
  the bench, the training loop, and the serving fastpath all divide by the
  same denominators.  ``PEAKS`` carries a CPU entry: fallback runs report a
  real (if rough) MFU instead of null, which keeps regression ratios
  comparable run-over-run on the same host.
* **Rolling-window dispatch accountant** (:class:`DeviceUtilization`).
  The serving fastpath annotates every AOT bucket with FLOPs/bytes from
  ``compiled.cost_analysis()`` (analytic fallback when the compiler
  declines) and records each dispatch's device wall here; the ALS train
  loop does the same per training step.  :meth:`DeviceUtilization.snapshot`
  reduces the window into achieved FLOP/s, HBM GB/s, MFU, HBM utilization,
  and device busy fraction — the live ``pio_device_*`` gauge families.
* **On-demand profile capture** (:func:`capture_profile`): a bounded
  ``jax.profiler`` window written under the basedir, driven by the query
  server's ``POST /debug/profile`` and the ``pio profile`` CLI.

Knobs: ``PIO_DEVPROF_WINDOW`` — rolling-window length in seconds for the
live gauges (default 60).
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Optional

__all__ = [
    "PEAKS",
    "peak_for",
    "als_train_cost",
    "als_train_cost_amplified",
    "fused_train_cost",
    "fused_train_vread_bytes",
    "train_utilization",
    "score_cost",
    "DeviceUtilization",
    "train_recorder",
    "train_snapshot",
    "capture_profile",
]

# Per-chip peaks for utilization accounting. v5e: 197 TFLOP/s bf16 MXU,
# 819 GB/s HBM (public spec). mfu is defined against the bf16 peak — the
# number the hardware markets — so a 10× utilization regression is visible
# regardless of the dtype in use. The CPU row is an order-of-magnitude
# stand-in for a modern server socket (~1 TFLOP/s f32 SIMD, ~100 GB/s
# DRAM): good for run-over-run ratios on the same fallback host, not for
# publishing as an absolute hardware number. Platforms not listed report
# null utilization.
PEAKS = {
    "tpu": {"flops": 197e12, "hbm_gbps": 819e9},
    "cpu": {"flops": 1e12, "hbm_gbps": 100e9},
}

DEFAULT_WINDOW_S = 60.0


def peak_for(platform: Optional[str]) -> Optional[dict]:
    """Per-chip peak {flops, hbm_gbps} for a jax platform name, or None."""
    if platform is None:
        return None
    return PEAKS.get(str(platform).lower())


def als_train_cost(
    n_ratings: int, n_users: int, n_items: int, rank: int, dtype: str = "f32"
) -> tuple[float, float]:
    """Analytic (FLOPs, HBM bytes) of ONE dense-solver ALS iteration.

    Cost model (both half-steps of one iteration, dense solver):
      FLOPs: per rating 2·(2k² + 4k) madds (outer product + rhs accumulate,
      both sides) + per entity 2·(k³/3) Cholesky factor+solve madds.
      HBM bytes: per rating, both sides: k·s gather read + 12 B of
      idx/rat/msk + k·s of A-tile write amortized; per entity k·4 factor
      write + opposite-factor read once per half-step.
    A model, not a measurement — good for regression visibility, not for
    publishing as achieved hardware counters.
    """
    k = rank
    s = 2 if dtype == "bf16" else 4  # bytes per factor element
    ents = n_users + n_items
    flops_per_iter = n_ratings * 2 * (2 * k * k + 4 * k) * 2 + ents * (
        2 * k**3 / 3
    )
    bytes_per_iter = (
        n_ratings * 2 * (k * s + 12)  # gather + idx/rat/msk streams
        + ents * k * (4 + s)  # factor write (f32) + opposite read
    )
    return float(flops_per_iter), float(bytes_per_iter)


def train_utilization(
    n_ratings, n_users, n_items, rank, iterations, dtype, dt, n_chips,
    platform,
) -> dict:
    """Analytic achieved-FLOP/s + HBM-GB/s from workload dims and wall time.

    The shape ``bench.py`` publishes in its ``utilization`` block; the
    cost model is :func:`als_train_cost`, the denominators :data:`PEAKS`.
    """
    flops_per_iter, bytes_per_iter = als_train_cost(
        n_ratings, n_users, n_items, rank, dtype
    )
    flops = flops_per_iter * iterations / dt / n_chips
    gbps = bytes_per_iter * iterations / dt / n_chips
    peak = peak_for(platform)
    return {
        "model_flops_per_sec_per_chip": round(flops / 1e9, 2),  # GFLOP/s
        "model_hbm_gbps_per_chip": round(gbps / 1e9, 2),
        "mfu": round(flops / peak["flops"], 6) if peak else None,
        "hbm_util": round(gbps / peak["hbm_gbps"], 6) if peak else None,
    }


# bytes per factor element by serving dtype (mirrors ops/quantize.py;
# duplicated here so the obs layer never imports the ops layer)
_FACTOR_BYTES = {"f32": 4.0, "bf16": 2.0, "int8": 1.0}

# XLA's TPU row gather reads one sector per row regardless of row width —
# the read-amplification constant docs/perf_roofline.md derives (~512 B
# per 40 B factor row at rank 10).
SECTOR_BYTES = 512.0


def als_train_cost_amplified(
    n_ratings: int, n_users: int, n_items: int, rank: int, dtype: str = "f32"
) -> tuple[float, float]:
    """:func:`als_train_cost` with the gather term XLA actually pays.

    The plain model charges ``k·s`` bytes per gathered factor row; on TPU
    the XLA gather reads a full ~512 B sector per row (``SECTOR_BYTES``),
    a ~12.8× amplification at rank 10 f32 that dominates the half-step's
    bytes.  This is the honest reference-backend roofline the fused
    kernel's intensity is compared against in ``bench.py``.
    """
    k = rank
    s = _FACTOR_BYTES.get(dtype, 4.0)
    flops, _ = als_train_cost(n_ratings, n_users, n_items, rank, dtype)
    ents = n_users + n_items
    nbytes = (
        n_ratings * 2 * (max(SECTOR_BYTES, k * s) + 12)  # sector reads
        + ents * k * (4 + s)  # factor write (f32) + opposite read
    )
    return float(flops), float(nbytes)


def fused_train_vread_bytes(
    n_users: int, n_items: int, rank: int, compute_dtype: str = "f32"
) -> float:
    """Bytes of the fused kernel's ONE sequential opposite-factor read per
    iteration (both half-steps): each side streams the other side's
    matrix into VMEM once at the compute dtype, plus the per-row f32
    scale column when int8.  This is the term the compute dtype narrows —
    the bench gate holds int8 to ≤ 0.5× the f32 value.
    """
    s = _FACTOR_BYTES.get(compute_dtype, 4.0)
    ents = float(n_users + n_items)
    nbytes = ents * rank * s
    if compute_dtype == "int8":
        nbytes += ents * 4.0
    return nbytes


def fused_train_cost(
    n_ratings: int, n_users: int, n_items: int, rank: int,
    compute_dtype: str = "f32",
) -> tuple[float, float]:
    """Analytic (FLOPs, HBM bytes) of ONE FUSED-kernel ALS iteration.

    The Pallas training kernel (``ops/train_kernel.py``) streams the
    opposite factor matrix into VMEM once per half-step and gathers rows
    against VMEM, so the per-rating gather term — ``SECTOR_BYTES`` under
    XLA, ``k·s`` even in the charitable model — disappears from HBM
    entirely.  What remains:

    * per rating, both sides: 12 B of idx/rat/msk stream;
    * per half-step: the one sequential opposite-matrix read at the
      compute dtype (:func:`fused_train_vread_bytes`);
    * per entity: the k·4 f32 factor write.

    FLOPs match :func:`als_train_cost` — same contraction, same Cholesky;
    the fused win is bytes, i.e. arithmetic intensity.
    """
    k = rank
    flops, _ = als_train_cost(n_ratings, n_users, n_items, rank)
    ents = n_users + n_items
    nbytes = (
        n_ratings * 2 * 12.0  # idx/rat/msk streams, both sides
        + fused_train_vread_bytes(n_users, n_items, rank, compute_dtype)
        + ents * k * 4.0  # solved-factor write (always f32)
    )
    return float(flops), float(nbytes)


def score_cost(
    batch: int, n_items: int, rank: int, dtype: str = "f32"
) -> tuple[float, float]:
    """Analytic (FLOPs, HBM bytes) of one bucketed score+top-k dispatch.

    Fallback for buckets where ``compiled.cost_analysis()`` declines:
    the (B, k) × (k, I) score matmul dominates FLOPs (plus ~8 ops/score
    for masking and the top-k compare network); bytes are the factor
    reads, the materialized score matrix round-trip, and the (B, k)
    result write.
    """
    b, i, k = float(batch), float(n_items), float(rank)
    s = _FACTOR_BYTES.get(dtype, 4.0)
    flops = b * i * (2.0 * k + 8.0)
    # quantized reference still materializes the dequantized f32 copy and
    # the f32 score matrix; only the factor stream itself narrows
    nbytes = i * k * s + b * k * s + 2.0 * b * i * 4.0 + b * k * 8.0
    return flops, nbytes


def fused_score_cost(
    batch: int, n_items: int, rank: int, top_k: int, dtype: str = "f32"
) -> tuple[float, float]:
    """Analytic (FLOPs, HBM bytes) of one FUSED score+top-k dispatch.

    The Pallas kernel (``ops/score_kernel.py``) keeps the score matrix in
    VMEM, so the reference model's dominant ``2·B·I·4`` HBM round-trip
    term disappears: bytes are just the one-pass factor stream (at the
    storage dtype — this is where bf16/int8 pay off), the B gathered user
    rows, the int8 per-row scales when present, the mask stream, and the
    (B, k) result write.  FLOPs match the reference (same matmul + ~8
    ops/score of masking/merge work), so the fused intensity gain is the
    byte reduction, directly.
    """
    b, i, r, k = float(batch), float(n_items), float(rank), float(top_k)
    s = _FACTOR_BYTES.get(dtype, 4.0)
    flops = b * i * (2.0 * r + 8.0)
    nbytes = i * r * s + b * r * s  # item stream + gathered user rows
    if dtype == "int8":
        nbytes += (i + b) * 4.0  # per-row f32 scales
    nbytes += i * 1.0  # int8 exclusion-mask stream
    nbytes += b * 4.0 + b * k * 8.0  # index upload + (vals, idx) readback
    return flops, nbytes


class DeviceUtilization:
    """Rolling-window accountant for cost-annotated device dispatches.

    The owner annotates each dispatch class (serving bucket, train step)
    with its FLOPs/bytes once via :meth:`set_cost`, then calls
    :meth:`record` with the measured device wall per dispatch.  Records
    older than the window age out; :meth:`snapshot` reduces what's left
    into achieved rates and utilization against the platform peak.  All
    methods are thread-safe; ``record`` is O(1) amortized.
    """

    def __init__(
        self,
        platform: Optional[str] = None,
        window_s: Optional[float] = None,
    ):
        if window_s is None:
            window_s = float(
                os.environ.get("PIO_DEVPROF_WINDOW", DEFAULT_WINDOW_S)
            )
        self.window_s = max(1.0, float(window_s))
        self.platform = platform
        self._costs: dict = {}  # dispatch key → (flops, bytes)
        self._cost_source: dict = {}  # dispatch key → "xla" | "analytic"
        # (t_recorded, device_seconds, flops, bytes) per dispatch
        self._records: deque = deque()
        self._lock = threading.Lock()
        self._t_created = time.monotonic()
        self.dispatches = 0  # lifetime, never pruned

    def set_cost(
        self, key, flops: Optional[float], nbytes: Optional[float],
        source: str = "xla",
    ) -> None:
        """Annotate dispatch class ``key`` with per-dispatch FLOPs/bytes."""
        with self._lock:
            self._costs[key] = (
                float(flops) if flops else 0.0,
                float(nbytes) if nbytes else 0.0,
            )
            self._cost_source[key] = source

    def costs(self) -> dict:
        with self._lock:
            return {
                k: {
                    "flops": f, "bytes": by,
                    "source": self._cost_source.get(k),
                }
                for k, (f, by) in self._costs.items()
            }

    def record(self, key, seconds: float) -> None:
        """Charge one dispatch of class ``key`` with measured device wall."""
        if seconds < 0:
            seconds = 0.0
        now = time.monotonic()
        with self._lock:
            flops, nbytes = self._costs.get(key, (0.0, 0.0))
            self._records.append((now, float(seconds), flops, nbytes))
            self.dispatches += 1
            self._prune(now)

    def _prune(self, now: float) -> None:
        cutoff = now - self.window_s
        while self._records and self._records[0][0] < cutoff:
            self._records.popleft()

    def snapshot(self) -> Optional[dict]:
        """Windowed rates + utilization; None before the first dispatch.

        ``busy_fraction`` (and the rates) divide by the OBSERVED span —
        window length once the accountant has lived that long, its age
        before that — so a freshly warmed server reports its true duty
        cycle instead of a number diluted by a mostly-empty window.
        """
        now = time.monotonic()
        with self._lock:
            self._prune(now)
            if not self.dispatches:
                return None
            elapsed = min(self.window_s, max(1e-9, now - self._t_created))
            busy = sum(r[1] for r in self._records)
            flops = sum(r[2] for r in self._records)
            nbytes = sum(r[3] for r in self._records)
            n = len(self._records)
        flops_per_s = flops / elapsed
        gbps = nbytes / elapsed
        peak = peak_for(self.platform)
        return {
            "platform": self.platform,
            "window_s": self.window_s,
            "elapsed_s": round(elapsed, 3),
            "dispatches_window": n,
            "dispatches_total": self.dispatches,
            "busy_s": round(busy, 6),
            "busy_fraction": round(min(1.0, busy / elapsed), 6),
            "flops_per_s": round(flops_per_s, 2),
            # 6 decimals: a rank-2 toy model on CPU still reads non-zero
            "hbm_gbps": round(gbps / 1e9, 6),
            "mfu": round(flops_per_s / peak["flops"], 9) if peak else None,
            "hbm_util": round(gbps / peak["hbm_gbps"], 9) if peak else None,
        }


def default_platform() -> Optional[str]:
    """The jax default backend's platform name (lazy import; None if jax
    is unavailable or not yet initializable)."""
    try:
        import jax

        return jax.default_backend()
    except Exception:
        return None


# -- train-side recorder ------------------------------------------------------
# `pio train` has no HTTP server to scrape, so the train loop records into
# a process-global accountant; the CLI and tests read the snapshot, and the
# loop logs a utilization line per step so a long train is visible live.

_train_lock = threading.Lock()
_train_acc: Optional[DeviceUtilization] = None


def train_recorder(platform: Optional[str] = None) -> DeviceUtilization:
    """The process-global training accountant (created on first use)."""
    global _train_acc
    with _train_lock:
        if _train_acc is None or (
            platform is not None and _train_acc.platform != platform
        ):
            _train_acc = DeviceUtilization(platform=platform)
        return _train_acc


def train_snapshot() -> Optional[dict]:
    with _train_lock:
        acc = _train_acc
    return acc.snapshot() if acc is not None else None


# -- on-demand profile capture ------------------------------------------------


def capture_profile(ms: int, out_dir: Optional[str] = None) -> str:
    """Run ``jax.profiler`` for a bounded window; return the trace dir.

    Blocks the calling thread for ``ms`` milliseconds while the rest of
    the process keeps serving — exactly what the query server's
    ``POST /debug/profile`` wants. Traces land under
    ``<basedir>/profiles/<stamp>`` unless ``out_dir`` overrides.
    """
    import jax

    from predictionio_tpu.utils.fs import pio_base_dir

    ms = max(1, int(ms))
    if out_dir is None:
        stamp = time.strftime("%Y%m%d-%H%M%S", time.gmtime())
        out_dir = os.path.join(
            pio_base_dir(), "profiles", f"{stamp}-{os.getpid()}"
        )
    os.makedirs(out_dir, exist_ok=True)
    jax.profiler.start_trace(out_dir)
    try:
        time.sleep(ms / 1e3)
    finally:
        jax.profiler.stop_trace()
    return out_dir
