"""Unified observability: metrics registry, exposition, request tracing.

The reference system exposed two serving-seconds gauges and whatever the
Spark UI showed (``CreateServer.scala:415-417``; SURVEY §5).  This package
replaces the reproduction's scattered per-component dicts (``Stats``,
``LatencyHistogram``, ``ErrorCounters``, ``MicroBatcher.stats()``) with one
substrate:

* :mod:`~predictionio_tpu.obs.metrics` — lock-cheap ``Counter`` /
  ``Gauge`` / ``Histogram`` with labels, Prometheus text + JSON exposition,
  and a strict parser for round-trip tests and scraping.
* :mod:`~predictionio_tpu.obs.tracing` — head-sampled request traces with
  a per-stage breakdown, propagated cross-thread (micro-batcher) and
  cross-service (``X-Request-Id``), kept in a bounded in-memory ring.
* :class:`Telemetry` — one bundle per server: installs ``GET /metrics``
  and ``GET /trace/recent.json`` on an
  :class:`~predictionio_tpu.common.http.HttpService` and instruments its
  request loop (request counter, latency histogram, serialize stage).

Knobs (env): ``PIO_TELEMETRY=0`` disables installation, ``PIO_TRACE_SAMPLE``
sets the head-sampling rate (default 0.1), ``PIO_TRACE_RING`` the ring size
(default 256), ``PIO_METRICS_MAX_SERIES`` the per-metric label-cardinality
cap (default 512), ``PIO_SLOW_TRACE_QUANTILE`` / ``PIO_SLOW_TRACE_RING``
the flight recorder's tail-sampling quantile and ring (0.99 / 64).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional

from predictionio_tpu.obs.metrics import (
    DEFAULT_LATENCY_BUCKETS,
    MetricsRegistry,
    parse_prometheus,
)
from predictionio_tpu.obs.tracing import TRACE_HEADER, Tracer

__all__ = [
    "Telemetry",
    "MetricsRegistry",
    "Tracer",
    "TRACE_HEADER",
    "parse_prometheus",
    "telemetry_enabled",
]

PROMETHEUS_CTYPE = "text/plain; version=0.0.4; charset=utf-8"


def telemetry_enabled() -> bool:
    """Global kill switch: ``PIO_TELEMETRY=0`` turns the subsystem off."""
    return os.environ.get("PIO_TELEMETRY", "1") != "0"


class Telemetry:
    """One server's observability bundle: registry + tracer + HTTP hooks.

    Each server owns its own registry (its ``/metrics`` is its own truth —
    two servers in one process never share series), mirroring one
    Prometheus target per listening port.
    """

    def __init__(
        self,
        service_name: str,
        sample_rate: Optional[float] = None,
        ring_size: Optional[int] = None,
        registry: Optional[MetricsRegistry] = None,
    ):
        self.service_name = service_name
        self.registry = registry or MetricsRegistry()
        self.tracer = Tracer(sample_rate=sample_rate, ring_size=ring_size)
        self._start = time.monotonic()
        reg = self.registry
        self._http_requests = reg.counter(
            "pio_http_requests_total",
            "HTTP requests served, by method, route, and status code.",
            ("method", "path", "status"),
        )
        self._http_latency = reg.histogram(
            "pio_http_request_seconds",
            "End-to-end HTTP request latency (accept to last byte).",
            ("path",),
            buckets=DEFAULT_LATENCY_BUCKETS,
        )
        info = reg.gauge(
            "pio_server_info",
            "Constant 1, labeled with the serving component's name.",
            ("service",),
        )
        info.labels(service_name).set(1)
        reg.gauge_fn(
            "pio_uptime_seconds",
            "Seconds since this server's telemetry was created.",
            lambda: time.monotonic() - self._start,
        )
        reg.gauge_fn(
            "pio_threads",
            "Live Python threads in this process.",
            lambda: float(threading.active_count()),
        )
        reg.gauge_fn(
            "pio_traces_sampled_total",
            "Requests admitted by the head sampler since start.",
            lambda: float(self.tracer.sampled),
        )
        reg.gauge_fn(
            "pio_trace_ring_size",
            "Finished traces currently held in the in-memory ring.",
            lambda: float(len(self.tracer.ring)),
        )
        reg.gauge_fn(
            "pio_slow_trace_retained",
            "Slow-request exemplars retained by the flight recorder "
            "since start (tail sampling above the rolling quantile).",
            lambda: float(self.tracer.slow_retained),
        )
        reg.gauge_fn(
            "pio_slow_trace_threshold_seconds",
            "Current rolling-quantile wall-time threshold for slow-trace "
            "retention (NaN until the reservoir warms up).",
            lambda: float(self.tracer.slow_threshold_s() or float("nan")),
        )

    # -- HTTP request-loop hooks (called from common/http.py) ---------------
    def observe_http(
        self, method: str, path: str, status: int, seconds: float,
        known_path: bool,
    ) -> None:
        # unknown paths collapse into one label value so a hostile URL
        # stream can't mint unbounded series
        p = path if known_path else "/other"
        self._http_requests.labels(method, p, str(status)).inc()
        self._http_latency.labels(p).observe(seconds)

    # -- route installation --------------------------------------------------
    def install(self, service) -> "Telemetry":
        """Attach to an HttpService: request hooks + exposition routes."""
        service.telemetry = self

        @service.route("GET", r"/metrics")
        def _metrics(req):
            from predictionio_tpu.common.http import Response

            if req.params.get("format") == "json":
                return Response(status=200, body=self.registry.render_json())
            return Response(
                status=200,
                body=self.registry.render_prometheus().encode("utf-8"),
                content_type=PROMETHEUS_CTYPE,
            )

        @service.route("GET", r"/trace/recent\.json")
        def _traces(req):
            from predictionio_tpu.common.http import json_response

            limit = int(req.params.get("limit") or 0) or None
            return json_response(
                200,
                {
                    "service": self.service_name,
                    "sampleRate": self.tracer.sample_rate,
                    "ringSize": self.tracer.ring_max,
                    "traces": self.tracer.recent(limit),
                },
            )

        @service.route("GET", r"/trace/slow\.json")
        def _slow_traces(req):
            from predictionio_tpu.common.http import json_response

            limit = int(req.params.get("limit") or 0) or None
            thr = self.tracer.slow_threshold_s()
            return json_response(
                200,
                {
                    "service": self.service_name,
                    "quantile": self.tracer.slow_quantile,
                    "ringSize": self.tracer.slow_ring_max,
                    "thresholdMs": (
                        None if thr is None else round(thr * 1e3, 4)
                    ),
                    "retained": self.tracer.slow_retained,
                    "traces": self.tracer.slow_recent(limit),
                },
            )

        return self


def maybe_install(service, service_name: str, **kw) -> Optional[Telemetry]:
    """Install a fresh :class:`Telemetry` unless globally disabled."""
    if not telemetry_enabled():
        return None
    return Telemetry(service_name, **kw).install(service)
