"""Serving load test: concurrent queries against a deployed engine.

The p50-predict-latency companion to bench.py's training throughput
(BASELINE.md headline metrics). Fires N concurrent workers at
``/queries.json`` and reports client-side latency quantiles + QPS; the
server's own histogram (its ``GET /`` route) gives the service-side view.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request


def run_loadtest(
    url: str,
    query: dict,
    requests: int = 200,
    concurrency: int = 8,
    timeout: float = 30.0,
) -> dict:
    latencies: list[float] = []
    errors: list[str] = []
    lock = threading.Lock()
    counter = {"next": 0}

    payload = json.dumps(query).encode()

    def worker():
        while True:
            with lock:
                if counter["next"] >= requests:
                    return
                counter["next"] += 1
            req = urllib.request.Request(
                f"{url}/queries.json",
                data=payload,
                method="POST",
                headers={"Content-Type": "application/json"},
            )
            t0 = time.perf_counter()
            try:
                with urllib.request.urlopen(req, timeout=timeout) as r:
                    r.read()
                with lock:
                    latencies.append(time.perf_counter() - t0)
            except Exception as e:
                with lock:
                    errors.append(str(e))

    threads = [threading.Thread(target=worker) for _ in range(concurrency)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    latencies.sort()

    def q(p: float) -> float:
        if not latencies:
            return float("nan")
        return latencies[min(int(p * len(latencies)), len(latencies) - 1)] * 1e3

    return {
        "requests": requests,
        "concurrency": concurrency,
        "ok": len(latencies),
        "errors": len(errors),
        "wallSec": round(wall, 3),
        "qps": round(len(latencies) / wall, 1) if wall > 0 else 0.0,
        "p50Ms": round(q(0.50), 3),
        "p90Ms": round(q(0.90), 3),
        "p99Ms": round(q(0.99), 3),
    }
