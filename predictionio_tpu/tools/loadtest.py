"""Serving load test: concurrent queries against a deployed engine.

The p50-predict-latency companion to bench.py's training throughput
(BASELINE.md headline metrics). Fires N concurrent workers at
``/queries.json`` and reports client-side latency quantiles + QPS; the
server's own histogram (its ``GET /`` route) gives the service-side view.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request


def run_loadtest(
    url: str,
    query: dict,
    requests: int = 200,
    concurrency: int = 8,
    timeout: float = 30.0,
    samples: dict = None,
) -> dict:
    """``samples`` maps a query FIELD to a list of values; request ``i``
    sends the query with ``field = values[i % len(values)]`` (round-robin,
    deterministic). One fixed payload measures one warm jit path and one
    hot cache line — p50 flatters; mixed keys are what tail latency
    means. Without ``samples`` the single payload is sent verbatim."""
    latencies: list[float] = []
    errors: list[str] = []
    lock = threading.Lock()
    counter = {"next": 0}

    fixed_payload = json.dumps(query).encode()

    def payload_for(i: int) -> bytes:
        if not samples:
            return fixed_payload
        q = dict(query)
        for field, values in samples.items():
            q[field] = values[i % len(values)]
        return json.dumps(q).encode()

    def worker():
        while True:
            with lock:
                if counter["next"] >= requests:
                    return
                i = counter["next"]
                counter["next"] += 1
            req = urllib.request.Request(
                f"{url}/queries.json",
                data=payload_for(i),
                method="POST",
                headers={"Content-Type": "application/json"},
            )
            t0 = time.perf_counter()
            try:
                with urllib.request.urlopen(req, timeout=timeout) as r:
                    r.read()
                with lock:
                    latencies.append(time.perf_counter() - t0)
            except Exception as e:
                with lock:
                    errors.append(str(e))

    threads = [threading.Thread(target=worker) for _ in range(concurrency)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    latencies.sort()

    def q(p: float) -> float:
        if not latencies:
            return float("nan")
        return latencies[min(int(p * len(latencies)), len(latencies) - 1)] * 1e3

    return {
        "requests": requests,
        "concurrency": concurrency,
        "ok": len(latencies),
        "errors": len(errors),
        "wallSec": round(wall, 3),
        "qps": round(len(latencies) / wall, 1) if wall > 0 else 0.0,
        "p50Ms": round(q(0.50), 3),
        "p90Ms": round(q(0.90), 3),
        "p99Ms": round(q(0.99), 3),
    }
