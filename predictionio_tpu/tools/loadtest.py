"""Serving load test: concurrent queries against a deployed engine.

The p50-predict-latency companion to bench.py's training throughput
(BASELINE.md headline metrics). Fires N concurrent workers at
``/queries.json`` and reports client-side latency quantiles + QPS; the
server's own histogram (its ``GET /`` route) gives the service-side view.

Each worker holds ONE persistent HTTP/1.1 connection (keep-alive) for its
whole run — the realistic client shape (SDKs pool connections), and the
only shape that measures the server rather than the TCP handshake: a
fresh connect per request adds a connect+thread-spawn tax that dwarfs
sub-millisecond serve times.  A failed request closes and re-opens the
worker's connection; the failure is still counted.
"""

from __future__ import annotations

import http.client
import json
import threading
import time
import urllib.parse


def zipf_mandelbrot_weights(n: int, s: float = 1.1, q: float = 50.0):
    """Zipf-Mandelbrot pmf ``P(k) ∝ (k+q)^-s`` over ranks ``[0, n)``.

    The q shift matches real catalogs: at s=1.1, q=50 the hottest of ~59k
    ids draws ~0.4% of traffic, like ML-25M's ~0.32% — a pure Zipf head
    would take ~10%, which no real workload does.  Shared with bench.py's
    ``_sample_ids`` so the load test and the training bench agree on what
    "skewed" means.  Returns a normalized float64 numpy array (numpy is
    imported lazily: round-robin load tests stay stdlib-only).
    """
    import numpy as np

    ranks = np.arange(1, n + 1, dtype=np.float64)
    p = (ranks + q) ** -s
    return p / p.sum()


def scrape_metrics(url: str, timeout: float = 10.0) -> dict:
    """Scrape ``GET /metrics`` off the server under test and return the
    parsed series as ``{(name, ((label, value), ...)): value}``.

    The load test's client-side quantiles say what callers experienced;
    the scrape says what the server *did* (batch occupancy, fastpath
    compile count, shed counters).  Run it after the load so the deltas
    reflect the run.  Raises on transport errors or an invalid
    exposition — a loadtest that can't trust its telemetry should say so
    rather than report half a picture.
    """
    from predictionio_tpu.obs.metrics import parse_prometheus

    parsed = urllib.parse.urlsplit(url)
    host = parsed.hostname
    port = parsed.port or (443 if parsed.scheme == "https" else 80)
    conn_cls = (
        http.client.HTTPSConnection
        if parsed.scheme == "https"
        else http.client.HTTPConnection
    )
    conn = conn_cls(host, port, timeout=timeout)
    try:
        conn.request("GET", (parsed.path.rstrip("/") or "") + "/metrics")
        resp = conn.getresponse()
        body = resp.read().decode("utf-8", "replace")
        if resp.status != 200:
            raise RuntimeError(f"GET /metrics -> HTTP {resp.status}")
        return parse_prometheus(body)
    finally:
        conn.close()


def summarize_metrics(series: dict) -> dict:
    """Condense a :func:`scrape_metrics` result to the handful of series a
    loadtest report cares about (JSON-friendly, stable keys)."""

    def total(name: str, **want: str) -> float:
        return sum(
            v
            for (n, labels), v in series.items()
            if n == name
            and all(dict(labels).get(k) == val for k, val in want.items())
        )

    out = {
        "seriesCount": len(series),
        "httpRequests": total("pio_http_requests_total"),
        "fastpathCompiles": total("pio_fastpath_compiles_total"),
        "batcherQueries": total("pio_batcher_queries_total"),
        "eventsIngested": total("pio_events_ingested_total"),
    }
    # skew-path families only exist when the serving caches are on — a
    # zipf loadtest without these keys means the server isn't configured
    # to absorb the hot head
    if total("pio_result_cache_enabled"):
        out["resultCacheHits"] = total(
            "pio_result_cache_lookups_total", outcome="hit"
        )
        out["resultCacheMisses"] = total(
            "pio_result_cache_lookups_total", outcome="miss"
        )
    if ("pio_batcher_coalesced_total", ()) in series:
        out["coalesced"] = total("pio_batcher_coalesced_total")
    if total("pio_hotset_size"):
        out["hotsetHits"] = total("pio_hotset_lookups_total", outcome="hit")
        out["hotsetResident"] = total("pio_hotset_resident")
    # device-utilization families (ISSUE 8) only exist once the scorer has
    # recorded at least one cost-annotated dispatch; they carry a
    # {generation} label, so take the max across label sets — after a
    # reload the freshest generation is the one that describes this run
    def latest(name: str):
        vals = [v for (n, _labels), v in series.items() if n == name]
        return max(vals) if vals else None

    if latest("pio_device_busy_fraction") is not None:
        out["deviceBusyFraction"] = latest("pio_device_busy_fraction")
        out["deviceFlopsPerSec"] = latest("pio_device_flops_per_s")
        out["deviceHbmGbps"] = latest("pio_device_hbm_gbps")
        if latest("pio_device_mfu") is not None:
            out["deviceMfu"] = latest("pio_device_mfu")
        if latest("pio_device_hbm_util") is not None:
            out["deviceHbmUtil"] = latest("pio_device_hbm_util")
    if ("pio_slow_trace_retained", ()) in series:
        out["slowTraces"] = total("pio_slow_trace_retained")
    # score-kernel identity (ISSUE 9): which backend actually served this
    # run and at what factor dtype — a fused-TPU loadtest that reports
    # backend=reference means the dispatch seam fell back
    for (name, labels), v in series.items():
        if name == "pio_kernel_info" and v:
            lbl = dict(labels)
            out["kernelBackend"] = lbl.get("backend", "")
            out["kernelFactorDtype"] = lbl.get("dtype", "")
    if latest("pio_kernel_resident_factor_bytes") is not None:
        out["kernelResidentFactorBytes"] = latest(
            "pio_kernel_resident_factor_bytes"
        )
        out["kernelIntensity"] = latest("pio_kernel_intensity_flops_per_byte")
    # retrieval identity (ISSUE 16): pio_ivf_* emits only while an IVF
    # index is live, so its presence IS the backend signal — a deploy
    # meant to serve IVF that reports "exact" degraded at load/resolve
    if "kernelBackend" in out:
        out["retrievalBackend"] = "exact"
    for (name, labels), v in series.items():
        if name == "pio_ivf_info" and v:
            out["retrievalBackend"] = "ivf"
    if latest("pio_ivf_nprobe") is not None:
        out["ivfNprobe"] = latest("pio_ivf_nprobe")
        out["ivfScannedFraction"] = latest("pio_ivf_scanned_fraction")
    for (name, labels), v in sorted(series.items()):
        if name.endswith("_breaker_state"):
            out.setdefault("breakerStates", {})[
                ",".join(f"{k}={val}" for k, val in labels)
            ] = v
    # progressive delivery (ISSUE 20): pio_canary_info exists only behind
    # a canary-armed router; its labels say whether this run's traffic hit
    # a fleet mid-canary, and the quarantine gauge says whether any model
    # generation is blocked from deployment right now
    for (name, labels), v in series.items():
        if name == "pio_canary_info" and v:
            lbl = dict(labels)
            out["canaryState"] = lbl.get("state", "")
            out["canaryGeneration"] = lbl.get("candidate", "")
    if latest("pio_canary_quarantined_generations") is not None:
        out["quarantinedGenerations"] = latest(
            "pio_canary_quarantined_generations"
        )
    return out


def _schedule_stop(
    parsed, conn_cls, kill_after_s: float, stop_state: dict,
    timeout: float = 5.0,
) -> threading.Timer:
    """``--kill-after``: POST /stop at the server mid-run so the load test
    exercises graceful drain under live traffic. ``stop_state['posted']``
    flips once the stop landed; workers then classify connection failures
    as ``afterStop`` instead of errors (an intentionally-stopped server
    refusing connections is the expected outcome, not a failure)."""
    host = parsed.hostname
    port = parsed.port or (443 if parsed.scheme == "https" else 80)
    path = (parsed.path.rstrip("/") or "") + "/stop"

    def _post_stop():
        conn = conn_cls(host, port, timeout=timeout)
        try:
            conn.request("POST", path, body=b"")
            conn.getresponse().read()
            stop_state["posted"] = True
        except Exception as e:
            stop_state["error"] = str(e)
        finally:
            conn.close()

    timer = threading.Timer(kill_after_s, _post_stop)
    timer.daemon = True
    timer.start()
    return timer


def _per_key_summary(key_lats: dict, top_n: int = 8) -> dict:
    """Per-key latency percentiles: the ``top_n`` most-requested keys
    individually, the rest folded into one ``coldTail`` aggregate.  Under
    skew this is the interesting split — hot keys should ride the cache
    (p50 well under the cold tail's) and the cold tail should not be
    starved by them."""

    def pct(lats: list, p: float) -> float:
        return round(lats[min(int(p * len(lats)), len(lats) - 1)] * 1e3, 3)

    ranked = sorted(key_lats.items(), key=lambda kv: -len(kv[1]))
    hot, cold = ranked[:top_n], ranked[top_n:]
    out = {
        "distinctKeys": len(key_lats),
        "hotKeys": [
            {"key": k, "n": len(v), "p50Ms": pct(sorted(v), 0.50),
             "p99Ms": pct(sorted(v), 0.99)}
            for k, v in hot
        ],
    }
    cold_all = sorted(dt for _, v in cold for dt in v)
    if cold_all:
        out["coldTail"] = {
            "keys": len(cold), "n": len(cold_all),
            "p50Ms": pct(cold_all, 0.50), "p99Ms": pct(cold_all, 0.99),
        }
    return out


def run_loadtest(
    url: str,
    query: dict,
    requests: int = 200,
    concurrency: int = 8,
    timeout: float = 30.0,
    samples: dict = None,
    deadline_ms: float = None,
    kill_after_s: float = None,
    dist: str = "roundrobin",
    zipf_s: float = 1.1,
    zipf_q: float = 50.0,
    seed: int = 0,
) -> dict:
    """``samples`` maps a query FIELD to a list of values; request ``i``
    sends the query with ``field = values[i % len(values)]`` (round-robin,
    deterministic). One fixed payload measures one warm jit path and one
    hot cache line — p50 flatters; mixed keys are what tail latency
    means. Without ``samples`` the single payload is sent verbatim.

    ``dist="zipf"`` replaces the round-robin rotation with Zipf-Mandelbrot
    draws (``P(k) ∝ (k+q)^-s``, early sample values hottest) — the shape
    real traffic has, and the one the serving hot path (result cache,
    single-flight, hot-set) is built to exploit.  Draws are seeded, so a
    run is reproducible.  With ``samples`` set, the summary also carries
    ``perKey``: per-key latency percentiles for the hottest keys plus a
    cold-tail aggregate, which is where a skew win (hot keys far below
    the cold p50) or a skew bug (hot keys starving the tail) shows up.

    ``deadline_ms`` attaches an ``X-Request-Deadline`` budget to every
    request; the server sheds (503) or deadline-504s what it can't serve
    in time, and both are broken out of ``errors`` in the result."""
    if dist not in ("roundrobin", "zipf"):
        raise ValueError(f"dist must be roundrobin|zipf, got {dist!r}")
    # request i's value index per sample field (zipf pre-draws the whole
    # schedule up front so worker interleaving can't change the workload)
    sample_idx: dict = {}
    if dist == "zipf" and samples:
        import numpy as np

        rng = np.random.default_rng(seed)
        for field, values in samples.items():
            weights = zipf_mandelbrot_weights(len(values), zipf_s, zipf_q)
            sample_idx[field] = rng.choice(
                len(values), size=requests, p=weights
            ).tolist()

    latencies: list[float] = []
    key_lats: dict = {}  # sampled-field values → successful latencies
    errors: list[str] = []
    shed = [0]  # 503: admission control turned the request away
    deadline_exceeded = [0]  # 504: budget lapsed before/while serving
    after_stop = [0]  # failures once --kill-after stopped the server
    stop_state: dict = {"posted": False}
    lock = threading.Lock()
    counter = {"next": 0}

    parsed = urllib.parse.urlsplit(url)
    host = parsed.hostname
    port = parsed.port or (443 if parsed.scheme == "https" else 80)
    path = (parsed.path.rstrip("/") or "") + "/queries.json"
    conn_cls = (
        http.client.HTTPSConnection
        if parsed.scheme == "https"
        else http.client.HTTPConnection
    )
    if kill_after_s is not None:
        _schedule_stop(parsed, conn_cls, kill_after_s, stop_state)
    headers = {"Content-Type": "application/json"}
    if deadline_ms is not None:
        headers["X-Request-Deadline"] = f"{deadline_ms:g}"

    fixed_payload = json.dumps(query).encode()

    def payload_for(i: int) -> tuple:
        if not samples:
            return fixed_payload, None
        q = dict(query)
        picked = []
        for field, values in samples.items():
            idx = sample_idx[field][i] if field in sample_idx else i % len(values)
            q[field] = values[idx]
            picked.append(str(values[idx]))
        return json.dumps(q).encode(), "|".join(picked)

    def worker():
        conn = conn_cls(host, port, timeout=timeout)
        try:
            while True:
                with lock:
                    if counter["next"] >= requests:
                        return
                    i = counter["next"]
                    counter["next"] += 1
                body, key = payload_for(i)
                t0 = time.perf_counter()
                try:
                    conn.request("POST", path, body=body, headers=headers)
                    resp = conn.getresponse()
                    resp.read()  # drain so the connection can be reused
                    if resp.status == 503:
                        with lock:
                            shed[0] += 1
                        continue  # shed, not broken: connection stays warm
                    if resp.status == 504:
                        with lock:
                            deadline_exceeded[0] += 1
                        continue
                    if resp.status >= 400:
                        raise RuntimeError(f"HTTP {resp.status}")
                    dt = time.perf_counter() - t0
                    with lock:
                        latencies.append(dt)
                        if key is not None:
                            key_lats.setdefault(key, []).append(dt)
                except Exception as e:
                    with lock:
                        if stop_state["posted"]:
                            after_stop[0] += 1
                        else:
                            errors.append(str(e))
                    conn.close()  # next request reconnects cleanly
        finally:
            conn.close()

    threads = [threading.Thread(target=worker) for _ in range(concurrency)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    latencies.sort()

    def q(p: float) -> float:
        if not latencies:
            return float("nan")
        return latencies[min(int(p * len(latencies)), len(latencies) - 1)] * 1e3

    out = {
        "requests": requests,
        "concurrency": concurrency,
        "dist": dist,
        "ok": len(latencies),
        "errors": len(errors),
        "shed": shed[0],
        "deadlineExceeded": deadline_exceeded[0],
        "wallSec": round(wall, 3),
        "qps": round(len(latencies) / wall, 1) if wall > 0 else 0.0,
        "p50Ms": round(q(0.50), 3),
        "p90Ms": round(q(0.90), 3),
        "p99Ms": round(q(0.99), 3),
    }
    if key_lats:
        out["perKey"] = _per_key_summary(key_lats)
    if kill_after_s is not None:
        out["killAfterSec"] = kill_after_s
        out["stopPosted"] = stop_state["posted"]
        out["afterStop"] = after_stop[0]
    return out


def run_ingest_loadtest(
    url: str,
    access_key: str,
    events: int = 1000,
    concurrency: int = 8,
    batch_size: int = 1,
    timeout: float = 30.0,
    event_template: dict = None,
    channel: str = None,
    kill_after_s: float = None,
) -> dict:
    """Ingest-side load test: POST events at a live Event Server.

    ``batch_size=1`` drives ``POST /events.json`` (one event per request
    — the write-behind buffer's shape); larger sizes drive
    ``POST /batch/events.json`` with ``batch_size`` events per request
    (the vectorized endpoint's shape).  Entity ids rotate per event so the
    workload isn't one hot row.  Latency quantiles are per-REQUEST ack
    times; ``eventsPerSec`` is the headline ingest throughput.  503s count
    as ``shed`` (buffer backpressure), not errors, mirroring
    :func:`run_loadtest`.
    """
    template = dict(event_template or {
        "event": "rate",
        "entityType": "user",
        "targetEntityType": "item",
        "properties": {"rating": 5},
    })
    batch_size = max(1, int(batch_size))
    n_requests = (events + batch_size - 1) // batch_size

    latencies: list[float] = []
    errors: list[str] = []
    shed = [0]
    acked = [0]
    after_stop = [0]
    stop_state: dict = {"posted": False}
    lock = threading.Lock()
    counter = {"next": 0}

    parsed = urllib.parse.urlsplit(url)
    host = parsed.hostname
    port = parsed.port or (443 if parsed.scheme == "https" else 80)
    qs = urllib.parse.urlencode(
        {"accessKey": access_key, **({"channel": channel} if channel else {})}
    )
    path = (parsed.path.rstrip("/") or "") + (
        "/batch/events.json" if batch_size > 1 else "/events.json"
    ) + "?" + qs
    conn_cls = (
        http.client.HTTPSConnection
        if parsed.scheme == "https"
        else http.client.HTTPConnection
    )
    if kill_after_s is not None:
        _schedule_stop(parsed, conn_cls, kill_after_s, stop_state)
    headers = {"Content-Type": "application/json"}

    def payload_for(i: int) -> tuple[bytes, int]:
        lo = i * batch_size
        n = min(batch_size, events - lo)
        items = [
            dict(template, entityId=f"u{lo + j}", targetEntityId=f"i{(lo + j) % 97}")
            for j in range(n)
        ]
        body = items if batch_size > 1 else items[0]
        return json.dumps(body).encode(), n

    def worker():
        conn = conn_cls(host, port, timeout=timeout)
        try:
            while True:
                with lock:
                    if counter["next"] >= n_requests:
                        return
                    i = counter["next"]
                    counter["next"] += 1
                body, n = payload_for(i)
                t0 = time.perf_counter()
                try:
                    conn.request("POST", path, body=body, headers=headers)
                    resp = conn.getresponse()
                    raw = resp.read()
                    if resp.status == 503:
                        with lock:
                            shed[0] += 1
                        continue
                    if resp.status >= 400:
                        raise RuntimeError(f"HTTP {resp.status}")
                    ok_items = n
                    if batch_size > 1:
                        ok_items = sum(
                            1 for r in json.loads(raw.decode())
                            if r.get("status") in (201, 202)
                        )
                    with lock:
                        latencies.append(time.perf_counter() - t0)
                        acked[0] += ok_items
                except Exception as e:
                    with lock:
                        if stop_state["posted"]:
                            after_stop[0] += 1
                        else:
                            errors.append(str(e))
                    conn.close()
        finally:
            conn.close()

    threads = [threading.Thread(target=worker) for _ in range(concurrency)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    latencies.sort()

    def q(p: float) -> float:
        if not latencies:
            return float("nan")
        return latencies[min(int(p * len(latencies)), len(latencies) - 1)] * 1e3

    out = {
        "events": events,
        "batchSize": batch_size,
        "requests": n_requests,
        "concurrency": concurrency,
        "acked": acked[0],
        "errors": len(errors),
        "shed": shed[0],
        "wallSec": round(wall, 3),
        "eventsPerSec": round(acked[0] / wall, 1) if wall > 0 else 0.0,
        "ackP50Ms": round(q(0.50), 3),
        "ackP99Ms": round(q(0.99), 3),
    }
    if kill_after_s is not None:
        out["killAfterSec"] = kill_after_s
        out["stopPosted"] = stop_state["posted"]
        out["afterStop"] = after_stop[0]
    return out
