"""Serving load test: concurrent queries against a deployed engine.

The p50-predict-latency companion to bench.py's training throughput
(BASELINE.md headline metrics). Fires N concurrent workers at
``/queries.json`` and reports client-side latency quantiles + QPS; the
server's own histogram (its ``GET /`` route) gives the service-side view.

Each worker holds ONE persistent HTTP/1.1 connection (keep-alive) for its
whole run — the realistic client shape (SDKs pool connections), and the
only shape that measures the server rather than the TCP handshake: a
fresh connect per request adds a connect+thread-spawn tax that dwarfs
sub-millisecond serve times.  A failed request closes and re-opens the
worker's connection; the failure is still counted.
"""

from __future__ import annotations

import http.client
import json
import threading
import time
import urllib.parse


def scrape_metrics(url: str, timeout: float = 10.0) -> dict:
    """Scrape ``GET /metrics`` off the server under test and return the
    parsed series as ``{(name, ((label, value), ...)): value}``.

    The load test's client-side quantiles say what callers experienced;
    the scrape says what the server *did* (batch occupancy, fastpath
    compile count, shed counters).  Run it after the load so the deltas
    reflect the run.  Raises on transport errors or an invalid
    exposition — a loadtest that can't trust its telemetry should say so
    rather than report half a picture.
    """
    from predictionio_tpu.obs.metrics import parse_prometheus

    parsed = urllib.parse.urlsplit(url)
    host = parsed.hostname
    port = parsed.port or (443 if parsed.scheme == "https" else 80)
    conn_cls = (
        http.client.HTTPSConnection
        if parsed.scheme == "https"
        else http.client.HTTPConnection
    )
    conn = conn_cls(host, port, timeout=timeout)
    try:
        conn.request("GET", (parsed.path.rstrip("/") or "") + "/metrics")
        resp = conn.getresponse()
        body = resp.read().decode("utf-8", "replace")
        if resp.status != 200:
            raise RuntimeError(f"GET /metrics -> HTTP {resp.status}")
        return parse_prometheus(body)
    finally:
        conn.close()


def summarize_metrics(series: dict) -> dict:
    """Condense a :func:`scrape_metrics` result to the handful of series a
    loadtest report cares about (JSON-friendly, stable keys)."""

    def total(name: str) -> float:
        return sum(v for (n, _), v in series.items() if n == name)

    out = {
        "seriesCount": len(series),
        "httpRequests": total("pio_http_requests_total"),
        "fastpathCompiles": total("pio_fastpath_compiles_total"),
        "batcherQueries": total("pio_batcher_queries_total"),
        "eventsIngested": total("pio_events_ingested_total"),
    }
    for (name, labels), v in sorted(series.items()):
        if name.endswith("_breaker_state"):
            out.setdefault("breakerStates", {})[
                ",".join(f"{k}={val}" for k, val in labels)
            ] = v
    return out


def _schedule_stop(
    parsed, conn_cls, kill_after_s: float, stop_state: dict,
    timeout: float = 5.0,
) -> threading.Timer:
    """``--kill-after``: POST /stop at the server mid-run so the load test
    exercises graceful drain under live traffic. ``stop_state['posted']``
    flips once the stop landed; workers then classify connection failures
    as ``afterStop`` instead of errors (an intentionally-stopped server
    refusing connections is the expected outcome, not a failure)."""
    host = parsed.hostname
    port = parsed.port or (443 if parsed.scheme == "https" else 80)
    path = (parsed.path.rstrip("/") or "") + "/stop"

    def _post_stop():
        conn = conn_cls(host, port, timeout=timeout)
        try:
            conn.request("POST", path, body=b"")
            conn.getresponse().read()
            stop_state["posted"] = True
        except Exception as e:
            stop_state["error"] = str(e)
        finally:
            conn.close()

    timer = threading.Timer(kill_after_s, _post_stop)
    timer.daemon = True
    timer.start()
    return timer


def run_loadtest(
    url: str,
    query: dict,
    requests: int = 200,
    concurrency: int = 8,
    timeout: float = 30.0,
    samples: dict = None,
    deadline_ms: float = None,
    kill_after_s: float = None,
) -> dict:
    """``samples`` maps a query FIELD to a list of values; request ``i``
    sends the query with ``field = values[i % len(values)]`` (round-robin,
    deterministic). One fixed payload measures one warm jit path and one
    hot cache line — p50 flatters; mixed keys are what tail latency
    means. Without ``samples`` the single payload is sent verbatim.

    ``deadline_ms`` attaches an ``X-Request-Deadline`` budget to every
    request; the server sheds (503) or deadline-504s what it can't serve
    in time, and both are broken out of ``errors`` in the result."""
    latencies: list[float] = []
    errors: list[str] = []
    shed = [0]  # 503: admission control turned the request away
    deadline_exceeded = [0]  # 504: budget lapsed before/while serving
    after_stop = [0]  # failures once --kill-after stopped the server
    stop_state: dict = {"posted": False}
    lock = threading.Lock()
    counter = {"next": 0}

    parsed = urllib.parse.urlsplit(url)
    host = parsed.hostname
    port = parsed.port or (443 if parsed.scheme == "https" else 80)
    path = (parsed.path.rstrip("/") or "") + "/queries.json"
    conn_cls = (
        http.client.HTTPSConnection
        if parsed.scheme == "https"
        else http.client.HTTPConnection
    )
    if kill_after_s is not None:
        _schedule_stop(parsed, conn_cls, kill_after_s, stop_state)
    headers = {"Content-Type": "application/json"}
    if deadline_ms is not None:
        headers["X-Request-Deadline"] = f"{deadline_ms:g}"

    fixed_payload = json.dumps(query).encode()

    def payload_for(i: int) -> bytes:
        if not samples:
            return fixed_payload
        q = dict(query)
        for field, values in samples.items():
            q[field] = values[i % len(values)]
        return json.dumps(q).encode()

    def worker():
        conn = conn_cls(host, port, timeout=timeout)
        try:
            while True:
                with lock:
                    if counter["next"] >= requests:
                        return
                    i = counter["next"]
                    counter["next"] += 1
                body = payload_for(i)
                t0 = time.perf_counter()
                try:
                    conn.request("POST", path, body=body, headers=headers)
                    resp = conn.getresponse()
                    resp.read()  # drain so the connection can be reused
                    if resp.status == 503:
                        with lock:
                            shed[0] += 1
                        continue  # shed, not broken: connection stays warm
                    if resp.status == 504:
                        with lock:
                            deadline_exceeded[0] += 1
                        continue
                    if resp.status >= 400:
                        raise RuntimeError(f"HTTP {resp.status}")
                    with lock:
                        latencies.append(time.perf_counter() - t0)
                except Exception as e:
                    with lock:
                        if stop_state["posted"]:
                            after_stop[0] += 1
                        else:
                            errors.append(str(e))
                    conn.close()  # next request reconnects cleanly
        finally:
            conn.close()

    threads = [threading.Thread(target=worker) for _ in range(concurrency)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    latencies.sort()

    def q(p: float) -> float:
        if not latencies:
            return float("nan")
        return latencies[min(int(p * len(latencies)), len(latencies) - 1)] * 1e3

    out = {
        "requests": requests,
        "concurrency": concurrency,
        "ok": len(latencies),
        "errors": len(errors),
        "shed": shed[0],
        "deadlineExceeded": deadline_exceeded[0],
        "wallSec": round(wall, 3),
        "qps": round(len(latencies) / wall, 1) if wall > 0 else 0.0,
        "p50Ms": round(q(0.50), 3),
        "p90Ms": round(q(0.90), 3),
        "p99Ms": round(q(0.99), 3),
    }
    if kill_after_s is not None:
        out["killAfterSec"] = kill_after_s
        out["stopPosted"] = stop_state["posted"]
        out["afterStop"] = after_stop[0]
    return out


def run_ingest_loadtest(
    url: str,
    access_key: str,
    events: int = 1000,
    concurrency: int = 8,
    batch_size: int = 1,
    timeout: float = 30.0,
    event_template: dict = None,
    channel: str = None,
    kill_after_s: float = None,
) -> dict:
    """Ingest-side load test: POST events at a live Event Server.

    ``batch_size=1`` drives ``POST /events.json`` (one event per request
    — the write-behind buffer's shape); larger sizes drive
    ``POST /batch/events.json`` with ``batch_size`` events per request
    (the vectorized endpoint's shape).  Entity ids rotate per event so the
    workload isn't one hot row.  Latency quantiles are per-REQUEST ack
    times; ``eventsPerSec`` is the headline ingest throughput.  503s count
    as ``shed`` (buffer backpressure), not errors, mirroring
    :func:`run_loadtest`.
    """
    template = dict(event_template or {
        "event": "rate",
        "entityType": "user",
        "targetEntityType": "item",
        "properties": {"rating": 5},
    })
    batch_size = max(1, int(batch_size))
    n_requests = (events + batch_size - 1) // batch_size

    latencies: list[float] = []
    errors: list[str] = []
    shed = [0]
    acked = [0]
    after_stop = [0]
    stop_state: dict = {"posted": False}
    lock = threading.Lock()
    counter = {"next": 0}

    parsed = urllib.parse.urlsplit(url)
    host = parsed.hostname
    port = parsed.port or (443 if parsed.scheme == "https" else 80)
    qs = urllib.parse.urlencode(
        {"accessKey": access_key, **({"channel": channel} if channel else {})}
    )
    path = (parsed.path.rstrip("/") or "") + (
        "/batch/events.json" if batch_size > 1 else "/events.json"
    ) + "?" + qs
    conn_cls = (
        http.client.HTTPSConnection
        if parsed.scheme == "https"
        else http.client.HTTPConnection
    )
    if kill_after_s is not None:
        _schedule_stop(parsed, conn_cls, kill_after_s, stop_state)
    headers = {"Content-Type": "application/json"}

    def payload_for(i: int) -> tuple[bytes, int]:
        lo = i * batch_size
        n = min(batch_size, events - lo)
        items = [
            dict(template, entityId=f"u{lo + j}", targetEntityId=f"i{(lo + j) % 97}")
            for j in range(n)
        ]
        body = items if batch_size > 1 else items[0]
        return json.dumps(body).encode(), n

    def worker():
        conn = conn_cls(host, port, timeout=timeout)
        try:
            while True:
                with lock:
                    if counter["next"] >= n_requests:
                        return
                    i = counter["next"]
                    counter["next"] += 1
                body, n = payload_for(i)
                t0 = time.perf_counter()
                try:
                    conn.request("POST", path, body=body, headers=headers)
                    resp = conn.getresponse()
                    raw = resp.read()
                    if resp.status == 503:
                        with lock:
                            shed[0] += 1
                        continue
                    if resp.status >= 400:
                        raise RuntimeError(f"HTTP {resp.status}")
                    ok_items = n
                    if batch_size > 1:
                        ok_items = sum(
                            1 for r in json.loads(raw.decode())
                            if r.get("status") in (201, 202)
                        )
                    with lock:
                        latencies.append(time.perf_counter() - t0)
                        acked[0] += ok_items
                except Exception as e:
                    with lock:
                        if stop_state["posted"]:
                            after_stop[0] += 1
                        else:
                            errors.append(str(e))
                    conn.close()
        finally:
            conn.close()

    threads = [threading.Thread(target=worker) for _ in range(concurrency)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    latencies.sort()

    def q(p: float) -> float:
        if not latencies:
            return float("nan")
        return latencies[min(int(p * len(latencies)), len(latencies) - 1)] * 1e3

    out = {
        "events": events,
        "batchSize": batch_size,
        "requests": n_requests,
        "concurrency": concurrency,
        "acked": acked[0],
        "errors": len(errors),
        "shed": shed[0],
        "wallSec": round(wall, 3),
        "eventsPerSec": round(acked[0] / wall, 1) if wall > 0 else 0.0,
        "ackP50Ms": round(q(0.50), 3),
        "ackP99Ms": round(q(0.99), 3),
    }
    if kill_after_s is not None:
        out["killAfterSec"] = kill_after_s
        out["stopPosted"] = stop_state["posted"]
        out["afterStop"] = after_stop[0]
    return out
