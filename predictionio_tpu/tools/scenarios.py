"""Traffic-scenario engine: time-varying load programs for ``pio loadtest``.

:func:`~predictionio_tpu.tools.loadtest.run_loadtest` drives constant
closed-loop traffic; real serving load is diurnal, spiky, and
adversarial (ROADMAP item 4).  This module models that as a **scenario
program**: an ordered list of phases, each a time-varying arrival-rate
shape with optional workload-skew dynamics, compiled to a deterministic
open-loop arrival schedule and replayed against a live server with
per-phase SLO accounting (p50/p99/shed/error per segment).

DSL (``--scenario``): phases are ``;``-separated, each phase is
``kind:key=val,key=val`` — the same spelling as the fault-plan DSL::

    steady:rate=30,duration=6;flash:base=30,peak=300,at=2,duration=12

Phase kinds:

* ``steady`` — constant ``rate`` req/s.
* ``ramp`` — linear ``start`` → ``end`` req/s over the phase.
* ``sine`` — diurnal shape: ``base + amp * sin(2πt/period)``, floored
  at 0 (one ``period`` = one compressed "day").
* ``flash`` — flash crowd: ``base`` until ``at`` seconds in, then a
  step to ``peak`` (default ``10 × base``) for ``hold`` seconds
  (default: the rest of the phase), then back to ``base``.
* ``zipfdrift`` — constant ``rate`` while the Zipf exponent of sampled
  keys drifts ``s0`` → ``s1`` (a hot set heating up or dissolving —
  stresses the skew-aware caches).
* ``mixshift`` — constant ``rate`` while the traffic mix between two
  tenant halves of the sample values shifts ``from`` → ``to`` (share
  of the first half).

Everything up to the actual HTTP replay is pure math on a simulated
clock — :meth:`ScenarioProgram.arrivals` and the payload schedule are
deterministic given the seed, which is what the tier-1 smoke tests
exercise without a single sleep.
"""

from __future__ import annotations

import http.client
import json
import math
import threading
import time
import urllib.parse
from dataclasses import dataclass, field
from typing import Optional

from predictionio_tpu.tools.loadtest import zipf_mandelbrot_weights

KINDS = ("steady", "ramp", "sine", "flash", "zipfdrift", "mixshift")

#: Hard cap on one program's compiled arrival schedule — a typo'd
#: ``rate=30000`` should fail loudly, not allocate forever.
MAX_ARRIVALS = 200_000


@dataclass
class Phase:
    """One segment of a scenario program.  ``rate_at``/``zipf_s_at``/
    ``mix_at`` take the phase-local time in ``[0, duration_s)``."""

    kind: str
    duration_s: float
    params: dict = field(default_factory=dict)
    name: str = ""

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(
                f"unknown scenario kind {self.kind!r}; one of {KINDS}"
            )
        if self.duration_s <= 0:
            raise ValueError(f"phase {self.kind}: duration must be > 0")
        if not self.name:
            self.name = self.kind

    def _p(self, key: str, default=None) -> Optional[float]:
        v = self.params.get(key, default)
        return None if v is None else float(v)

    def rate_at(self, t: float) -> float:
        p = self._p
        if self.kind == "steady":
            return max(0.0, p("rate", 10.0))
        if self.kind == "ramp":
            frac = min(1.0, max(0.0, t / self.duration_s))
            return max(
                0.0, p("start", 1.0) + (p("end", 10.0) - p("start", 1.0)) * frac
            )
        if self.kind == "sine":
            base = p("base", 10.0)
            amp = p("amp", base * 0.5)
            period = p("period", self.duration_s)
            return max(0.0, base + amp * math.sin(2 * math.pi * t / period))
        if self.kind == "flash":
            base = p("base", 10.0)
            at = p("at", self.duration_s / 3.0)
            hold = p("hold", self.duration_s - at)
            if at <= t < at + hold:
                return max(0.0, p("peak", base * 10.0))
            return max(0.0, base)
        # zipfdrift / mixshift hold their rate constant; the *workload*
        # moves instead
        return max(0.0, p("rate", 10.0))

    def zipf_s_at(self, t: float) -> Optional[float]:
        if self.kind != "zipfdrift":
            return self._p("zipf_s")
        frac = min(1.0, max(0.0, t / self.duration_s))
        s0 = self._p("s0", 1.1)
        s1 = self._p("s1", 1.1)
        return s0 + (s1 - s0) * frac

    def mix_at(self, t: float) -> Optional[float]:
        """Share of the FIRST tenant half of the sample values, or None
        when this phase doesn't shift the mix."""
        if self.kind != "mixshift":
            return None
        frac = min(1.0, max(0.0, t / self.duration_s))
        lo = self._p("from", 0.9)
        hi = self._p("to", 0.1)
        return min(1.0, max(0.0, lo + (hi - lo) * frac))


class ScenarioProgram:
    """Phases glued end to end on one clock, compiled to arrivals."""

    def __init__(self, phases: list[Phase]):
        if not phases:
            raise ValueError("a scenario needs at least one phase")
        self.phases = list(phases)
        self._starts: list[float] = []
        acc = 0.0
        for ph in self.phases:
            self._starts.append(acc)
            acc += ph.duration_s
        self.duration_s = acc

    def phase_at(self, t: float) -> tuple[int, Phase, float]:
        """(index, phase, phase-local time) for global time ``t``;
        times past the end clamp to the last phase."""
        for i in range(len(self.phases) - 1, -1, -1):
            if t >= self._starts[i]:
                return i, self.phases[i], t - self._starts[i]
        return 0, self.phases[0], 0.0

    def rate_at(self, t: float) -> float:
        i, ph, lt = self.phase_at(t)
        return ph.rate_at(lt)

    def arrivals(self, max_requests: int = MAX_ARRIVALS) -> list:
        """The compiled schedule: ``[(t, phase_index), ...]`` — request
        n fires 1/rate after request n-1, rates sampled at emit time.
        Pure math, deterministic, no clock reads."""
        out: list[tuple[float, int]] = []
        t = 0.0
        while t < self.duration_s:
            i, ph, lt = self.phase_at(t)
            rate = ph.rate_at(lt)
            if rate <= 0.0:
                t += 0.05  # idle gap: re-sample the shape 20x/s
                continue
            out.append((t, i))
            if len(out) >= max_requests:
                raise ValueError(
                    f"scenario compiles to more than {max_requests} "
                    "arrivals; lower the rates or durations"
                )
            t += 1.0 / rate
        return out

    def describe(self) -> list[dict]:
        return [
            {
                "name": ph.name,
                "kind": ph.kind,
                "startS": round(self._starts[i], 3),
                "endS": round(self._starts[i] + ph.duration_s, 3),
                "params": {k: v for k, v in ph.params.items() if k != "name"},
            }
            for i, ph in enumerate(self.phases)
        ]


def parse_scenario(spec: str) -> ScenarioProgram:
    """``--scenario`` DSL → program.  Phases are ``;``-separated
    ``kind:key=val,key=val`` chunks; every numeric param is a float,
    ``name=`` labels the phase in the per-segment report."""
    phases = []
    for chunk in spec.split(";"):
        chunk = chunk.strip()
        if not chunk:
            continue
        kind, sep, rest = chunk.partition(":")
        kind = kind.strip()
        params: dict = {}
        name = ""
        if sep:
            for pair in rest.split(","):
                pair = pair.strip()
                if not pair:
                    continue
                k, psep, v = pair.partition("=")
                if not psep:
                    raise ValueError(
                        f"bad scenario token {pair!r} in {chunk!r}"
                    )
                k = k.strip()
                if k == "name":
                    name = v.strip()
                else:
                    params[k] = float(v)
        duration = params.pop("duration", 10.0)
        phases.append(
            Phase(kind=kind, duration_s=duration, params=params, name=name)
        )
    return ScenarioProgram(phases)


def _build_payloads(
    program: ScenarioProgram,
    arrivals: list,
    query: dict,
    samples: Optional[dict],
    seed: int,
    zipf_q: float,
) -> list:
    """Pre-draw every request body so worker interleaving can't change
    the workload (the same contract run_loadtest keeps).  Zipf weights
    are cached per (n, s rounded to 2 decimals) — a drift re-weighs at
    most ~100 times, not once per request."""
    if not samples:
        body = json.dumps(query).encode()
        return [body] * len(arrivals)
    import numpy as np

    rng = np.random.default_rng(seed)
    weight_cache: dict = {}
    out = []
    for i, (t, pidx) in enumerate(arrivals):
        ph = program.phases[pidx]
        lt = t - program._starts[pidx]
        q = dict(query)
        for fname, values in samples.items():
            share = ph.mix_at(lt)
            s = ph.zipf_s_at(lt)
            if share is not None and len(values) >= 2:
                half = len(values) // 2
                pool = values[:half] if rng.random() < share else values[half:]
                v = pool[int(rng.integers(len(pool)))]
            elif s is not None:
                key = (len(values), round(s, 2))
                if key not in weight_cache:
                    weight_cache[key] = zipf_mandelbrot_weights(
                        len(values), key[1], zipf_q
                    )
                v = values[int(rng.choice(len(values), p=weight_cache[key]))]
            else:
                v = values[i % len(values)]
            q[fname] = v
        out.append(json.dumps(q).encode())
    return out


def run_scenario(
    url: str,
    query: dict,
    program: ScenarioProgram,
    samples: Optional[dict] = None,
    concurrency: int = 16,
    timeout: float = 30.0,
    deadline_ms: Optional[float] = None,
    seed: int = 0,
    zipf_q: float = 50.0,
    slo_p99_ms: Optional[float] = None,
) -> dict:
    """Replay a scenario program against a live ``/queries.json``.

    Open-loop: requests fire at their compiled arrival times (a worker
    that falls behind fires immediately — lateness is reported, never
    silently absorbed into the shape).  503s count as ``shed`` and 504s
    as ``deadlineExceeded`` per phase, mirroring run_loadtest; with
    ``slo_p99_ms`` each phase gets a ``sloHeld`` verdict (p99 within
    bound AND zero errors) and the summary ANDs them.
    """
    arrivals = program.arrivals()
    payloads = _build_payloads(
        program, arrivals, query, samples, seed, zipf_q
    )
    parsed = urllib.parse.urlsplit(url)
    host = parsed.hostname
    port = parsed.port or (443 if parsed.scheme == "https" else 80)
    path = (parsed.path.rstrip("/") or "") + "/queries.json"
    conn_cls = (
        http.client.HTTPSConnection
        if parsed.scheme == "https"
        else http.client.HTTPConnection
    )
    headers = {"Content-Type": "application/json"}
    if deadline_ms is not None:
        headers["X-Request-Deadline"] = f"{deadline_ms:g}"

    nphase = len(program.phases)
    lock = threading.Lock()
    counter = {"next": 0}
    lat = [[] for _ in range(nphase)]  # successful latencies (s)
    shed = [0] * nphase
    deadline_x = [0] * nphase
    errors: list[list] = [[] for _ in range(nphase)]
    late = [0.0]  # worst scheduled-vs-actual fire lag

    def worker(t0: float):
        conn = conn_cls(host, port, timeout=timeout)
        try:
            while True:
                with lock:
                    if counter["next"] >= len(arrivals):
                        return
                    i = counter["next"]
                    counter["next"] += 1
                sched_t, pidx = arrivals[i]
                delay = (t0 + sched_t) - time.perf_counter()
                if delay > 0:
                    time.sleep(delay)
                else:
                    with lock:
                        late[0] = max(late[0], -delay)
                t1 = time.perf_counter()
                try:
                    conn.request(
                        "POST", path, body=payloads[i], headers=headers
                    )
                    resp = conn.getresponse()
                    resp.read()
                    if resp.status == 503:
                        with lock:
                            shed[pidx] += 1
                        continue
                    if resp.status == 504:
                        with lock:
                            deadline_x[pidx] += 1
                        continue
                    if resp.status >= 400:
                        raise RuntimeError(f"HTTP {resp.status}")
                    dt = time.perf_counter() - t1
                    with lock:
                        lat[pidx].append(dt)
                except Exception as e:
                    with lock:
                        errors[pidx].append(str(e))
                    conn.close()
        finally:
            conn.close()

    t0 = time.perf_counter()
    threads = [
        threading.Thread(target=worker, args=(t0,), name=f"scenario-{w}")
        for w in range(concurrency)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0

    def q(sorted_lats: list, p: float) -> float:
        if not sorted_lats:
            return float("nan")
        i = min(int(p * len(sorted_lats)), len(sorted_lats) - 1)
        return sorted_lats[i] * 1e3

    offered = [0] * nphase
    for _, pidx in arrivals:
        offered[pidx] += 1
    phase_reports = []
    slo_held_all = True
    worst_p99 = 0.0
    for i, desc in enumerate(program.describe()):
        ls = sorted(lat[i])
        p99 = q(ls, 0.99)
        dur = desc["endS"] - desc["startS"]
        rep = {
            **desc,
            "offered": offered[i],
            "ok": len(ls),
            "errors": len(errors[i]),
            "shed": shed[i],
            "deadlineExceeded": deadline_x[i],
            "p50Ms": round(q(ls, 0.50), 3),
            "p99Ms": round(p99, 3),
            "qps": round(len(ls) / dur, 1) if dur > 0 else 0.0,
        }
        if ls:
            worst_p99 = max(worst_p99, p99)
        if slo_p99_ms is not None:
            held = len(errors[i]) == 0 and (
                not ls or p99 <= slo_p99_ms
            )
            rep["sloHeld"] = held
            slo_held_all = slo_held_all and held
        phase_reports.append(rep)
    out = {
        "requests": len(arrivals),
        "concurrency": concurrency,
        "durationS": round(program.duration_s, 3),
        "wallSec": round(wall, 3),
        "worstLagS": round(late[0], 3),
        "ok": sum(len(l) for l in lat),
        "errors": sum(len(e) for e in errors),
        "shed": sum(shed),
        "deadlineExceeded": sum(deadline_x),
        "worstP99Ms": round(worst_p99, 3),
        "phases": phase_reports,
    }
    if slo_p99_ms is not None:
        out["sloP99Ms"] = slo_p99_ms
        out["sloHeld"] = slo_held_all
    err_samples = [e for es in errors for e in es[:3]]
    if err_samples:
        out["errorSamples"] = err_samples[:5]
    return out
