"""Dashboard: HTML list of evaluation instances with per-instance results.

Parity: ``tools/.../dashboard/Dashboard.scala:45-160`` — an HTML index of
completed evaluations plus ``evaluator_results.{txt,html,json}`` per instance
(``Dashboard.scala:112-154``).
"""

from __future__ import annotations

import html
from typing import Optional

from predictionio_tpu import obs
from predictionio_tpu.common.http import HttpService, Response, json_response
from predictionio_tpu.data.storage.registry import Storage


class Dashboard:
    def __init__(self, storage: Optional[Storage] = None,
                 telemetry: bool = True):
        self.storage = storage or Storage.instance()
        self.service = HttpService("dashboard")
        self.telemetry = (
            obs.Telemetry("dashboard").install(self.service)
            if telemetry and obs.telemetry_enabled()
            else None
        )
        self._register()

    CORS_HEADERS = {  # parity: tools/dashboard/CorsSupport.scala
        "Access-Control-Allow-Origin": "*",
        "Access-Control-Allow-Methods": "GET, OPTIONS",
        "Access-Control-Allow-Headers": "Content-Type",
    }

    def _register(self):
        svc = self.service
        storage = self.storage

        _orig_dispatch = svc.dispatch

        def dispatch_with_cors(req):
            resp = _orig_dispatch(req)
            resp.headers.update(self.CORS_HEADERS)
            return resp

        svc.dispatch = dispatch_with_cors

        @svc.route("GET", r"/")
        def index(req):
            rows = []
            for i in storage.get_meta_data_evaluation_instances().get_completed():
                rows.append(
                    f"<tr><td>{html.escape(i.id)}</td>"
                    f"<td>{html.escape(i.evaluation_class)}</td>"
                    f"<td>{i.start_time:%Y-%m-%d %H:%M:%S}</td>"
                    f"<td>{i.end_time:%Y-%m-%d %H:%M:%S}</td>"
                    f"<td><a href='/engine_instances/{i.id}/evaluator_results.txt'>txt</a> "
                    f"<a href='/engine_instances/{i.id}/evaluator_results.html'>html</a> "
                    f"<a href='/engine_instances/{i.id}/evaluator_results.json'>json</a>"
                    f"</td></tr>"
                )
            body = (
                "<html><head><title>Evaluation Dashboard</title></head><body>"
                "<h1>Evaluation Instances</h1>"
                "<table border='1'><tr><th>ID</th><th>Evaluation</th>"
                "<th>Start</th><th>End</th><th>Results</th></tr>"
                + "".join(rows)
                + "</table></body></html>"
            )
            return Response(200, body)

        @svc.route(
            "GET", r"/engine_instances/(?P<iid>[^/]+)/evaluator_results\.(?P<fmt>\w+)"
        )
        def results(req):
            inst = storage.get_meta_data_evaluation_instances().get(
                req.match.group("iid")
            )
            if inst is None:
                return json_response(404, {"message": "not found"})
            fmt = req.match.group("fmt")
            if fmt == "txt":
                return Response(200, inst.evaluator_results, content_type="text/plain")
            if fmt == "html":
                return Response(200, inst.evaluator_results_html)
            if fmt == "json":
                return Response(
                    200, inst.evaluator_results_json, content_type="application/json"
                )
            return json_response(404, {"message": f"unknown format {fmt}"})

    def start(self, host: str = "127.0.0.1", port: int = 9000) -> int:
        return self.service.start(host, port)

    def stop(self) -> None:
        self.service.stop()
