"""Event export/import as JSON-lines files.

Parity: ``tools/.../export/EventsToFile.scala`` and
``imprt/FileToEvents.scala`` (the ``pio export`` / ``pio import`` verbs) —
one Event JSON per line, the reference's interchange format.
"""

from __future__ import annotations

from typing import Optional

from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage.registry import Storage


def _channel_id(storage: Storage, app_id: int, channel: Optional[str]) -> Optional[int]:
    if channel is None:
        return None
    match = [
        c
        for c in storage.get_meta_data_channels().get_by_app_id(app_id)
        if c.name == channel
    ]
    if not match:
        raise ValueError(f"channel {channel!r} not found for app {app_id}")
    return match[0].id


def export_events(
    storage: Storage, app_id: int, output_path: str, channel: Optional[str] = None
) -> tuple[int, str]:
    """Stream the columnar bulk read out as JSON lines (rows built lazily).

    Multi-host (``pio launch -- export``): the reference's export is a
    Spark job writing ``part-NNNNN`` files; here each process pulls its
    1/N of the rows with row-keyed DAO shard pushdown and writes
    ``<output>.part-<i>`` — N hosts each scan and serialize 1/N.
    Returns (rows written by THIS process, the path it wrote).
    """
    from predictionio_tpu.parallel import distributed

    channel_id = _channel_id(storage, app_id, channel)
    pid, n_procs = distributed.process_slot()
    shard = (pid, n_procs) if n_procs > 1 else None
    # the FALLIBLE scan runs before output hygiene: a failed export must
    # leave the previous good export files untouched
    batch = storage.get_p_events().find(
        app_id, channel_id=channel_id, shard=shard
    )
    # part-file path + stale-output hygiene: the shared distributed-writer
    # contract (see distributed.shard_output_path)
    _, _, output_path = distributed.shard_output_path(output_path)
    n = 0
    with open(output_path, "w") as f:
        for e in batch:  # EventBatch materializes one row at a time
            f.write(e.to_json() + "\n")
            n += 1
    return n, output_path


IMPORT_CHUNK = 10_000


def import_events(
    storage: Storage, app_id: int, input_path: str, channel: Optional[str] = None
) -> int:
    """Chunked inserts: bounded memory however large the file is.

    Multi-host (``pio launch -- import``): the reference's FileToEvents is
    a Spark job too — each process here inserts the lines with
    ``line_index % N == process_index`` (events carry their eventIds, so
    the split is exact and re-imports stay idempotent). Point the storage
    at a shared backend (`network` driver or a shared filesystem) and N
    hosts ingest concurrently.
    """
    from predictionio_tpu.parallel import distributed

    pid, n_procs = distributed.process_slot()
    channel_id = _channel_id(storage, app_id, channel)
    le = storage.get_l_events()
    le.init(app_id, channel_id)
    n = 0
    chunk: list[Event] = []
    with open(input_path) as f:
        for line_no, line in enumerate(f):
            if n_procs > 1 and line_no % n_procs != pid:
                continue
            line = line.strip()
            if not line:
                continue
            chunk.append(Event.from_json(line))
            if len(chunk) >= IMPORT_CHUNK:
                le.batch_insert(chunk, app_id, channel_id)
                n += len(chunk)
                chunk = []
    if chunk:
        le.batch_insert(chunk, app_id, channel_id)
        n += len(chunk)
    return n
