"""Multi-host launch orchestration — the ``Runner.runOnSpark`` role.

The reference CLI never runs workloads in-process: it builds a
``spark-submit`` argv and lets Spark place executors across the cluster
(``tools/src/main/scala/org/apache/predictionio/tools/Runner.scala:185-334``).
The TPU-native equivalent has no cluster manager in the middle — one
process per host runs the SAME program under the ``jax.distributed``
SPMD contract (``parallel/distributed.py``):

    PIO_COORDINATOR=host0:port PIO_NUM_PROCESSES=N PIO_PROCESS_ID=i pio <verb>

``pio launch`` materializes that contract two ways:

* **local mode** (default): spawn all N processes on this machine —
  exercising real cross-process collectives (the Spark ``local[N]`` role,
  and exactly how a single multi-chip host runs).
* **--hosts h0,h1,...**: print the per-host command lines (host 0 is the
  coordinator) for the operator's parallel-ssh tooling; this image has no
  ssh, and the reference similarly delegates placement (to Spark).

Every line of a worker's output is prefixed ``[p<i>] `` so interleaved
logs stay attributable; exit status is 0 only if every worker exited 0
(signal-killed workers report negative codes and still fail the launch).
"""

from __future__ import annotations

import os
import shlex
import subprocess
import sys
import threading
import uuid
from typing import Optional, Sequence

WORKER_PREFIX = "[p{index}] "


def worker_env(
    base_env: dict,
    coordinator: str,
    num_processes: int,
    process_id: int,
    run_id: Optional[str] = None,
) -> dict:
    env = dict(base_env)
    env.update(
        {
            "PIO_COORDINATOR": coordinator,
            "PIO_NUM_PROCESSES": str(num_processes),
            "PIO_PROCESS_ID": str(process_id),
        }
    )
    if run_id is not None:
        # launch-scoped id shared by every worker: scopes cross-host
        # rendezvous artifacts (sharded-ingest map exchange) per run
        env["PIO_RUN_ID"] = run_id
    return env


def _pump(proc: subprocess.Popen, index: int, out) -> None:
    prefix = WORKER_PREFIX.format(index=index)
    for line in proc.stdout:
        out.write(prefix + line)
        out.flush()


def launch_local(
    pio_args: Sequence[str],
    num_processes: int,
    coordinator_port: int,
    env: Optional[dict] = None,
    out=None,
) -> int:
    """Run ``pio <pio_args>`` as N coordinated local processes.

    Returns 0 iff every worker exited 0. Signal-killed workers report
    negative codes on POSIX (SIGKILL=-9, SIGSEGV=-11), so ``max()`` alone
    would mask a dead worker whenever any sibling exited 0; instead any
    nonzero code — positive or negative — fails the launch, and the
    failing process indices are logged with their raw codes. A worker
    that dies takes the rendezvous with it, so the rest exit too rather
    than hanging forever — jax.distributed's barrier sees the drop.
    """
    out = out or sys.stdout
    base = dict(env if env is not None else os.environ)
    coordinator = f"127.0.0.1:{coordinator_port}"
    run = uuid.uuid4().hex[:12]
    procs: list[subprocess.Popen] = []
    pumps: list[threading.Thread] = []
    for i in range(num_processes):
        p = subprocess.Popen(
            [sys.executable, "-m", "predictionio_tpu.tools.cli", *pio_args],
            env=worker_env(base, coordinator, num_processes, i, run_id=run),
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        procs.append(p)
        t = threading.Thread(target=_pump, args=(p, i, out), daemon=True)
        t.start()
        pumps.append(t)
    rcs = [p.wait() for p in procs]
    for t in pumps:
        t.join(timeout=5)
    return aggregate_exit_codes(rcs, out)


def aggregate_exit_codes(rcs: Sequence[int], out=None) -> int:
    """Collapse per-worker exit codes into the launch exit code.

    0 only when EVERY worker exited 0 — ``max()`` would hide signal-killed
    workers (negative POSIX codes: SIGKILL=-9, SIGSEGV=-11) behind any
    sibling's 0. Negative codes map to 1 (shells can't carry them).
    """
    out = out or sys.stdout
    failed = [(i, rc) for i, rc in enumerate(rcs) if rc != 0]
    if not failed:
        return 0
    for i, rc in failed:
        out.write(f"ERROR: process {i} exited with code {rc}\n")
    out.flush()
    first = failed[0][1]
    return first if first > 0 else 1


def render_host_commands(
    pio_args: Sequence[str],
    hosts: Sequence[str],
    coordinator_port: int,
) -> list[str]:
    """Per-host command lines; hosts[0] is the coordinator."""
    coordinator = f"{hosts[0]}:{coordinator_port}"
    quoted = " ".join(shlex.quote(a) for a in pio_args)
    run = uuid.uuid4().hex[:12]
    lines = [
        "# PIO_RUN_ID scopes the run's cross-host rendezvous state; it must "
        "be IDENTICAL on every host\n"
        "# and FRESH per launch attempt — re-render (or substitute a new "
        "shared id) before re-running."
    ]
    for i, host in enumerate(hosts):
        lines.append(
            f"# on {host}:\n"
            f"PIO_COORDINATOR={coordinator} "
            f"PIO_NUM_PROCESSES={len(hosts)} "
            f"PIO_PROCESS_ID={i} "
            f"PIO_RUN_ID={run} pio {quoted}"
        )
    return lines
