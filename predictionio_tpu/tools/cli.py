"""``pio`` CLI: the operator surface.

Parity: ``tools/.../console/Console.scala:134-827`` verb tree (app/accesskey/
channel CRUD, train, deploy, undeploy, eval, batchpredict, eventserver,
adminserver, dashboard, status, export, import, build, version).  Structural
difference from the reference: no spark-submit hop — ``train``/``deploy`` run
in-process against the device mesh (``Runner.runOnSpark`` has no equivalent;
SURVEY.md §7).

Usage: ``python -m predictionio_tpu.tools.cli <verb> ...`` (or the ``pio``
console script).
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys
from typing import Optional

from predictionio_tpu import __version__

logger = logging.getLogger("pio")


def _storage():
    from predictionio_tpu.data.storage.registry import Storage

    return Storage.instance()


def _die(msg: str, code: int = 1) -> int:
    print(f"[ERROR] {msg}", file=sys.stderr)
    return code


# -- engine.json handling ----------------------------------------------------


def load_variant(args) -> dict:
    engine_dir = getattr(args, "engine_dir", None) or os.getcwd()
    path = getattr(args, "variant", None) or os.path.join(engine_dir, "engine.json")
    if not os.path.exists(path):
        raise FileNotFoundError(
            f"{path} not found. Run from an engine directory or pass --variant."
        )
    # user engine code lives beside engine.json (parity: `pio build` compiles
    # the engine directory) — make it importable for engineFactory resolution
    for p in (engine_dir, os.path.dirname(os.path.abspath(path))):
        if p and p not in sys.path:
            sys.path.insert(0, p)
    with open(path) as f:
        variant = json.load(f)
    if "engineFactory" not in variant:
        raise ValueError(f"{path} has no engineFactory field")
    return variant


def engine_identity(variant: dict) -> tuple[str, str, str]:
    """(engine_id, engine_version, engine_variant) from the variant JSON."""
    return (
        variant.get("engineId", variant["engineFactory"]),
        variant.get("engineVersion", "default"),
        variant.get("id", "default"),
    )


def resolve_engine_from_variant(variant: dict):
    from predictionio_tpu.core.workflow import resolve_engine

    return resolve_engine(variant["engineFactory"])


def make_ctx(variant: dict):
    from predictionio_tpu.parallel import distributed
    from predictionio_tpu.parallel.mesh import MeshContext

    distributed.initialize()  # no-op unless PIO_COORDINATOR is set
    conf = variant.get("mesh") or {}
    return MeshContext.create(conf=conf)


def load_plugins(paths: list[str], group: Optional[str] = None) -> list:
    """Explicit ``--plugin dotted.path.Class`` instances + auto-discovered
    entry-point/PIO_PLUGINS plugins (the ServiceLoader role,
    EngineServerPluginContext.scala:34-97 — serving/plugins.py)."""
    from predictionio_tpu.core.persistence import resolve_class
    from predictionio_tpu.serving.plugins import ENGINE_GROUP, discover_plugins

    explicit = [resolve_class(p)() for p in paths or []]
    seen = {type(p) for p in explicit}
    return explicit + [
        p
        for p in discover_plugins(group or ENGINE_GROUP)
        if type(p) not in seen
    ]


BUILTIN_TEMPLATES = {
    "recommendation": "predictionio_tpu.templates.recommendation.RecommendationEngine",
    "classification": "predictionio_tpu.templates.classification.ClassificationEngine",
    "similarproduct": "predictionio_tpu.templates.similarproduct.SimilarProductEngine",
    "similaruser": "predictionio_tpu.templates.similaruser.SimilarUserEngine",
    "ecommercerecommendation": "predictionio_tpu.templates.ecommerce.ECommerceEngine",
    "sequentialrecommendation": (
        "predictionio_tpu.templates.sequentialrecommendation."
        "SequentialRecommendationEngine"
    ),
    "universalrecommender": "predictionio_tpu.templates.universal.UniversalRecommenderEngine",
    "python": "predictionio_tpu.pypio.PythonEngine",
}


# -- verbs --------------------------------------------------------------------


def cmd_version(args) -> int:
    print(__version__)
    return 0


def cmd_status(args) -> int:
    # parity: `pio status` → Storage.verifyAllDataObjects smoke check
    try:
        storage = _storage()
        for repo, (source, stype) in sorted(storage.repository_bindings().items()):
            print(f"[INFO] {repo:<9} -> source {source} (type {stype})")
        ok = storage.verify_all_data_objects()
    except Exception as e:
        return _die(f"Unable to connect to all storage backends: {e}")
    if ok:
        print("[INFO] All storage backends are properly configured.")
        print("Your system is all ready to go.")
        return 0
    return _die("Storage verification failed.")


def cmd_build(args) -> int:
    """Compile check: resolve the engine factory and bind the variant params."""
    variant = load_variant(args)
    engine = resolve_engine_from_variant(variant)
    engine.params_from_variant(variant)
    print(f"[INFO] Engine {variant['engineFactory']} is ready for training.")
    return 0


def cmd_app(args) -> int:
    from predictionio_tpu.data.storage.base import AccessKey, App, Channel

    storage = _storage()
    apps = storage.get_meta_data_apps()
    keys = storage.get_meta_data_access_keys()
    channels = storage.get_meta_data_channels()

    if args.app_command == "new":
        app_id = apps.insert(App(0, args.name, args.description))
        if app_id is None:
            return _die(f"App {args.name} already exists.")
        storage.get_l_events().init(app_id)
        key = keys.insert(AccessKey(args.access_key or "", app_id, []))
        print(f"[INFO] App created: ID {app_id}, Name {args.name}.")
        print(f"[INFO] Access Key: {key}")
        return 0
    if args.app_command == "list":
        print(f"{'ID':>4} {'Name':<24} Access Key")
        for app in apps.get_all():
            for k in keys.get_by_app_id(app.id) or [None]:
                print(f"{app.id:>4} {app.name:<24} {k.key if k else '-'}")
        return 0
    if args.app_command == "show":
        app = apps.get_by_name(args.name)
        if app is None:
            return _die(f"App {args.name} does not exist.")
        print(f"[INFO] App: ID {app.id}, Name {app.name}, Desc {app.description}")
        for k in keys.get_by_app_id(app.id):
            allowed = "(all)" if not k.events else ",".join(k.events)
            print(f"[INFO] Access Key: {k.key} | Events: {allowed}")
        for c in channels.get_by_app_id(app.id):
            print(f"[INFO] Channel: ID {c.id}, Name {c.name}")
        return 0
    if args.app_command == "delete":
        app = apps.get_by_name(args.name)
        if app is None:
            return _die(f"App {args.name} does not exist.")
        for c in channels.get_by_app_id(app.id):
            storage.get_l_events().remove(app.id, c.id)
            channels.delete(c.id)
        storage.get_l_events().remove(app.id)
        for k in keys.get_by_app_id(app.id):
            keys.delete(k.key)
        apps.delete(app.id)
        print(f"[INFO] App {args.name} deleted.")
        return 0
    if args.app_command == "data-delete":
        app = apps.get_by_name(args.name)
        if app is None:
            return _die(f"App {args.name} does not exist.")
        if args.channel:
            match = [
                c for c in channels.get_by_app_id(app.id) if c.name == args.channel
            ]
            if not match:
                return _die(f"Channel {args.channel} does not exist.")
            storage.get_l_events().remove(app.id, match[0].id)
            storage.get_l_events().init(app.id, match[0].id)
        else:
            storage.get_l_events().remove(app.id)
            storage.get_l_events().init(app.id)
        print(f"[INFO] Data of app {args.name} deleted.")
        return 0
    if args.app_command == "channel-new":
        app = apps.get_by_name(args.name)
        if app is None:
            return _die(f"App {args.name} does not exist.")
        cid = channels.insert(Channel(0, args.channel, app.id))
        if cid is None:
            return _die(f"Invalid channel name {args.channel}.")
        storage.get_l_events().init(app.id, cid)
        print(f"[INFO] Channel created: ID {cid}, Name {args.channel}.")
        return 0
    if args.app_command == "channel-delete":
        app = apps.get_by_name(args.name)
        if app is None:
            return _die(f"App {args.name} does not exist.")
        match = [c for c in channels.get_by_app_id(app.id) if c.name == args.channel]
        if not match:
            return _die(f"Channel {args.channel} does not exist.")
        storage.get_l_events().remove(app.id, match[0].id)
        channels.delete(match[0].id)
        print(f"[INFO] Channel {args.channel} deleted.")
        return 0
    return _die(f"unknown app command {args.app_command}")


def cmd_accesskey(args) -> int:
    from predictionio_tpu.data.storage.base import AccessKey

    storage = _storage()
    keys = storage.get_meta_data_access_keys()
    if args.ak_command == "new":
        app = storage.get_meta_data_apps().get_by_name(args.app_name)
        if app is None:
            return _die(f"App {args.app_name} does not exist.")
        key = keys.insert(AccessKey("", app.id, args.event or []))
        print(f"[INFO] Access Key: {key}")
        return 0
    if args.ak_command == "list":
        for k in keys.get_all():
            print(f"{k.key} | app {k.app_id} | events {k.events or '(all)'}")
        return 0
    if args.ak_command == "delete":
        if keys.delete(args.key):
            print("[INFO] Deleted.")
            return 0
        return _die("Key not found.")
    return _die(f"unknown accesskey command {args.ak_command}")


def cmd_launch(args) -> int:
    """Multi-host/process launch (Runner.runOnSpark role, Runner.scala:185)."""
    from predictionio_tpu.tools import launcher

    pio_args = list(args.pio_args)
    if pio_args and pio_args[0] == "--":
        pio_args = pio_args[1:]
    if not pio_args:
        print("[ERROR] launch needs a pio command after --", file=sys.stderr)
        return 1
    if args.hosts:
        hosts = [h.strip() for h in args.hosts.split(",") if h.strip()]
        for line in launcher.render_host_commands(
            pio_args, hosts, args.coordinator_port
        ):
            print(line)
        return 0
    rc = launcher.launch_local(
        pio_args,
        num_processes=args.num_processes,
        coordinator_port=args.coordinator_port,
    )
    if rc == 0:
        print(f"[INFO] all {args.num_processes} processes completed")
    else:
        print(f"[ERROR] a worker failed (exit {rc})", file=sys.stderr)
    return rc


def cmd_train(args) -> int:
    from predictionio_tpu.core.workflow import WorkflowParams, run_train

    variant = load_variant(args)
    engine = resolve_engine_from_variant(variant)
    engine_params = engine.params_from_variant(variant)
    engine_id, engine_version, engine_variant = engine_identity(variant)
    ctx = make_ctx(variant)
    wp = WorkflowParams(
        batch=args.batch or "",
        skip_sanity_check=args.skip_sanity_check,
        stop_after_read=args.stop_after_read,
        stop_after_prepare=args.stop_after_prepare,
    )
    instance_id = run_train(
        engine,
        engine_params,
        engine_factory=variant["engineFactory"],
        storage=_storage(),
        ctx=ctx,
        workflow_params=wp,
        engine_id=engine_id,
        engine_version=engine_version,
        engine_variant=engine_variant,
    )
    print(f"[INFO] Training completed. Engine instance ID: {instance_id}")
    return 0


def cmd_eval(args) -> int:
    from predictionio_tpu.core.evaluation import run_evaluation

    # an explicit variant supplies the mesh configuration for the eval run
    variant = load_variant(args) if (args.variant or args.engine_dir) else None
    result = run_evaluation(
        evaluation_class=args.evaluation_class,
        engine_params_generator_class=args.engine_params_generator_class,
        storage=_storage(),
        ctx=make_ctx(variant) if variant else None,
        batch=args.batch or "",
        output_path=args.output_best,
    )
    print(f"[INFO] Evaluation completed. Instance ID: {result.instance_id}")
    print(result.summary)
    if args.output_best:
        print(f"[INFO] Best engine params written to {args.output_best}")
    return 0


def _install_drain_handler(server) -> None:
    """SIGTERM → graceful drain → clean exit (the orchestrator contract:
    a TERM'd server finishes in-flight work inside PIO_DRAIN_TIMEOUT_MS
    and exits 0, instead of dropping it on the floor)."""
    import signal

    def _term(signum, frame):
        server.drain()
        raise SystemExit(0)

    try:
        signal.signal(signal.SIGTERM, _term)
    except ValueError:
        pass  # not the main thread (embedded use): skip


def _child_deploy_argv(args, port: int) -> list[str]:
    """Re-exec this CLI as a single-replica ``deploy`` child on ``port``
    (fleet mode: the parent becomes the router, children do the serving)."""
    argv = [
        sys.executable, "-m", "predictionio_tpu.tools.cli", "deploy",
        "--ip", "127.0.0.1", "--port", str(port),
    ]
    if getattr(args, "engine_dir", None):
        argv += ["--engine-dir", args.engine_dir]
    if getattr(args, "variant", None):
        argv += ["--variant", args.variant]
    if args.feedback:
        argv += [
            "--feedback",
            "--event-server-ip", args.event_server_ip,
            "--event-server-port", str(args.event_server_port),
        ]
    if args.accesskey:
        argv += ["--accesskey", args.accesskey]
    for p in args.plugin:
        argv += ["--plugin", p]
    if args.batching:
        argv += ["--batching"]
    return argv


def _deploy_fleet(args) -> int:
    """``pio deploy --fleet N``: N replica subprocesses on ports
    port+1..port+N behind a health-checked, hedging router on ``port``,
    supervised for crash-restart and rolling deploys.  With
    ``--autoscale`` (or ``PIO_AUTOSCALE=1``) an autoscaler control loop
    grows/shrinks the replica set from the router's own load signals;
    scale-up replicas take the next sequential ports past the initial
    range."""
    import itertools
    import subprocess

    from predictionio_tpu.serving.autoscaler import Autoscaler
    from predictionio_tpu.serving.fleet import FleetSupervisor
    from predictionio_tpu.serving.router import Router

    ports = [args.port + 1 + i for i in range(args.fleet)]
    next_ports = itertools.count(args.port + 1 + args.fleet)

    def spawn(port: int) -> subprocess.Popen:
        return subprocess.Popen(_child_deploy_argv(args, port))

    router = Router([f"http://127.0.0.1:{p}" for p in ports])
    fleet = FleetSupervisor(
        spawn, ports, router=router,
        port_allocator=lambda: next(next_ports),
    )
    router.attach_fleet(fleet)
    # multi-tenant fleet: the router admits per tenant at the edge; the
    # replica subprocesses inherit PIO_TENANTS and enforce the same
    # registry behind it (auth is checked on both hops)
    from predictionio_tpu.serving.tenancy import tenants_from_env

    tenants = tenants_from_env()
    if tenants is not None:
        router.attach_tenants(tenants)
    autoscale = (
        getattr(args, "autoscale", False)
        or os.environ.get("PIO_AUTOSCALE", "0") != "0"
    )
    scaler = None
    if autoscale:
        scaler = Autoscaler(router, fleet)
        router.attach_autoscaler(scaler)
    canary = None
    if (
        getattr(args, "canary", False)
        or os.environ.get("PIO_CANARY", "0") != "0"
    ):
        from predictionio_tpu.serving.canary import CanaryController

        variant = load_variant(args)
        engine_id, engine_version, engine_variant = engine_identity(variant)
        canary = CanaryController(
            router, fleet=fleet, storage=_storage(),
            engine_id=engine_id, engine_version=engine_version,
            engine_variant=engine_variant,
        )
        router.attach_canary(canary)
    fleet.start()
    if scaler is not None:
        scaler.start()
    if canary is not None:
        # finish whatever a killed predecessor left mid-flight (and
        # fence it, should it still be alive somewhere)
        resumed = canary.resume()
        if resumed:
            print(f"[INFO] Canary journal recovered: {resumed}.")
    port = router.start(args.ip, args.port)
    _install_drain_handler(router)
    print(
        f"[INFO] Fleet of {args.fleet} replicas (ports "
        f"{ports[0]}-{ports[-1]}) is deploying behind the router at "
        f"http://{args.ip}:{port}. Roll with `pio fleet roll`."
        + (" Autoscaler is active." if scaler is not None else "")
        + (" Canary controller is armed (`pio canary status`)."
           if canary is not None else "")
    )
    try:
        router.service.serve_forever()
    except KeyboardInterrupt:
        router.shutdown()
    return 0


def cmd_deploy(args) -> int:
    from predictionio_tpu.serving.query_server import QueryServer

    # --tenants / --pipeline publish through the env knobs so fleet
    # replica subprocesses inherit the same registry and pipeline
    if getattr(args, "tenants", None):
        os.environ["PIO_TENANTS"] = args.tenants
    if getattr(args, "pipeline", None):
        os.environ["PIO_PIPELINE"] = args.pipeline
    if getattr(args, "fleet", 0) and args.fleet > 1:
        return _deploy_fleet(args)
    variant = load_variant(args)
    engine = resolve_engine_from_variant(variant)
    engine_id, engine_version, engine_variant = engine_identity(variant)
    qs = QueryServer(
        engine,
        storage=_storage(),
        ctx=make_ctx(variant),
        engine_id=engine_id,
        engine_version=engine_version,
        engine_variant=engine_variant,
        feedback=args.feedback,
        event_server_url=(
            f"http://{args.event_server_ip}:{args.event_server_port}"
            if args.feedback
            else None
        ),
        access_key=args.accesskey,
        plugins=load_plugins(args.plugin),
        batching=args.batching,
    )
    port = qs.start(args.ip, args.port, cert_path=args.cert_path,
                    key_path=args.key_path)
    _install_drain_handler(qs)
    print(f"[INFO] Engine is deployed and running. Engine API is live at "
          f"http://{args.ip}:{port}.")
    try:
        qs.service.serve_forever()
    except KeyboardInterrupt:
        qs.drain()
    return 0


def cmd_fleet(args) -> int:
    """Operate a running fleet router: ``status`` prints the replica
    table; ``roll`` triggers a zero-downtime rolling deploy and waits
    for it to finish."""
    import time as _time
    import urllib.error
    import urllib.request

    base = f"http://{args.ip}:{args.port}"

    def get_fleet() -> dict:
        with urllib.request.urlopen(base + "/fleet", timeout=10) as r:
            return json.loads(r.read().decode("utf-8"))

    try:
        if args.fleet_command == "status":
            print(json.dumps(get_fleet(), indent=2))
            return 0
        # roll
        req = urllib.request.Request(base + "/fleet/roll", method="POST")
        with urllib.request.urlopen(req, timeout=10) as r:
            print(f"[INFO] {json.loads(r.read().decode())['message']}")
        deadline = _time.monotonic() + args.timeout
        while _time.monotonic() < deadline:
            state = get_fleet()
            if not state.get("rolling"):
                print(json.dumps(state, indent=2))
                print("[INFO] Roll complete.")
                return 0
            _time.sleep(0.5)
        return _die(f"roll still in progress after {args.timeout}s")
    except urllib.error.HTTPError as e:
        return _die(f"router answered {e.code}: {e.read().decode()}")
    except OSError as e:
        return _die(f"no router at {base}: {e}")


def cmd_canary(args) -> int:
    """Operate a fleet router's canary controller: ``status`` prints the
    state machine + verdict inputs; ``start`` begins a canary (newest
    non-quarantined candidate, or ``--instance``); ``promote`` skips the
    rest of the window; ``abort`` rolls back WITHOUT quarantining;
    ``quarantine`` lists receipts (``--release ID`` clears one)."""
    import urllib.error
    import urllib.request

    base = f"http://{args.ip}:{args.port}"

    def call(path: str, method: str = "GET", payload: Optional[dict] = None):
        data = json.dumps(payload).encode("utf-8") if payload else b""
        req = urllib.request.Request(
            base + path, method=method,
            data=data if method == "POST" else None,
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=30) as r:
            return json.loads(r.read().decode("utf-8"))

    try:
        cmd = args.canary_command
        if cmd == "status":
            print(json.dumps(call("/canary"), indent=2))
            return 0
        if cmd == "start":
            payload = {}
            if getattr(args, "instance", None):
                payload["instanceId"] = args.instance
            if getattr(args, "force", False):
                payload["force"] = True
            out = call("/canary/start", "POST", payload)
            print(json.dumps(out, indent=2))
            print("[INFO] Canary started; watch `pio canary status`.")
            return 0
        if cmd == "promote":
            print(json.dumps(call("/canary/promote", "POST"), indent=2))
            return 0
        if cmd == "abort":
            print(json.dumps(call("/canary/abort", "POST"), indent=2))
            return 0
        # quarantine
        if getattr(args, "release", None):
            out = call(
                "/canary/quarantine/release", "POST",
                {"instanceId": args.release},
            )
            print(json.dumps(out, indent=2))
            return 0 if out.get("released") else _die(
                f"no quarantine receipt for {args.release}"
            )
        print(json.dumps(call("/canary/quarantine"), indent=2))
        return 0
    except urllib.error.HTTPError as e:
        return _die(f"router answered {e.code}: {e.read().decode()}")
    except OSError as e:
        return _die(f"no router at {base}: {e}")


def cmd_tenants(args) -> int:
    """``pio tenants check|list``: validate a tenant registry config
    offline (check), or print a live server's per-tenant admission /
    variant stats (list)."""
    from predictionio_tpu.serving.tenancy import registry_from_config

    if args.tenants_command == "check":
        source = args.config or os.environ.get("PIO_TENANTS", "")
        if not source:
            return _die("no config: pass --config or set PIO_TENANTS")
        try:
            if source.strip().startswith(("{", "[")):
                config = json.loads(source)
            else:
                with open(source, "r", encoding="utf-8") as f:
                    config = json.load(f)
            reg = registry_from_config(config)
        except (OSError, ValueError) as e:
            return _die(f"invalid tenant config: {e}")
        print(json.dumps(
            {
                "tenants": [s.to_dict() for s in reg.specs()],
                "engineVariants": sorted(reg.engine_variants()),
            },
            indent=2,
        ))
        print(f"[INFO] Tenant config OK ({len(reg.specs())} tenants).")
        return 0
    # list: live server stats
    import urllib.request

    url = f"http://{args.ip}:{args.port}/"
    try:
        with urllib.request.urlopen(url, timeout=10) as r:
            info = json.loads(r.read().decode("utf-8"))
    except OSError as e:
        return _die(f"no server at {url}: {e}")
    tenancy = info.get("tenancy")
    if tenancy is None:
        print("[INFO] Server has no tenant registry (PIO_TENANTS unset).")
        return 0
    print(json.dumps(tenancy, indent=2))
    return 0


def cmd_pipeline(args) -> int:
    """``pio pipeline seal|show``: publish a pipeline JSON config as a
    sealed deployable blob, or open + verify + describe a sealed one."""
    from predictionio_tpu.core.persistence import ModelIntegrityError
    from predictionio_tpu.serving.pipeline import (
        PipelineConfig, load_pipeline, save_pipeline,
    )

    if args.pipeline_command == "seal":
        try:
            with open(args.config, "r", encoding="utf-8") as f:
                config = PipelineConfig.from_dict(json.load(f))
        except (OSError, ValueError, KeyError) as e:
            return _die(f"invalid pipeline config: {e}")
        save_pipeline(config, args.out)
        print(f"[INFO] Sealed pipeline {config.name!r} "
              f"({config.fingerprint}) -> {args.out}. "
              f"Deploy with PIO_PIPELINE={args.out}.")
        return 0
    # show
    try:
        config = load_pipeline(args.path)
    except ModelIntegrityError as e:
        return _die(f"pipeline blob failed integrity check: {e}")
    except (OSError, ValueError) as e:
        return _die(f"cannot load pipeline: {e}")
    print(json.dumps(config.describe(), indent=2))
    return 0


def cmd_undeploy(args) -> int:
    import http.client
    import urllib.request

    url = f"http://{args.ip}:{args.port}/stop"
    try:
        with urllib.request.urlopen(
            urllib.request.Request(url, method="POST"), timeout=5
        ) as r:
            print(f"[INFO] {r.read().decode()}")
        return 0
    except (http.client.RemoteDisconnected, ConnectionResetError):
        # the server can tear the socket down mid-response while shutting
        # down — the stop still happened
        print("[INFO] Server stopped.")
        return 0
    except Exception as e:
        return _die(f"Undeploy failed: {e}")


def cmd_batchpredict(args) -> int:
    from predictionio_tpu.serving.batch_predict import run_batch_predict

    variant = load_variant(args)
    engine = resolve_engine_from_variant(variant)
    engine_id, engine_version, engine_variant = engine_identity(variant)
    n, written = run_batch_predict(
        engine,
        args.input,
        args.output,
        storage=_storage(),
        ctx=make_ctx(variant),
        engine_id=engine_id,
        engine_version=engine_version,
        engine_variant=engine_variant,
    )
    # `written` is the ACTUAL path this process wrote (a .part-<i> file
    # under a multi-host launch), not the requested base path
    print(f"[INFO] Batch predict completed: {n} predictions -> {written}")
    return 0


def cmd_shell(args) -> int:
    """Interactive console with the framework preloaded (parity role:
    bin/pio-shell's sbt console — here a Python REPL with pypio ready)."""
    import code

    from predictionio_tpu import pypio
    from predictionio_tpu.data.store import LEventStore, PEventStore

    ns = {
        "pypio": pypio,
        "PEventStore": PEventStore,
        "LEventStore": LEventStore,
        "storage": _storage(),
    }
    banner = (
        "predictionio_tpu shell — preloaded: pypio, PEventStore, "
        "LEventStore, storage (the PIO_STORAGE_* backends).\n"
        "Start with pypio.init(); try pypio.find_events(app_name=...)."
    )
    code.interact(banner=banner, local=ns, exitmsg="")
    return 0


def cmd_eventserver(args) -> int:
    from predictionio_tpu.data.api.event_server import EventServer

    from predictionio_tpu.serving.plugins import EVENT_GROUP

    es = EventServer(
        storage=_storage(), stats=args.stats,
        plugins=load_plugins(args.plugin, group=EVENT_GROUP),
        ingest_mode=args.ingest_buffer,
        ingest_flush_ms=args.flush_ms,
        ingest_buffer_max=args.buffer_max,
        wal_dir=args.wal_dir,
    )
    port = es.start(args.ip, args.port, cert_path=args.cert_path,
                    key_path=args.key_path)
    _install_drain_handler(es)
    print(f"[INFO] Event Server is listening at http://{args.ip}:{port}")
    try:
        es.service.serve_forever()
    except KeyboardInterrupt:
        es.stop()
    return 0


def cmd_storageserver(args) -> int:
    """Serve the locally-configured storage to other hosts (network driver).

    The data-plane service of the multi-host topology: run it on the host
    owning the data; every other host sets TYPE=network + URL to this
    address (parity role: the Postgres/HBase server in the reference stack).
    """
    from predictionio_tpu.data.storage.network import StorageServer

    server = StorageServer(storage=_storage(), secret=args.secret)
    port = server.start(args.ip, args.port, allow_insecure=args.allow_insecure,
                        cert_path=args.cert_path, key_path=args.key_path)
    print(f"[INFO] Storage Server is listening at http://{args.ip}:{port}")
    try:
        server.service.serve_forever()
    except KeyboardInterrupt:
        server.stop()
    return 0


def cmd_adminserver(args) -> int:
    from predictionio_tpu.tools.admin import AdminServer

    server = AdminServer(storage=_storage())
    port = server.start(args.ip, args.port)
    print(f"[INFO] Admin Server is listening at http://{args.ip}:{port}")
    try:
        server.service.serve_forever()
    except KeyboardInterrupt:
        server.stop()
    return 0


def cmd_dashboard(args) -> int:
    from predictionio_tpu.tools.dashboard import Dashboard

    server = Dashboard(storage=_storage())
    port = server.start(args.ip, args.port)
    print(f"[INFO] Dashboard is listening at http://{args.ip}:{port}")
    try:
        server.service.serve_forever()
    except KeyboardInterrupt:
        server.stop()
    return 0


def cmd_template(args) -> int:
    # parity: `pio template list/get` — templates ship in-tree here
    if args.template_command == "list":
        for name, factory in BUILTIN_TEMPLATES.items():
            print(f"{name:<26} {factory}")
        return 0
    if args.template_command == "get":
        name = args.name
        if name not in BUILTIN_TEMPLATES:
            return _die(f"Unknown template {name}. Try `pio template list`.")
        factory = BUILTIN_TEMPLATES[name]
        os.makedirs(args.directory or name, exist_ok=True)
        path = os.path.join(args.directory or name, "engine.json")
        with open(path, "w") as f:
            json.dump(
                {
                    "id": "default",
                    "description": f"{name} template",
                    "engineFactory": factory,
                    "datasource": {"params": {"appName": "CHANGE_ME"}},
                    "algorithms": [],
                },
                f,
                indent=2,
            )
        print(f"[INFO] Engine skeleton created at {path}")
        return 0
    return _die(f"unknown template command {args.template_command}")


def cmd_run(args) -> int:
    """Parity: `pio run <main-class>` — execute a dotted callable in-process."""
    from predictionio_tpu.core.persistence import resolve_class

    obj = resolve_class(args.main)
    result = obj(*args.args) if callable(obj) else None
    if result is not None:
        print(result)
    return 0


def cmd_instances(args) -> int:
    """Field-query train/eval runs (the Elasticsearch METADATA search
    role, ESEngineInstances.scala:28-120) — `pio instances --status
    COMPLETED --text als --limit 5`."""
    from predictionio_tpu.data.event import parse_time_or_none

    storage = _storage()
    kwargs = dict(
        status=args.status,
        since=parse_time_or_none(args.since) if args.since else None,
        until=parse_time_or_none(args.until) if args.until else None,
        text=args.text,
        limit=args.limit,
    )
    if args.eval:
        if args.variant:
            return _die("--variant does not apply to --eval instances")
        dao = storage.get_meta_data_evaluation_instances()
        rows = dao.query(evaluation_class=args.factory, **kwargs)
        cols = ["id", "status", "start_time", "evaluation_class", "batch"]
    else:
        dao = storage.get_meta_data_engine_instances()
        rows = dao.query(engine_factory=args.factory,
                         engine_variant=args.variant, **kwargs)
        cols = ["id", "status", "start_time", "engine_factory",
                "engine_variant", "batch"]
    if args.json:
        out = [
            {c: (str(getattr(i, c)) if c == "start_time" else getattr(i, c))
             for c in cols}
            for i in rows
        ]
        print(json.dumps(out))
        return 0
    header = "  ".join(f"{c:<20}" for c in cols)
    print(header)
    for i in rows:
        print("  ".join(f"{str(getattr(i, c)):<20.20}" for c in cols))
    print(f"[INFO] {len(rows)} instance(s)")
    return 0


def cmd_shards(args) -> int:
    """Inspect or rebuild a published model's ShardingPlan.

    ``show`` reads the sealed plan.blob beside a checkpoint-persisted
    model's factors; ``rebuild`` re-balances the item→shard assignment
    offline and republishes it through the same atomic sealed-blob
    machinery (tmp+fsync+rename), so a live server picks the new plan up
    on its next ``POST /reload`` — or falls back to its last-known-good
    generation if the rewrite was torn mid-flight.
    """
    import os
    import pickle

    from predictionio_tpu.serving import sharding as _sharding
    from predictionio_tpu.utils.fs import pio_base_dir

    base = os.path.join(pio_base_dir(), "persistent_models")

    def plan_path(iid: str) -> str:
        return os.path.join(base, iid, "plan.blob")

    if args.shards_command == "show":
        if args.instance:
            instances = [args.instance]
        elif os.path.isdir(base):
            instances = sorted(os.listdir(base))
        else:
            instances = []
        rows = []
        for iid in instances:
            p = plan_path(iid)
            if not os.path.exists(p):
                if args.instance:
                    print(f"[INFO] {iid}: no sharding plan (replicated)")
                continue
            try:
                plan = _sharding.load_plan(p)
                rows.append({"instance": iid, **plan.describe()})
            except Exception as e:
                rows.append({"instance": iid, "error": str(e)})
        print(json.dumps(rows, indent=2))
        return 0

    # rebuild
    iid = args.instance
    d = os.path.join(base, iid)
    maps_path = os.path.join(d, "maps.pkl")
    if not os.path.exists(maps_path):
        return _die(f"no checkpoint-persisted model at {d}")
    from predictionio_tpu.core.checkpoint import restore_pytree

    factors = restore_pytree(os.path.join(d, "factors"))
    V = factors["item_factors"]
    n_items = int(V.shape[0])
    bytes_per_item = float(V.shape[1]) * 4.0
    weights = None
    if args.weights == "norm":
        import numpy as np

        weights = np.linalg.norm(np.asarray(V, np.float32), axis=1)
    try:
        plan = _sharding.build_plan(
            n_items,
            n_shards=args.shards,
            weights=weights,
            strategy=args.strategy,
            capacity_budget_bytes=args.budget,
            bytes_per_item=bytes_per_item,
            host_groups=getattr(args, "host_groups", 1),
        )
    except ValueError as e:
        return _die(f"cannot build plan: {e}")
    _sharding.save_plan(plan_path(iid), plan)
    with open(maps_path, "rb") as f:
        meta = pickle.load(f)
    meta["sharding"] = {
        "n_shards": plan.n_shards,
        "strategy": plan.strategy,
        "fingerprint": plan.fingerprint,
        "host_groups": plan.host_groups,
    }
    tmp = f"{maps_path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        pickle.dump(meta, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, maps_path)
    print(json.dumps({"instance": iid, **plan.describe()}, indent=2))
    print(
        "[INFO] Plan resealed. POST /reload on the serving deployment to "
        "pick it up (the LKG machinery guards the swap)."
    )
    return 0


def cmd_ivf(args) -> int:
    """Inspect or rebuild a published model's IVF retrieval index.

    ``show`` reads the sealed ivf.blob beside a checkpoint-persisted
    model's factors; ``rebuild`` retrains the k-means coarse partition
    offline, re-runs the recall@10 publish gate against the exact
    ranking, and — only if it clears the threshold — republishes the
    index through the same atomic sealed-blob machinery as ``pio shards
    rebuild``, so a live server picks it up on ``POST /reload``.  A
    below-threshold rebuild refuses and leaves the deployed artifacts
    untouched.
    """
    import os
    import pickle

    from predictionio_tpu.ops import ivf as _ivf
    from predictionio_tpu.utils.fs import pio_base_dir

    base = os.path.join(pio_base_dir(), "persistent_models")

    def index_path(iid: str) -> str:
        return os.path.join(base, iid, "ivf.blob")

    if args.ivf_command == "show":
        if args.instance:
            instances = [args.instance]
        elif os.path.isdir(base):
            instances = sorted(os.listdir(base))
        else:
            instances = []
        rows = []
        for iid in instances:
            p = index_path(iid)
            if not os.path.exists(p):
                if args.instance:
                    print(f"[INFO] {iid}: no IVF index (exact retrieval)")
                continue
            try:
                index = _ivf.load_index(p)
                rows.append({"instance": iid, **index.describe()})
            except Exception as e:
                rows.append({"instance": iid, "error": str(e)})
        print(json.dumps(rows, indent=2))
        return 0

    # rebuild
    iid = args.instance
    d = os.path.join(base, iid)
    maps_path = os.path.join(d, "maps.pkl")
    if not os.path.exists(maps_path):
        return _die(f"no checkpoint-persisted model at {d}")
    from predictionio_tpu.core.checkpoint import restore_pytree

    factors = restore_pytree(os.path.join(d, "factors"))
    U, V = factors["user_factors"], factors["item_factors"]
    try:
        index = _ivf.build_index(V, args.nlist, nprobe=args.nprobe)
    except ValueError as e:
        return _die(f"cannot build IVF index: {e}")
    k = min(10, int(V.shape[0]))
    threshold = float(
        args.min_recall
        if args.min_recall is not None
        else os.environ.get("PIO_IVF_MIN_RECALL", "0.95")
    )
    recall = _ivf.measure_recall(U, V, index, k=k)
    if recall < threshold:
        return _die(
            f"IVF rebuild REFUSED: recall@{k} {recall:.4f} < "
            f"{threshold:.4f}; the deployed index is untouched"
        )
    import dataclasses

    index = dataclasses.replace(
        index, recall_at_publish=recall,
        recall_threshold=threshold, recall_k=k,
    )
    _ivf.save_index(index_path(iid), index)
    with open(maps_path, "rb") as f:
        meta = pickle.load(f)
    meta["ivf"] = {
        "nlist": index.nlist, "nprobe": index.nprobe,
        "recall": recall, "threshold": threshold, "k": k,
        "fingerprint": index.fingerprint,
    }
    tmp = f"{maps_path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        pickle.dump(meta, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, maps_path)
    print(json.dumps({"instance": iid, **index.describe()}, indent=2))
    print(
        "[INFO] Index resealed. POST /reload on the serving deployment to "
        "pick it up (the LKG machinery guards the swap)."
    )
    return 0


def cmd_loadtest(args) -> int:
    from predictionio_tpu.tools.loadtest import run_ingest_loadtest, run_loadtest

    url = f"http://{args.ip}:{args.port}"

    def attach_metrics(result: dict) -> dict:
        if not args.scrape_metrics:
            return result
        from predictionio_tpu.tools.loadtest import (
            scrape_metrics, summarize_metrics,
        )
        try:
            result["serverMetrics"] = summarize_metrics(scrape_metrics(url))
        except Exception as e:  # report, don't fail the loadtest itself
            result["serverMetrics"] = {"error": str(e)}
        return result

    if args.events:
        # ingest mode: hammer a live Event Server instead of a query server
        if not args.access_key:
            print("[ERROR] --events mode needs --access-key")
            return 1
        result = run_ingest_loadtest(
            url=url,
            access_key=args.access_key,
            events=args.events,
            concurrency=args.concurrency,
            batch_size=args.batch_size,
            channel=args.channel,
            kill_after_s=args.kill_after,
        )
        print(json.dumps(attach_metrics(result)))
        return 0 if result["errors"] == 0 else 1
    samples = {}
    for spec in args.sample or []:
        field, _, vals = spec.partition("=")
        # drop empties (trailing comma) so '' never enters the rotation
        values = [v for v in vals.split(",") if v]
        if not field or not values:
            print(f"[ERROR] --sample expects FIELD=v1,v2,..., got {spec!r}")
            return 1
        samples[field] = values
    if args.scenario:
        # scenario mode: a time-varying traffic program with per-phase
        # SLO accounting instead of constant closed-loop load
        from predictionio_tpu.tools.scenarios import (
            parse_scenario, run_scenario,
        )
        try:
            program = parse_scenario(args.scenario)
        except ValueError as e:
            print(f"[ERROR] bad --scenario: {e}")
            return 1
        result = run_scenario(
            url=url,
            query=json.loads(args.query),
            program=program,
            samples=samples or None,
            concurrency=args.concurrency,
            deadline_ms=args.deadline_ms,
            seed=args.seed,
            zipf_q=args.zipf_q,
            slo_p99_ms=args.slo_p99_ms,
        )
        print(json.dumps(attach_metrics(result)))
        ok = result["errors"] == 0 and result.get("sloHeld", True)
        return 0 if ok else 1
    result = run_loadtest(
        url=url,
        query=json.loads(args.query),
        requests=args.requests,
        concurrency=args.concurrency,
        samples=samples or None,
        deadline_ms=args.deadline_ms,
        kill_after_s=args.kill_after,
        dist=args.dist,
        zipf_s=args.zipf_s,
        zipf_q=args.zipf_q,
    )
    print(json.dumps(attach_metrics(result)))
    return 0 if result["errors"] == 0 else 1


def cmd_profile(args) -> int:
    """``pio profile``: capture a device profile off a live query server
    while driving load through the capture window, then print the
    utilization picture (MFU / HBM / busy fraction) next to the client
    quantiles.  The capture runs in a background thread so the loadtest
    traffic is what the profiler sees; size ``--requests`` so the run
    outlasts ``--ms`` or the tail of the window profiles an idle server.
    """
    import http.client
    import threading

    from predictionio_tpu.tools.loadtest import (
        run_loadtest, scrape_metrics, summarize_metrics,
    )

    url = f"http://{args.ip}:{args.port}"
    capture: dict = {}

    def _capture() -> None:
        conn = http.client.HTTPConnection(
            args.ip, args.port, timeout=args.ms / 1e3 + 30.0
        )
        try:
            conn.request("POST", f"/debug/profile?ms={args.ms}")
            resp = conn.getresponse()
            body = resp.read().decode("utf-8", "replace")
            if resp.status == 200:
                capture.update(json.loads(body))
            else:
                capture["error"] = f"HTTP {resp.status}: {body[:200]}"
        except Exception as e:
            capture["error"] = str(e)
        finally:
            conn.close()

    t = threading.Thread(target=_capture, name="pio-profile-capture")
    t.start()
    result = run_loadtest(
        url=url,
        query=json.loads(args.query),
        requests=args.requests,
        concurrency=args.concurrency,
    )
    t.join()
    try:
        metrics = summarize_metrics(scrape_metrics(url))
    except Exception as e:
        metrics = {"error": str(e)}

    if capture.get("path"):
        print(f"[INFO] profile trace ({args.ms} ms): {capture['path']}")
    else:
        print(f"[WARN] profile capture failed: {capture.get('error')}")
    print(
        f"[INFO] loadtest: ok={result['ok']} errors={result['errors']} "
        f"qps={result['qps']} p50={result['p50Ms']}ms p99={result['p99Ms']}ms"
    )
    busy = metrics.get("deviceBusyFraction")
    if busy is None:
        print("[WARN] no pio_device_* series on /metrics — the server has "
              "not recorded a cost-annotated dispatch yet")
    else:
        mfu = metrics.get("deviceMfu")
        hbm = metrics.get("deviceHbmUtil")
        gflops = (metrics.get("deviceFlopsPerSec") or 0.0) / 1e9
        print(
            f"[INFO] device: busy={busy * 100:.2f}%  {gflops:.2f} GFLOP/s"
            + (f"  MFU={mfu * 100:.4f}%" if mfu is not None else "")
            + (f"  HBM={metrics.get('deviceHbmGbps'):.3f} GB/s "
               f"({hbm * 100:.4f}% of peak)" if hbm is not None else "")
        )
        if mfu is not None and hbm is not None:
            bound = "HBM-bandwidth" if hbm >= mfu else "compute"
            print(f"[INFO] roofline: {bound}-bound at this batch mix "
                  "(docs/perf_roofline.md has the peak table)")
    if metrics.get("slowTraces") is not None:
        print(f"[INFO] slow traces retained: {int(metrics['slowTraces'])} "
              "(GET /trace/slow.json)")
    print(json.dumps({
        "profile": capture,
        "loadtest": {k: result.get(k)
                     for k in ("ok", "errors", "qps", "p50Ms", "p99Ms")},
        "serverMetrics": metrics,
    }))
    return 0 if capture.get("path") and result["errors"] == 0 else 1


def cmd_upgrade(args) -> int:
    # parity: Console "upgrade" verb — storage schemas here are
    # self-migrating (CREATE IF NOT EXISTS), so this is informational
    print(f"[INFO] predictionio_tpu {__version__}: storage schemas are "
          "current; nothing to upgrade.")
    return 0


def cmd_export(args) -> int:
    from predictionio_tpu.tools.export_import import export_events

    n, written = export_events(
        _storage(), args.appid, args.output, channel=args.channel
    )
    print(f"[INFO] Exported {n} events to {written}")
    return 0


def cmd_import(args) -> int:
    from predictionio_tpu.tools.export_import import import_events

    n = import_events(_storage(), args.appid, args.input, channel=args.channel)
    print(f"[INFO] Imported {n} events.")
    return 0


def _git_changed(root: str) -> set[str]:
    """Repo-relative paths that differ from HEAD, plus untracked files."""
    import subprocess

    paths: set[str] = set()
    for argv in (
        ["git", "diff", "--name-only", "HEAD"],
        ["git", "ls-files", "--others", "--exclude-standard"],
    ):
        out = subprocess.run(
            argv, cwd=root, capture_output=True, text=True, check=False
        )
        if out.returncode != 0:
            raise RuntimeError(
                f"--changed-only needs a git checkout: {out.stderr.strip()}"
            )
        paths.update(p.strip() for p in out.stdout.splitlines() if p.strip())
    return paths


def cmd_analyze(args) -> int:
    import importlib

    from predictionio_tpu.analysis import core

    # import-for-effect: the package __init__ registers every analyzer
    importlib.import_module("predictionio_tpu.analysis")
    if args.list_rules:
        for name in sorted(core.ANALYZER_RULES):
            for rid in core.ANALYZER_RULES[name]:
                r = core.RULES[rid]
                print(f"{rid:28} {r.severity:8} [{name}] {r.summary}")
        return 0
    root = args.root
    names = args.analyzers.split(",") if args.analyzers else None
    changed = _git_changed(root) if args.changed_only else None
    baseline_path = args.baseline or os.path.join(root, core.BASELINE_NAME)
    if args.graph:
        from predictionio_tpu.analysis import lockorder

        index = core.RepoIndex(root)
        print(lockorder.to_dot(index), end="")
        return 0
    if args.prune_baseline:
        index = core.RepoIndex(root)
        removed = core.prune_baseline(baseline_path, index)
        for key in removed:
            print(f"[INFO] pruned stale baseline entry {key}")
        print(f"[INFO] {len(removed)} stale entr"
              f"{'y' if len(removed) == 1 else 'ies'} pruned from "
              f"{baseline_path}")
        return 0
    rep = core.run(
        root,
        analyzers=names,
        # "" never names a file, so a --write-baseline run sees every
        # finding instead of hiding the currently-acknowledged ones
        baseline_path="" if args.write_baseline else baseline_path,
        changed_only=changed,
    )
    if args.write_baseline:
        core.write_baseline(baseline_path, rep.findings)
        print(f"[INFO] Acknowledged {len(rep.findings)} finding(s) in "
              f"{baseline_path}")
        return 0
    if args.format == "json":
        print(json.dumps(rep.to_dict(), indent=2))
    elif args.format == "sarif":
        print(json.dumps(core.to_sarif(rep), indent=2))
    else:
        print(rep.render())
    return 1 if rep.errors else 0


# -- parser --------------------------------------------------------------------


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="pio", description="TPU-native ML serving platform CLI"
    )
    p.add_argument("--verbose", action="store_true")
    sub = p.add_subparsers(dest="command", required=True)

    sub.add_parser("version").set_defaults(func=cmd_version)
    sub.add_parser("status").set_defaults(func=cmd_status)

    def add_engine_args(sp):
        sp.add_argument("--engine-dir", default=None)
        sp.add_argument("--variant", "-v", default=None)

    sp = sub.add_parser("build")
    add_engine_args(sp)
    sp.set_defaults(func=cmd_build)

    sp = sub.add_parser("app")
    app_sub = sp.add_subparsers(dest="app_command", required=True)
    x = app_sub.add_parser("new")
    x.add_argument("name")
    x.add_argument("--description", default=None)
    x.add_argument("--access-key", default=None)
    app_sub.add_parser("list")
    x = app_sub.add_parser("show")
    x.add_argument("name")
    x = app_sub.add_parser("delete")
    x.add_argument("name")
    x = app_sub.add_parser("data-delete")
    x.add_argument("name")
    x.add_argument("--channel", default=None)
    x = app_sub.add_parser("channel-new")
    x.add_argument("name")
    x.add_argument("channel")
    x = app_sub.add_parser("channel-delete")
    x.add_argument("name")
    x.add_argument("channel")
    sp.set_defaults(func=cmd_app)

    sp = sub.add_parser("accesskey")
    ak_sub = sp.add_subparsers(dest="ak_command", required=True)
    x = ak_sub.add_parser("new")
    x.add_argument("app_name")
    x.add_argument("event", nargs="*")
    ak_sub.add_parser("list")
    x = ak_sub.add_parser("delete")
    x.add_argument("key")
    sp.set_defaults(func=cmd_accesskey)

    sp = sub.add_parser("train")
    add_engine_args(sp)
    sp.add_argument("--batch", default="")
    sp.add_argument("--skip-sanity-check", action="store_true")
    sp.add_argument("--stop-after-read", action="store_true")
    sp.add_argument("--stop-after-prepare", action="store_true")
    sp.set_defaults(func=cmd_train)

    sp = sub.add_parser(
        "launch",
        help="run a pio command as N coordinated processes (multi-host "
        "SPMD launch contract; Runner.runOnSpark role)",
    )
    sp.add_argument("-n", "--num-processes", type=int, default=2)
    sp.add_argument("--coordinator-port", type=int, default=7654)
    sp.add_argument(
        "--hosts",
        default=None,
        help="comma-separated host list: print per-host command lines "
        "instead of spawning locally (hosts[0] is the coordinator)",
    )
    sp.add_argument(
        "pio_args",
        nargs=argparse.REMAINDER,
        help="the pio command to launch, after --  (e.g. -- train)",
    )
    sp.set_defaults(func=cmd_launch)

    sp = sub.add_parser("eval")
    sp.add_argument("evaluation_class")
    sp.add_argument("engine_params_generator_class", nargs="?", default=None)
    add_engine_args(sp)
    sp.add_argument("--batch", default="")
    sp.add_argument(
        "--output-best",
        default=None,
        metavar="PATH",
        help="write the best engine params as JSON (parity: "
        "MetricEvaluator.saveEngineJson best.json, MetricEvaluator.scala:193)",
    )
    sp.set_defaults(func=cmd_eval)

    sp = sub.add_parser("deploy")
    add_engine_args(sp)
    sp.add_argument("--ip", default="0.0.0.0")
    sp.add_argument("--port", type=int, default=8000)
    sp.add_argument("--feedback", action="store_true")
    sp.add_argument("--event-server-ip", default="0.0.0.0")
    sp.add_argument("--event-server-port", type=int, default=7070)
    sp.add_argument("--accesskey", default=None)
    sp.add_argument("--plugin", action="append", default=[])
    sp.add_argument("--cert-path", default=None)
    sp.add_argument("--key-path", default=None)
    sp.add_argument("--batching", action="store_true",
                    help="micro-batch concurrent queries into one device pass")
    sp.add_argument(
        "--fleet", type=int, default=0, metavar="N",
        help="serve N replica subprocesses (ports PORT+1..PORT+N) behind "
        "a health-checked, hedging router on PORT",
    )
    sp.add_argument(
        "--autoscale", action="store_true",
        help="with --fleet: scale the replica set up/down from the "
        "router's load signals (PIO_AUTOSCALE_* knobs set the bounds "
        "and thresholds); equivalent to PIO_AUTOSCALE=1",
    )
    sp.add_argument(
        "--canary", action="store_true",
        help="with --fleet: arm the canary controller — `pio canary "
        "start` then rolls ONE replica to a candidate generation, "
        "verifies it against SLOs under real traffic, and promotes or "
        "auto-rolls-back (quarantining the bad generation); equivalent "
        "to PIO_CANARY=1",
    )
    sp.add_argument(
        "--tenants", default=None, metavar="PATH_OR_JSON",
        help="tenant registry config (JSON file or inline): per-tenant "
        "access keys, quotas, SLOs, weights, A/B variants; equivalent "
        "to PIO_TENANTS",
    )
    sp.add_argument(
        "--pipeline", default=None, metavar="PATH_OR_JSON",
        help="composed retrieval->ranking pipeline: sealed blob from "
        "`pio pipeline seal` (or inline JSON for dev); equivalent to "
        "PIO_PIPELINE",
    )
    sp.set_defaults(func=cmd_deploy)

    sp = sub.add_parser(
        "tenants", help="validate tenant configs / inspect live "
        "per-tenant admission and A/B stats"
    )
    tenants_sub = sp.add_subparsers(dest="tenants_command", required=True)
    x = tenants_sub.add_parser(
        "check", help="validate a tenant registry config offline"
    )
    x.add_argument("--config", default=None,
                   help="JSON file or inline JSON (default: PIO_TENANTS)")
    x.set_defaults(func=cmd_tenants)
    x = tenants_sub.add_parser(
        "list", help="print a live server's per-tenant stats"
    )
    x.add_argument("--ip", default="127.0.0.1")
    x.add_argument("--port", type=int, default=8000)
    x.set_defaults(func=cmd_tenants)

    sp = sub.add_parser(
        "pipeline", help="seal or inspect a composed retrieval->ranking "
        "pipeline config"
    )
    pipeline_sub = sp.add_subparsers(dest="pipeline_command", required=True)
    x = pipeline_sub.add_parser(
        "seal", help="publish pipeline JSON as a sealed deployable blob"
    )
    x.add_argument("--config", required=True, help="pipeline JSON file")
    x.add_argument("--out", required=True, help="sealed blob output path")
    x.set_defaults(func=cmd_pipeline)
    x = pipeline_sub.add_parser(
        "show", help="open + verify + describe a sealed pipeline blob"
    )
    x.add_argument("path", help="sealed pipeline blob")
    x.set_defaults(func=cmd_pipeline)

    sp = sub.add_parser(
        "fleet", help="operate a running fleet router (status / roll)"
    )
    fleet_sub = sp.add_subparsers(dest="fleet_command", required=True)
    x = fleet_sub.add_parser("status")
    x.add_argument("--ip", default="127.0.0.1")
    x.add_argument("--port", type=int, default=8000)
    x.set_defaults(func=cmd_fleet)
    x = fleet_sub.add_parser(
        "roll", help="zero-downtime rolling deploy to the latest "
        "trained model generation",
    )
    x.add_argument("--ip", default="127.0.0.1")
    x.add_argument("--port", type=int, default=8000)
    x.add_argument("--timeout", type=float, default=600.0,
                   help="seconds to wait for the roll to finish")
    x.set_defaults(func=cmd_fleet)

    sp = sub.add_parser(
        "canary", help="operate a fleet router's SLO-guarded canary "
        "rollout (status / start / promote / abort / quarantine)"
    )
    canary_sub = sp.add_subparsers(dest="canary_command", required=True)
    for verb, help_text in (
        ("status", "print the canary state machine and verdict inputs"),
        ("start", "canary ONE replica onto a candidate generation"),
        ("promote", "skip the rest of the verification window"),
        ("abort", "roll the canary back WITHOUT quarantining"),
        ("quarantine", "list quarantine receipts (--release ID clears)"),
    ):
        x = canary_sub.add_parser(verb, help=help_text)
        x.add_argument("--ip", default="127.0.0.1")
        x.add_argument("--port", type=int, default=8000)
        if verb == "start":
            x.add_argument(
                "--instance", default=None,
                help="candidate engine instance id (default: newest "
                "non-quarantined COMPLETED generation)",
            )
            x.add_argument(
                "--force", action="store_true",
                help="canary a quarantined candidate anyway",
            )
        if verb == "quarantine":
            x.add_argument(
                "--release", default=None, metavar="INSTANCE_ID",
                help="clear the receipt for this instance id",
            )
        x.set_defaults(func=cmd_canary)

    sp = sub.add_parser(
        "shards", help="inspect or rebuild a published model's sharded-"
        "serving plan",
    )
    shards_sub = sp.add_subparsers(dest="shards_command", required=True)
    x = shards_sub.add_parser(
        "show", help="print the sealed ShardingPlan of one (or every) "
        "checkpoint-persisted model instance",
    )
    x.add_argument("--instance", default=None)
    x.set_defaults(func=cmd_shards)
    x = shards_sub.add_parser(
        "rebuild", help="re-balance the item→shard assignment offline and "
        "reseal plan.blob; a live server adopts it on POST /reload",
    )
    x.add_argument("--instance", required=True)
    x.add_argument("--shards", type=int, default=None,
                   help="explicit shard count")
    x.add_argument("--budget", type=int, default=None,
                   help="per-shard HBM byte budget (derives the count)")
    x.add_argument("--strategy", default="popularity",
                   choices=["popularity", "round_robin", "contiguous"])
    x.add_argument("--weights", default="norm",
                   choices=["norm", "uniform"],
                   help="popularity weights: item-factor L2 norms (the "
                   "traffic proxy) or uniform")
    x.add_argument("--host-groups", type=int, default=1,
                   help="pod host groups: shards partition into this many "
                   "contiguous groups, one per serving host (two-tier "
                   "merge; must divide the shard count)")
    x.set_defaults(func=cmd_shards)

    sp = sub.add_parser(
        "ivf", help="inspect or rebuild a published model's IVF "
        "approximate-retrieval index",
    )
    ivf_sub = sp.add_subparsers(dest="ivf_command", required=True)
    x = ivf_sub.add_parser(
        "show", help="print the sealed IVF index of one (or every) "
        "checkpoint-persisted model instance",
    )
    x.add_argument("--instance", default=None)
    x.set_defaults(func=cmd_ivf)
    x = ivf_sub.add_parser(
        "rebuild", help="retrain the k-means coarse partition offline, "
        "re-run the recall gate, and reseal ivf.blob; a live server "
        "adopts it on POST /reload",
    )
    x.add_argument("--instance", required=True)
    x.add_argument("--nlist", type=int, required=True,
                   help="cluster count for the coarse partition")
    x.add_argument("--nprobe", type=int, default=None,
                   help="default probe count (default: nlist // 8)")
    x.add_argument("--min-recall", type=float, default=None,
                   help="recall@10 gate (default: PIO_IVF_MIN_RECALL "
                   "or 0.95)")
    x.set_defaults(func=cmd_ivf)

    sp = sub.add_parser("undeploy")
    sp.add_argument("--ip", default="127.0.0.1")
    sp.add_argument("--port", type=int, default=8000)
    sp.set_defaults(func=cmd_undeploy)

    sp = sub.add_parser("batchpredict")
    add_engine_args(sp)
    sp.add_argument("--input", required=True)
    sp.add_argument("--output", required=True)
    sp.set_defaults(func=cmd_batchpredict)

    sp = sub.add_parser("eventserver")
    sp.add_argument("--ip", default="0.0.0.0")
    sp.add_argument("--port", type=int, default=7070)
    sp.add_argument("--stats", action="store_true")
    sp.add_argument("--plugin", action="append", default=[])
    sp.add_argument("--cert-path", default=None)
    sp.add_argument("--key-path", default=None)
    sp.add_argument(
        "--ingest-buffer", choices=["off", "durable", "fast"], default=None,
        help="group-commit write-behind for single-event POSTs "
        "(default: PIO_INGEST_BUFFER env or off)",
    )
    sp.add_argument("--flush-ms", type=float, default=None,
                    help="write-behind flush interval (PIO_INGEST_FLUSH_MS)")
    sp.add_argument("--buffer-max", type=int, default=None,
                    help="write-behind capacity; beyond it single-event "
                    "POSTs shed 503 (PIO_INGEST_BUFFER_MAX)")
    sp.add_argument("--wal-dir", default=None,
                    help="fast-mode durability: journal fast-acked events "
                    "to this write-ahead-log directory and replay them on "
                    "startup (PIO_WAL_DIR; fsync via PIO_WAL_FSYNC)")
    sp.set_defaults(func=cmd_eventserver)

    sp = sub.add_parser("storageserver")
    sp.add_argument("--ip", default="0.0.0.0")
    sp.add_argument("--port", type=int, default=7077)
    sp.add_argument("--secret", default=None)
    sp.add_argument("--allow-insecure", action="store_true",
                    help="serve without a secret on non-loopback interfaces")
    sp.add_argument("--cert-path", default=None)
    sp.add_argument("--key-path", default=None)
    sp.set_defaults(func=cmd_storageserver)

    sp = sub.add_parser("adminserver")
    sp.add_argument("--ip", default="127.0.0.1")
    sp.add_argument("--port", type=int, default=7071)
    sp.set_defaults(func=cmd_adminserver)

    sp = sub.add_parser("dashboard")
    sp.add_argument("--ip", default="127.0.0.1")
    sp.add_argument("--port", type=int, default=9000)
    sp.set_defaults(func=cmd_dashboard)

    sp = sub.add_parser(
        "instances",
        help="field-query train/eval runs (the ES metadata-search role)",
    )
    sp.add_argument("--status")
    sp.add_argument("--factory", help="engineFactory (or evaluation class)")
    sp.add_argument("--variant")
    sp.add_argument("--since", help="ISO time lower bound on start_time")
    sp.add_argument("--until", help="ISO time upper bound on start_time")
    sp.add_argument("--text", help="free-text match over params/results")
    sp.add_argument("--limit", type=int)
    sp.add_argument("--eval", action="store_true",
                    help="query evaluation instances instead")
    sp.add_argument("--json", action="store_true")
    sp.set_defaults(func=cmd_instances)

    sp = sub.add_parser("loadtest")
    sp.add_argument("--ip", default="127.0.0.1")
    sp.add_argument("--port", type=int, default=8000)
    sp.add_argument("--query", default='{"user": "u1", "num": 10}')
    sp.add_argument("--requests", type=int, default=200)
    sp.add_argument("--concurrency", type=int, default=8)
    sp.add_argument(
        "--sample", action="append", metavar="FIELD=V1,V2,...",
        help="rotate FIELD through the listed values round-robin, one per "
        "request (mixed-key tail latency instead of one hot payload)",
    )
    sp.add_argument(
        "--dist", choices=("roundrobin", "zipf"), default="roundrobin",
        help="how --sample values are drawn: roundrobin cycles them "
        "evenly; zipf draws Zipf-Mandelbrot skew (early values hottest — "
        "real traffic's shape, what the serving caches exploit) and adds "
        "per-key latency percentiles to the report",
    )
    sp.add_argument(
        "--zipf-s", type=float, default=1.1,
        help="Zipf-Mandelbrot exponent for --dist zipf (higher = hotter "
        "head)",
    )
    sp.add_argument(
        "--zipf-q", type=float, default=50.0,
        help="Zipf-Mandelbrot shift for --dist zipf (higher = flatter "
        "head, like real catalogs)",
    )
    sp.add_argument(
        "--deadline-ms", type=float, default=None,
        help="per-request X-Request-Deadline budget; over-budget requests "
        "are shed by the server (503/504) and reported separately",
    )
    sp.add_argument(
        "--events", type=int, default=None,
        help="ingest mode: POST this many events at an Event Server "
        "(reports events/s + ack p50/p99) instead of querying",
    )
    sp.add_argument("--access-key", default=None,
                    help="access key for --events mode")
    sp.add_argument(
        "--batch-size", type=int, default=1,
        help="--events mode: events per request (1 = /events.json, "
        ">1 = /batch/events.json)",
    )
    sp.add_argument("--channel", default=None,
                    help="--events mode: target channel name")
    sp.add_argument(
        "--scrape-metrics", action="store_true",
        help="after the run, GET /metrics off the server under test and "
        "include a server-side summary (batch occupancy, fastpath "
        "compiles, breaker states) in the JSON report",
    )
    sp.add_argument(
        "--kill-after", type=float, default=None, metavar="SECONDS",
        help="POST /stop to the server this many seconds into the run — "
        "exercises graceful drain under live load; post-stop connection "
        "failures are reported as afterStop, not errors",
    )
    sp.add_argument(
        "--scenario", default=None, metavar="SPEC",
        help="time-varying traffic program instead of constant load: "
        "';'-separated phases of kind:key=val,... (steady, ramp, sine, "
        "flash, zipfdrift, mixshift — see docs/operations.md); reports "
        "p50/p99/shed/error per phase",
    )
    sp.add_argument(
        "--slo-p99-ms", type=float, default=None,
        help="--scenario mode: per-phase p99 SLO bound; each phase gets "
        "a sloHeld verdict and the exit code fails if any phase breaks it",
    )
    sp.add_argument(
        "--seed", type=int, default=0,
        help="--scenario mode: seed for the pre-drawn workload schedule "
        "(zipf draws, tenant-mix picks) — same seed, same workload",
    )
    sp.set_defaults(func=cmd_loadtest)

    sp = sub.add_parser(
        "profile",
        help="capture a device profile off a live query server under "
        "load and print the MFU/HBM/roofline summary",
    )
    sp.add_argument("--ip", default="127.0.0.1")
    sp.add_argument("--port", type=int, default=8000)
    sp.add_argument("--query", default='{"user": "u1", "num": 10}')
    sp.add_argument(
        "--ms", type=int, default=500,
        help="profiler capture window in milliseconds (server caps at 10s)",
    )
    sp.add_argument(
        "--requests", type=int, default=500,
        help="loadtest requests driven through the capture window — size "
        "it so the traffic outlasts --ms",
    )
    sp.add_argument("--concurrency", type=int, default=8)
    sp.set_defaults(func=cmd_profile)

    sub.add_parser("upgrade").set_defaults(func=cmd_upgrade)

    sp = sub.add_parser("template")
    t_sub = sp.add_subparsers(dest="template_command", required=True)
    t_sub.add_parser("list")
    x = t_sub.add_parser("get")
    x.add_argument("name")
    x.add_argument("--directory", default=None)
    sp.set_defaults(func=cmd_template)

    sub.add_parser("shell").set_defaults(func=cmd_shell)

    sp = sub.add_parser("run")
    sp.add_argument("main")
    sp.add_argument("args", nargs="*")
    sp.set_defaults(func=cmd_run)

    sp = sub.add_parser("export")
    sp.add_argument("--appid", type=int, required=True)
    sp.add_argument("--output", required=True)
    sp.add_argument("--channel", default=None)
    sp.set_defaults(func=cmd_export)

    sp = sub.add_parser("import")
    sp.add_argument("--appid", type=int, required=True)
    sp.add_argument("--input", required=True)
    sp.add_argument("--channel", default=None)
    sp.set_defaults(func=cmd_import)

    sp = sub.add_parser(
        "analyze",
        help="whole-repo static analysis: hot-path hazards, races, "
        "knob/metric contract drift (docs/analysis.md)",
    )
    sp.add_argument("--root", default=".",
                    help="repo root to analyze (default: cwd)")
    sp.add_argument("--format", choices=("human", "json", "sarif"),
                    default="human")
    sp.add_argument("--analyzers", default=None,
                    help="comma-separated subset (default: all registered)")
    sp.add_argument(
        "--changed-only", action="store_true",
        help="report only findings in files changed vs HEAD (plus "
        "untracked); analyzers still see the whole repo",
    )
    sp.add_argument("--baseline", default=None,
                    help="baseline path (default: "
                    "<root>/.pio-analysis-baseline.json)")
    sp.add_argument(
        "--write-baseline", action="store_true",
        help="acknowledge every current finding into the baseline "
        "instead of reporting",
    )
    sp.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    sp.add_argument(
        "--graph", choices=("lockorder",), default=None,
        help="dump an analysis graph as DOT instead of findings "
        "(lockorder: the global lock-order graph, cycles in red)",
    )
    sp.add_argument(
        "--prune-baseline", action="store_true",
        help="drop baseline entries whose rule/file/symbol no longer "
        "resolves (reported as baseline-stale warnings otherwise)",
    )
    sp.set_defaults(func=cmd_analyze)

    return p


def main(argv: Optional[list[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    logging.basicConfig(
        level=logging.DEBUG if args.verbose else logging.WARNING,
        format="[%(levelname)s] [%(name)s] %(message)s",
    )
    if os.environ.get("JAX_PLATFORMS"):
        # honor the operator's platform choice before anything can touch a
        # device backend — an unreachable accelerator plugin must not hang
        # CPU-only verbs (see parallel/mesh.pin_platform_from_env)
        from predictionio_tpu.parallel.mesh import pin_platform_from_env

        pin_platform_from_env()
    if os.environ.get("PIO_COORDINATOR"):
        # the multi-host contract requires jax.distributed.initialize()
        # before ANY backend-initializing jax call; engine/template imports
        # can touch the backend, so join the rendezvous first
        from predictionio_tpu.parallel import distributed

        distributed.initialize()
    try:
        return args.func(args)
    except BrokenPipeError:
        # `pio status | head` closing the pipe early is not an error;
        # devnull the streams so interpreter shutdown can't re-raise
        devnull = os.open(os.devnull, os.O_WRONLY)
        os.dup2(devnull, sys.stdout.fileno())
        return 0
    except (FileNotFoundError, ValueError, RuntimeError) as e:
        return _die(str(e))


if __name__ == "__main__":
    sys.exit(main())
