"""Admin server: minimal REST admin plane.

Parity: ``tools/.../admin/AdminAPI.scala:45-130`` + ``CommandClient.scala``
(GET ``/`` status, ``/cmd/app`` list/create/delete routes).
"""

from __future__ import annotations

from typing import Optional

from predictionio_tpu.common.http import HttpService, json_response
from predictionio_tpu.data.storage.base import AccessKey, App
from predictionio_tpu.data.storage.registry import Storage


class AdminServer:
    def __init__(self, storage: Optional[Storage] = None):
        self.storage = storage or Storage.instance()
        self.service = HttpService("adminserver")
        self._register()

    def _register(self):
        svc = self.service
        storage = self.storage

        @svc.route("GET", r"/")
        def index(req):
            return json_response(
                200, {"status": "alive", "description": "admin server"}
            )

        @svc.route("GET", r"/cmd/app")
        def app_list(req):
            apps = storage.get_meta_data_apps().get_all()
            keys = storage.get_meta_data_access_keys()
            return json_response(
                200,
                [
                    {
                        "id": a.id,
                        "name": a.name,
                        "description": a.description,
                        "accessKeys": [k.key for k in keys.get_by_app_id(a.id)],
                    }
                    for a in apps
                ],
            )

        @svc.route("POST", r"/cmd/app")
        def app_new(req):
            data = req.json() or {}
            name = data.get("name")
            if not name:
                return json_response(400, {"message": "name is required"})
            app_id = storage.get_meta_data_apps().insert(
                App(0, name, data.get("description"))
            )
            if app_id is None:
                return json_response(409, {"message": f"app {name} already exists"})
            storage.get_l_events().init(app_id)
            key = storage.get_meta_data_access_keys().insert(
                AccessKey("", app_id, [])
            )
            return json_response(
                201, {"id": app_id, "name": name, "accessKey": key}
            )

        @svc.route("DELETE", r"/cmd/app/(?P<name>[^/]+)")
        def app_delete(req):
            apps = storage.get_meta_data_apps()
            app = apps.get_by_name(req.match.group("name"))
            if app is None:
                return json_response(404, {"message": "app not found"})
            storage.get_l_events().remove(app.id)
            for k in storage.get_meta_data_access_keys().get_by_app_id(app.id):
                storage.get_meta_data_access_keys().delete(k.key)
            apps.delete(app.id)
            return json_response(200, {"message": f"deleted {app.name}"})

        @svc.route("DELETE", r"/cmd/app/(?P<name>[^/]+)/data")
        def app_data_delete(req):
            apps = storage.get_meta_data_apps()
            app = apps.get_by_name(req.match.group("name"))
            if app is None:
                return json_response(404, {"message": "app not found"})
            storage.get_l_events().remove(app.id)
            storage.get_l_events().init(app.id)
            return json_response(200, {"message": f"deleted data of {app.name}"})

    def start(self, host: str = "127.0.0.1", port: int = 7071) -> int:
        return self.service.start(host, port)

    def stop(self) -> None:
        self.service.stop()
