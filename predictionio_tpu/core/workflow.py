"""Workflow executors: train and deploy-preparation entry points.

Parity: ``workflow/CoreWorkflow.scala:45-164`` (runTrain: context → train →
serialize models → EngineInstance COMPLETED) and ``Engine.prepareDeploy``
(``Engine.scala:198-267``).  Key structural difference from the reference:
there is NO spark-submit process hop (``tools/Runner.scala:185-334``) — the
mesh lives in-process, so ``run_train`` is a plain function call from the CLI
(SURVEY.md §7 "spark-submit process hop → in-process train()").
"""

from __future__ import annotations

import dataclasses
import datetime as _dt
import logging
from typing import Optional

from predictionio_tpu.core import persistence
from predictionio_tpu.core.engine import Engine, EngineParams
from predictionio_tpu.data.storage.base import EngineInstance, Model
from predictionio_tpu.data.storage.registry import Storage
from predictionio_tpu.parallel.mesh import MeshContext

logger = logging.getLogger(__name__)

UTC = _dt.timezone.utc


class CleanupFunctions:
    """End-of-workflow hooks (parity: workflow/CleanupFunctions.scala and
    pypio's cleanup_functions): register callables to run when a train or
    evaluation workflow finishes, success or failure."""

    _fns: list = []

    @classmethod
    def add(cls, fn) -> None:
        cls._fns.append(fn)

    @classmethod
    def run(cls) -> None:
        for fn in cls._fns:
            try:
                fn()
            except Exception:
                logger.exception("cleanup function %r failed", fn)

    @classmethod
    def clear(cls) -> None:
        cls._fns = []


@dataclasses.dataclass
class WorkflowParams:
    """Knobs of a workflow run (parity: workflow/WorkflowParams.scala)."""

    batch: str = ""
    verbose: int = 0
    skip_sanity_check: bool = False
    stop_after_read: bool = False
    stop_after_prepare: bool = False


def resolve_engine(engine_factory: str) -> Engine:
    """Dotted-path → Engine (parity: CreateWorkflow reflective factory load,
    ``CreateWorkflow.scala:196-204``)."""
    obj = persistence.resolve_class(engine_factory)
    if isinstance(obj, Engine):
        return obj
    if isinstance(obj, type):
        candidate = obj.apply() if hasattr(obj, "apply") else obj()
    elif callable(obj):
        candidate = obj()
    else:
        candidate = obj
    if not isinstance(candidate, Engine):
        raise TypeError(
            f"{engine_factory} resolved to {type(candidate).__name__}, not an Engine"
        )
    return candidate


def run_train(
    engine: Engine,
    engine_params: EngineParams,
    engine_factory: str,
    storage: Optional[Storage] = None,
    ctx: Optional[MeshContext] = None,
    workflow_params: Optional[WorkflowParams] = None,
    engine_id: str = "default",
    engine_version: str = "default",
    engine_variant: str = "default",
    env: Optional[dict] = None,
) -> str:
    """Train and persist; returns the COMPLETED EngineInstance id.

    Parity with CoreWorkflow.runTrain (CoreWorkflow.scala:45-101):
    insert INIT instance → train → serialize models into MODELDATA →
    update status COMPLETED.
    """
    storage = storage or Storage.instance()
    ctx = ctx or MeshContext.create()
    wp = workflow_params or WorkflowParams()

    # multi-host SPMD: every process trains (reads events, joins the
    # collectives), but ONLY the coordinator writes meta/model rows — the
    # reference has one Spark driver doing these writes; process 0 plays
    # that role here (parallel/distributed.py launch contract).
    from predictionio_tpu.parallel import distributed

    writer = distributed.should_write_storage()

    instances = storage.get_meta_data_engine_instances()
    now = _dt.datetime.now(tz=UTC)
    instance = EngineInstance(
        id="",
        status=instances.STATUS_INIT,
        start_time=now,
        end_time=now,
        engine_id=engine_id,
        engine_version=engine_version,
        engine_variant=engine_variant,
        engine_factory=engine_factory,
        batch=wp.batch,
        env=dict(env or {}),
        mesh_conf=dict(ctx.conf),
        **engine_params.to_json_strings(),
    )
    instance_id = ""
    if writer:
        instance_id = instances.insert(instance)
        logger.info("engine instance %s: training started", instance_id)
        instance.status = instances.STATUS_TRAINING
        instances.update(instance)

    try:
        algorithms = engine.make_algorithms(engine_params)
        models = engine.train(
            ctx,
            engine_params,
            skip_sanity_check=wp.skip_sanity_check,
            stop_after_read=wp.stop_after_read,
            stop_after_prepare=wp.stop_after_prepare,
            algorithms=algorithms,
        )

        # serialize on EVERY process: gathering a cross-process sharded
        # model is a collective (device_get_global), so all processes must
        # participate; only the coordinator then inserts the blob.
        # (PersistentModel.save file writes inside serialize_models are
        # writer-gated there.)
        algo_params = [p for _, p in engine_params.algorithm_params_list]
        blob = persistence.serialize_models(
            instance_id, algorithms, models, algo_params
        )
        if writer:
            # checksum envelope: deploy verifies content integrity before
            # unpickling, so a torn blob degrades instead of crashing
            storage.get_model_data_models().insert(
                Model(id=instance_id, models=persistence.seal_model_blob(blob))
            )
    except BaseException:
        # no zombie TRAINING rows: mark the run aborted, then propagate
        if writer:
            instance.status = instances.STATUS_ABORTED
            instance.end_time = _dt.datetime.now(tz=UTC)
            instances.update(instance)
        raise
    finally:
        CleanupFunctions.run()

    if writer:
        instance.status = instances.STATUS_COMPLETED
        instance.end_time = _dt.datetime.now(tz=UTC)
        instances.update(instance)
        logger.info("engine instance %s: training completed", instance_id)
    return instance_id


def prepare_deploy(
    engine: Engine,
    instance: EngineInstance,
    storage: Optional[Storage] = None,
    ctx: Optional[MeshContext] = None,
):
    """Load a COMPLETED instance's models for serving.

    Returns (engine_params, algorithms, serving, models).
    Parity: CreateServer.createPredictionServerWithEngine + Engine.prepareDeploy
    (CreateServer.scala:193-206, Engine.scala:198-267): rebuild EngineParams
    from the instance row, invert the model blob, retrain Unit-mode slots.
    """
    storage = storage or Storage.instance()
    ctx = ctx or MeshContext.create(conf=instance.mesh_conf)

    engine_params = engine.params_from_instance_strings(
        {
            "data_source_params": instance.data_source_params,
            "preparator_params": instance.preparator_params,
            "algorithms_params": instance.algorithms_params,
            "serving_params": instance.serving_params,
        }
    )
    algorithms = engine.make_algorithms(engine_params)
    algo_params = [p for _, p in engine_params.algorithm_params_list]

    model_row = storage.get_model_data_models().get(instance.id)
    if model_row is None:
        raise RuntimeError(f"no model blob for engine instance {instance.id}")
    # raises ModelIntegrityError on a torn/corrupt blob — callers with an
    # older generation (query server last-known-good) degrade to it
    blob = persistence.open_model_blob(model_row.models)
    models, retrain_idx = persistence.deserialize_models(
        blob, instance.id, algorithms, algo_params, ctx
    )
    if retrain_idx:
        # Unit-model mode: retrain ONLY those slots (Engine.scala:210-232);
        # read+prepare once, skip algorithms whose models deserialized.
        logger.info("retrain-on-deploy for algorithm slots %s", retrain_idx)
        pd = engine.prepare_data(ctx, engine_params, skip_sanity_check=True)
        for i in retrain_idx:
            models[i] = algorithms[i].train(ctx, pd)
    serving = engine.make_serving(engine_params)
    return engine_params, algorithms, serving, models


def get_latest_completed_instance(
    storage: Storage,
    engine_id: str = "default",
    engine_version: str = "default",
    engine_variant: str = "default",
) -> EngineInstance:
    """Deploy-time lookup (parity: commands/Engine.scala:234-241).

    Skips quarantined generations: a canary rollback writes a durable
    receipt (core/persistence.quarantined_instance_ids) and every
    newest-COMPLETED selection — cold start, /reload, fleet-roll respawn,
    batch predict — walks past those ids to the newest instance that has
    NOT failed online verification. A fleet restart therefore never
    re-deploys the generation that was just rolled back.
    """
    instances = storage.get_meta_data_engine_instances()
    quarantined = persistence.quarantined_instance_ids(
        engine_id, engine_version, engine_variant
    )
    inst = None
    if quarantined:
        for cand in instances.get_completed(engine_id, engine_version,
                                            engine_variant):
            if cand.id not in quarantined:
                inst = cand
                break
            logger.warning(
                "skipping quarantined engine instance %s for %s/%s/%s",
                cand.id, engine_id, engine_version, engine_variant,
            )
    else:
        inst = instances.get_latest_completed(
            engine_id, engine_version, engine_variant
        )
    if inst is None:
        raise RuntimeError(
            f"No completed engine instance for {engine_id}/{engine_version}/"
            f"{engine_variant}. Run train first."
        )
    return inst
