"""Evaluation + hyperparameter tuning: grid search over EngineParams.

Parity:

* :class:`Evaluation` — binds an engine to metric(s)
  (``controller/Evaluation.scala:34``).
* :class:`EngineParamsGenerator` — the candidate grid
  (``controller/EngineParamsGenerator.scala:30``).
* :class:`MetricEvaluator` — scores every candidate, tracks the best, renders
  a results summary and optional ``best.json``
  (``controller/MetricEvaluator.scala:116-263``).
* :func:`run_evaluation` — the workflow entry writing an EvaluationInstance
  (``workflow/CoreWorkflow.runEvaluation``, CoreWorkflow.scala:104-164).
* :class:`FastEvalCache` — memoizes DS/Prep/train stage results across
  candidates sharing a params prefix (``FastEvalEngine.scala:92-266``); here
  the cache keys are the JSON forms of the stage params.
"""

from __future__ import annotations

import dataclasses
import datetime as _dt
import json
import logging
from typing import Any, Optional, Sequence

from predictionio_tpu.core.engine import Engine, EngineParams, params_to_json
from predictionio_tpu.core.metrics import Metric
from predictionio_tpu.core.persistence import resolve_class
from predictionio_tpu.data.storage.base import EvaluationInstance
from predictionio_tpu.data.storage.registry import Storage
from predictionio_tpu.parallel.mesh import MeshContext

logger = logging.getLogger(__name__)
UTC = _dt.timezone.utc


def quantized_topk_overlap(
    user_factors,
    item_factors,
    user_q,
    user_scale,
    item_q,
    item_scale,
    k: int = 100,
    sample: int = 256,
) -> float:
    """Mean top-k overlap of quantized vs fp32 scoring — the publish gate.

    For an evenly-spaced deterministic sample of users, ranks the catalog
    with the fp32 factors and with the dequantized quantized variant
    (``ops/quantize.py``), and returns the mean ``|topk ∩ topk_q| / k``
    over the sample.  A quantized generation whose overlap falls below
    ``PIO_QUANT_MIN_OVERLAP`` is refused at publish (``models/als.py``) —
    serving keeps the fp32 factors, so a lossy quantization can never
    silently change what users are recommended.  Host numpy throughout:
    this runs once per publish, off the serving path.
    """
    import numpy as np

    from predictionio_tpu.ops.quantize import dequantize_factors

    U = np.asarray(user_factors, np.float32)
    V = np.asarray(item_factors, np.float32)
    n_users, n_items = U.shape[0], V.shape[0]
    k = min(k, n_items)
    n = min(max(1, sample), n_users)
    users = np.unique(
        np.linspace(0, n_users - 1, n).round().astype(np.int64)
    )
    Uq = dequantize_factors(user_q, user_scale)
    Vq = dequantize_factors(item_q, item_scale)
    ref = np.argpartition(-(U[users] @ V.T), k - 1, axis=1)[:, :k]
    quant = np.argpartition(-(Uq[users] @ Vq.T), k - 1, axis=1)[:, :k]
    overlaps = [
        len(np.intersect1d(r, q, assume_unique=True)) / k
        for r, q in zip(ref, quant)
    ]
    return float(np.mean(overlaps))


def recall_at_k(exact_idx, approx_idx, k: int) -> float:
    """Mean recall@k of an approximate ranking vs the exact one.

    ``exact_idx``/``approx_idx`` are ``(rows, ≥k)`` integer id matrices —
    per-row top-k item ids from the exact scorer and from an approximate
    retrieval path (IVF, ``ops/ivf.py``).  Per row, recall is
    ``|exact[:k] ∩ approx[:k]| / min(k, real exact ids)``: padding slots
    (negative ids, or the ``PAD_SENTINEL`` used for padded leaderboard
    slots) are excluded from BOTH sides, and the denominator shrinks with
    them, so a row with fewer than ``k`` real candidates is scored
    against what an exact ranker could actually return rather than
    penalized for ids that do not exist.  Set intersection makes the
    metric tie-order independent: any exact top-k among tied scores
    counts the same.  This is the ``PIO_IVF_MIN_RECALL`` publish-gate
    metric, parallel to :func:`quantized_topk_overlap` for quantization.
    """
    import numpy as np

    from predictionio_tpu.serving.sharding import PAD_SENTINEL

    exact = np.atleast_2d(np.asarray(exact_idx, np.int64))[:, :k]
    approx = np.atleast_2d(np.asarray(approx_idx, np.int64))[:, :k]
    if exact.shape[0] != approx.shape[0]:
        raise ValueError(
            f"row mismatch: exact has {exact.shape[0]}, "
            f"approx has {approx.shape[0]}"
        )
    recalls = []
    for e_row, a_row in zip(exact, approx):
        e = np.unique(e_row[(e_row >= 0) & (e_row < int(PAD_SENTINEL))])
        a = np.unique(a_row[(a_row >= 0) & (a_row < int(PAD_SENTINEL))])
        denom = min(int(k), len(e))
        if denom == 0:
            recalls.append(1.0)  # nothing retrievable ⇒ nothing missed
            continue
        recalls.append(len(np.intersect1d(e, a, assume_unique=True)) / denom)
    return float(np.mean(recalls))


class EngineParamsGenerator:
    """Parity: EngineParamsGenerator.scala:30."""

    engine_params_list: list[EngineParams] = []


class Evaluation:
    """Parity: Evaluation.scala:34 — engine + metric(s) binding."""

    engine: Engine = None
    metric: Metric = None
    metrics: Optional[list[Metric]] = None  # optional extra columns

    @property
    def all_metrics(self) -> list[Metric]:
        extra = self.metrics or []
        return [self.metric] + [m for m in extra if m is not self.metric]


@dataclasses.dataclass
class MetricScores:
    score: float
    other_scores: list[float]
    engine_params: EngineParams


@dataclasses.dataclass
class EvaluationResult:
    instance_id: str
    best: MetricScores
    all_results: list[MetricScores]
    summary: str

    def to_json(self) -> str:
        def ep_json(ep: EngineParams) -> dict:
            return {
                "dataSourceParams": params_to_json(ep.data_source_params),
                "preparatorParams": params_to_json(ep.preparator_params),
                "algorithmParamsList": [
                    {"name": n, "params": params_to_json(p)}
                    for n, p in ep.algorithm_params_list
                ],
                "servingParams": params_to_json(ep.serving_params),
            }

        return json.dumps(
            {
                "bestScore": self.best.score,
                "bestEngineParams": ep_json(self.best.engine_params),
                "results": [
                    {"score": r.score, "engineParams": ep_json(r.engine_params)}
                    for r in self.all_results
                ],
            }
        )


class FastEvalCache:
    """Stage memoization across candidates (FastEvalEngine parity).

    Candidates sharing a params prefix (data source → preparator → algorithms)
    reuse read_eval folds and trained models instead of recomputing them.

    Memory is bounded by prefix-scoped eviction: when the full candidate list
    is known up front, each cache entry carries a refcount of the candidates
    still needing it, and :meth:`release` drops folds/prepared/model entries
    the moment no remaining candidate shares that prefix — so peak residency
    tracks *live* prefixes, not the whole grid.
    """

    def __init__(
        self,
        engine: Engine,
        ctx: MeshContext,
        candidates: Optional[Sequence[EngineParams]] = None,
    ):
        self.engine = engine
        self.ctx = ctx
        self._folds: dict[str, list] = {}
        self._prepared: dict[str, list] = {}
        self._models: dict[str, list] = {}
        self._remaining: Optional[dict[str, dict[str, int]]] = None
        if candidates is not None:
            self._remaining = {"folds": {}, "prepared": {}, "models": {}}
            for ep in candidates:
                for level, key in zip(
                    ("folds", "prepared", "models"), self.candidate_keys(ep)
                ):
                    counts = self._remaining[level]
                    counts[key] = counts.get(key, 0) + 1

    @staticmethod
    def _key(*parts: Any) -> str:
        return json.dumps(parts, sort_keys=True, default=str)

    def candidate_keys(self, ep: EngineParams) -> tuple[str, str, str]:
        ds = params_to_json(ep.data_source_params)
        prep = params_to_json(ep.preparator_params)
        algos = [(n, params_to_json(p)) for n, p in ep.algorithm_params_list]
        return (
            self._key(ds),
            self._key(ds, prep),
            self._key(ds, prep, algos),
        )

    def release(self, ep: EngineParams) -> None:
        """Candidate finished: evict any prefix no remaining candidate shares."""
        if self._remaining is None:
            return
        stores = {
            "folds": self._folds,
            "prepared": self._prepared,
            "models": self._models,
        }
        for level, key in zip(
            ("folds", "prepared", "models"), self.candidate_keys(ep)
        ):
            counts = self._remaining[level]
            if key in counts:
                counts[key] -= 1
                if counts[key] <= 0:
                    del counts[key]
                    stores[level].pop(key, None)

    @property
    def entry_count(self) -> int:
        return len(self._folds) + len(self._prepared) + len(self._models)

    def folds(self, ds_params) -> list:
        key = self._key(params_to_json(ds_params))
        if key not in self._folds:
            ds = self.engine.data_source_cls(ds_params)
            self._folds[key] = list(ds.read_eval(self.ctx))
        return self._folds[key]

    def prepared(self, ds_params, prep_params) -> list:
        key = self._key(params_to_json(ds_params), params_to_json(prep_params))
        if key not in self._prepared:
            prep = self.engine.preparator_cls(prep_params)
            self._prepared[key] = [
                (prep.prepare(self.ctx, td), qa)
                for td, qa in self.folds(ds_params)
            ]
        return self._prepared[key]

    def models(self, ds_params, prep_params, algo_list) -> list:
        key = self._key(
            params_to_json(ds_params),
            params_to_json(prep_params),
            [(n, params_to_json(p)) for n, p in algo_list],
        )
        if key not in self._models:
            per_fold = []
            for pd, _ in self.prepared(ds_params, prep_params):
                algorithms = [
                    self.engine.algorithm_cls_map[n](p) for n, p in algo_list
                ]
                per_fold.append(
                    (algorithms, [a.train(self.ctx, pd) for a in algorithms])
                )
            self._models[key] = per_fold
        return self._models[key]


class MetricEvaluator:
    """Parity: MetricEvaluator.scala:116-263."""

    def __init__(self, metric: Metric, metrics: Optional[Sequence[Metric]] = None):
        self.metric = metric
        self.metrics = list(metrics or [])

    def evaluate_base(
        self,
        ctx: MeshContext,
        engine: Engine,
        engine_params_list: Sequence[EngineParams],
        output_path: Optional[str] = None,
    ) -> EvaluationResult:
        if not engine_params_list:
            raise ValueError("engine_params_list is empty; nothing to evaluate")
        cache = FastEvalCache(engine, ctx, candidates=engine_params_list)
        results: list[MetricScores] = []
        best: Optional[MetricScores] = None
        for i, ep in enumerate(engine_params_list):
            qpas = self._eval_candidate(cache, engine, ctx, ep)
            score = self.metric.calculate(ctx, qpas)
            others = [m.calculate(ctx, qpas) for m in self.metrics]
            cache.release(ep)
            ms = MetricScores(score, others, ep)
            results.append(ms)
            logger.info("candidate %d: %s = %s", i, self.metric.header, score)
            if best is None or self.metric.compare(score, best.score) > 0:
                best = ms
        result = EvaluationResult(
            instance_id="",
            best=best,
            all_results=results,
            summary=self._summary(results, best),
        )
        if output_path:
            # parity: MetricEvaluator.saveEngineJson best.json (:193)
            with open(output_path, "w") as f:
                f.write(result.to_json())
        return result

    def _eval_candidate(self, cache, engine, ctx, ep: EngineParams):
        serving = engine.make_serving(ep)
        per_fold = cache.models(
            ep.data_source_params, ep.preparator_params, ep.algorithm_params_list
        )
        folds = cache.folds(ep.data_source_params)
        qpas = []
        for fold_idx, ((algorithms, models), (_, qa_list)) in enumerate(
            zip(per_fold, folds)
        ):
            supplemented = [(i, serving.supplement(q)) for i, (q, _) in enumerate(qa_list)]
            per_algo = [
                dict(a.batch_predict(m, supplemented))
                for a, m in zip(algorithms, models)
            ]
            triples = []
            for i, (q, a) in enumerate(qa_list):
                preds = [d[i] for d in per_algo if i in d]
                triples.append((q, serving.serve(supplemented[i][1], preds), a))
            qpas.append((fold_idx, triples))
        return qpas

    def _summary(self, results, best) -> str:
        """Per-candidate metric columns + the best row, like the reference's
        MetricEvaluator printout (MetricEvaluator.scala:218-263)."""
        headers = [self.metric.header] + [m.header for m in self.metrics]
        widths = [max(len(h), 12) for h in headers]
        lines = [
            "[RESULT] Metric evaluation",
            f"  candidates: {len(results)}",
            f"  metric: {self.metric.header}",
            "  "
            + " | ".join(h.ljust(w) for h, w in zip(["#"] + headers, [3] + widths))
            + " | params",
        ]
        for i, r in enumerate(results):
            cells = [f"{r.score:.6g}"] + [f"{s:.6g}" for s in r.other_scores]
            mark = "*" if r is best else " "
            lines.append(
                f"  {mark}{i:<2} | "
                + " | ".join(c.ljust(w) for c, w in zip(cells, widths))
                + " | "
                + r.engine_params.to_json_strings()["algorithms_params"]
            )
        lines += [
            f"  best score: {best.score}",
            f"  best params: {best.engine_params.to_json_strings()['algorithms_params']}",
        ]
        return "\n".join(lines)


@dataclasses.dataclass
class RunEvaluationResult:
    instance_id: str
    best_score: float
    summary: str


def run_evaluation(
    evaluation_class: str,
    engine_params_generator_class: Optional[str] = None,
    storage: Optional[Storage] = None,
    ctx: Optional[MeshContext] = None,
    batch: str = "",
    output_path: Optional[str] = None,
) -> RunEvaluationResult:
    """Workflow entry (parity: CoreWorkflow.runEvaluation:104-164)."""
    storage = storage or Storage.instance()
    ctx = ctx or MeshContext.create()
    evaluation: Evaluation = _instantiate(resolve_class(evaluation_class))
    generator_cls = engine_params_generator_class or evaluation_class
    generator: EngineParamsGenerator = _instantiate(resolve_class(generator_cls))
    if not generator.engine_params_list:
        raise ValueError(
            f"{generator_cls} has an empty engine_params_list; nothing to evaluate"
        )

    # multi-host SPMD: every process evaluates (joins any collectives) but
    # ONLY the coordinator records EvaluationInstances / best.json — the
    # same single-writer contract as run_train (CoreWorkflow role)
    from predictionio_tpu.parallel import distributed

    writer = distributed.should_write_storage()

    instances = storage.get_meta_data_evaluation_instances()
    now = _dt.datetime.now(tz=UTC)
    instance = EvaluationInstance(
        id="",
        status=instances.STATUS_INIT,
        start_time=now,
        end_time=now,
        evaluation_class=evaluation_class,
        engine_params_generator_class=generator_cls,
        batch=batch,
        mesh_conf=dict(ctx.conf),
    )
    instance_id = ""
    if writer:
        instance_id = instances.insert(instance)
        instance.status = instances.STATUS_EVALUATING
        instances.update(instance)

    try:
        evaluator = MetricEvaluator(evaluation.metric, evaluation.metrics)
        result = evaluator.evaluate_base(
            ctx, evaluation.engine, generator.engine_params_list,
            output_path if writer else None,
        )
    except BaseException:
        if writer:
            instance.status = instances.STATUS_ABORTED
            instance.end_time = _dt.datetime.now(tz=UTC)
            instances.update(instance)
        raise
    finally:
        from predictionio_tpu.core.workflow import CleanupFunctions

        CleanupFunctions.run()
    result.instance_id = instance_id

    if writer:
        instance.status = instances.STATUS_COMPLETED
        instance.end_time = _dt.datetime.now(tz=UTC)
        instance.evaluator_results = result.summary
        instance.evaluator_results_html = (
            f"<html><body><pre>{result.summary}</pre></body></html>"
        )
        instance.evaluator_results_json = result.to_json()
        instances.update(instance)
    return RunEvaluationResult(
        instance_id=instance_id, best_score=result.best.score, summary=result.summary
    )


def _instantiate(obj):
    return obj() if isinstance(obj, type) else obj
