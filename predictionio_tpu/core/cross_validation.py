"""k-fold cross-validation splitting helper.

Parity: ``e2/.../evaluation/CrossValidation.scala:24-67`` — deterministic
k-fold assignment by row index (the reference uses ``zipWithUniqueId`` % k);
here indices are explicit so any array-like dataset splits the same way.
"""

from __future__ import annotations

from typing import Callable, Sequence, TypeVar

import numpy as np

T = TypeVar("T")


def k_fold_indices(n: int, k: int) -> list[tuple[np.ndarray, np.ndarray]]:
    """[(train_idx, test_idx)] per fold; row i belongs to fold i % k."""
    if k < 2:
        raise ValueError("k must be >= 2")
    fold_of = np.arange(n) % k
    out = []
    for f in range(k):
        test = np.nonzero(fold_of == f)[0]
        train = np.nonzero(fold_of != f)[0]
        out.append((train, test))
    return out


def k_fold(
    data: Sequence[T], k: int
) -> list[tuple[list[T], list[T]]]:
    """Materialized (train, test) row lists per fold."""
    splits = k_fold_indices(len(data), k)
    return [
        ([data[i] for i in tr], [data[i] for i in te]) for tr, te in splits
    ]


def k_fold_eval(
    data: Sequence[T],
    k: int,
    to_training: Callable[[list[T]], object],
    to_query_actual: Callable[[T], tuple],
):
    """Build DataSource.read_eval-shaped folds from a row dataset."""
    return [
        (to_training(train), [to_query_actual(row) for row in test])
        for train, test in k_fold(data, k)
    ]
