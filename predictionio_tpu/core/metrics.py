"""Metric hierarchy for evaluation/tuning.

Parity: ``core/.../controller/Metric.scala:39-269`` — Metric base with
``calculate``, plus the statistics subclasses: :class:`AverageMetric` (:99),
:class:`OptionAverageMetric` (:124, None scores excluded),
:class:`StdevMetric` (:151), :class:`SumMetric` (:205),
:class:`ZeroMetric` (:234).  ``compare`` defaults to larger-is-better.
"""

from __future__ import annotations

import abc
import math
from typing import Any, Optional, Sequence

# one fold's scored data: [(query, prediction, actual)]
QPA = Sequence[tuple[Any, Any, Any]]


class Metric(abc.ABC):
    """Parity: Metric.scala:39."""

    @abc.abstractmethod
    def calculate(self, ctx, qpas: list[tuple[Any, QPA]]) -> float:
        """Score across all evaluation folds."""

    def compare(self, r0: float, r1: float) -> int:
        """>0 if r0 is better (larger-is-better by default)."""
        return (r0 > r1) - (r0 < r1)

    @property
    def header(self) -> str:
        return type(self).__name__


class AverageMetric(Metric):
    """Mean of per-(q,p,a) scores across all folds (Metric.scala:99)."""

    @abc.abstractmethod
    def calculate_one(self, query, prediction, actual) -> float: ...

    def calculate(self, ctx, qpas) -> float:
        scores = [
            self.calculate_one(q, p, a) for _, triples in qpas for q, p, a in triples
        ]
        if not scores:
            return float("nan")
        return sum(scores) / len(scores)


class OptionAverageMetric(Metric):
    """Mean of the non-None scores only (Metric.scala:124)."""

    @abc.abstractmethod
    def calculate_one(self, query, prediction, actual) -> Optional[float]: ...

    def calculate(self, ctx, qpas) -> float:
        scores = [
            s
            for _, triples in qpas
            for q, p, a in triples
            if (s := self.calculate_one(q, p, a)) is not None
        ]
        if not scores:
            return float("nan")
        return sum(scores) / len(scores)


class StdevMetric(Metric):
    """Population stdev of per-row scores (Metric.scala:151)."""

    @abc.abstractmethod
    def calculate_one(self, query, prediction, actual) -> float: ...

    def calculate(self, ctx, qpas) -> float:
        scores = [
            self.calculate_one(q, p, a) for _, triples in qpas for q, p, a in triples
        ]
        if not scores:
            return float("nan")
        mean = sum(scores) / len(scores)
        return math.sqrt(sum((s - mean) ** 2 for s in scores) / len(scores))


class SumMetric(Metric):
    """Sum of per-row scores (Metric.scala:205)."""

    @abc.abstractmethod
    def calculate_one(self, query, prediction, actual) -> float: ...

    def calculate(self, ctx, qpas) -> float:
        return float(
            sum(self.calculate_one(q, p, a) for _, triples in qpas for q, p, a in triples)
        )


class ZeroMetric(Metric):
    """Always 0 (Metric.scala:234) — placeholder for unscored evaluations."""

    def calculate(self, ctx, qpas) -> float:
        return 0.0
