from predictionio_tpu.core.controller import (
    Algorithm,
    AverageServing,
    DataSource,
    FirstServing,
    IdentityPreparator,
    Params,
    Preparator,
    Serving,
    ShardedAlgorithm,
)
from predictionio_tpu.core.engine import Engine, EngineFactory, EngineParams
from predictionio_tpu.core.persistence import PersistentModel

__all__ = [
    "Algorithm",
    "AverageServing",
    "DataSource",
    "Engine",
    "EngineFactory",
    "EngineParams",
    "FirstServing",
    "IdentityPreparator",
    "Params",
    "PersistentModel",
    "Preparator",
    "Serving",
    "ShardedAlgorithm",
]
