"""Crash-safe streaming micro-generations: the sealed delta log.

Full retrains publish *generations*; this module fills the gap between
them with *micro-generations*: small, sealed, epoch-numbered deltas that
fold freshly committed events into the live serving factors without a
recompile and without a generation swap.  The pipeline is hardened at
every hop:

* **Sealed envelope** — every delta is written as ``delta-<epoch>.blob``
  through the :mod:`core.persistence` checksum envelope (atomic
  tmp+fsync+rename).  A torn or bit-flipped blob surfaces as
  :class:`~predictionio_tpu.core.persistence.ModelIntegrityError`, never
  as silently corrupt factors.
* **Epoch fencing** — epochs are monotonic per base generation and every
  delta carries the ``base_fingerprint`` of the generation it was folded
  against.  A replica refuses any delta whose fingerprint does not match
  its live generation (stale publisher, split-brain, mid-roll mixups),
  and re-applying an already-applied epoch is an idempotent no-op —
  exactly-once by construction, kill -9 anywhere in the apply path
  included.  On the fold side, sealed deltas carry the durable ids of
  the events they folded, and the publisher skips replayed events that
  already sealed — WAL/ring replay after a clean restart never
  double-folds (see :class:`DeltaPublisher`).
* **Quality gate** — fold-in rows are gated on top-k overlap against a
  full-fidelity reference solve on sampled users
  (``PIO_DELTA_MIN_OVERLAP``, the streaming analogue of the
  ``PIO_QUANT_MIN_OVERLAP`` / ``PIO_IVF_MIN_RECALL`` publish gates).  A
  below-threshold micro-generation is quarantined: no blob is sealed,
  a refusal receipt is recorded, and serving continues on the last-good
  epoch.
* **Catch-up** — a replica that missed deltas (crash-restart, fresh
  autoscaled replica, mid-roll) replays the sealed log from its applied
  high-water mark before readmission; the fencing rules above make the
  replay safe to repeat from any point.

Chaos sites compiled in: ``crash:delta:before_seal`` (publisher dies
after the WAL ack but before the delta is sealed — replay must regrow
it) and ``crash:delta:mid_apply`` (replica dies between receiving a
delta and recording it applied — restart must catch up).

``PIO_STREAMING=0`` (the default) disables every code path here; the
platform behaves bit-identically to full-retrain-only serving.
"""

from __future__ import annotations

import collections
import dataclasses
import hashlib
import json
import logging
import os
import pickle
import re
import threading
import time
from typing import Callable, Optional

import numpy as np

from predictionio_tpu.common import faults as _faults
from predictionio_tpu.core.persistence import (
    ModelIntegrityError, open_blob_file, seal_blob_file,
)

log = logging.getLogger("pio.delta")

DELTA_PAYLOAD_VERSION = 1
_DELTA_RE = re.compile(r"^delta-(\d{8})\.blob$")

# Bound on the publisher's folded-event-id dedupe window.  WAL replay
# and the committed-event ring only ever re-deliver *recent* events, so
# the window needs to cover a few retention-worths of folds, not history.
_DEDUP_KEEP = 65536


def streaming_enabled() -> bool:
    """One-env kill switch: ``PIO_STREAMING=0`` (default) → no streaming."""
    return os.environ.get("PIO_STREAMING", "0") == "1"


def default_delta_dir() -> str:
    """Where sealed ``delta-<epoch>.blob`` files live.

    ``PIO_DELTA_DIR`` overrides; otherwise ``<base>/deltas`` so the
    delta log survives process restarts alongside model checkpoints.
    """
    configured = os.environ.get("PIO_DELTA_DIR", "")
    if configured:
        return configured
    from predictionio_tpu.utils.fs import pio_base_dir
    return os.path.join(pio_base_dir(), "deltas")


def delta_dir_for(base_fingerprint: str,
                  base_dir: Optional[str] = None) -> str:
    """Per-base-generation delta log directory.

    Each base generation keeps its own epoch sequence under
    ``<delta_dir>/<fingerprint>/`` — a replica rolling onto a new base
    starts from an empty log instead of wading through (and refusing)
    every stale epoch sealed against the previous generation.  The
    per-delta fingerprint fence still guards split-brain within a
    directory.
    """
    return os.path.join(base_dir or default_delta_dir(), base_fingerprint)


def model_fingerprint(user_factors: np.ndarray,
                      item_factors: np.ndarray) -> str:
    """Stable identity of a base generation's factor matrices.

    Deltas are fenced against this: the publisher stamps the fingerprint
    of the generation it folded against, and a replica refuses deltas
    whose stamp does not match its own live generation.  Computed over
    shapes + bytes of the float32 host factors, so publisher and replica
    agree whenever they loaded the same sealed artifacts.
    """
    h = hashlib.sha256()
    for a in (user_factors, item_factors):
        a = np.ascontiguousarray(np.asarray(a, dtype=np.float32))
        h.update(repr(a.shape).encode("ascii"))
        h.update(a.tobytes())
    return h.hexdigest()[:16]


@dataclasses.dataclass
class Delta:
    """One micro-generation: fold-in rows + cooccurrence count updates.

    ``user_idx``/``user_rows`` are replacement rows for the (replicated)
    user-factor matrix; ``item_idx``/``item_rows`` — normally empty for
    user-side fold-in — are routed to their owning shard through the
    ShardingPlan by the fastpath apply.  ``cooc_updates`` is an (m, 3)
    int64 array of ``(item_a, item_b, +count)`` pair increments.
    ``event_ids`` records the durable ids of the committed events folded
    in — the sealed log doubles as the publisher's folded-event
    high-water record, so a restarted publisher skips WAL/ring-replayed
    events that already sealed into a prior epoch instead of folding
    them twice.
    """

    epoch: int
    base_fingerprint: str
    user_ids: tuple  # external entity ids, for targeted cache invalidation
    user_idx: np.ndarray  # (n,) int32 rows into user_factors
    user_rows: np.ndarray  # (n, rank) float32 replacement rows
    item_idx: np.ndarray  # (k,) int32 rows into item_factors (may be empty)
    item_rows: np.ndarray  # (k, rank) float32
    cooc_updates: np.ndarray  # (m, 3) int64 (item_a, item_b, +count)
    events: int  # committed events folded into this delta
    created_unix: float
    quality: dict  # gate receipt: {"overlap": .., "threshold": ..}
    event_ids: tuple = ()  # durable ids of the folded events (dedupe fence)

    def to_payload(self) -> bytes:
        return pickle.dumps({
            "version": DELTA_PAYLOAD_VERSION,
            "epoch": int(self.epoch),
            "base_fingerprint": self.base_fingerprint,
            "user_ids": tuple(self.user_ids),
            "user_idx": np.asarray(self.user_idx, dtype=np.int32),
            "user_rows": np.asarray(self.user_rows, dtype=np.float32),
            "item_idx": np.asarray(self.item_idx, dtype=np.int32),
            "item_rows": np.asarray(self.item_rows, dtype=np.float32),
            "cooc_updates": np.asarray(self.cooc_updates, dtype=np.int64),
            "events": int(self.events),
            "created_unix": float(self.created_unix),
            "quality": dict(self.quality),
            "event_ids": tuple(self.event_ids),
        })

    @classmethod
    def from_payload(cls, payload: bytes) -> "Delta":
        d = pickle.loads(payload)
        if d.get("version") != DELTA_PAYLOAD_VERSION:
            raise ModelIntegrityError(
                f"unsupported delta payload version {d.get('version')!r}")
        return cls(
            epoch=int(d["epoch"]),
            base_fingerprint=d["base_fingerprint"],
            user_ids=tuple(d["user_ids"]),
            user_idx=d["user_idx"],
            user_rows=d["user_rows"],
            item_idx=d["item_idx"],
            item_rows=d["item_rows"],
            cooc_updates=d["cooc_updates"],
            events=int(d["events"]),
            created_unix=float(d["created_unix"]),
            quality=d.get("quality", {}),
            event_ids=tuple(d.get("event_ids", ())),
        )


def empty_delta(epoch: int, base_fingerprint: str, **kw) -> Delta:
    """A structurally valid delta with no rows (testing + catch-up probes)."""
    rank = int(kw.pop("rank", 0))
    defaults = dict(
        user_ids=(), user_idx=np.zeros((0,), np.int32),
        user_rows=np.zeros((0, rank), np.float32),
        item_idx=np.zeros((0,), np.int32),
        item_rows=np.zeros((0, rank), np.float32),
        cooc_updates=np.zeros((0, 3), np.int64),
        events=0, created_unix=0.0, quality={},
    )
    defaults.update(kw)
    return Delta(epoch=epoch, base_fingerprint=base_fingerprint, **defaults)


class DeltaLog:
    """Epoch-ordered directory of sealed ``delta-<epoch>.blob`` files.

    The log is the single source of truth for catch-up: a replica that
    crashed, restarted, or just autoscaled into the fleet replays every
    epoch past its applied high-water mark before it rejoins.
    """

    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)

    def path(self, epoch: int) -> str:
        return os.path.join(self.directory, f"delta-{epoch:08d}.blob")

    def epochs(self) -> list:
        out = []
        try:
            names = os.listdir(self.directory)
        except OSError:
            return out
        for name in names:
            m = _DELTA_RE.match(name)
            if m:
                out.append(int(m.group(1)))
        out.sort()
        return out

    def last_epoch(self) -> int:
        eps = self.epochs()
        return eps[-1] if eps else 0

    def seal(self, delta: Delta) -> str:
        """Seal one delta through the checksum envelope (atomic publish).

        ``crash:delta:before_seal`` sits between the committed-event ack
        and the seal — the exact window WAL replay must repair: the
        events are durable, the delta is not, and a restarted publisher
        regrows it from replayed commits.
        """
        _faults.crash_point("crash:delta:before_seal")
        p = self.path(delta.epoch)
        seal_blob_file(p, delta.to_payload())
        return p

    def read(self, epoch: int) -> Delta:
        """Open + verify one sealed epoch; raises ModelIntegrityError on
        a torn blob, FileNotFoundError on a missing one."""
        return Delta.from_payload(open_blob_file(self.path(epoch)))

    def read_since(self, epoch: int) -> list:
        """All sealed deltas with epoch > ``epoch``, in order (catch-up)."""
        return [self.read(e) for e in self.epochs() if e > epoch]

    def oldest_unapplied_age_s(self, applied_epoch: int) -> float:
        """Age of the oldest sealed-but-unapplied delta (0.0 if caught up).

        Uses file mtime so staleness costs one stat, not a blob read."""
        pending = [e for e in self.epochs() if e > applied_epoch]
        if not pending:
            return 0.0
        try:
            return max(0.0, time.time() - os.path.getmtime(
                self.path(pending[0])))
        except OSError:
            return 0.0

    def prune(self, keep: Optional[int] = None) -> int:
        """Drop the oldest sealed epochs beyond the retention window."""
        if keep is None:
            keep = int(os.environ.get("PIO_DELTA_LOG_KEEP", "64"))
        eps = self.epochs()
        drop = eps[:-keep] if keep > 0 else eps
        removed = 0
        for e in drop:
            try:
                os.remove(self.path(e))
                removed += 1
            except OSError:
                pass
        return removed


def instance_receipt_recorder(storage, instance_id: str,
                              max_keep: int = 16) -> Callable[[dict], None]:
    """``on_receipt`` hook that lands publish receipts — refusals
    especially — in the deployed EngineInstance's free-form ``env``
    metadata, so ``pio status`` / the registry shows WHY a
    micro-generation was quarantined without grepping logs."""

    def record(receipt: dict) -> None:
        try:
            ei = storage.get_meta_data_engine_instances()
            inst = ei.get(instance_id)
            if inst is None:
                return
            kept = list(inst.env.get("delta_receipts", []))
            kept.append(receipt)
            del kept[:-max_keep]
            inst.env["delta_receipts"] = kept
            ei.update(inst)
        except Exception:
            log.exception("could not record delta receipt on instance %s",
                          instance_id)

    return record


class DeltaApplier:
    """Replica-side fencing + exactly-once application of deltas.

    ``apply_fn(delta)`` performs the actual in-place work (device factor
    patch, cooccurrence counts, cache invalidation); this class owns the
    decision of *whether* it runs: fingerprint fence, idempotent replay
    of old epochs, and in-order application with log catch-up across
    gaps.  All receipts are plain dicts so they serialize straight into
    HTTP acks and instance metadata.
    """

    def __init__(self, base_fingerprint: str,
                 apply_fn: Callable[[Delta], None],
                 delta_log: Optional[DeltaLog] = None):
        self.base_fingerprint = base_fingerprint
        self._apply_fn = apply_fn
        self.log = delta_log
        self.applied_epoch = 0
        self.last_apply_unix = 0.0
        self._lock = threading.Lock()
        self._applied = 0
        self._noops = 0
        self._refused = {}  # reason -> count
        self._visible_ms = []  # rolling event->visible latencies

    # -- receipts ----------------------------------------------------------

    def refuse(self, reason: str, **extra) -> dict:
        """Record + shape a refusal receipt (also used by the transport
        layer for torn-in-transit payloads that never reach apply())."""
        self._refused[reason] = self._refused.get(reason, 0) + 1
        r = {"refused": True, "reason": reason,
             "applied_epoch": self.applied_epoch}
        r.update(extra)
        return r

    # -- apply -------------------------------------------------------------

    def apply(self, delta: Delta) -> dict:
        """Fence + apply one delta; returns the ack receipt."""
        with self._lock:
            return self._apply_locked(delta)

    def _apply_locked(self, delta: Delta) -> dict:
        if delta.base_fingerprint != self.base_fingerprint:
            return self.refuse(
                "fingerprint", epoch=delta.epoch,
                want=self.base_fingerprint, got=delta.base_fingerprint)
        if delta.epoch <= self.applied_epoch:
            # exactly-once: replay of an applied epoch is a no-op ack
            self._noops += 1
            return {"noop": True, "epoch": delta.epoch,
                    "applied_epoch": self.applied_epoch}
        if delta.epoch != self.applied_epoch + 1:
            # a gap means missed epochs: catch up from the sealed log
            # first, then retry this delta in order
            if self.log is not None:
                rc = self._catch_up_locked(upto=delta.epoch - 1)
                if rc.get("refused"):
                    return rc
            if delta.epoch != self.applied_epoch + 1:
                return self.refuse("gap", epoch=delta.epoch)
        return self._apply_one(delta)

    def _apply_one(self, delta: Delta) -> dict:
        # the mid-apply crash window: factors may be half-patched in this
        # process, but applied_epoch has NOT advanced — a restarted
        # replica reloads clean base factors and replays from the log
        _faults.crash_point("crash:delta:mid_apply")
        self._apply_fn(delta)
        self.applied_epoch = delta.epoch
        self.last_apply_unix = time.time()
        self._applied += 1
        if delta.created_unix:
            vis = max(0.0, self.last_apply_unix - delta.created_unix)
            self._visible_ms.append(vis * 1000.0)
            del self._visible_ms[:-512]
        return {"applied": True, "epoch": delta.epoch,
                "applied_epoch": self.applied_epoch,
                "rows": int(np.asarray(delta.user_idx).shape[0])}

    # -- catch-up ----------------------------------------------------------

    def catch_up(self, upto: Optional[int] = None) -> dict:
        """Replay every sealed epoch past the applied high-water mark.

        Run before readmission (restart, autoscale-in, post-roll).  A
        torn blob stops the replay at the last good epoch and reports a
        refusal — the replica serves degraded rather than crashing.
        """
        with self._lock:
            return self._catch_up_locked(upto=upto)

    def _catch_up_locked(self, upto: Optional[int] = None) -> dict:
        if self.log is None:
            return {"caught_up": 0, "applied_epoch": self.applied_epoch}
        applied = 0
        for epoch in self.log.epochs():
            if epoch <= self.applied_epoch:
                continue
            if upto is not None and epoch > upto:
                break
            if epoch != self.applied_epoch + 1:
                rc = self.refuse("gap", epoch=epoch)
                rc["caught_up"] = applied
                return rc
            try:
                delta = self.log.read(epoch)
            except (ModelIntegrityError, OSError) as exc:
                log.warning("delta catch-up stopped at epoch %d: %s",
                            epoch, exc)
                rc = self.refuse("integrity", epoch=epoch, error=str(exc))
                rc["caught_up"] = applied
                return rc
            rc = self._apply_locked(delta)
            if rc.get("refused"):
                rc["caught_up"] = applied
                return rc
            applied += 1
        return {"caught_up": applied, "applied_epoch": self.applied_epoch}

    def stats(self) -> dict:
        with self._lock:
            vis = sorted(self._visible_ms)
            p99 = vis[min(len(vis) - 1, int(len(vis) * 0.99))] if vis else 0.0
            return {
                "applied_epoch": self.applied_epoch,
                "applied": self._applied,
                "noops": self._noops,
                "refused": dict(self._refused),
                "last_apply_unix": self.last_apply_unix,
                "visible_p99_ms": p99,
            }


class DeltaPublisher:
    """Event-plane side: folds committed events into sealed deltas.

    Subscribes to the event server's committed-event notifications
    (``attach_delta_sink``), buffers them, and on flush solves ALS
    user-side fold-in rows against the base generation's item factors,
    gates them on top-k overlap vs a full-fidelity reference solve, and
    seals the surviving micro-generation into the :class:`DeltaLog`.

    ``history_fn(user_id)`` (optional) returns the user's full
    ``[(item_id, rating), ...]`` history so fold-in recomputes the row
    from everything known about the user, not just this delta's events —
    the property the exact-equality test pins down.  Refused deltas
    never seal: the epoch is not burned, a ``refusal-<epoch>.json``
    receipt lands next to the log, and ``on_receipt`` (when wired)
    records it in instance metadata.

    Exactly-once on the fold side rests on two mechanisms:

    * **One flush at a time** — ``_seal_lock`` serializes every flush
      (the paced worker, size-triggered inline flushes on commit
      threads, and the drain-time final fold) across epoch allocation,
      the seal, and the publisher-side factor update, so two concurrent
      flushes can never mint the same epoch or overwrite each other's
      sealed blob.
    * **Folded-event dedupe** — each sealed delta carries the durable
      ids of the events it folded; a publisher primes its dedupe window
      from the sealed log at construction and ``on_committed`` skips
      events already folded (or already pending), so WAL replay and
      committed-ring replay after a clean restart never double-fold.
    """

    def __init__(self, model, delta_log: DeltaLog, *,
                 history_fn: Optional[Callable] = None,
                 on_receipt: Optional[Callable[[dict], None]] = None,
                 max_events: Optional[int] = None,
                 min_overlap: Optional[float] = None,
                 gate_sample: Optional[int] = None,
                 gate_k: int = 10):
        self.model = model
        self.log = delta_log
        self.history_fn = history_fn
        self.on_receipt = on_receipt
        self.max_events = int(
            os.environ.get("PIO_DELTA_MAX_EVENTS", "512")
            if max_events is None else max_events)
        self.min_overlap = float(
            os.environ.get("PIO_DELTA_MIN_OVERLAP", "0.6")
            if min_overlap is None else min_overlap)
        self.gate_sample = int(
            os.environ.get("PIO_DELTA_GATE_SAMPLE", "8")
            if gate_sample is None else gate_sample)
        self.gate_k = gate_k
        self.base_fingerprint = model_fingerprint(
            model.user_factors, model.item_factors)
        self._lock = threading.Lock()  # buffers, counters, dedupe window
        self._seal_lock = threading.Lock()  # serializes whole flushes
        self._pending = []  # [(user_id, item_id, rating, event_id|None)]
        self._pending_ids: set = set()  # durable ids buffered in _pending
        self._folded_ids: set = set()  # recently folded durable ids
        self._folded_order: collections.deque = collections.deque()
        self._sealed = 0
        self._seal_refused = 0
        self._events_folded = 0
        self._unknown_users = 0
        self._dedup_skipped = 0
        self._last_receipt: Optional[dict] = None
        # prime the dedupe window from the sealed log: after a clean
        # restart, WAL/ring replay re-delivers events that already
        # sealed into prior epochs — they must not fold twice
        for epoch in delta_log.epochs():
            try:
                self._remember_folded(delta_log.read(epoch).event_ids)
            except (ModelIntegrityError, OSError) as exc:
                log.warning("dedupe prime skipped epoch %d: %s", epoch, exc)

    def _remember_folded(self, event_ids) -> None:
        """Record durable event ids as folded (bounded window).
        Caller holds neither lock at __init__ time; every other caller
        takes ``self._lock`` here."""
        with self._lock:
            for eid in event_ids:
                if eid is None or eid in self._folded_ids:
                    continue
                self._folded_ids.add(eid)
                self._folded_order.append(eid)
            while len(self._folded_order) > _DEDUP_KEEP:
                self._folded_ids.discard(self._folded_order.popleft())

    # -- ingestion hook ----------------------------------------------------

    def on_committed(self, events) -> None:
        """Committed-event sink (fires on the storage-commit path AND on
        WAL/ring replay).  Replayed events whose durable id already
        folded into a sealed epoch — or is already buffered — are
        skipped, so a delta lost to a pre-seal crash is regrown from the
        same durable events while a clean restart never folds twice."""
        batch = []
        for ev in events:
            ent = getattr(ev, "entity_id", None)
            tgt = getattr(ev, "target_entity_id", None)
            if ent is None or tgt is None:
                continue
            props = getattr(ev, "properties", None) or {}
            try:
                rating = float(props.get("rating", 1.0))
            except (TypeError, ValueError):
                rating = 1.0
            eid = getattr(ev, "event_id", None)
            batch.append((str(ent), str(tgt), rating, eid))
        if not batch:
            return
        flush_now = False
        with self._lock:
            for item in batch:
                eid = item[3]
                if eid is not None and (eid in self._folded_ids
                                        or eid in self._pending_ids):
                    self._dedup_skipped += 1
                    continue
                if eid is not None:
                    self._pending_ids.add(eid)
                self._pending.append(item)
            flush_now = len(self._pending) >= self.max_events
        if flush_now:
            self.flush()

    def pending(self) -> int:
        with self._lock:
            return len(self._pending)

    # -- build + gate + seal ----------------------------------------------

    def flush(self) -> Optional[dict]:
        """Fold the pending buffer into one sealed micro-generation.

        Returns the publish receipt (or None when there was nothing to
        fold).  A below-threshold fold-in is quarantined: nothing seals,
        the receipt says why, serving stays on the last-good epoch.

        ``_seal_lock`` is held across the pending swap, epoch
        allocation, gate, seal, and publisher-side factor update:
        concurrent flushes (size-triggered on commit threads, the paced
        worker, drain) serialize here, so epochs are allocated once and
        a sealed blob is never silently overwritten by a racing seal.
        """
        with self._seal_lock:
            with self._lock:
                pending, self._pending = self._pending, []
                self._pending_ids = set()
            if not pending:
                return None
            receipt = self._build_and_seal(pending)
            # every flushed event is now accounted for (sealed, or
            # dropped by a refusal receipt): never re-fold its replay
            self._remember_folded(eid for _, _, _, eid in pending)
        with self._lock:
            self._last_receipt = receipt
        if self.on_receipt is not None:
            try:
                self.on_receipt(receipt)
            except Exception:
                log.exception("delta receipt callback failed")
        return receipt

    def _build_and_seal(self, pending) -> dict:
        from predictionio_tpu.models.als import fold_in_users
        from predictionio_tpu.models.cooccurrence import (
            cooccurrence_increments,
        )

        by_user = {}
        event_ids = []
        for user_id, item_id, rating, eid in pending:
            by_user.setdefault(user_id, []).append((item_id, rating))
            if eid is not None:
                event_ids.append(eid)
        model = self.model
        interactions = {}
        new_items = {}  # uidx -> item indices of THIS batch's events
        prior_items = {}  # uidx -> items already counted by base/deltas
        user_ids = []
        unknown = 0
        for user_id, batch_pairs in by_user.items():
            uidx = model.user_map.get(user_id)
            if uidx is None:
                # fold-in updates existing rows in place; brand-new users
                # wait for the next full retrain (bucket shapes and the
                # factor matrix never change mid-generation)
                unknown += 1
                continue
            pairs = batch_pairs
            if self.history_fn is not None:
                try:
                    pairs = list(self.history_fn(user_id)) or batch_pairs
                except Exception:
                    log.exception("history_fn failed for %r", user_id)
            items = []
            for item_id, rating in pairs:
                iidx = model.item_map.get(str(item_id))
                if iidx is not None:
                    items.append((iidx, float(rating)))
            if items:
                interactions[uidx] = items
                user_ids.append(user_id)
                # cooc increments count only THIS batch's events — the
                # history expansion above recomputes the fold-in row but
                # its historical pairs were already counted by the base
                # Gram and earlier deltas (multiset-subtracting the
                # batch from the full history leaves the prior items,
                # so cross pairs new×prior still count exactly once)
                raw = collections.Counter(
                    model.item_map.get(str(i)) for i, _ in batch_pairs)
                raw.pop(None, None)
                new_items[uidx] = list(raw)
                if pairs is not batch_pairs:
                    full = collections.Counter(
                        model.item_map.get(str(i)) for i, _ in pairs)
                    full.pop(None, None)
                    prior_items[uidx] = list(full - raw)
        epoch = self.log.last_epoch() + 1
        if not interactions:
            receipt = {"refused": True, "reason": "empty", "epoch": epoch,
                       "events": len(pending), "unknown_users": unknown}
            with self._lock:
                self._unknown_users += unknown
                self._seal_refused += 1
            return receipt

        cfg = model.config
        user_idx = np.array(sorted(interactions), dtype=np.int32)
        rows = fold_in_users(
            model.item_factors, {u: interactions[u] for u in user_idx},
            rank=cfg.rank, reg=cfg.reg, implicit=cfg.implicit,
            alpha=cfg.alpha, compute_dtype=cfg.compute_dtype)
        overlap = self._gate_overlap(user_idx, interactions, rows)
        quality = {"overlap": round(float(overlap), 6),
                   "threshold": self.min_overlap,
                   "sampled_users": min(self.gate_sample, len(user_idx)),
                   "k": self.gate_k}
        if overlap < self.min_overlap:
            # quarantine: nothing seals, epoch not burned, serving stays
            # on last-good; the refusal receipt is durable next to the log
            with self._lock:
                self._unknown_users += unknown
                self._seal_refused += 1
            receipt = {"refused": True, "reason": "quality", "epoch": epoch,
                       "events": len(pending), "users": len(user_idx),
                       "rolled_back_to": self.log.last_epoch(), **quality}
            self._write_refusal(epoch, receipt)
            log.warning(
                "delta epoch %d REFUSED: fold-in top-%d overlap %.4f < "
                "%.4f (PIO_DELTA_MIN_OVERLAP); serving stays on epoch %d",
                epoch, self.gate_k, overlap, self.min_overlap,
                self.log.last_epoch())
            return receipt

        cooc = cooccurrence_increments(new_items, prior_by_user=prior_items)
        delta = Delta(
            epoch=epoch, base_fingerprint=self.base_fingerprint,
            user_ids=tuple(user_ids), user_idx=user_idx,
            user_rows=rows,
            item_idx=np.zeros((0,), np.int32),
            item_rows=np.zeros((0, cfg.rank), np.float32),
            cooc_updates=cooc, events=len(pending),
            created_unix=time.time(), quality=quality,
            event_ids=tuple(event_ids))
        path = self.log.seal(delta)
        # keep the publisher's own base factors current so the NEXT
        # fold-in gate references the updated rows too (the caller's
        # _seal_lock makes this write race-free against other flushes)
        model.user_factors[user_idx] = rows
        with self._lock:
            self._unknown_users += unknown
            self._sealed += 1
            self._events_folded += len(pending)
        return {"sealed": True, "epoch": epoch, "path": path,
                "events": len(pending), "users": len(user_idx),
                "unknown_users": unknown, **quality}

    def _gate_overlap(self, user_idx, interactions, rows) -> float:
        """Top-k overlap of candidate fold-in rows vs a float64 reference
        solve on sampled users (the fold-in analogue of the quantization
        publish gate)."""
        from predictionio_tpu.models.als import fold_in_users

        n = len(user_idx)
        if n == 0:
            return 1.0
        sample = user_idx[:: max(1, n // max(1, self.gate_sample))]
        sample = sample[: self.gate_sample]
        cfg = self.model.config
        ref = fold_in_users(
            self.model.item_factors,
            {u: interactions[u] for u in sample},
            rank=cfg.rank, reg=cfg.reg, implicit=cfg.implicit,
            alpha=cfg.alpha, compute_dtype="f64")
        pos = {int(u): i for i, u in enumerate(user_idx)}
        V = np.asarray(self.model.item_factors, dtype=np.float32)
        k = min(self.gate_k, V.shape[0])
        if k == 0:
            return 1.0
        hits = 0
        for j, u in enumerate(sample):
            cand = rows[pos[int(u)]] @ V.T
            want = ref[j] @ V.T
            top_c = set(np.argsort(-cand)[:k].tolist())
            top_w = set(np.argsort(-want)[:k].tolist())
            hits += len(top_c & top_w) / float(k)
        return hits / float(len(sample))

    def _write_refusal(self, epoch: int, receipt: dict) -> None:
        p = os.path.join(self.log.directory, f"refusal-{epoch:08d}.json")
        try:
            with open(p, "w") as f:
                json.dump(receipt, f, sort_keys=True)
        except OSError:
            log.exception("could not persist refusal receipt %s", p)

    def stats(self) -> dict:
        with self._lock:
            return {
                "sealed": self._sealed,
                "seal_refused": self._seal_refused,
                "events_folded": self._events_folded,
                "unknown_users": self._unknown_users,
                "dedup_skipped": self._dedup_skipped,
                "pending": len(self._pending),
                "log_epoch": self.log.last_epoch(),
                "base_fingerprint": self.base_fingerprint,
                "last_receipt": self._last_receipt,
            }
