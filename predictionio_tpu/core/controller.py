"""DASE controller API: the typed pipeline engine developers implement.

Capability parity with the reference controller layer
(``core/.../controller/``): DataSource → Preparator → Algorithm(s) → Serving,
plus SanityCheck.  Differences by design (SURVEY.md §7):

* The reference's three algorithm flavors (``PAlgorithm.scala:46``,
  ``P2LAlgorithm.scala:46``, ``LAlgorithm.scala:45``) distinguish where the
  model LIVES on a Spark cluster (RDD-distributed vs driver-local).  On a TPU
  mesh that split collapses to :class:`Algorithm` (host model, auto-pickled)
  vs :class:`ShardedAlgorithm` (model is a pytree of device-sharded
  ``jax.Array``s; auto-persisted by gathering to host numpy, re-placed onto
  the mesh at deploy).  Both keep the reference's persistence escape hatches
  (PersistentModel / retrain-on-deploy), see ``persistence.py``.
* ``Params`` are plain dataclasses; ``engine.json`` parity parsing lives in
  ``engine.py``.
* All components receive a :class:`~predictionio_tpu.parallel.mesh.MeshContext`
  where the reference passed ``sc: SparkContext``.
"""

from __future__ import annotations

import abc
import dataclasses
from typing import Any, Generic, Optional, Sequence, TypeVar

TD = TypeVar("TD")  # training data
PD = TypeVar("PD")  # prepared data
M = TypeVar("M")  # model
Q = TypeVar("Q")  # query
P = TypeVar("P")  # predicted result
A = TypeVar("A")  # actual result


class Params:
    """Marker base for component parameter dataclasses (controller/Params.scala).

    Subclasses should be ``@dataclasses.dataclass``; they are constructed from
    the ``engine.json`` variant's ``params`` objects by ``engine.py``.
    """


@dataclasses.dataclass
class EmptyParams(Params):
    pass


class SanityCheck(abc.ABC):
    """Optional self-check on data objects (controller/SanityCheck.scala)."""

    @abc.abstractmethod
    def sanity_check(self) -> None:
        """Raise if the data object is malformed (e.g. empty training set)."""


class DataSource(Generic[TD, Q, A], abc.ABC):
    """Reads training and evaluation data from the event store.

    Parity: ``controller/PDataSource.scala`` / ``LDataSource.scala``
    (``readTraining``, ``readEval``).
    """

    def __init__(self, params: Optional[Params] = None):
        self.params = params

    @abc.abstractmethod
    def read_training(self, ctx) -> TD: ...

    def read_eval(self, ctx) -> list[tuple[TD, Sequence[tuple[Q, A]]]]:
        """k folds of (training data, [(query, actual)]) for evaluation."""
        raise NotImplementedError(
            f"{type(self).__name__} does not implement read_eval; "
            "evaluation is unavailable for this engine."
        )


class Preparator(Generic[TD, PD], abc.ABC):
    """Parity: ``controller/PPreparator.scala`` / ``LPreparator.scala``."""

    def __init__(self, params: Optional[Params] = None):
        self.params = params

    @abc.abstractmethod
    def prepare(self, ctx, training_data: TD) -> PD: ...


class IdentityPreparator(Preparator[TD, TD]):
    """Pass-through (controller/IdentityPreparator.scala)."""

    def prepare(self, ctx, training_data: TD) -> TD:
        return training_data


class Algorithm(Generic[PD, M, Q, P], abc.ABC):
    """Host-model algorithm: train on the mesh, model lives as a host object.

    Parity: ``P2LAlgorithm.scala:46``/``LAlgorithm.scala:45`` (model is a
    plain object, auto-serialized into the MODELDATA repo like the reference's
    Kryo blobs, ``CoreWorkflow.scala:76-81``).
    """

    def __init__(self, params: Optional[Params] = None):
        self.params = params

    @abc.abstractmethod
    def train(self, ctx, prepared_data: PD) -> M: ...

    @abc.abstractmethod
    def predict(self, model: M, query: Q) -> P: ...

    def batch_predict(self, model: M, queries: Sequence[tuple[int, Q]]) -> list[tuple[int, P]]:
        """Bulk predict for evaluation (parity: batchPredictBase,
        ``BaseAlgorithm.scala:81``).  Override to vectorize on device."""
        return [(i, self.predict(model, q)) for i, q in queries]

    # -- persistence hooks (parity: BaseAlgorithm.makePersistentModel:111) --
    def make_serializable_model(self, model: M) -> Any:
        """Return the picklable form of the model (identity by default).

        Returning :data:`predictionio_tpu.core.persistence.RETRAIN` opts into
        retrain-on-deploy (the reference's Unit-model mode,
        ``Engine.scala:210-232``).  A model implementing
        :class:`~predictionio_tpu.core.persistence.PersistentModel` is saved
        through its own ``save`` with a manifest instead.
        """
        return model

    def load_serializable_model(self, ctx, blob: Any) -> M:
        """Rebuild the in-memory model at deploy time (identity by default)."""
        return blob


class ShardedAlgorithm(Algorithm[PD, M, Q, P]):
    """Device-model algorithm: the model is a pytree of sharded jax.Arrays.

    Parity role: ``PAlgorithm.scala:46-126`` (distributed model).  Unlike the
    reference — where RDD-backed models cannot be auto-serialized and must be
    retrained or custom-persisted — sharded pytrees gather to host numpy for
    free, so auto-persistence WORKS here: ``make_serializable_model`` pulls
    the pytree to host, ``load_serializable_model`` re-places it with
    :meth:`model_sharding` onto the deploy mesh.
    """

    def make_serializable_model(self, model: M) -> Any:
        import jax

        from predictionio_tpu.parallel.mesh import device_get_global

        # multi-host: the all-gather under device_get_global is a
        # collective — run_train therefore gathers on EVERY process and
        # gates only the storage write to the coordinator
        return jax.tree.map(device_get_global, model)

    def load_serializable_model(self, ctx, blob: Any) -> M:
        return self.place_model(ctx, blob)

    def model_sharding(self, ctx, host_model: Any) -> Any:
        """Pytree of NamedShardings (or None = replicate) matching the model.

        Default: replicate everything; override to shard factor matrices.
        """
        return None

    def place_model(self, ctx, host_model: Any) -> M:
        import jax

        shardings = self.model_sharding(ctx, host_model)
        if shardings is None:
            return jax.tree.map(lambda a: ctx.replicate(a), host_model)
        return jax.tree.map(
            lambda a, s: jax.device_put(a, s) if s is not None else ctx.replicate(a),
            host_model,
            shardings,
        )


class Serving(Generic[Q, P], abc.ABC):
    """Merges per-algorithm predictions (controller/LServing.scala)."""

    def __init__(self, params: Optional[Params] = None):
        self.params = params

    def supplement(self, query: Q) -> Q:
        """Pre-process the query (parity: LServing.supplement)."""
        return query

    @abc.abstractmethod
    def serve(self, query: Q, predictions: Sequence[P]) -> P: ...


class FirstServing(Serving[Q, P]):
    """Serve the first algorithm's prediction (LFirstServing.scala)."""

    def serve(self, query: Q, predictions: Sequence[P]) -> P:
        return predictions[0]


class AverageServing(Serving[Q, float]):
    """Average numeric predictions (LAverageServing.scala)."""

    def serve(self, query: Q, predictions: Sequence[float]) -> float:
        return sum(predictions) / len(predictions)
