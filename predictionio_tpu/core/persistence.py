"""Model persistence: the three deploy-time modes, kept from the reference.

Parity: ``controller/PersistentModel.scala`` + ``BaseAlgorithm.makePersistentModel``
(``BaseAlgorithm.scala:111-115``) + manifest dispatch
(``controller/Engine.scala:241-250``):

1. **Auto-serialized** — the default: the (host-gathered) model pytree is
   pickled into the MODELDATA repository, mirroring the reference's Kryo blob
   (``CoreWorkflow.scala:76-81``; read back ``CreateServer.scala:202-206``).
2. **PersistentModel** — the model class implements ``save``/``load`` itself
   (e.g. orbax checkpoints of huge factor matrices); only a manifest naming
   the class is stored in MODELDATA.
3. **Retrain-on-deploy** — ``make_serializable_model`` returns :data:`RETRAIN`
   and deploy re-runs training (the reference's Unit-model mode,
   ``Engine.prepareDeploy``, ``Engine.scala:210-232``).
"""

from __future__ import annotations

import abc
import hashlib
import importlib
import pickle
from typing import Any

# Content-checksum envelope around the MODELDATA blob: magic + version +
# sha256(payload) + payload. Deploy verifies the digest before unpickling,
# turning a torn or bit-flipped blob into a clean ModelIntegrityError the
# server can degrade on (last-known-good) instead of a pickle crash deep
# in deserialization. Pickles start with b"\x80", so legacy un-enveloped
# blobs can never collide with the magic and keep loading as-is.
_ENVELOPE_MAGIC = b"PIOM1"
_DIGEST_LEN = 32  # sha256


class ModelIntegrityError(Exception):
    """The stored model blob fails its content checksum (torn write,
    media corruption); the blob must not be deserialized."""


def seal_model_blob(payload: bytes) -> bytes:
    """Wrap a serialized-models payload in the checksum envelope."""
    return _ENVELOPE_MAGIC + hashlib.sha256(payload).digest() + payload


def open_model_blob(blob: bytes) -> bytes:
    """Verify and strip the envelope; raises :class:`ModelIntegrityError`
    on digest mismatch. Legacy blobs (no magic) pass through unchanged."""
    if not blob.startswith(_ENVELOPE_MAGIC):
        return blob
    header_len = len(_ENVELOPE_MAGIC) + _DIGEST_LEN
    if len(blob) < header_len:
        raise ModelIntegrityError(
            f"model blob shorter than its envelope header ({len(blob)} bytes)"
        )
    digest = blob[len(_ENVELOPE_MAGIC):header_len]
    payload = blob[header_len:]
    if hashlib.sha256(payload).digest() != digest:
        raise ModelIntegrityError(
            "model blob checksum mismatch (torn write or corruption)"
        )
    return payload


def seal_blob_file(path: str, payload: bytes) -> None:
    """Atomically write ``payload`` to ``path`` inside the checksum
    envelope (tmp + rename, so a crash mid-write leaves either the old
    file or none — never a torn blob that passes ``startswith`` but fails
    later).  Sidecar artifacts (e.g. quantized factor variants) seal
    through this so deploy gets the same integrity guarantee as the
    MODELDATA blob itself."""
    import os

    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(seal_model_blob(payload))
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def open_blob_file(path: str) -> bytes:
    """Read and verify a :func:`seal_blob_file` artifact; raises
    :class:`ModelIntegrityError` on checksum mismatch, ``OSError`` when
    missing — both of which deploy treats as 'variant unavailable' and
    degrades to the base (fp32) model rather than failing the load."""
    with open(path, "rb") as f:
        return open_model_blob(f.read())


# ---------------------------------------------------------------------------
# Generation quarantine: durable receipts for generations that failed ONLINE
# verification (canary rollback, soak-watchdog rollback). A receipt is a
# sealed JSON blob under <PIO_FS_BASEDIR>/quarantine/<engine-key>/<id>.json;
# newest-COMPLETED selection (workflow.get_latest_completed_instance), the
# query server's cold-start fallback, fleet.roll() targets and future
# canaries all consult the set so a bad generation is never auto-deployed
# twice. Receipts seal through the same checksum envelope as model blobs;
# a torn/corrupt receipt still QUARANTINES its id (fail-safe: the filename
# carries the id, so an unreadable receipt can only over-block, never
# silently re-admit a known-bad generation).


def _engine_key(engine_id: str, engine_version: str, engine_variant: str) -> str:
    import re

    raw = f"{engine_id}-{engine_version}-{engine_variant}"
    return re.sub(r"[^A-Za-z0-9_.-]", "_", raw)


def quarantine_dir(
    engine_id: str = "default",
    engine_version: str = "default",
    engine_variant: str = "default",
) -> str:
    """Receipt directory for one engine key (created lazily by writes)."""
    import os

    from predictionio_tpu.utils.fs import pio_base_dir

    return os.path.join(
        pio_base_dir(),
        "quarantine",
        _engine_key(engine_id, engine_version, engine_variant),
    )


def _receipt_path(dirname: str, instance_id: str) -> str:
    import os
    import re

    safe = re.sub(r"[^A-Za-z0-9_.-]", "_", instance_id)
    return os.path.join(dirname, f"{safe}.json")


def write_quarantine_receipt(
    instance_id: str,
    reason: str,
    engine_id: str = "default",
    engine_version: str = "default",
    engine_variant: str = "default",
    epoch: int = 0,
    details: dict | None = None,
) -> str:
    """Durably quarantine ``instance_id``; returns the receipt path.

    The receipt is sealed (checksum envelope) and published atomically
    (tmp + fsync + rename), so a crash mid-write leaves either no receipt
    or a whole one — and callers that must not lose the quarantine on
    crash write their intent to a journal FIRST and re-issue this call on
    resume (it is idempotent: re-writing an existing receipt keeps the
    earliest epoch's verdict by simply overwriting with equivalent data).
    """
    import json
    import os
    import time

    dirname = quarantine_dir(engine_id, engine_version, engine_variant)
    os.makedirs(dirname, exist_ok=True)
    path = _receipt_path(dirname, instance_id)
    receipt = {
        "instanceId": instance_id,
        "reason": reason,
        "epoch": int(epoch),
        "quarantinedAt": time.time(),
        "engineId": engine_id,
        "engineVersion": engine_version,
        "engineVariant": engine_variant,
        "details": details or {},
    }
    seal_blob_file(path, json.dumps(receipt, sort_keys=True).encode("utf-8"))
    from predictionio_tpu.utils.fs import fsync_dir

    fsync_dir(dirname)
    return path


def read_quarantine_receipts(
    engine_id: str = "default",
    engine_version: str = "default",
    engine_variant: str = "default",
) -> list[dict]:
    """All receipts for one engine key, unreadable ones included.

    A receipt that fails its checksum (torn write the atomic protocol
    should prevent, or media corruption) is surfaced as
    ``{"instanceId": <from filename>, "reason": "unreadable-receipt"}`` —
    quarantine fails SAFE: a damaged receipt blocks the generation rather
    than re-admitting it.
    """
    import json
    import os

    dirname = quarantine_dir(engine_id, engine_version, engine_variant)
    try:
        names = sorted(os.listdir(dirname))
    except OSError:
        return []
    receipts: list[dict] = []
    for name in names:
        if not name.endswith(".json"):
            continue
        path = os.path.join(dirname, name)
        try:
            receipts.append(json.loads(open_blob_file(path).decode("utf-8")))
        except (ModelIntegrityError, OSError, ValueError):
            receipts.append(
                {"instanceId": name[: -len(".json")],
                 "reason": "unreadable-receipt"}
            )
    return receipts


def quarantined_instance_ids(
    engine_id: str = "default",
    engine_version: str = "default",
    engine_variant: str = "default",
) -> set:
    """The ids no selection path may auto-deploy."""
    return {
        str(r.get("instanceId"))
        for r in read_quarantine_receipts(engine_id, engine_version,
                                          engine_variant)
        if r.get("instanceId")
    }


def is_quarantined(
    instance_id: str,
    engine_id: str = "default",
    engine_version: str = "default",
    engine_variant: str = "default",
) -> bool:
    import os

    dirname = quarantine_dir(engine_id, engine_version, engine_variant)
    return os.path.exists(_receipt_path(dirname, instance_id))


def clear_quarantine(
    instance_id: str,
    engine_id: str = "default",
    engine_version: str = "default",
    engine_variant: str = "default",
) -> bool:
    """Operator-only release of a quarantined generation (``pio canary
    quarantine --release``); returns False when no receipt existed."""
    import os

    dirname = quarantine_dir(engine_id, engine_version, engine_variant)
    try:
        os.unlink(_receipt_path(dirname, instance_id))
    except OSError:
        return False
    from predictionio_tpu.utils.fs import fsync_dir

    fsync_dir(dirname)
    return True


class _RetrainSentinel:
    def __repr__(self) -> str:
        return "RETRAIN"


RETRAIN = _RetrainSentinel()


class PersistentModel(abc.ABC):
    """Self-persisting model (parity: trait PersistentModel/Loader)."""

    @abc.abstractmethod
    def save(self, instance_id: str, params: Any) -> bool:
        """Persist; return True to store a manifest (False ⇒ auto-pickle)."""

    @classmethod
    @abc.abstractmethod
    def load(cls, instance_id: str, params: Any, ctx) -> "PersistentModel":
        """Rebuild at deploy time."""


def class_path(obj_or_cls) -> str:
    cls = obj_or_cls if isinstance(obj_or_cls, type) else type(obj_or_cls)
    return f"{cls.__module__}.{cls.__qualname__}"


def resolve_class(path: str):
    """Import ``pkg.mod.Class`` (the Python replacement for JVM reflection)."""
    module_name, _, cls_name = path.rpartition(".")
    obj: Any = importlib.import_module(module_name)
    for part in cls_name.split("."):
        obj = getattr(obj, part)
    return obj


def serialize_models(
    instance_id: str, algorithms: list, models: list, algo_params: list
) -> bytes:
    """Build the MODELDATA blob (parity: Engine.makeSerializableModels:284).

    Each slot is one of ``("pickle", blob)``, ``("manifest", class_path)`` or
    ``("retrain", None)``.
    """
    slots = []
    for algo, model, params in zip(algorithms, models, algo_params):
        if isinstance(model, PersistentModel):
            # multi-host: EVERY process calls save() — implementations that
            # persist through save_pytree run an orbax collective (which
            # barriers across hosts and writes once), so gating the call to
            # the coordinator would deadlock the job. Implementations gate
            # their own non-collective file writes (e.g. the id-map pickle
            # in CheckpointedALSModel.save) to stay single-writer.
            if model.save(instance_id, params):
                slots.append(("manifest", class_path(model)))
            else:
                slots.append(("pickle", algo.make_serializable_model(model)))
            continue
        serializable = algo.make_serializable_model(model)
        if serializable is RETRAIN or isinstance(serializable, _RetrainSentinel):
            slots.append(("retrain", None))
        else:
            slots.append(("pickle", serializable))
    return pickle.dumps(slots, protocol=pickle.HIGHEST_PROTOCOL)


def deserialize_models(
    blob: bytes, instance_id: str, algorithms: list, algo_params: list, ctx
) -> tuple[list, list[int]]:
    """Rebuild models at deploy; returns (models, indices_needing_retrain).

    Parity: ``Engine.prepareDeploy`` (``Engine.scala:198-267``).
    """
    slots = pickle.loads(blob)
    models: list = []
    retrain_idx: list[int] = []
    for i, ((kind, payload), algo, params) in enumerate(
        zip(slots, algorithms, algo_params)
    ):
        if kind == "pickle":
            models.append(algo.load_serializable_model(ctx, payload))
        elif kind == "manifest":
            cls = resolve_class(payload)
            # manifest loaders return HOST-form models; route through the
            # algorithm's load hook so deploy-side state (device placement,
            # scorers) binds to THIS ctx, same as the pickle path
            models.append(
                algo.load_serializable_model(ctx, cls.load(instance_id, params, ctx))
            )
        elif kind == "retrain":
            models.append(None)
            retrain_idx.append(i)
        else:
            raise ValueError(f"unknown model slot kind {kind!r}")
    return models, retrain_idx
