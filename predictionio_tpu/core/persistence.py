"""Model persistence: the three deploy-time modes, kept from the reference.

Parity: ``controller/PersistentModel.scala`` + ``BaseAlgorithm.makePersistentModel``
(``BaseAlgorithm.scala:111-115``) + manifest dispatch
(``controller/Engine.scala:241-250``):

1. **Auto-serialized** — the default: the (host-gathered) model pytree is
   pickled into the MODELDATA repository, mirroring the reference's Kryo blob
   (``CoreWorkflow.scala:76-81``; read back ``CreateServer.scala:202-206``).
2. **PersistentModel** — the model class implements ``save``/``load`` itself
   (e.g. orbax checkpoints of huge factor matrices); only a manifest naming
   the class is stored in MODELDATA.
3. **Retrain-on-deploy** — ``make_serializable_model`` returns :data:`RETRAIN`
   and deploy re-runs training (the reference's Unit-model mode,
   ``Engine.prepareDeploy``, ``Engine.scala:210-232``).
"""

from __future__ import annotations

import abc
import hashlib
import importlib
import pickle
from typing import Any

# Content-checksum envelope around the MODELDATA blob: magic + version +
# sha256(payload) + payload. Deploy verifies the digest before unpickling,
# turning a torn or bit-flipped blob into a clean ModelIntegrityError the
# server can degrade on (last-known-good) instead of a pickle crash deep
# in deserialization. Pickles start with b"\x80", so legacy un-enveloped
# blobs can never collide with the magic and keep loading as-is.
_ENVELOPE_MAGIC = b"PIOM1"
_DIGEST_LEN = 32  # sha256


class ModelIntegrityError(Exception):
    """The stored model blob fails its content checksum (torn write,
    media corruption); the blob must not be deserialized."""


def seal_model_blob(payload: bytes) -> bytes:
    """Wrap a serialized-models payload in the checksum envelope."""
    return _ENVELOPE_MAGIC + hashlib.sha256(payload).digest() + payload


def open_model_blob(blob: bytes) -> bytes:
    """Verify and strip the envelope; raises :class:`ModelIntegrityError`
    on digest mismatch. Legacy blobs (no magic) pass through unchanged."""
    if not blob.startswith(_ENVELOPE_MAGIC):
        return blob
    header_len = len(_ENVELOPE_MAGIC) + _DIGEST_LEN
    if len(blob) < header_len:
        raise ModelIntegrityError(
            f"model blob shorter than its envelope header ({len(blob)} bytes)"
        )
    digest = blob[len(_ENVELOPE_MAGIC):header_len]
    payload = blob[header_len:]
    if hashlib.sha256(payload).digest() != digest:
        raise ModelIntegrityError(
            "model blob checksum mismatch (torn write or corruption)"
        )
    return payload


def seal_blob_file(path: str, payload: bytes) -> None:
    """Atomically write ``payload`` to ``path`` inside the checksum
    envelope (tmp + rename, so a crash mid-write leaves either the old
    file or none — never a torn blob that passes ``startswith`` but fails
    later).  Sidecar artifacts (e.g. quantized factor variants) seal
    through this so deploy gets the same integrity guarantee as the
    MODELDATA blob itself."""
    import os

    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(seal_model_blob(payload))
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def open_blob_file(path: str) -> bytes:
    """Read and verify a :func:`seal_blob_file` artifact; raises
    :class:`ModelIntegrityError` on checksum mismatch, ``OSError`` when
    missing — both of which deploy treats as 'variant unavailable' and
    degrades to the base (fp32) model rather than failing the load."""
    with open(path, "rb") as f:
        return open_model_blob(f.read())


class _RetrainSentinel:
    def __repr__(self) -> str:
        return "RETRAIN"


RETRAIN = _RetrainSentinel()


class PersistentModel(abc.ABC):
    """Self-persisting model (parity: trait PersistentModel/Loader)."""

    @abc.abstractmethod
    def save(self, instance_id: str, params: Any) -> bool:
        """Persist; return True to store a manifest (False ⇒ auto-pickle)."""

    @classmethod
    @abc.abstractmethod
    def load(cls, instance_id: str, params: Any, ctx) -> "PersistentModel":
        """Rebuild at deploy time."""


def class_path(obj_or_cls) -> str:
    cls = obj_or_cls if isinstance(obj_or_cls, type) else type(obj_or_cls)
    return f"{cls.__module__}.{cls.__qualname__}"


def resolve_class(path: str):
    """Import ``pkg.mod.Class`` (the Python replacement for JVM reflection)."""
    module_name, _, cls_name = path.rpartition(".")
    obj: Any = importlib.import_module(module_name)
    for part in cls_name.split("."):
        obj = getattr(obj, part)
    return obj


def serialize_models(
    instance_id: str, algorithms: list, models: list, algo_params: list
) -> bytes:
    """Build the MODELDATA blob (parity: Engine.makeSerializableModels:284).

    Each slot is one of ``("pickle", blob)``, ``("manifest", class_path)`` or
    ``("retrain", None)``.
    """
    slots = []
    for algo, model, params in zip(algorithms, models, algo_params):
        if isinstance(model, PersistentModel):
            # multi-host: EVERY process calls save() — implementations that
            # persist through save_pytree run an orbax collective (which
            # barriers across hosts and writes once), so gating the call to
            # the coordinator would deadlock the job. Implementations gate
            # their own non-collective file writes (e.g. the id-map pickle
            # in CheckpointedALSModel.save) to stay single-writer.
            if model.save(instance_id, params):
                slots.append(("manifest", class_path(model)))
            else:
                slots.append(("pickle", algo.make_serializable_model(model)))
            continue
        serializable = algo.make_serializable_model(model)
        if serializable is RETRAIN or isinstance(serializable, _RetrainSentinel):
            slots.append(("retrain", None))
        else:
            slots.append(("pickle", serializable))
    return pickle.dumps(slots, protocol=pickle.HIGHEST_PROTOCOL)


def deserialize_models(
    blob: bytes, instance_id: str, algorithms: list, algo_params: list, ctx
) -> tuple[list, list[int]]:
    """Rebuild models at deploy; returns (models, indices_needing_retrain).

    Parity: ``Engine.prepareDeploy`` (``Engine.scala:198-267``).
    """
    slots = pickle.loads(blob)
    models: list = []
    retrain_idx: list[int] = []
    for i, ((kind, payload), algo, params) in enumerate(
        zip(slots, algorithms, algo_params)
    ):
        if kind == "pickle":
            models.append(algo.load_serializable_model(ctx, payload))
        elif kind == "manifest":
            cls = resolve_class(payload)
            # manifest loaders return HOST-form models; route through the
            # algorithm's load hook so deploy-side state (device placement,
            # scorers) binds to THIS ctx, same as the pickle path
            models.append(
                algo.load_serializable_model(ctx, cls.load(instance_id, params, ctx))
            )
        elif kind == "retrain":
            models.append(None)
            retrain_idx.append(i)
        else:
            raise ValueError(f"unknown model slot kind {kind!r}")
    return models, retrain_idx
