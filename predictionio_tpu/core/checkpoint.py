"""Sharded checkpointing: orbax-backed pytree save/restore + step state.

SURVEY.md §5 calls for "orbax-style checkpoint of sharded factor matrices +
step state" on top of the reference's three deploy-time persistence modes
(which ``core/persistence.py`` keeps).  This module supplies:

* :func:`save_pytree` / :func:`restore_pytree` — orbax round trip of any
  pytree of arrays; on restore, arrays are placed onto the given
  :class:`MeshContext` with per-leaf shardings (or replicated).
* :class:`CheckpointManager` — step-numbered checkpoints under a directory
  (``latest_step``/``save``/``restore``), the mid-training checkpoint/resume
  primitive (the reference's only analogue is MLlib ALS's
  ``setCheckpointInterval``, which truncates RDD lineage rather than
  persisting progress).
"""

from __future__ import annotations

import hashlib
import logging
import os
import re
from typing import Any, Optional

import numpy as np

logger = logging.getLogger(__name__)


def dataset_digest(*arrays) -> int:
    """Order-sensitive dataset digest for checkpoint fingerprints.

    sha1 over the raw bytes of each array in sequence (incremental — no
    concatenated copy of a multi-GB dataset), truncated to 48 bits so the
    value stays exact inside the float64 fingerprint arrays the trainers
    build. Permutation-sensitive by construction: element sums are not
    (a reordered/relabeled dataset must NOT resume a foreign checkpoint).
    """
    h = hashlib.sha1()
    for a in arrays:
        # .data is a zero-copy memoryview; tobytes() would transiently
        # double memory per array on multi-GB datasets
        h.update(np.ascontiguousarray(a).data)
    return int(h.hexdigest()[:12], 16)


_CHECKPOINTER = None


def _checkpointer():
    global _CHECKPOINTER
    if _CHECKPOINTER is None:
        import orbax.checkpoint as ocp

        _CHECKPOINTER = ocp.PyTreeCheckpointer()
    return _CHECKPOINTER


def save_pytree(path: str, tree: Any) -> None:
    """Persist a pytree of (device or host) arrays at ``path``.

    Multi-host: device_get_global all-gathers process-spanning shards —
    a COLLECTIVE, so when leaves are sharded across processes this must
    be called from every process (gather on all, write where called).
    """
    import jax

    from predictionio_tpu.parallel.mesh import device_get_global

    host_tree = jax.tree.map(device_get_global, tree)
    _checkpointer().save(os.path.abspath(path), host_tree, force=True)


def restore_pytree(path: str, ctx=None, shardings: Any = None) -> Any:
    """Restore a pytree; with ``ctx`` the leaves are placed on its mesh
    (replicated, or per-leaf ``shardings``)."""
    import jax

    tree = _checkpointer().restore(os.path.abspath(path))
    if ctx is None:
        return tree
    if shardings is None:
        return jax.tree.map(ctx.replicate, tree)
    return jax.tree.map(
        lambda a, s: jax.device_put(a, s) if s is not None else ctx.replicate(a),
        tree,
        shardings,
        is_leaf=lambda x: x is None,  # None sharding leaf means replicate
    )


class CheckpointManager:
    """Step-numbered checkpoints: ``<dir>/step_<n>/`` per save."""

    STEP_RE = re.compile(r"^step_(\d+)$")

    def __init__(self, directory: str, keep: int = 2):
        self.directory = os.path.abspath(directory)
        self.keep = keep
        os.makedirs(self.directory, exist_ok=True)

    def _step_dir(self, step: int) -> str:
        return os.path.join(self.directory, f"step_{step}")

    def steps(self) -> list[int]:
        out = []
        for name in os.listdir(self.directory):
            m = self.STEP_RE.match(name)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    def latest_step(self) -> Optional[int]:
        steps = self.steps()
        return steps[-1] if steps else None

    def _fp_path(self, step: int) -> str:
        return os.path.join(self.directory, f"step_{step}.fp.npy")

    def save(self, step: int, tree: Any) -> None:
        """Persist a step. Multi-host: EVERY process must call this — the
        orbax write is a collective (it runs sync_global_devices barriers;
        gating it to the coordinator deadlocks the job). orbax itself
        writes host arrays once; the sidecar and retention file ops below
        are plain filesystem writes, so those ARE coordinator-gated to
        keep a shared checkpoint_dir single-writer.
        """
        from predictionio_tpu.parallel import distributed

        save_pytree(self._step_dir(step), tree)
        if not distributed.should_write_storage():
            return
        # fingerprint sidecar: resume_from can reject a non-matching step
        # without restoring its full (possibly multi-GB) state
        if isinstance(tree, dict) and tree.get("fingerprint") is not None:
            np.save(self._fp_path(step), np.asarray(tree["fingerprint"]))
        # retention: drop oldest beyond keep
        import shutil

        steps = self.steps()
        for old in steps[: max(0, len(steps) - self.keep)]:
            shutil.rmtree(self._step_dir(old), ignore_errors=True)
            try:
                os.remove(self._fp_path(old))
            except FileNotFoundError:
                pass

    def saved_fingerprint(self, step: int):
        """The sidecar fingerprint for ``step``, or None if absent."""
        try:
            return np.load(self._fp_path(step))
        except (FileNotFoundError, ValueError):
            return None

    def restore(self, step: Optional[int] = None, ctx=None, shardings=None) -> Any:
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {self.directory}")
        return restore_pytree(self._step_dir(step), ctx=ctx, shardings=shardings)


def resume_from(manager: CheckpointManager, fingerprint, max_step: int):
    """The fingerprint-gated resume policy shared by the trainers.

    Returns ``(start_step, host_state)`` for the LARGEST checkpoint step
    <= ``max_step`` whose stored fingerprint matches, or ``(0, None)``.
    Scanning past the global latest matters: a leftover step from a longer
    run (e.g. step_20 when rerunning with 10 iterations) must not disable
    resume from a valid earlier step, and a foreign/stale checkpoint is
    skipped with a warning, never silently loaded.
    """
    want = np.asarray(fingerprint)
    skipped_high = []
    for step in sorted(manager.steps(), reverse=True):
        if step > max_step:
            skipped_high.append(step)
            continue
        # cheap rejection via the sidecar before touching the full state
        side = manager.saved_fingerprint(step)
        if side is not None and not (
            side.shape == want.shape and np.allclose(side, want)
        ):
            logger.warning(
                "checkpoint step %d under %s does not match this "
                "config/dataset; ignoring", step, manager.directory,
            )
            continue
        state = manager.restore(step)  # host pytree
        got = np.asarray(state.get("fingerprint"))
        if got.shape == want.shape and np.allclose(got, want):
            logger.info(
                "resuming from checkpoint step %d under %s",
                step, manager.directory,
            )
            return step, state
        logger.warning(
            "checkpoint step %d under %s does not match this config/dataset; "
            "ignoring", step, manager.directory,
        )
    if skipped_high:
        logger.warning(
            "checkpoint steps %s under %s exceed the requested %d; "
            "starting fresh", skipped_high, manager.directory, max_step,
        )
    return 0, None


def validate_interval(interval: int) -> None:
    if interval < 1:
        raise ValueError(f"checkpoint_interval must be >= 1, got {interval}")


def save_due(step_done: int, interval: int, total_steps: int) -> bool:
    """The save cadence both trainers follow: every ``interval`` completed
    steps, plus always at the end of the run."""
    return step_done % interval == 0 or step_done == total_steps
