"""Engine: wires DASE components and owns train/eval execution.

Parity: ``controller/Engine.scala:82-829`` + ``EngineParams.scala:35`` +
``EngineFactory.scala:33``.  ``Engine.train`` mirrors ``Engine.object.train``
(``Engine.scala:623-710``): read → sanity-check → prepare → per-algorithm
train, with ``stop_after_read``/``stop_after_prepare`` debug interrupts
(``Engine.scala:664-688``).  ``Engine.eval`` mirrors ``Engine.object.eval``
(``Engine.scala:728-817``): per-fold train + batch predict + serving join.

``engine.json`` variants parse exactly like the reference
(``Engine.jValueToEngineParams``, ``Engine.scala:355-418``): the JSON params
of each component are bound to that component's declared ``Params`` dataclass
(Python dataclasses replace the json4s/Gson dual extractor,
``JsonExtractor.scala:59-79``).
"""

from __future__ import annotations

import dataclasses
import json
import logging
from typing import Any, Generic, Optional, Sequence, Type, TypeVar

from predictionio_tpu.core.controller import (
    Algorithm,
    DataSource,
    EmptyParams,
    Params,
    Preparator,
    SanityCheck,
    Serving,
)

logger = logging.getLogger(__name__)

Q = TypeVar("Q")
P = TypeVar("P")


class StopAfterReadInterruption(Exception):
    """Parity: Engine.scala:664 — debug interrupt after DataSource.read."""


class StopAfterPrepareInterruption(Exception):
    """Parity: Engine.scala:676 — debug interrupt after Preparator.prepare."""


def params_from_json(params_cls: Optional[Type[Params]], obj: Any) -> Params:
    """Bind a JSON object to a Params dataclass (JsonExtractor parity).

    Unknown keys are rejected so engine.json typos fail loudly, like the
    reference's typed extraction.
    """
    if params_cls is None:
        if obj:
            raise ValueError(
                f"params {sorted(obj)} supplied but the component declares no "
                "params_cls; remove them or declare a Params dataclass"
            )
        return EmptyParams()
    if obj is None:
        obj = {}
    if not dataclasses.is_dataclass(params_cls):
        raise TypeError(f"{params_cls} must be a dataclass Params")
    # json_aliases maps JSON keys that aren't valid Python identifiers
    # (e.g. the reference's "lambda") onto dataclass field names
    aliases = getattr(params_cls, "json_aliases", {})
    if aliases:
        obj = {aliases.get(k, k): v for k, v in obj.items()}
    names = {f.name for f in dataclasses.fields(params_cls)}
    unknown = set(obj) - names
    if unknown:
        raise ValueError(
            f"unknown parameter(s) {sorted(unknown)} for {params_cls.__name__} "
            f"(accepted: {sorted(names)})"
        )
    return params_cls(**obj)


def params_to_json(params: Optional[Params]) -> dict:
    if params is None:
        return {}
    if dataclasses.is_dataclass(params):
        return dataclasses.asdict(params)
    return dict(params)  # type: ignore[arg-type]


@dataclasses.dataclass
class EngineParams:
    """One full pipeline configuration (parity: EngineParams.scala:35)."""

    data_source_params: Params = dataclasses.field(default_factory=EmptyParams)
    preparator_params: Params = dataclasses.field(default_factory=EmptyParams)
    algorithm_params_list: list[tuple[str, Params]] = dataclasses.field(
        default_factory=list
    )
    serving_params: Params = dataclasses.field(default_factory=EmptyParams)

    def to_json_strings(self) -> dict[str, str]:
        """Serialized form stored on EngineInstance rows."""
        return {
            "data_source_params": json.dumps(params_to_json(self.data_source_params)),
            "preparator_params": json.dumps(params_to_json(self.preparator_params)),
            "algorithms_params": json.dumps(
                [
                    {"name": n, "params": params_to_json(p)}
                    for n, p in self.algorithm_params_list
                ]
            ),
            "serving_params": json.dumps(params_to_json(self.serving_params)),
        }


class Engine(Generic[Q, P]):
    """Parity: controller/Engine.scala:82 (the DASE wiring object)."""

    def __init__(
        self,
        data_source_cls: Type[DataSource],
        preparator_cls: Type[Preparator],
        algorithm_cls_map: dict[str, Type[Algorithm]],
        serving_cls: Type[Serving],
        query_cls: Optional[type] = None,
    ):
        self.data_source_cls = data_source_cls
        self.preparator_cls = preparator_cls
        self.algorithm_cls_map = dict(algorithm_cls_map)
        self.serving_cls = serving_cls
        self.query_cls = query_cls

    # -- engine.json binding (Engine.jValueToEngineParams parity) ----------
    @staticmethod
    def _params_cls_of(component_cls) -> Optional[Type[Params]]:
        return getattr(component_cls, "params_cls", None)

    def params_from_variant(self, variant: dict) -> EngineParams:
        ds = params_from_json(
            self._params_cls_of(self.data_source_cls),
            (variant.get("datasource") or {}).get("params"),
        )
        prep = params_from_json(
            self._params_cls_of(self.preparator_cls),
            (variant.get("preparator") or {}).get("params"),
        )
        algo_list: list[tuple[str, Params]] = []
        for spec in variant.get("algorithms") or []:
            name = spec.get("name")
            if name not in self.algorithm_cls_map:
                raise ValueError(
                    f"algorithm {name!r} not registered in engine "
                    f"(available: {sorted(self.algorithm_cls_map)})"
                )
            algo_list.append(
                (
                    name,
                    params_from_json(
                        self._params_cls_of(self.algorithm_cls_map[name]),
                        spec.get("params"),
                    ),
                )
            )
        if not algo_list:
            # default: first registered algorithm with default params
            name = next(iter(self.algorithm_cls_map))
            algo_list = [
                (name, params_from_json(self._params_cls_of(self.algorithm_cls_map[name]), {}))
            ]
        serving = params_from_json(
            self._params_cls_of(self.serving_cls),
            (variant.get("serving") or {}).get("params"),
        )
        return EngineParams(ds, prep, algo_list, serving)

    def params_from_instance_strings(self, strings: dict[str, str]) -> EngineParams:
        """Rebuild EngineParams from EngineInstance rows (deploy path).

        Parity: ``Engine.engineInstanceToEngineParams`` (Engine.scala:420-490).
        """
        ds = params_from_json(
            self._params_cls_of(self.data_source_cls),
            json.loads(strings.get("data_source_params") or "{}"),
        )
        prep = params_from_json(
            self._params_cls_of(self.preparator_cls),
            json.loads(strings.get("preparator_params") or "{}"),
        )
        algo_list = []
        for spec in json.loads(strings.get("algorithms_params") or "[]"):
            name = spec["name"]
            algo_list.append(
                (
                    name,
                    params_from_json(
                        self._params_cls_of(self.algorithm_cls_map[name]),
                        spec.get("params"),
                    ),
                )
            )
        serving = params_from_json(
            self._params_cls_of(self.serving_cls),
            json.loads(strings.get("serving_params") or "{}"),
        )
        return EngineParams(ds, prep, algo_list, serving)

    # -- component instantiation (Doer.apply parity, AbstractDoer.scala:46) -
    def make_algorithms(self, engine_params: EngineParams) -> list[Algorithm]:
        return [
            self.algorithm_cls_map[name](params)
            for name, params in engine_params.algorithm_params_list
        ]

    def make_serving(self, engine_params: EngineParams) -> Serving:
        return self.serving_cls(engine_params.serving_params)

    # -- train (Engine.object.train parity, Engine.scala:623-710) ----------
    def prepare_data(
        self,
        ctx,
        engine_params: EngineParams,
        skip_sanity_check: bool = False,
        stop_after_read: bool = False,
        stop_after_prepare: bool = False,
    ):
        """Read + prepare (the DS→Prep half of train)."""
        data_source = self.data_source_cls(engine_params.data_source_params)
        td = data_source.read_training(ctx)
        if not skip_sanity_check and isinstance(td, SanityCheck):
            logger.info("sanity-checking training data %s", type(td).__name__)
            td.sanity_check()
        if stop_after_read:
            raise StopAfterReadInterruption()
        preparator = self.preparator_cls(engine_params.preparator_params)
        pd = preparator.prepare(ctx, td)
        if not skip_sanity_check and isinstance(pd, SanityCheck):
            pd.sanity_check()
        if stop_after_prepare:
            raise StopAfterPrepareInterruption()
        return pd

    def train(
        self,
        ctx,
        engine_params: EngineParams,
        skip_sanity_check: bool = False,
        stop_after_read: bool = False,
        stop_after_prepare: bool = False,
        algorithms: Optional[Sequence[Algorithm]] = None,
    ) -> list:
        pd = self.prepare_data(
            ctx,
            engine_params,
            skip_sanity_check=skip_sanity_check,
            stop_after_read=stop_after_read,
            stop_after_prepare=stop_after_prepare,
        )
        if algorithms is None:
            algorithms = self.make_algorithms(engine_params)
        models = []
        for algo in algorithms:
            model = algo.train(ctx, pd)
            if not skip_sanity_check and isinstance(model, SanityCheck):
                model.sanity_check()
            models.append(model)
        return models

    # -- eval (Engine.object.eval parity, Engine.scala:728-817) ------------
    def eval(
        self, ctx, engine_params: EngineParams
    ) -> list[tuple[Any, Sequence[tuple[Q, P, Any]]]]:
        """Per evaluation fold: (query, prediction, actual) triples.

        Returns [(fold_info, [(q, p, a), ...])] — the input MetricEvaluator
        scores (reference shape: RDD[(Q, P, A)] per fold).
        """
        data_source = self.data_source_cls(engine_params.data_source_params)
        folds = data_source.read_eval(ctx)
        preparator = self.preparator_cls(engine_params.preparator_params)
        serving = self.make_serving(engine_params)
        results = []
        for fold_idx, (td, qa_list) in enumerate(folds):
            pd = preparator.prepare(ctx, td)
            algorithms = self.make_algorithms(engine_params)
            models = [algo.train(ctx, pd) for algo in algorithms]
            supplemented = [
                (i, serving.supplement(q)) for i, (q, _) in enumerate(qa_list)
            ]
            # per-algorithm batch predict, then join per query index
            # (parity: algo.batchPredictBase + union/groupByKey,
            #  Engine.scala:781-794)
            per_algo: list[dict[int, P]] = []
            for algo, model in zip(algorithms, models):
                preds = algo.batch_predict(model, supplemented)
                per_algo.append(dict(preds))
            triples = []
            for i, (q, a) in enumerate(qa_list):
                predictions = [d[i] for d in per_algo if i in d]
                p = serving.serve(supplemented[i][1], predictions)
                triples.append((q, p, a))
            results.append((fold_idx, triples))
        return results


class EngineFactory:
    """Parity: EngineFactory.scala:33 — named constructor for an Engine.

    Subclasses override :meth:`apply`; the workflow resolves the factory by
    dotted path from ``engine.json``'s ``engineFactory`` field.
    """

    @classmethod
    def apply(cls) -> Engine:
        raise NotImplementedError
