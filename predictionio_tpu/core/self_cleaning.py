"""Self-cleaning data source: sliding-window event compaction.

Parity: ``core/.../core/SelfCleaningDataSource.scala:42-324``:

* :class:`EventWindow` (``:320``) — ``duration`` (seconds here; the reference
  parses "1 day"-style strings, accepted too), ``remove_duplicates``,
  ``compress_properties``.
* :func:`clean_persisted_events` (``cleanPersistedPEvents:160``) — compacts
  each entity's ``$set``/``$unset`` stream into ONE ``$set`` snapshot
  (``compressPProperties:106``), optionally dedups identical regular events,
  drops events older than the window, and rewrites the store in place.
* :class:`SelfCleaningDataSource` — mixin giving any DataSource a
  ``clean_persisted_events`` hook to call before reading.
"""

from __future__ import annotations

import dataclasses
import datetime as _dt
import logging
import re
from typing import Optional

from predictionio_tpu.data.event import Event, EventValidation, utcnow
from predictionio_tpu.data.storage.registry import Storage

logger = logging.getLogger(__name__)

_DURATION_RE = re.compile(r"(\d+)\s*(second|minute|hour|day|week)s?")
_UNIT_SECONDS = {
    "second": 1,
    "minute": 60,
    "hour": 3600,
    "day": 86400,
    "week": 604800,
}


def parse_duration(d) -> float:
    """Seconds from a number or a reference-style '2 days' string."""
    if isinstance(d, (int, float)):
        return float(d)
    m = _DURATION_RE.fullmatch(str(d).strip().lower())
    if not m:
        raise ValueError(f"cannot parse duration {d!r}")
    return int(m.group(1)) * _UNIT_SECONDS[m.group(2)]


@dataclasses.dataclass
class EventWindow:
    """Parity: SelfCleaningDataSource.scala:320 EventWindow."""

    duration: Optional[object] = None  # seconds or "N days"
    remove_duplicates: bool = False
    compress_properties: bool = False


def clean_persisted_events(
    storage: Storage,
    app_id: int,
    window: EventWindow,
    channel_id: Optional[int] = None,
    now: Optional[_dt.datetime] = None,
) -> dict:
    """Compact the event store in place; returns {'before': n, 'after': m}."""
    le = storage.get_l_events()
    events = list(le.find(app_id, channel_id=channel_id))
    before = len(events)
    now = now or utcnow()

    cutoff = None
    if window.duration is not None:
        cutoff = now - _dt.timedelta(seconds=parse_duration(window.duration))

    # 1. window: drop REGULAR events older than the cutoff; property events
    # are exempt — dropping them would destroy entity state (parity:
    # SelfCleaningDataSource.scala:83,101 `isAfter(cutoff) || isSetEvent(e)`)
    special = [e for e in events if e.event in EventValidation.SPECIAL_EVENTS]
    regular = [
        e
        for e in events
        if e.event not in EventValidation.SPECIAL_EVENTS
        and (cutoff is None or e.event_time >= cutoff)
    ]

    # 2. compress properties: one $set snapshot per (entityType, entityId)
    if window.compress_properties:
        from predictionio_tpu.data.aggregator import aggregate_properties

        compressed: list[Event] = []
        by_type: dict[str, list[Event]] = {}
        for e in special:
            by_type.setdefault(e.entity_type, []).append(e)
        for entity_type, evs in by_type.items():
            snapshots = aggregate_properties(evs)
            for entity_id, pm in snapshots.items():
                compressed.append(
                    Event(
                        event="$set",
                        entity_type=entity_type,
                        entity_id=entity_id,
                        properties=pm.to_dict(),
                        event_time=pm.last_updated,
                    )
                )
        special = compressed

    # 3. dedup identical regular events (same signature, keep earliest)
    if window.remove_duplicates:
        seen: set = set()
        deduped = []
        for e in sorted(regular, key=lambda e: (e.event_time, e.creation_time)):
            sig = (
                e.event,
                e.entity_type,
                e.entity_id,
                e.target_entity_type,
                e.target_entity_id,
                tuple(sorted(e.properties.to_dict().items())),
            )
            if sig in seen:
                continue
            seen.add(sig)
            deduped.append(e)
        regular = deduped

    new_events = special + regular
    # rewrite in place (parity: removePEvents + wipe + write)
    le.remove(app_id, channel_id)
    le.init(app_id, channel_id)
    le.batch_insert(new_events, app_id, channel_id)
    logger.info(
        "cleaned app %s channel %s: %d -> %d events", app_id, channel_id,
        before, len(new_events),
    )
    return {"before": before, "after": len(new_events)}


class SelfCleaningDataSource:
    """Mixin: DataSources with an ``event_window`` get pre-read compaction.

    Subclass declares ``app_name``/``event_window`` (usually from params) and
    calls :meth:`clean_persisted_events` at the top of ``read_training``.
    """

    @property
    def event_window(self) -> Optional[EventWindow]:
        p = getattr(self, "params", None)
        w = getattr(p, "eventWindow", None) if p else None
        if w is None:
            return None
        if isinstance(w, EventWindow):
            return w
        return EventWindow(
            duration=w.get("duration"),
            remove_duplicates=bool(w.get("removeDuplicates", False)),
            compress_properties=bool(w.get("compressProperties", False)),
        )

    def clean_persisted_events(self, storage: Optional[Storage] = None) -> Optional[dict]:
        window = self.event_window
        if window is None:
            return None
        from predictionio_tpu.data.store import get_storage, resolve_app
        from predictionio_tpu.parallel import distributed

        if distributed.is_multihost_env() and not distributed.is_coordinator():
            # destructive store rewrite must run exactly once: in SPMD every
            # process executes read_training, so only the coordinator compacts
            return None
        storage = storage or get_storage()
        app_id, channel_id = resolve_app(self.params.appName)
        return clean_persisted_events(storage, app_id, window, channel_id)
