"""Composed retrieval→ranking pipelines with per-stage deadline budgets.

Low-latency prediction serving is a DATAFLOW, not one monolithic model
call (PAPERS.md: Cloudburst's serverless prediction-serving result):
a cheap high-recall retrieval stage prunes the catalog to a candidate
set, and an exact ranking stage scores only those candidates.  We
already own both stages — IVF coarse retrieval (PR 16) and the fused
ALS ranker's candidate/exclusion path (PR 9/13) — and this module
composes them:

* :class:`PipelineConfig` — the deployable artifact: an ordered list
  of :class:`StageSpec` (name, kind, per-stage share of the request
  deadline, params), published and loaded through the same sealed-blob
  checksum envelope as every model artifact (a torn pipeline config is
  REFUSED at load, and the server degrades to single-stage serving).
* :class:`PipelineEngine` — executes the stages under the PR 15
  ambient request deadline, split into per-stage budgets by
  ``budget_fraction``.  A ranking stage that overruns its budget (or
  fails) degrades to the RETRIEVAL-ONLY answer tagged
  ``degraded:true`` — coarse scores beat a blown end-to-end SLO.
  Every stage boundary is a fault-injection site
  (``server:pipeline:<stage>``), so chaos tests can starve one stage
  without touching the others.

The engine is generic over stage runners; :func:`build_recommendation_
stages` binds a config to a deployed ALS recommendation algorithm
(host IVF probe → device/host candidate ranking).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import logging
import os
import time
from typing import Any, Callable, Optional

from predictionio_tpu.common import faults as _faults
from predictionio_tpu.common.resilience import (
    Deadline,
    DeadlineExceeded,
    current_deadline,
    deadline_scope,
)
from predictionio_tpu.core.persistence import open_blob_file, seal_blob_file
from predictionio_tpu.utils.profiling import LatencyHistogram

logger = logging.getLogger(__name__)

STAGE_KINDS = ("retrieval", "ranking")

_CONFIG_VERSION = 1


@dataclasses.dataclass(frozen=True)
class StageSpec:
    """One pipeline stage declaration.  ``budget_fraction`` is this
    stage's share of the request's TOTAL deadline budget."""

    name: str
    kind: str
    budget_fraction: float = 0.5
    params: tuple = ()  # sorted (key, value) pairs — hashable, canonical

    def param(self, key: str, default=None):
        for k, v in self.params:
            if k == key:
                return v
        return default

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "kind": self.kind,
            "budgetFraction": self.budget_fraction,
            "params": dict(self.params),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "StageSpec":
        return cls(
            name=str(d["name"]),
            kind=str(d["kind"]),
            budget_fraction=float(d.get("budgetFraction", 0.5)),
            params=tuple(sorted((d.get("params") or {}).items())),
        )


@dataclasses.dataclass(frozen=True)
class PipelineConfig:
    """A deployable pipeline: ordered stages + identity."""

    name: str
    stages: tuple[StageSpec, ...]

    def validate(self) -> None:
        if not self.name:
            raise ValueError("pipeline name must be non-empty")
        if not self.stages:
            raise ValueError(f"pipeline {self.name}: no stages")
        if self.stages[0].kind != "retrieval":
            raise ValueError(
                f"pipeline {self.name}: first stage must be retrieval "
                "(the degraded answer comes from it)"
            )
        seen = set()
        total = 0.0
        for st in self.stages:
            if st.kind not in STAGE_KINDS:
                raise ValueError(
                    f"pipeline {self.name}: stage {st.name!r} kind "
                    f"{st.kind!r} not in {STAGE_KINDS}"
                )
            if st.name in seen:
                raise ValueError(
                    f"pipeline {self.name}: duplicate stage {st.name!r}"
                )
            seen.add(st.name)
            if not 0.0 < st.budget_fraction <= 1.0:
                raise ValueError(
                    f"pipeline {self.name}: stage {st.name!r} "
                    f"budget_fraction {st.budget_fraction} outside (0, 1]"
                )
            total += st.budget_fraction
        if total > 1.0 + 1e-9:
            raise ValueError(
                f"pipeline {self.name}: stage budget fractions sum to "
                f"{total:.3f} > 1 — the stages would overdraw the request "
                "deadline"
            )

    @property
    def fingerprint(self) -> str:
        """Content hash — the pipeline's deployed identity."""
        return hashlib.sha256(self.to_payload()).hexdigest()[:16]

    def to_payload(self) -> bytes:
        return json.dumps(
            {
                "version": _CONFIG_VERSION,
                "name": self.name,
                "stages": [st.to_dict() for st in self.stages],
            },
            sort_keys=True, separators=(",", ":"),
        ).encode()

    @classmethod
    def from_payload(cls, payload: bytes) -> "PipelineConfig":
        d = json.loads(payload.decode())
        config = cls(
            name=str(d["name"]),
            stages=tuple(StageSpec.from_dict(s) for s in d["stages"]),
        )
        config.validate()
        return config

    @classmethod
    def from_dict(cls, d: dict) -> "PipelineConfig":
        config = cls(
            name=str(d.get("name") or "pipeline"),
            stages=tuple(StageSpec.from_dict(s) for s in d.get("stages", [])),
        )
        config.validate()
        return config

    def describe(self) -> dict:
        return {
            "name": self.name,
            "fingerprint": self.fingerprint,
            "stages": [st.to_dict() for st in self.stages],
        }


def save_pipeline(config: PipelineConfig, path: str) -> None:
    """Publish a pipeline as a sealed-blob artifact (tmp+fsync+rename,
    checksum envelope) — the same integrity contract as model blobs."""
    config.validate()
    seal_blob_file(path, config.to_payload())


def load_pipeline(path: str) -> PipelineConfig:
    """Load a sealed pipeline artifact; raises ``ModelIntegrityError``
    on a torn or forged blob (callers degrade to single-stage)."""
    return PipelineConfig.from_payload(open_blob_file(path))


def pipeline_from_env() -> Optional[PipelineConfig]:
    """``PIO_PIPELINE``: path to a sealed pipeline blob, or (dev/tests)
    the JSON config inline.  None when unset — single-stage serving,
    byte-identical to the pre-pipeline server."""
    raw = os.environ.get("PIO_PIPELINE", "").strip()
    if not raw:
        return None
    if raw.startswith("{"):
        return PipelineConfig.from_dict(json.loads(raw))
    return load_pipeline(raw)


class StageFault(Exception):
    """An injected ``server:pipeline:<stage>`` error fault."""


class _ShortCircuit(Exception):
    """A stage produced the final answer early (e.g. unknown user)."""

    def __init__(self, prediction: Any):
        self.prediction = prediction


def _fault_latency(act) -> None:
    # the injected stall IS the fault being modeled (a slow stage);
    # exempted by name in analysis/blocking.py
    if act.latency_s:
        time.sleep(act.latency_s)


class PipelineEngine:
    """Executes a bound pipeline as a dataflow under per-stage budgets.

    ``stages`` pairs each :class:`StageSpec` with a runner
    ``runner(ctx, deadline)`` that reads/writes the shared per-request
    ``ctx`` dict (``query`` in; retrieval sets ``candidates`` /
    ``cand_scores``; the final stage sets ``prediction``).
    ``degrade_fn(ctx)`` builds the retrieval-only answer when a later
    stage overruns or fails.
    """

    def __init__(
        self,
        config: PipelineConfig,
        stages: list[tuple[StageSpec, Callable]],
        degrade_fn: Callable[[dict], Any],
    ):
        config.validate()
        self.config = config
        self._stages = list(stages)
        self._degrade = degrade_fn
        import threading

        self._stats_lock = threading.Lock()
        self._stage_stats = {
            spec.name: {
                "runs": 0, "overruns": 0, "errors": 0, "faults": 0,
                "latency": LatencyHistogram(),
            }
            for spec, _ in self._stages
        }
        self._degraded_total = 0
        self._short_circuits = 0

    # entry point: named run_* so analysis/deadline.py scans it as a
    # serving entry that must forward budgets downstream
    def run_pipeline(
        self, query: Any, deadline: Optional[Deadline] = None
    ) -> tuple[Any, dict]:
        """Run every stage; returns ``(prediction, meta)`` where meta
        carries ``degraded`` and the stage that degraded (if any).

        The ambient request deadline is split by each stage's
        ``budget_fraction`` of the TOTAL budget remaining at entry; a
        non-retrieval stage that raises, exceeds its slice, or would
        start with no slice left degrades to the retrieval-only answer
        instead of blowing the end-to-end SLO.  Retrieval-stage
        failures re-raise: with no candidates there is nothing to
        degrade TO, and the server's own fallback chain takes over.
        """
        if deadline is None:
            deadline = current_deadline()
        total_ms = deadline.remaining_ms() if deadline is not None else None
        ctx: dict = {"query": query}
        prediction = None
        for spec, runner in self._stages:
            can_degrade = (
                spec.kind != "retrieval" and ctx.get("candidates") is not None
            )
            act = _faults.check(f"server:pipeline:{spec.name}")
            if act is not None:
                _fault_latency(act)
                if act.kind in ("error", "drop", "crash"):
                    self._note(spec.name, "faults")
                    if can_degrade:
                        return self._degrade_to_retrieval(ctx, spec.name)
                    raise StageFault(
                        f"injected fault at pipeline stage {spec.name}"
                    )
            sub = None
            if total_ms is not None:
                remaining = deadline.remaining_ms()
                if remaining <= 0.0:
                    if can_degrade:
                        return self._degrade_to_retrieval(ctx, spec.name)
                    raise DeadlineExceeded(
                        f"deadline exhausted before pipeline stage "
                        f"{spec.name}"
                    )
                sub = Deadline.after_ms(
                    max(1.0, min(total_ms * spec.budget_fraction, remaining))
                )
            t0 = time.perf_counter()
            try:
                with deadline_scope(sub if sub is not None else deadline):
                    runner(ctx, sub)
            except _ShortCircuit as sc:
                self._note(spec.name, "runs", time.perf_counter() - t0)
                with self._stats_lock:
                    self._short_circuits += 1
                return sc.prediction, {"degraded": False, "pipeline": True}
            except DeadlineExceeded:
                self._note(spec.name, "overruns")
                if can_degrade:
                    return self._degrade_to_retrieval(ctx, spec.name)
                raise
            except Exception:
                self._note(spec.name, "errors")
                if can_degrade:
                    return self._degrade_to_retrieval(ctx, spec.name)
                raise
            dt = time.perf_counter() - t0
            self._note(spec.name, "runs", dt)
            if sub is not None and sub.expired():
                # the stage FINISHED but past its slice: a late exact
                # answer still blows the end-to-end SLO, so the budget
                # verdict stands — serve the retrieval-only answer
                self._note(spec.name, "overruns")
                if can_degrade:
                    return self._degrade_to_retrieval(ctx, spec.name)
            prediction = ctx.get("prediction", prediction)
        return prediction, {"degraded": False, "pipeline": True}

    def _degrade_to_retrieval(self, ctx: dict, stage: str) -> tuple[Any, dict]:
        with self._stats_lock:
            self._degraded_total += 1
        return self._degrade(ctx), {
            "degraded": True, "pipeline": True, "stage": stage,
        }

    def _note(self, stage: str, key: str, dt: Optional[float] = None) -> None:
        with self._stats_lock:
            entry = self._stage_stats[stage]
            if dt is not None:
                entry["latency"].observe(dt)
                entry["runs"] += 1
            else:
                entry[key] += 1

    def stats(self) -> dict:
        with self._stats_lock:
            stages = {}
            for spec, _ in self._stages:
                entry = self._stage_stats[spec.name]
                lat: LatencyHistogram = entry["latency"]
                stages[spec.name] = {
                    "kind": spec.kind,
                    "budget_fraction": spec.budget_fraction,
                    "runs": entry["runs"],
                    "overruns": entry["overruns"],
                    "errors": entry["errors"],
                    "faults": entry["faults"],
                    "p50_ms": round(lat.quantile(0.50), 3),
                    "p99_ms": round(lat.quantile(0.99), 3),
                }
            return {
                "name": self.config.name,
                "fingerprint": self.config.fingerprint,
                "degraded_total": self._degraded_total,
                "short_circuits": self._short_circuits,
                "stages": stages,
            }


# -- recommendation binding --------------------------------------------------
def _ivf_candidates(index, q, n: int):
    """Host-side coarse probe: score the (tiny) centroid matrix, take
    clusters best-first, and pool their members until ``n`` candidates.
    Coarse scores are the owning cluster's centroid score — enough to
    order a degraded answer, deliberately NOT the exact dot (that is
    the ranking stage's job)."""
    import numpy as np

    cscores = np.asarray(index.centroids, np.float32) @ np.asarray(
        q, np.float32
    )
    order = np.argsort(-cscores)
    cand: list = []
    coarse: list = []
    for c in order:
        members = index.plan.shard_items(int(c))
        cand.append(members)
        coarse.append(np.full(len(members), cscores[int(c)], np.float32))
        if sum(len(m) for m in cand) >= n:
            break
    idx = np.concatenate(cand) if cand else np.zeros(0, np.int32)
    sc = np.concatenate(coarse) if coarse else np.zeros(0, np.float32)
    if len(idx) > n:
        idx, sc = idx[:n], sc[:n]
    return idx, sc


def build_recommendation_stages(
    config: PipelineConfig, algo: Any, model: Any
) -> Optional[PipelineEngine]:
    """Bind a pipeline config to a deployed recommendation algorithm.

    Needs the ALS surface: ``model.user_map``/``item_map`` (entity id
    maps), host ``user_factors``, and the algorithm's scorer with the
    fused candidate-ranking path.  Returns None when the deployment
    lacks those hooks — the caller serves single-stage as before.
    Retrieval prefers the model's published IVF index (host centroid
    probe); without one it falls back to a host scan that still feeds
    the fused ranker a bounded candidate set.
    """
    import numpy as np

    user_map = getattr(model, "user_map", None)
    item_map = getattr(model, "item_map", None)
    factors = getattr(model, "user_factors", None)
    item_factors = getattr(model, "item_factors", None)
    scorer_fn = getattr(algo, "_scorer", None)
    if any(
        x is None
        for x in (user_map, item_map, factors, item_factors, scorer_fn)
    ):
        return None
    from predictionio_tpu.templates.recommendation import (
        ItemScore, PredictedResult,
    )

    ivf = getattr(model, "ivf_index", None)
    inv_items = item_map.inverse

    def _result(idx, scores, num: int) -> PredictedResult:
        order = np.argsort(-np.asarray(scores))[:num]
        return PredictedResult(
            itemScores=[
                ItemScore(item=inv_items[int(idx[i])], score=float(scores[i]))
                for i in order
            ]
        )

    def stage_retrieval(ctx: dict, deadline) -> None:
        query = ctx["query"]
        uidx = user_map.get(query.user)
        if uidx is None:
            # nothing to retrieve for an unknown user: final answer now
            raise _ShortCircuit(PredictedResult(itemScores=[]))
        spec: StageSpec = ctx["__spec__"]
        n = int(spec.param("candidates", max(64, 8 * int(query.num))))
        q = np.asarray(factors[int(uidx)], np.float32)
        if ivf is not None:
            idx, coarse = _ivf_candidates(ivf, q, n)
        else:
            # host scan fallback: exact dots, truncated — the ranking
            # stage still wins by running exclusions + top-k on device
            scores = np.asarray(item_factors, np.float32) @ q
            idx = np.argpartition(-scores, min(n, len(scores) - 1))[:n]
            coarse = scores[idx]
        exclude = None
        if getattr(query, "blackList", None):
            excl = item_map.to_index_array(query.blackList)
            exclude = excl[excl >= 0]
            keep = ~np.isin(idx, exclude)
            idx, coarse = idx[keep], coarse[keep]
        if getattr(query, "whiteList", None):
            white = item_map.to_index_array(query.whiteList)
            keep = np.isin(idx, white[white >= 0])
            idx, coarse = idx[keep], coarse[keep]
        ctx["user_idx"] = int(uidx)
        ctx["candidates"] = idx.astype(np.int32)
        ctx["cand_scores"] = coarse
        ctx["exclude"] = exclude

    def stage_ranking(ctx: dict, deadline) -> None:
        query = ctx["query"]
        cand = ctx["candidates"]
        if len(cand) == 0:
            ctx["prediction"] = PredictedResult(itemScores=[])
            return
        scorer = scorer_fn(model)
        idx, scores = scorer.recommend(
            ctx["user_idx"], int(query.num),
            exclude_items=ctx.get("exclude"), candidate_items=cand,
        )
        ctx["prediction"] = PredictedResult(
            itemScores=[
                ItemScore(item=inv_items[int(i)], score=float(s))
                for i, s in zip(idx, scores)
            ]
        )

    def degrade_fn(ctx: dict):
        # retrieval-only answer: coarse scores, tagged degraded upstream
        query = ctx["query"]
        return _result(ctx["candidates"], ctx["cand_scores"], int(query.num))

    runners = {"retrieval": stage_retrieval, "ranking": stage_ranking}
    stages = []
    for spec in config.stages:
        runner = runners[spec.kind]

        def bound(ctx, deadline, _spec=spec, _runner=runner):
            ctx["__spec__"] = _spec
            _runner(ctx, deadline)

        stages.append((spec, bound))
    return PipelineEngine(config, stages, degrade_fn)


def build_pipeline_engine(
    config: Optional[PipelineConfig], algorithms: list, models: list
) -> Optional[PipelineEngine]:
    """Bind ``config`` against the first deployed algorithm exposing
    the recommendation surface; None when no stage binding is possible
    (the server keeps single-stage serving)."""
    if config is None:
        return None
    for algo, model in zip(algorithms, models):
        try:
            engine = build_recommendation_stages(config, algo, model)
        except Exception:
            logger.exception(
                "pipeline %s failed to bind against %s",
                config.name, type(algo).__name__,
            )
            continue
        if engine is not None:
            return engine
    return None
