"""Bucketed pre-compiled serving fast path: device-resident score+top-k.

The query server's device work is one fused gather→score→top-k program
(:func:`predictionio_tpu.ops.topk.gather_score_topk`), but naively jitting
it per batch size would retrace for every distinct size and pay compile
latency on live traffic.  This module removes both costs:

* **Bucket ladder** — batches are padded up to a fixed ladder of sizes
  (:data:`BUCKETS`); the padded tail rows are scored and discarded on host
  (they cost one extra matmul row each), and the padded ITEM tail is masked
  inside the program via ``top_k_with_mask``.  Only ``len(BUCKETS)``
  programs ever exist.
* **AOT warmup** — every bucket's program is compiled at construction time
  with ``jax.jit(...).lower(...).compile()`` (deploy/reload, never on a
  request thread), so no query ever pays trace or compile latency.  Calls
  go straight to the pre-built executable; a recompile is structurally
  impossible on the serve path, and :meth:`BucketedScorer.stats` exposes
  the compile/hit counters that prove it.

The factor matrices are placed replicated on the mesh ONCE and stay
resident in device memory between queries (Cloudburst's model-next-to-
compute rule, arXiv:2007.05832); per-call traffic is the (B,) user-index
upload and the (B, k) result readback.
"""

from __future__ import annotations

import threading
from typing import Optional

import jax
import numpy as np

from predictionio_tpu.obs import tracing as _tracing
from predictionio_tpu.ops.topk import gather_score_topk
from predictionio_tpu.parallel.mesh import MeshContext, pad_to_multiple
from predictionio_tpu.utils import profiling as _profiling

# The batch-size ladder. Powers of two above a singleton lane: 1 serves the
# trickle case with zero padding, 64 matches MicroBatcher's default
# max_batch. Tails between rungs pad to the next rung (worst waste: 7 rows
# at rung 8).
BUCKETS = (1, 8, 16, 32, 64)


def bucket_for(n: int, buckets=BUCKETS) -> Optional[int]:
    """Smallest ladder rung ≥ n, or None when n overflows the ladder."""
    for b in buckets:
        if n <= b:
            return b
    return None


class BucketedScorer:
    """Pre-compiled per-bucket score+top-k over device-resident factors."""

    def __init__(
        self,
        ctx: MeshContext,
        user_factors: np.ndarray,
        item_factors: np.ndarray,
        max_k: int = 100,
        buckets=BUCKETS,
    ):
        self.ctx = ctx
        self.n_users = user_factors.shape[0]
        self.n_items = item_factors.shape[0]
        self._n_items_pad = pad_to_multiple(self.n_items, 8)
        self.k = min(max_k, self.n_items)
        self.buckets = tuple(sorted(set(int(b) for b in buckets)))
        self._repl = ctx.replicated()
        pad_i = self._n_items_pad - self.n_items
        self._U = ctx.replicate(np.asarray(user_factors, np.float32))
        self._V = ctx.replicate(
            np.pad(np.asarray(item_factors, np.float32), ((0, pad_i), (0, 0)))
        )
        self._item_pad_mask = ctx.replicate(
            np.arange(self._n_items_pad) >= self.n_items
        )
        self._lock = threading.Lock()
        self.compile_count = 0
        self.hits: dict[int, int] = {b: 0 for b in self.buckets}
        self.queries = 0
        self.padded_rows = 0
        # AOT warmup: every rung compiled before the first request
        self._fns = {b: self._compile(b) for b in self.buckets}

    def _compile(self, b: int):
        """Lower + compile the bucket-b program ahead of time."""
        k = self.k

        def fn(U, V, item_pad_mask, u_idx):
            return gather_score_topk(U, V, u_idx, k, item_mask=item_pad_mask)

        dummy_idx = jax.device_put(np.zeros(b, np.int32), self._repl)
        compiled = (
            jax.jit(fn)
            .lower(self._U, self._V, self._item_pad_mask, dummy_idx)
            .compile()
        )
        self.compile_count += 1
        return compiled

    def score_topk(
        self, user_indices: np.ndarray, k: int
    ) -> tuple[np.ndarray, np.ndarray]:
        """Top-``k`` (indices, values) for every user in ``user_indices``.

        Batches larger than the top rung are served in top-rung chunks, so
        any size works without growing the compile cache.  ``k`` beyond the
        compiled width raises ValueError — callers route that to their
        exact path instead of silently truncating.
        """
        if k > self.k:
            raise ValueError(f"k={k} exceeds compiled top-k width {self.k}")
        users = np.asarray(user_indices, np.int32)
        top = self.buckets[-1]
        idx_parts, val_parts = [], []
        for s in range(0, len(users), top):
            chunk = users[s : s + top]
            b = bucket_for(len(chunk), self.buckets)
            padded = np.zeros(b, np.int32)
            padded[: len(chunk)] = chunk
            with _tracing.stage("h2d"):
                u_dev = jax.device_put(padded, self._repl)
            with _profiling.trace(stage="device_compute"):
                vals, idx = self._fns[b](
                    self._U, self._V, self._item_pad_mask, u_dev
                )
                if _tracing.active_traces():
                    # force completion INSIDE the stage so async dispatch
                    # can't smear device time into the d2h readback below
                    jax.block_until_ready((vals, idx))
            with self._lock:
                self.hits[b] += 1
                self.queries += len(chunk)
                self.padded_rows += b - len(chunk)
            # padded tail rows are real top-k rows for user 0 — dropped here
            idx_parts.append(np.asarray(idx)[: len(chunk), :k])
            val_parts.append(np.asarray(vals)[: len(chunk), :k])
        return np.concatenate(idx_parts), np.concatenate(val_parts)

    def stats(self) -> dict:
        """Counters for ``GET /`` stats and bench artifacts.

        ``compile_count`` only moves at construction (warmup); a nonzero
        delta across serving traffic IS a recompile and fails the bench's
        zero-recompile check.
        """
        with self._lock:
            hits = dict(self.hits)
            return {
                "buckets": list(self.buckets),
                "top_k": self.k,
                "compile_count": self.compile_count,
                "bucket_hits": {str(b): h for b, h in hits.items()},
                "calls": sum(hits.values()),
                "queries": self.queries,
                "padded_rows": self.padded_rows,
                "row_occupancy": round(
                    self.queries / (self.queries + self.padded_rows), 4
                )
                if self.queries
                else None,
            }
