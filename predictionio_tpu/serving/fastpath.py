"""Bucketed pre-compiled serving fast path: device-resident score+top-k.

The query server's device work is one fused gather→score→top-k program
(:func:`predictionio_tpu.ops.topk.gather_score_topk`), but naively jitting
it per batch size would retrace for every distinct size and pay compile
latency on live traffic.  This module removes both costs:

* **Bucket ladder** — batches are padded up to a fixed ladder of sizes
  (:data:`BUCKETS`); the padded tail rows are scored and discarded on host
  (they cost one extra matmul row each), and the padded ITEM tail is masked
  inside the program via ``top_k_with_mask``.  Only ``len(BUCKETS)``
  programs ever exist.
* **AOT warmup** — every bucket's program is compiled at construction time
  with ``jax.jit(...).lower(...).compile()`` (deploy/reload, never on a
  request thread), so no query ever pays trace or compile latency.  Calls
  go straight to the pre-built executable; a recompile is structurally
  impossible on the serve path, and :meth:`BucketedScorer.stats` exposes
  the compile/hit counters that prove it.

Factor placement is backend-dependent and happens ONCE at construction
(Cloudburst's model-next-to-compute rule, arXiv:2007.05832); per-call
traffic is the (B,) user-index upload and the (B, k) result readback.
``PIO_SERVING_SHARDING`` selects between two placements:

* **replicated** — a full copy of the factor matrices on every device;
  the catalog is capped at a single chip's HBM.
* **sharded** — item factors PARTITIONED across the mesh per an explicit
  :class:`~predictionio_tpu.serving.sharding.ShardingPlan`: each query
  fans out, every shard runs the same fused ``gather_score_topk`` over
  only its local item block, and one small all-gather of per-shard
  (B, local_k) leaderboards plus an on-device two-key merge
  (``ops.topk.merge_topk``) yields answers bit-identical to the
  replicated reference — the (B, n_items) score matrix never crosses a
  link.  ``auto`` (the default) serves sharded only when the model
  declares a plan AND the mesh has the devices for it, so every existing
  caller keeps replicated behavior unchanged.

RETRIEVAL (``PIO_RETRIEVAL=exact|ivf|auto``, default ``auto``): with an
:class:`~predictionio_tpu.ops.ivf.IVFIndex` declared at publish, the
replicated placement can serve the IVF-pruned scan instead of the full
one — the compiled program scores the batch against the ``nlist``
centroids, picks a probe set of clusters, and runs the SAME fused
``gather_score_topk`` over only those clusters' contiguous blocks (laid
out by ``build_layout`` exactly like shard blocks), merging per-probe
leaderboards with ``merge_topk``.  Because per-cluster blocks are
ascending by global id, probing EVERY cluster (``nprobe == nlist``)
returns answers bit-identical to the exact path — the same tie-order
proof as the sharded merge.  The probe budget scales with the rung —
``P_b = clamp(nprobe·b, min_probes, nlist)`` — so the per-query
amortized scanned fraction stays ≈ ``nprobe/nlist`` at every batch size
while the probed set covers each row's union of likely clusters
(``min_probes`` guarantees the probed clusters always hold ≥ k real
items).  IVF composes with the replicated placement only; a sharded plan
takes precedence and retrieval degrades to exact with a warning.

HOT-SET PATH (``PIO_HOTSET_SIZE``, off by default): ALS scores are static
between reloads — a hot user's top-k is the SAME answer every time until
the next generation deploys.  The scorer keeps decayed per-user request
counts; every ``PIO_HOTSET_REFRESH_QUERIES`` scored rows it re-ranks the
top ``PIO_HOTSET_SIZE`` users and materializes their full top-k table in
top-rung device passes through the already-compiled b=max program (zero
new compiles — the AOT contract holds).  Queries for hot users are then
answered from the table with zero device work; only cold users ride the
bucketed device path.  Decaying the counts at each re-rank lets the
working set track traffic drift.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Optional

import jax
import numpy as np

from predictionio_tpu.obs import devprof as _devprof
from predictionio_tpu.obs import tracing as _tracing
from predictionio_tpu.ops import ivf as _ivf
from predictionio_tpu.ops import quantize as _quantize
from predictionio_tpu.ops import score_kernel as _score_kernel
from predictionio_tpu.ops.topk import (
    gather_score_topk, merge_topk, resolve_backend, two_tier_merge_topk,
)
from predictionio_tpu.parallel.mesh import (
    DATA_AXIS, HOST_AXIS, MeshContext, pad_to_multiple, shard_map,
)
from predictionio_tpu.serving import sharding as _sharding
from predictionio_tpu.utils import profiling as _profiling

logger = logging.getLogger(__name__)

# The batch-size ladder. Powers of two above a singleton lane: 1 serves the
# trickle case with zero padding, 64 matches MicroBatcher's default
# max_batch. Tails between rungs pad to the next rung (worst waste: 7 rows
# at rung 8).
BUCKETS = (1, 8, 16, 32, 64)


def bucket_for(n: int, buckets=BUCKETS) -> Optional[int]:
    """Smallest ladder rung ≥ n, or None when n overflows the ladder."""
    for b in buckets:
        if n <= b:
            return b
    return None


SERVING_BACKENDS = ("replicated", "sharded", "auto")


def resolve_serving_backend(
    requested: Optional[str] = None,
    *,
    plan=None,
    ctx: Optional[MeshContext] = None,
) -> str:
    """Resolve the factor placement: ``"replicated"`` or ``"sharded"``.

    ``requested`` overrides ``PIO_SERVING_SHARDING`` (default ``auto``).
    ``auto`` serves sharded only when a :class:`ShardingPlan` with more
    than one shard is declared AND the mesh has at least that many
    devices — on a 1-device mesh, or for any model without a plan, it is
    exactly the replicated path, so existing callers see no behavior
    change.  An explicit ``sharded`` without a plan is a configuration
    error; a plan wider than the mesh degrades to replicated with a
    warning (the plan is an optimization, never a point of failure).
    """
    req = (
        requested or os.environ.get("PIO_SERVING_SHARDING") or "auto"
    ).strip().lower()
    if req not in SERVING_BACKENDS:
        raise ValueError(
            f"PIO_SERVING_SHARDING must be one of {SERVING_BACKENDS}, "
            f"got {req!r}"
        )
    if req == "replicated":
        return "replicated"
    n_dev = ctx.n_devices if ctx is not None else 1
    if req == "sharded":
        if plan is None:
            raise ValueError(
                "PIO_SERVING_SHARDING=sharded requires a ShardingPlan "
                "declared at publish (PIO_SHARD_COUNT/PIO_SHARD_HBM_BUDGET)"
            )
        if plan.n_shards > n_dev:
            logger.warning(
                "sharding plan wants %d shards but the mesh has %d "
                "devices; serving replicated", plan.n_shards, n_dev,
            )
            return "replicated"
        return "sharded"
    # auto
    if plan is not None and 1 < plan.n_shards <= n_dev:
        return "sharded"
    return "replicated"


class BucketedScorer:
    """Pre-compiled per-bucket score+top-k over device-resident factors."""

    def __init__(
        self,
        ctx: MeshContext,
        user_factors: np.ndarray,
        item_factors: np.ndarray,
        max_k: int = 100,
        buckets=BUCKETS,
        hot_size: Optional[int] = None,
        hot_refresh_queries: Optional[int] = None,
        factor_dtype: str = "f32",
        user_scale: Optional[np.ndarray] = None,
        item_scale: Optional[np.ndarray] = None,
        backend: Optional[str] = None,
        plan=None,
        sharding: Optional[str] = None,
        ivf_index=None,
        retrieval: Optional[str] = None,
    ):
        self.ctx = ctx
        self.n_users = user_factors.shape[0]
        self.n_items = item_factors.shape[0]
        # score-kernel backend for THIS scorer generation, resolved once at
        # construction (PIO_SCORE_KERNEL; auto → fused only on TPU)
        self.backend = resolve_backend(backend)
        self.factor_dtype = factor_dtype
        if factor_dtype == "int8" and (user_scale is None or item_scale is None):
            raise ValueError("int8 factors require user_scale and item_scale")
        self.k = min(max_k, self.n_items)
        self.buckets = tuple(sorted(set(int(b) for b in buckets)))
        # factor placement: replicated full copies, or item blocks
        # partitioned per the publish-time ShardingPlan (PIO_SERVING_SHARDING)
        self.plan = plan
        self.sharding = resolve_serving_backend(sharding, plan=plan, ctx=ctx)
        self._shard_acct: Optional[_sharding.ShardAccounting] = None
        # retrieval path (PIO_RETRIEVAL): IVF prunes the replicated scan;
        # it composes with the replicated placement only — a sharded plan
        # already partitions the scan across devices, and stacking the two
        # layouts would shard cluster blocks mid-block
        self.ivf_index = ivf_index
        retr = _ivf.resolve_retrieval(retrieval, index=ivf_index)
        if retr == "ivf" and self.sharding == "sharded":
            logger.warning(
                "IVF retrieval composes with replicated placement only; "
                "the sharding plan takes precedence — serving exact sharded"
            )
            retr = "exact"
        self.retrieval = retr
        if factor_dtype == "f32":
            user_factors = np.asarray(user_factors, np.float32)
            item_factors = np.asarray(item_factors, np.float32)
        # pod layout: plans with >1 host group run the two-tier merge over
        # a 2-D (host, data) mesh; placement/readback must then go through
        # the multi-process-safe helpers below
        self._pod = bool(
            self.sharding == "sharded"
            and getattr(plan, "host_groups", 1) > 1
        )
        self._pod_spans = False
        if self.sharding == "sharded":
            self._init_sharded_placement(
                user_factors, item_factors, user_scale, item_scale
            )
            # merged_k drives the cross-host tier-2 byte accounting; the
            # flat merge (including a rejected pod carve) has no tier 2
            self._shard_acct = _sharding.ShardAccounting(
                self.plan, self._local_k,
                merged_k=self.k if self._pod else None,
            )
        elif self.retrieval == "ivf":
            self._init_ivf_placement(
                user_factors, item_factors, user_scale, item_scale
            )
        else:
            self._init_replicated_placement(
                user_factors, item_factors, user_scale, item_scale
            )
        self.resident_factor_bytes = sum(
            int(a.nbytes)
            for a in (self._U, self._V, self._Uscale, self._Vscale)
            if a is not None
        ) + getattr(self, "_ivf_extra_bytes", 0)
        # IVF scan accounting (guarded by self._lock with the other
        # counters): probed blocks and scanned padded rows per dispatch,
        # against the exact path's would-have-scanned rows
        self._ivf_dispatches = 0
        self._ivf_probed_blocks = 0
        self._ivf_scanned_rows = 0
        self._ivf_dispatch_rows = 0
        self._lock = threading.Lock()
        self.compile_count = 0
        self.hits: dict[int, int] = {b: 0 for b in self.buckets}
        self.queries = 0
        self.padded_rows = 0
        # hot-set working set (off unless PIO_HOTSET_SIZE > 0): decayed
        # per-user request counts drive a periodic re-rank that materializes
        # the hot users' top-k once per refresh instead of once per query
        if hot_size is None:
            hot_size = int(os.environ.get("PIO_HOTSET_SIZE", "0") or 0)
        if hot_refresh_queries is None:
            hot_refresh_queries = int(
                os.environ.get("PIO_HOTSET_REFRESH_QUERIES", "2048") or 2048
            )
        self.hot_size = max(0, min(int(hot_size), self.n_users))
        self.hot_refresh_queries = max(1, int(hot_refresh_queries))
        self._hot_counts = (
            np.zeros(self.n_users, np.float32) if self.hot_size else None
        )
        self._hot_since_refresh = 0
        # user_idx → row in the materialized (hot_size, k) answer table
        self._hot_rows: dict[int, int] = {}
        self._hot_table_idx: Optional[np.ndarray] = None
        self._hot_table_val: Optional[np.ndarray] = None
        self.hot_hits = 0
        self.hot_misses = 0
        self.hot_refreshes = 0
        # device-utilization accountant: each bucket is cost-annotated at
        # compile time below, each dispatch records its device wall, and
        # the query server's bridge exports the windowed pio_device_*
        # gauges. One scorer == one model generation, so the accountant's
        # window never mixes generations.
        self.devprof = _devprof.DeviceUtilization(
            platform=jax.default_backend()
        )
        # per-bucket annotated HBM bytes, kept host-side so the sharded
        # merge-time attribution doesn't re-enter the accountant per call
        self._cost_bytes: dict[int, float] = {}
        # AOT warmup: every rung compiled before the first request, then
        # executed once — a lazily-materialized kernel (Pallas included)
        # can never surface its first-dispatch cost under traffic
        self.warmup_executions = 0
        self._fns = {b: self._compile(b) for b in self.buckets}
        for b in self.buckets:
            dummy_idx = self._put_repl(np.zeros(b, np.int32))
            jax.block_until_ready(self._fns[b](*self._static_args, dummy_idx))
            self.warmup_executions += 1

    def _put_repl(self, x: np.ndarray):
        """Replicate a host array on the serving mesh, multi-process safe.

        Pod meshes that span processes can't ``device_put`` (remote
        shards are non-addressable); every process supplies the same host
        copy through the shard-callback path.  SPMD contract: all
        processes dispatch the same batches in the same order.
        """
        if self._pod_spans:
            return self._shard_ctx.place(x)
        import jax.numpy as jnp

        return jax.device_put(jnp.asarray(x), self._repl)

    def _fetch(self, x) -> np.ndarray:
        """Device→host for a REPLICATED result, multi-process safe: any
        one addressable shard of a replicated array is the whole value."""
        if self._pod_spans:
            return np.asarray(x.addressable_data(0))
        return np.asarray(x)

    def _init_replicated_placement(
        self, user_factors, item_factors, user_scale, item_scale
    ) -> None:
        """Full factor copies on every device (the pre-sharding layout)."""
        ctx = self.ctx
        if self.backend == "fused":
            # the fused kernel streams the item matrix in fixed-size blocks
            self._n_items_pad = _score_kernel.pad_block_items(self.n_items)
        else:
            self._n_items_pad = pad_to_multiple(self.n_items, 8)
        self._repl = ctx.replicated()
        pad_i = self._n_items_pad - self.n_items
        self._U = ctx.replicate(np.asarray(user_factors))
        self._V = ctx.replicate(
            np.pad(np.asarray(item_factors), ((0, pad_i), (0, 0)))
        )
        if self.factor_dtype == "int8":
            self._Uscale = ctx.replicate(np.asarray(user_scale, np.float32))
            self._Vscale = ctx.replicate(
                np.pad(
                    np.asarray(item_scale, np.float32),
                    ((0, pad_i), (0, 0)),
                    constant_values=1.0,
                )
            )
        else:
            self._Uscale = self._Vscale = None
        self._item_pad_mask = ctx.replicate(
            np.arange(self._n_items_pad) >= self.n_items
        )
        # everything the compiled programs take except the per-call indices
        if self.factor_dtype == "int8":
            # construction-time: no other thread holds the scorer yet
            self._static_args = (  # pio: ignore[race-unguarded-rebind]
                self._U, self._V, self._Uscale, self._Vscale,
                self._item_pad_mask,
            )
        else:
            self._static_args = (  # pio: ignore[race-unguarded-rebind]
                self._U, self._V, self._item_pad_mask)

    def _init_ivf_placement(
        self, user_factors, item_factors, user_scale, item_scale
    ) -> None:
        """Replicated factors in IVF cluster-block layout + centroids.

        The item matrix is permuted into the index's cluster blocks via
        the SAME ``build_layout`` the sharded path uses — every cluster
        a contiguous kernel-aligned block of ``cap_pad`` rows, real slots
        ascending by global id (the tie-order invariant), global ids and
        a pad mask riding alongside flat.  The compiled program slices
        probe blocks out of this one replicated array, so compared to the
        exact replicated placement the only extra residency is the
        centroid matrix, the id/pad maps, and the per-cluster padding.
        ``_n_items_pad`` becomes the PER-PROBE block size; the dispatch
        cost annotation multiplies it by the rung's probe budget.
        """
        ctx = self.ctx
        index = self.ivf_index
        index.validate(self.n_items)
        plan = index.plan
        if self.backend == "fused":
            pad_to = _score_kernel.pad_block_items
        else:
            def pad_to(n):
                return pad_to_multiple(n, 8)
        layout = _sharding.build_layout(plan, pad_to)
        # written once here (an __init__ helper, before the scorer is
        # shared) and never rebound after
        self._ivf_layout = layout  # pio: ignore[race-unguarded-rebind]
        self._n_items_pad = layout.cap_pad
        # what the exact path would have scanned per row — the
        # scanned-fraction denominator
        self._exact_items_pad = int(pad_to(self.n_items))
        self._local_k = min(self.k, layout.cap_pad)
        # deploy-time probe budget: PIO_IVF_NPROBE overrides the
        # publish-time default, clamped to [1, nlist]
        env_nprobe = os.environ.get("PIO_IVF_NPROBE", "")
        nprobe = (
            int(env_nprobe) if env_nprobe.strip() else int(index.nprobe)
        )
        self._nprobe = max(1, min(nprobe, index.nlist))
        # smallest probe count whose clusters are GUARANTEED to hold >= k
        # real items (sum of the P smallest cluster sizes >= k), so padded
        # slots can never win a final leaderboard slot
        sizes = np.sort(plan.shard_sizes())
        self._min_probes = int(
            np.searchsorted(np.cumsum(sizes), self.k) + 1
        )
        self._probes = {  # pio: ignore[race-unguarded-rebind]
            b: min(
                index.nlist, max(self._min_probes, self._nprobe * b)
            )
            for b in self.buckets
        }
        self._repl = ctx.replicated()
        self._U = ctx.replicate(np.asarray(user_factors))
        self._V = ctx.replicate(
            layout.take_rows(np.asarray(item_factors))
        )
        C = np.asarray(index.centroids, np.float32)
        self._C = ctx.replicate(C)
        gid = layout.gid
        pad_mask = layout.pad_mask
        self._ivf_gid = ctx.replicate(gid)
        self._item_pad_mask = ctx.replicate(pad_mask)
        if self.factor_dtype == "int8":
            self._Uscale = ctx.replicate(np.asarray(user_scale, np.float32))
            self._Vscale = ctx.replicate(
                layout.take_rows(
                    np.asarray(item_scale, np.float32), fill=1.0
                )
            )
            self._static_args = (
                self._U, self._V, self._Uscale, self._Vscale,
                self._C, self._ivf_gid, self._item_pad_mask,
            )
        else:
            self._Uscale = self._Vscale = None
            self._static_args = (
                self._U, self._V, self._C, self._ivf_gid,
                self._item_pad_mask,
            )
        self._ivf_extra_bytes = (
            int(C.nbytes) + int(gid.nbytes) + int(pad_mask.nbytes)
        )

    def _init_sharded_placement(
        self, user_factors, item_factors, user_scale, item_scale
    ) -> None:
        """Item factors partitioned across the plan's shard submesh.

        Every shard's item block is padded to one common kernel-aligned
        capacity so the concatenated (S·cap_pad, rank) matrix shards
        evenly over the mesh 'data' axis; per-slot global ids and a pad
        mask ride alongside.  ``_n_items_pad`` becomes the PER-DEVICE
        block size — each device scores only its shard, which is the
        whole point — so the devprof cost annotation stays per-device
        truthful.  User factors and the (B,) query indices are replicated
        (users were never the HBM problem; items are).
        """
        import jax.numpy as jnp

        plan = self.plan
        plan.validate(self.n_items)
        if self.backend == "fused":
            pad_to = _score_kernel.pad_block_items
        else:
            def pad_to(n):
                return pad_to_multiple(n, 8)
        layout = _sharding.build_layout(plan, pad_to)
        self._shard_layout = layout
        self._n_items_pad = layout.cap_pad
        # per-shard leaderboard width: a shard with fewer than k real
        # items simply contributes its whole block; S·local_k ≥ k always
        # holds because S·cap_pad ≥ n_items ≥ self.k
        self._local_k = min(self.k, layout.cap_pad)
        if self._pod:
            # 2-D (host, data) mesh: shard s lands on host row s // G —
            # the plan's contiguous group blocks, by construction of the
            # process-major prefix carve.  A carve whose host rows do not
            # align with process boundaries is rejected by pod_submesh
            # (the two-tier merge's locality and ownership claims would
            # both be false); serving degrades to the flat merge.
            try:
                sc = self.ctx.pod_submesh(plan.n_shards, plan.host_groups)
                shard_axes = (HOST_AXIS, DATA_AXIS)
            except ValueError as e:
                logger.warning(
                    "pod layout rejected (%s); serving the flat "
                    "single-tier merge instead", e,
                )
                # construction-time rebind, before the scorer is shared
                self._pod = False  # pio: ignore[race-unguarded-rebind]
        if not self._pod:
            sc = self.ctx.submesh(plan.n_shards)
            shard_axes = DATA_AXIS
        self._shard_ctx = sc
        # set once during construction, read-only under traffic
        self._pod_spans = self._pod and sc.spans_processes  # pio: ignore[race-unguarded-rebind]
        self._repl = sc.replicated()
        self._U = sc.place(user_factors)
        self._V = sc.place(
            layout.take_rows(np.asarray(item_factors)), shard_axes, None
        )
        if self.factor_dtype == "int8":
            self._Uscale = sc.place(np.asarray(user_scale, np.float32))
            self._Vscale = sc.place(
                layout.take_rows(
                    np.asarray(item_scale, np.float32), fill=1.0
                ),
                shard_axes, None,
            )
        else:
            self._Uscale = self._Vscale = None
        self._shard_gid = sc.place(layout.gid, shard_axes)
        self._item_pad_mask = sc.place(layout.pad_mask, shard_axes)
        if self.factor_dtype == "int8":
            self._static_args = (
                self._U, self._V, self._Uscale, self._Vscale,
                self._shard_gid, self._item_pad_mask,
            )
        else:
            self._static_args = (
                self._U, self._V, self._shard_gid, self._item_pad_mask,
            )
        per_shard = int(self._V.nbytes) // plan.n_shards
        if self._Vscale is not None:
            per_shard += int(self._Vscale.nbytes) // plan.n_shards
        self.resident_shard_bytes = [per_shard] * plan.n_shards

    # -- streaming micro-generations (core/delta.py) -------------------------

    def _layout_slots(self) -> Optional[dict]:
        """global item id → laid-out row slot, for the active item layout."""
        layout = None
        if self.sharding == "sharded":
            layout = self._shard_layout
        elif self.retrieval == "ivf":
            layout = self._ivf_layout
        if layout is None:
            return None
        slots = getattr(self, "_delta_item_slots", None)
        if slots is None:
            gid = np.asarray(layout.gid)
            mask = np.asarray(layout.pad_mask)
            slots = {
                int(g): int(s) for s, g in enumerate(gid) if not mask[s]
            }
            # built once on first delta, read-only after
            self._delta_item_slots = slots  # pio: ignore[race-unguarded-rebind]
        return slots

    def apply_delta_rows(
        self, user_idx, user_rows, item_idx=None, item_rows=None
    ) -> dict:
        """Patch factor rows in place on the device-resident buffers.

        The micro-generation apply path: replacement rows land through a
        functional scatter on arrays whose shapes and dtypes never
        change, so every AOT-compiled bucket keeps serving the same
        executables — ``compile_count`` stays flat across any number of
        deltas (the invariant the streaming bench asserts).  User rows go
        to the replicated user matrix on every placement; item rows are
        routed to their owning shard/cluster slot through the active
        ShardingPlan layout.  Quantized factors are re-quantized row-wise
        (same per-row-scale scheme as publish).  Affected users fall out
        of the hot-set table so their next lookup re-ranks against the
        patched factors.
        """
        import jax.numpy as jnp

        if self._pod_spans:
            # `.at[].set` needs the whole array addressable; a pod mesh's
            # remote shards aren't.  Documented degrade (operations.md,
            # "Pod-scale serving"): streaming deltas don't compose with
            # multi-process serving — the next full reload picks them up.
            logger.warning(
                "apply_delta_rows skipped: factors span processes on a "
                "pod mesh; deltas apply at the next full publish/reload"
            )
            return {
                "users": 0, "items": 0,
                "compile_count": self.compile_count, "skipped": "pod",
            }
        users = np.asarray(user_idx, np.int32).reshape(-1)
        rows = np.asarray(user_rows, np.float32).reshape(len(users), -1)
        keep = users < self.n_users
        users, rows = users[keep], rows[keep]
        if len(users):
            u_dev = jnp.asarray(users)
            if self.factor_dtype == "int8":
                q, scale = _quantize.quantize_factors(rows, "int8")
                new_U = self._U.at[u_dev].set(jnp.asarray(q))
                new_Us = self._Uscale.at[u_dev].set(jnp.asarray(scale))
            else:
                new_U = self._U.at[u_dev].set(
                    jnp.asarray(rows).astype(self._U.dtype)
                )
                new_Us = self._Uscale
            with self._lock:
                self._U = new_U
                self._Uscale = new_Us
        n_items = self._apply_item_rows(item_idx, item_rows)
        with self._lock:
            self._rebuild_static_args()
            for u in users:
                self._hot_rows.pop(int(u), None)
        return {
            "users": int(len(users)), "items": int(n_items),
            "compile_count": self.compile_count,
        }

    def _apply_item_rows(self, item_idx, item_rows) -> int:
        if item_idx is None:
            return 0
        import jax.numpy as jnp

        idx = np.asarray(item_idx, np.int64).reshape(-1)
        if len(idx) == 0:
            return 0
        rows = np.asarray(item_rows, np.float32).reshape(len(idx), -1)
        keep = idx < self.n_items
        idx, rows = idx[keep], rows[keep]
        slots = self._layout_slots()
        if slots is not None:
            present = np.array([int(g) in slots for g in idx], bool)
            rows = rows[present]
            idx = np.array(
                [slots[int(g)] for g in idx[present]], np.int64
            )
        if len(idx) == 0:
            return 0
        i_dev = jnp.asarray(idx)
        if self.factor_dtype == "int8":
            q, scale = _quantize.quantize_factors(rows, "int8")
            new_V = self._V.at[i_dev].set(jnp.asarray(q))
            new_Vs = self._Vscale.at[i_dev].set(jnp.asarray(scale))
        else:
            new_V = self._V.at[i_dev].set(
                jnp.asarray(rows).astype(self._V.dtype)
            )
            new_Vs = self._Vscale
        with self._lock:
            self._V = new_V
            self._Vscale = new_Vs
        return len(idx)

    def _rebuild_static_args(self) -> None:
        """Re-point the AOT programs' captured operands after a patch.

        Same tuple orders as the three ``_init_*_placement`` builders —
        shapes and dtypes are identical by construction, so the compiled
        executables accept the new buffers without relowering.
        """
        int8 = self.factor_dtype == "int8"
        if self.sharding == "sharded":
            if int8:
                self._static_args = (
                    self._U, self._V, self._Uscale, self._Vscale,
                    self._shard_gid, self._item_pad_mask,
                )
            else:
                self._static_args = (
                    self._U, self._V, self._shard_gid, self._item_pad_mask,
                )
        elif self.retrieval == "ivf":
            if int8:
                self._static_args = (
                    self._U, self._V, self._Uscale, self._Vscale,
                    self._C, self._ivf_gid, self._item_pad_mask,
                )
            else:
                self._static_args = (
                    self._U, self._V, self._C, self._ivf_gid,
                    self._item_pad_mask,
                )
        else:
            if int8:
                self._static_args = (
                    self._U, self._V, self._Uscale, self._Vscale,
                    self._item_pad_mask,
                )
            else:
                self._static_args = (self._U, self._V, self._item_pad_mask)

    def _compile(self, b: int):
        """Lower + compile the bucket-b program ahead of time."""
        if self.sharding == "sharded":
            return self._compile_sharded(b)
        if self.retrieval == "ivf":
            return self._compile_ivf(b)
        k = self.k
        be = self.backend

        if self.factor_dtype == "int8":

            def fn(U, V, u_scale, v_scale, item_pad_mask, u_idx):
                return gather_score_topk(
                    U, V, u_idx, k, item_mask=item_pad_mask,
                    u_scale=u_scale, v_scale=v_scale, backend=be,
                )

        else:

            def fn(U, V, item_pad_mask, u_idx):
                return gather_score_topk(
                    U, V, u_idx, k, item_mask=item_pad_mask, backend=be
                )

        dummy_idx = self._put_repl(np.zeros(b, np.int32))
        compiled = (
            jax.jit(fn)
            .lower(*self._static_args, dummy_idx)
            .compile()
        )
        with self._lock:
            self.compile_count += 1
        self._annotate_cost(b, compiled)
        return compiled

    def _compile_ivf(self, b: int):
        """AOT-compile the bucket-b IVF probe → scan → merge program.

        One program per rung, same ladder/warmup contract as the other
        placements.  The batch's dequantized query rows score against the
        centroids; the rung's probe budget ``P_b`` of clusters is picked
        by ``lax.top_k`` over the row-wise MAX of centroid scores (at
        b=1 this is exactly per-query nprobe selection — the publish
        gate's measurement; at larger rungs the shared budget scales as
        ``nprobe·b`` so per-query amortized scan stays ≈ nprobe/nlist).
        A ``lax.scan`` over the probe ids dynamic-slices each cluster's
        contiguous block out of the layout arrays and runs the EXISTING
        ``gather_score_topk`` over it — per-probe leaderboards carry
        global ids, and ``merge_topk``'s (value desc, id asc) order makes
        the result bit-identical to the exact path when every cluster is
        probed.  Only the probe blocks are ever touched: the scan cost
        per dispatch is ``P_b·cap_pad`` rows instead of the full catalog.
        """
        import jax.numpy as jnp

        k = self.k
        lk = self._local_k
        be = self.backend
        cap = self._ivf_layout.cap_pad
        P_b = self._probes[b]

        if self.factor_dtype == "int8":

            def fn(U, V, u_scale, v_scale, C, gid, pad_mask, u_idx):
                q = U[u_idx].astype(jnp.float32) * u_scale[u_idx]
                agg = jnp.max(q @ C.T, axis=0)  # (nlist,)
                _, probes = jax.lax.top_k(agg, P_b)

                def step(carry, p):
                    s = p * cap
                    Vb = jax.lax.dynamic_slice_in_dim(V, s, cap, 0)
                    vsb = jax.lax.dynamic_slice_in_dim(v_scale, s, cap, 0)
                    gb = jax.lax.dynamic_slice_in_dim(gid, s, cap, 0)
                    mb = jax.lax.dynamic_slice_in_dim(pad_mask, s, cap, 0)
                    vals, idx = gather_score_topk(
                        U, Vb, u_idx, lk, item_mask=mb,
                        u_scale=u_scale, v_scale=vsb, backend=be,
                    )
                    return carry, (vals, jnp.take(gb, idx))

                _, (pv, pg) = jax.lax.scan(step, None, probes)
                cand_v = jnp.swapaxes(pv, 0, 1).reshape(b, P_b * lk)
                cand_g = jnp.swapaxes(pg, 0, 1).reshape(b, P_b * lk)
                return merge_topk(cand_v, cand_g, k)

        else:

            def fn(U, V, C, gid, pad_mask, u_idx):
                q = U[u_idx].astype(jnp.float32)
                agg = jnp.max(q @ C.T, axis=0)  # (nlist,)
                _, probes = jax.lax.top_k(agg, P_b)

                def step(carry, p):
                    s = p * cap
                    Vb = jax.lax.dynamic_slice_in_dim(V, s, cap, 0)
                    gb = jax.lax.dynamic_slice_in_dim(gid, s, cap, 0)
                    mb = jax.lax.dynamic_slice_in_dim(pad_mask, s, cap, 0)
                    vals, idx = gather_score_topk(
                        U, Vb, u_idx, lk, item_mask=mb, backend=be
                    )
                    return carry, (vals, jnp.take(gb, idx))

                _, (pv, pg) = jax.lax.scan(step, None, probes)
                cand_v = jnp.swapaxes(pv, 0, 1).reshape(b, P_b * lk)
                cand_g = jnp.swapaxes(pg, 0, 1).reshape(b, P_b * lk)
                return merge_topk(cand_v, cand_g, k)

        dummy_idx = self._put_repl(np.zeros(b, np.int32))
        compiled = (
            jax.jit(fn)
            .lower(*self._static_args, dummy_idx)
            .compile()
        )
        with self._lock:
            self.compile_count += 1
        # always the analytic model: the probe scan's Pallas calls are
        # opaque to XLA cost analysis, and the analytic scanned-rows
        # number (P_b·cap_pad, not the full catalog) IS the story
        rank = self._U.shape[1]
        scanned = P_b * cap
        if be == "fused":
            a_flops, a_bytes = _devprof.fused_score_cost(
                b, scanned, rank, lk, self.factor_dtype
            )
            self.devprof.set_cost(
                b, a_flops, a_bytes, source="analytic-fused"
            )
        else:
            a_flops, a_bytes = _devprof.score_cost(
                b, scanned, rank, dtype=self.factor_dtype
            )
            self.devprof.set_cost(b, a_flops, a_bytes, source="analytic")
        self._cost_bytes[b] = a_bytes
        return compiled

    def _compile_sharded(self, b: int):
        """AOT-compile the bucket-b fan-out → local top-k → merge program.

        One program per rung, same ladder and warmup contract as the
        replicated path.  Inside ``shard_map`` each device runs the
        existing ``gather_score_topk`` over ONLY its local item block and
        maps local winners to global ids; the shard-stacked
        (S, B, local_k) leaderboards leave the shard region sharded, and
        the transpose+merge outside forces the partitioner to emit one
        small leaderboard all-gather (S·B·local_k·8 bytes) — never the
        (B, n_items) score matrix.  ``merge_topk``'s (value desc, id asc)
        order makes the result bit-identical to the replicated reference.

        Pod layouts (``plan.host_groups > 1``) run the merge INSIDE the
        shard region instead: :func:`two_tier_merge_topk` gathers the G
        on-host leaderboards over the ``data`` axis, merges, then gathers
        only the H per-host ``(B, k)`` leaderboards over the ``host``
        axis — the flat ``(S, B, local_k)`` collective above never forms,
        and the cross-host wire carries ``H·B·k·8`` bytes per dispatch
        (docs/perf_roofline.md).  Same two-key sort both tiers, so the
        answers stay bit-identical.
        """
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        k = self.k
        lk = self._local_k
        be = self.backend
        S = self.plan.n_shards
        mesh = self._shard_ctx.mesh
        pod = self._pod
        shard_dim = (HOST_AXIS, DATA_AXIS) if pod else DATA_AXIS

        if self.factor_dtype == "int8":

            def local(U, Vl, u_scale, vs_l, gidl, maskl, u_idx):
                vals, idx = gather_score_topk(
                    U, Vl, u_idx, lk, item_mask=maskl,
                    u_scale=u_scale, v_scale=vs_l, backend=be,
                )
                gids = jnp.take(gidl, idx)
                if pod:
                    return two_tier_merge_topk(
                        vals, gids, k,
                        group_axis=DATA_AXIS, host_axis=HOST_AXIS,
                    )
                return vals[None], gids[None]

            in_specs = (
                P(), P(shard_dim, None), P(), P(shard_dim, None),
                P(shard_dim), P(shard_dim), P(),
            )
        else:

            def local(U, Vl, gidl, maskl, u_idx):
                vals, idx = gather_score_topk(
                    U, Vl, u_idx, lk, item_mask=maskl, backend=be
                )
                gids = jnp.take(gidl, idx)
                if pod:
                    return two_tier_merge_topk(
                        vals, gids, k,
                        group_axis=DATA_AXIS, host_axis=HOST_AXIS,
                    )
                return vals[None], gids[None]

            in_specs = (
                P(), P(shard_dim, None), P(shard_dim), P(shard_dim), P(),
            )
        if pod:
            # the two-tier merge already replicated the final (B, k)
            out_specs = (P(), P())

            def fn(*args):
                return shard_map(
                    local, mesh=mesh, in_specs=in_specs,
                    out_specs=out_specs,
                )(*args)

        else:
            out_specs = (
                P(DATA_AXIS, None, None), P(DATA_AXIS, None, None),
            )

            def fn(*args):
                lv, lg = shard_map(
                    local, mesh=mesh, in_specs=in_specs,
                    out_specs=out_specs,
                )(*args)
                # (S, B, lk) → (B, S·lk) candidate rows; the global
                # reshape is what pulls the leaderboards across the mesh
                cand_v = jnp.swapaxes(lv, 0, 1).reshape(b, S * lk)
                cand_g = jnp.swapaxes(lg, 0, 1).reshape(b, S * lk)
                return merge_topk(cand_v, cand_g, k)

        dummy_idx = self._put_repl(np.zeros(b, np.int32))
        compiled = (
            jax.jit(fn)
            .lower(*self._static_args, dummy_idx)
            .compile()
        )
        with self._lock:
            self.compile_count += 1
        self._annotate_cost(b, compiled)
        return compiled

    def _annotate_cost(self, b: int, compiled) -> None:
        """Record bucket-b per-dispatch FLOPs/bytes on the accountant.

        Prefers the compiler's own numbers for the ACTUAL optimized HLO;
        falls back to the analytic score model when cost_analysis
        declines (some backends return nothing useful).  Fused buckets
        always use the analytic fused model: the Pallas call is opaque to
        XLA's cost analysis, which would report the custom-call as ~free
        and make MFU read as zero forever.
        """
        rank = self._U.shape[1]
        if self.backend == "fused":
            a_flops, a_bytes = _devprof.fused_score_cost(
                b, self._n_items_pad, rank, self.k, self.factor_dtype
            )
            self.devprof.set_cost(
                b, a_flops, a_bytes, source="analytic-fused"
            )
            self._cost_bytes[b] = a_bytes
            return
        flops = nbytes = None
        try:
            ca = compiled.cost_analysis()
            if isinstance(ca, list):
                ca = ca[0] if ca else {}
            ca = ca or {}
            flops = ca.get("flops")
            nbytes = ca.get("bytes accessed")
        except Exception:  # pragma: no cover - backend-dependent
            pass
        if flops and nbytes:
            self.devprof.set_cost(b, flops, nbytes, source="xla")
            self._cost_bytes[b] = float(nbytes)
        else:
            a_flops, a_bytes = _devprof.score_cost(
                b, self._n_items_pad, rank, dtype=self.factor_dtype
            )
            self.devprof.set_cost(b, a_flops, a_bytes, source="analytic")
            self._cost_bytes[b] = a_bytes

    def score_topk(
        self, user_indices: np.ndarray, k: int
    ) -> tuple[np.ndarray, np.ndarray]:
        """Top-``k`` (indices, values) for every user in ``user_indices``.

        Batches larger than the top rung are served in top-rung chunks, so
        any size works without growing the compile cache.  ``k`` beyond the
        compiled width raises ValueError — callers route that to their
        exact path instead of silently truncating.

        With the hot set enabled, users present in the materialized table
        are answered from host memory (their scores cannot change until
        the next model generation replaces this scorer); only the cold
        remainder pays a device pass.  Output order is preserved.
        """
        if k > self.k:
            raise ValueError(f"k={k} exceeds compiled top-k width {self.k}")
        users = np.asarray(user_indices, np.int32)
        if self._hot_counts is None:
            return self._device_topk(users, k)
        self._note_traffic(users)
        with self._lock:
            rows = self._hot_rows
            table_idx = self._hot_table_idx
            table_val = self._hot_table_val
        if table_idx is None:
            return self._device_topk(users, k)
        hot_rows = np.fromiter(
            (rows.get(int(u), -1) for u in users), np.int64, count=len(users)
        )
        hot_mask = hot_rows >= 0
        n_hot = int(hot_mask.sum())
        with self._lock:
            self.hot_hits += n_hot
            self.hot_misses += len(users) - n_hot
        if n_hot == 0:
            return self._device_topk(users, k)
        idx_out = np.empty((len(users), k), table_idx.dtype)
        val_out = np.empty((len(users), k), table_val.dtype)
        idx_out[hot_mask] = table_idx[hot_rows[hot_mask], :k]
        val_out[hot_mask] = table_val[hot_rows[hot_mask], :k]
        cold = users[~hot_mask]
        if len(cold):
            c_idx, c_val = self._device_topk(cold, k)
            idx_out[~hot_mask] = c_idx
            val_out[~hot_mask] = c_val
        return idx_out, val_out

    def _device_topk(
        self, users: np.ndarray, k: int
    ) -> tuple[np.ndarray, np.ndarray]:
        """The bucketed device path (pre-hot-set ``score_topk`` body)."""
        top = self.buckets[-1]
        idx_parts, val_parts = [], []
        for s in range(0, len(users), top):
            chunk = users[s : s + top]
            b = bucket_for(len(chunk), self.buckets)
            padded = np.zeros(b, np.int32)
            padded[: len(chunk)] = chunk
            for t in _tracing.active_traces():
                t.annotate(bucket=b)
            with _tracing.stage("h2d"):
                u_dev = self._put_repl(padded)
            with _profiling.trace(stage="device_compute"):
                t0 = time.perf_counter()
                vals, idx = self._fns[b](*self._static_args, u_dev)
                # force completion INSIDE the stage so async dispatch
                # can't smear device time into the d2h readback below —
                # and so the utilization accountant charges true device
                # wall, not enqueue time. (The readback two lines down
                # would block here anyway; this only moves the wait.)
                jax.block_until_ready((vals, idx))  # pio: ignore[hotpath-block-sync]
                wall = time.perf_counter() - t0
                self.devprof.record(b, wall)
            idx_h = self._fetch(idx)
            val_h = self._fetch(vals)
            with self._lock:
                self.hits[b] += 1
                self.queries += len(chunk)
                self.padded_rows += b - len(chunk)
                if self._shard_acct is not None:
                    self._shard_acct.note(
                        idx_h[: len(chunk), :k], b, wall,
                        self._cost_bytes.get(b, 0.0),
                    )
                if self.retrieval == "ivf":
                    self._ivf_dispatches += 1
                    self._ivf_probed_blocks += self._probes[b]
                    self._ivf_scanned_rows += (
                        self._probes[b] * self._ivf_layout.cap_pad
                    )
                    self._ivf_dispatch_rows += b
            # padded tail rows are real top-k rows for user 0 — dropped here
            idx_parts.append(idx_h[: len(chunk), :k])
            val_parts.append(val_h[: len(chunk), :k])
        return np.concatenate(idx_parts), np.concatenate(val_parts)

    # -- hot set -------------------------------------------------------------
    def _note_traffic(self, users: np.ndarray) -> None:
        refresh = False
        with self._lock:
            np.add.at(self._hot_counts, users, 1.0)
            self._hot_since_refresh += len(users)
            if self._hot_since_refresh >= self.hot_refresh_queries:
                self._hot_since_refresh = 0
                refresh = True
        if refresh:
            self._refresh_hot_set()

    def _refresh_hot_set(self) -> None:
        """Re-rank the working set and materialize its top-k table.

        Runs on the calling thread (one batch pays ~hot_size/top_rung
        device passes per refresh interval) through the already-compiled
        rungs, so ``compile_count`` stays flat — the AOT contract the
        bench's zero-recompile check enforces.  The decay halves every
        count afterward so the ranking follows traffic drift rather than
        all-time popularity.
        """
        with self._lock:
            counts = self._hot_counts.copy()
        n = self.hot_size
        if n < len(counts):
            cand = np.argpartition(-counts, n - 1)[:n]
        else:
            cand = np.arange(len(counts))
        cand = cand[counts[cand] > 0]
        if len(cand) == 0:
            return
        cand = np.sort(cand).astype(np.int32)
        idx, vals = self._device_topk(cand, self.k)
        with self._lock:
            self._hot_rows = {int(u): i for i, u in enumerate(cand)}
            self._hot_table_idx = idx
            self._hot_table_val = vals
            self.hot_refreshes += 1
            self._hot_counts *= 0.5

    def stats(self) -> dict:
        """Counters for ``GET /`` stats and bench artifacts.

        ``compile_count`` only moves at construction (warmup); a nonzero
        delta across serving traffic IS a recompile and fails the bench's
        zero-recompile check.
        """
        with self._lock:
            hits = dict(self.hits)
            hot_lookups = self.hot_hits + self.hot_misses
            hotset = {
                "size": self.hot_size,
                "resident": len(self._hot_rows),
                "refresh_queries": self.hot_refresh_queries,
                "hits": self.hot_hits,
                "misses": self.hot_misses,
                "refreshes": self.hot_refreshes,
                "hit_rate": round(self.hot_hits / hot_lookups, 4)
                if hot_lookups
                else None,
            }
            top = self.buckets[-1]
            costs = self.devprof.costs()
            top_cost = costs.get(top) or {}
            flops = top_cost.get("flops")
            nbytes = top_cost.get("bytes")
            kernel = {
                "backend": self.backend,
                "factor_dtype": self.factor_dtype,
                "resident_factor_bytes": self.resident_factor_bytes,
                "block_items": (
                    min(_score_kernel.BLOCK_I, self._n_items_pad)
                    if self.backend == "fused" else None
                ),
                "warmup_executions": self.warmup_executions,
                # top-rung arithmetic intensity: the roofline position the
                # docs derive (docs/perf_roofline.md)
                "intensity_flops_per_byte": (
                    round(flops / nbytes, 3) if flops and nbytes else None
                ),
            }
            dev = self.devprof.snapshot()
            sharding = None
            if self._shard_acct is not None:
                sharding = self._shard_acct.snapshot(
                    (dev or {}).get("busy_fraction"),
                    self.resident_shard_bytes,
                )
            retrieval = None
            if self.retrieval == "ivf":
                index = self.ivf_index
                # scanned fraction: item rows the probe scans streamed /
                # rows the exact path would have streamed for the same
                # dispatches.  Per DISPATCH, not per row — one matmul
                # over the probe blocks serves every row in the rung,
                # exactly as one exact full scan would, so this is the
                # honest HBM-bytes ratio between the two paths.
                denom = self._ivf_dispatches * self._exact_items_pad
                retrieval = {
                    "backend": "ivf",
                    "nlist": index.nlist,
                    "nprobe": self._nprobe,
                    "min_probes": self._min_probes,
                    "cap_pad": self._ivf_layout.cap_pad,
                    "probes_per_rung": {
                        str(b): p for b, p in self._probes.items()
                    },
                    "dispatches": self._ivf_dispatches,
                    "dispatch_rows": self._ivf_dispatch_rows,
                    "probed_blocks": self._ivf_probed_blocks,
                    "scanned_rows": self._ivf_scanned_rows,
                    "scanned_fraction": round(
                        self._ivf_scanned_rows / denom, 6
                    )
                    if denom
                    else None,
                    "resident_extra_bytes": self._ivf_extra_bytes,
                    "recall_at_publish": index.recall_at_publish,
                    "fingerprint": index.fingerprint,
                }
            pod = None
            if self._pod:
                pod = {
                    "host_groups": self.plan.host_groups,
                    "shards_per_group": self.plan.shards_per_group,
                    "process_index": jax.process_index(),
                    "process_count": jax.process_count(),
                    "spans_processes": self._pod_spans,
                    "fingerprint": self.plan.fingerprint,
                    "cross_host_merge_bytes": (sharding or {}).get(
                        "pod_merge_bytes", 0.0
                    ),
                    "cross_host_merge_seconds": (sharding or {}).get(
                        "pod_merge_seconds", 0.0
                    ),
                    "dispatches": (sharding or {}).get("pod_dispatches", 0),
                }
            return {
                "buckets": list(self.buckets),
                "top_k": self.k,
                "serving_backend": self.sharding,
                "sharding": sharding,
                "pod": pod,
                "retrieval_backend": self.retrieval,
                "retrieval": retrieval,
                "kernel": kernel,
                "compile_count": self.compile_count,
                "bucket_hits": {str(b): h for b, h in hits.items()},
                "calls": sum(hits.values()),
                "queries": self.queries,
                "padded_rows": self.padded_rows,
                "row_occupancy": round(
                    self.queries / (self.queries + self.padded_rows), 4
                )
                if self.queries
                else None,
                "hotset": hotset if self.hot_size else None,
                "devprof": dev,
            }
