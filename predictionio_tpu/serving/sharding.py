"""Sharded serving: the ShardingPlan that scores catalogs bigger than one chip.

The replicated fast path (``serving/fastpath.py``) keeps a full copy of
the item-factor matrix on every device, capping the servable catalog at a
single chip's HBM.  This module grows the second axis of scale: an
explicit :class:`ShardingPlan` — shard count, item→shard assignment,
per-shard capacity budget, and a content fingerprint — declared at model
publish time and carried next to the factors through the sealed-blob
checksum envelope (``core/persistence.py``).

Execution shape (DrJAX's MapReduce-in-JAX playbook, PAPERS.md): item
factors live PARTITIONED across the mesh, each query fans out so every
shard runs the existing fused ``gather_score_topk`` kernel over only its
local item block, and the only cross-device traffic is one small
all-gather of per-shard ``(B, local_k)`` leaderboards plus an on-device
two-key merge (:func:`predictionio_tpu.ops.topk.merge_topk`) — the
``(B, n_items)`` score matrix never crosses a link.  Per-shard item lists
are sorted ascending by global index, so shard-local ``lax.top_k`` tie
order composes with the merge's ``(value desc, index asc)`` order into
answers bit-identical to the single-device reference, cross-shard ties
included.

Placement is popularity-aware: serving traffic is Zipf-shaped, and the
merge/readback load an item generates follows how often it WINS top-k
slots, not how many bytes it occupies.  :func:`build_plan`'s
``popularity`` strategy balances expected load (greedy LPT over item
weights — live hot-set win counts, or factor norms as the publish-time
proxy) under an item-count capacity cap, so both resident bytes and
expected traffic stay level across shards.
"""

from __future__ import annotations

import dataclasses
import hashlib
import logging
import pickle
from typing import Optional

import numpy as np

logger = logging.getLogger(__name__)

STRATEGIES = ("popularity", "round_robin", "contiguous")

# payload bytes per merged leaderboard slot: f32 value + i32 global index
MERGE_SLOT_BYTES = 8

# global-index sentinel for padded leaderboard slots: larger than any real
# item id, so an all-NEG_INF tie (fully masked row) still sorts real items
# ahead of padding in the merge
PAD_SENTINEL = np.int32(2**31 - 1)

_PLAN_VERSION = 1


@dataclasses.dataclass(frozen=True)
class ShardingPlan:
    """Item→shard partition declared at publish time.

    ``assignment[i]`` is the shard owning global item ``i``;
    ``load_share`` is the expected per-shard traffic fraction under the
    weights the plan was balanced with; ``capacity_budget_bytes`` records
    the per-shard HBM budget the shard count was derived from (None when
    the count was given explicitly).

    ``host_groups`` is the pod dimension (PR 18): the plan's shards are
    laid out as ``host_groups × shards_per_group`` rows of a 2-D
    ``(host, data)`` mesh, group ``g`` owning the CONTIGUOUS shard block
    ``[g·G, (g+1)·G)`` (G = ``shards_per_group``) — exactly how the
    prefix-carved process-major device list folds into host rows, so
    group membership needs no extra map.  ``host_groups == 1`` is the
    single-process layout and round-trips byte-identically with plans
    sealed before the field existed.
    """

    n_shards: int
    assignment: np.ndarray  # (n_items,) int32
    strategy: str
    load_share: np.ndarray  # (n_shards,) float64, sums to 1
    capacity_budget_bytes: Optional[int] = None
    host_groups: int = 1

    @property
    def n_items(self) -> int:
        return int(self.assignment.shape[0])

    def shard_items(self, shard: int) -> np.ndarray:
        """Global item ids on ``shard``, ascending (the on-device order)."""
        return np.flatnonzero(self.assignment == shard).astype(np.int32)

    def shard_sizes(self) -> np.ndarray:
        return np.bincount(self.assignment, minlength=self.n_shards)

    @property
    def shards_per_group(self) -> int:
        """Shards per host group (the pod mesh's within-host axis size)."""
        return self.n_shards // max(1, self.host_groups)

    def group_of_shard(self, shard: int) -> int:
        """Host group owning ``shard`` (contiguous G-sized blocks)."""
        return int(shard) // self.shards_per_group

    def group_of_item(self, item: int) -> int:
        """Host group owning global item ``item``."""
        return self.group_of_shard(int(self.assignment[int(item)]))

    @property
    def fingerprint(self) -> str:
        """Content hash over the partition itself — the plan's identity.

        Published into the model manifest and surfaced through serving
        stats/metrics, so a rebalance is visible as a generation change
        even when the factors did not move.  The host-group dimension is
        hashed only when it is non-trivial, so every plan sealed before
        the pod layout existed keeps its fingerprint.
        """
        h = hashlib.sha256()
        h.update(f"{_PLAN_VERSION}:{self.n_shards}:{self.strategy}:".encode())
        h.update(np.ascontiguousarray(self.assignment, np.int32).tobytes())
        if self.host_groups > 1:
            h.update(f":hg{self.host_groups}".encode())
        return h.hexdigest()[:16]

    def validate(self, n_items: Optional[int] = None) -> None:
        a = self.assignment
        if a.ndim != 1 or (n_items is not None and a.shape[0] != n_items):
            raise ValueError(
                f"assignment shape {a.shape} does not cover {n_items} items"
            )
        if self.n_shards < 1:
            raise ValueError(f"n_shards must be >= 1, got {self.n_shards}")
        if a.size and (a.min() < 0 or a.max() >= self.n_shards):
            raise ValueError("assignment references shards outside the plan")
        sizes = self.shard_sizes()
        if a.size and (sizes == 0).any():
            empty = np.flatnonzero(sizes == 0).tolist()
            raise ValueError(f"plan leaves shards empty: {empty}")
        if self.host_groups < 1:
            raise ValueError(
                f"host_groups must be >= 1, got {self.host_groups}"
            )
        if self.n_shards % self.host_groups:
            raise ValueError(
                f"host_groups={self.host_groups} must divide "
                f"n_shards={self.n_shards} (equal host rows)"
            )

    def to_payload(self) -> bytes:
        return pickle.dumps(
            {
                "version": _PLAN_VERSION,
                "n_shards": self.n_shards,
                "strategy": self.strategy,
                "assignment": np.ascontiguousarray(
                    self.assignment, np.int32
                ),
                "load_share": np.ascontiguousarray(
                    self.load_share, np.float64
                ),
                "capacity_budget_bytes": self.capacity_budget_bytes,
                "host_groups": self.host_groups,
            },
            protocol=pickle.HIGHEST_PROTOCOL,
        )

    @classmethod
    def from_payload(cls, payload: bytes) -> "ShardingPlan":
        d = pickle.loads(payload)
        plan = cls(
            n_shards=int(d["n_shards"]),
            assignment=np.asarray(d["assignment"], np.int32),
            strategy=str(d["strategy"]),
            load_share=np.asarray(d["load_share"], np.float64),
            capacity_budget_bytes=d.get("capacity_budget_bytes"),
            host_groups=int(d.get("host_groups", 1)),
        )
        plan.validate()
        return plan

    def describe(self) -> dict:
        """JSON-friendly summary for the ``pio shards`` CLI and stats."""
        sizes = self.shard_sizes()
        return {
            "n_shards": self.n_shards,
            "n_items": self.n_items,
            "strategy": self.strategy,
            "fingerprint": self.fingerprint,
            "capacity_budget_bytes": self.capacity_budget_bytes,
            "items_per_shard": sizes.tolist(),
            "load_share": [round(float(x), 6) for x in self.load_share],
            "host_groups": self.host_groups,
            "shards_per_group": self.shards_per_group,
        }


def shard_count_for_budget(
    n_items: int, bytes_per_item: float, budget_bytes: int
) -> int:
    """Smallest shard count whose per-shard resident bytes fit ``budget``."""
    if budget_bytes <= 0:
        raise ValueError("per-shard HBM budget must be positive")
    total = float(n_items) * float(bytes_per_item)
    return max(1, int(np.ceil(total / float(budget_bytes))))


def build_plan(
    n_items: int,
    n_shards: Optional[int] = None,
    *,
    weights: Optional[np.ndarray] = None,
    strategy: str = "popularity",
    capacity_budget_bytes: Optional[int] = None,
    bytes_per_item: Optional[float] = None,
    host_groups: int = 1,
) -> ShardingPlan:
    """Build a plan by explicit shard count or per-shard byte budget.

    ``weights`` are per-item expected-traffic weights (hot-set win
    counts, Zipf pmf, factor norms — any non-negative signal); the
    ``popularity`` strategy runs greedy LPT over them under an item-count
    capacity cap of ``ceil(n_items / n_shards)`` so byte residency stays
    balanced while expected load levels out.  ``round_robin`` and
    ``contiguous`` ignore the weights for assignment but still record the
    resulting per-shard load shares, so an imbalanced naive plan is
    visible in its own manifest.
    """
    if strategy not in STRATEGIES:
        raise ValueError(
            f"strategy must be one of {STRATEGIES}, got {strategy!r}"
        )
    if n_items < 1:
        raise ValueError("cannot shard an empty catalog")
    if n_shards is None:
        if capacity_budget_bytes is None or bytes_per_item is None:
            raise ValueError(
                "need n_shards, or capacity_budget_bytes + bytes_per_item"
            )
        n_shards = shard_count_for_budget(
            n_items, bytes_per_item, capacity_budget_bytes
        )
        # a budget-derived count rounds up to fill every host row.  When
        # the round-up overruns a catalog the budget alone could serve
        # (small catalog, many host rows), the POD knob — not the budget
        # — made the publish unservable: say so, rather than letting the
        # generic shard-count bound below obscure the cause.
        if host_groups > 1 and n_shards % host_groups:
            rounded = n_shards + host_groups - n_shards % host_groups
            if rounded > n_items >= n_shards:
                raise ValueError(
                    f"host_groups={host_groups} (PIO_POD_HOST_GROUPS) "
                    f"cannot be filled from this catalog: the "
                    f"budget-derived shard count {n_shards} rounds up "
                    f"to {rounded} > n_items={n_items} — lower "
                    "PIO_POD_HOST_GROUPS or raise the budget"
                )
            n_shards = rounded
    n_shards = int(n_shards)
    if host_groups > 1 and n_shards % host_groups:
        raise ValueError(
            f"host_groups={host_groups} (PIO_POD_HOST_GROUPS) must "
            f"divide n_shards={n_shards}: pod host rows must be equal"
        )
    if not 1 <= n_shards <= n_items:
        raise ValueError(
            f"n_shards={n_shards} outside [1, n_items={n_items}]"
        )
    if weights is None:
        w = np.ones(n_items, np.float64)
    else:
        w = np.asarray(weights, np.float64).reshape(-1)
        if w.shape[0] != n_items:
            raise ValueError(
                f"weights cover {w.shape[0]} items, catalog has {n_items}"
            )
        if (w < 0).any():
            raise ValueError("weights must be non-negative")

    assignment = np.empty(n_items, np.int32)
    if strategy == "round_robin":
        assignment[:] = np.arange(n_items, dtype=np.int32) % n_shards
    elif strategy == "contiguous":
        cap = int(np.ceil(n_items / n_shards))
        assignment[:] = np.minimum(
            np.arange(n_items, dtype=np.int64) // cap, n_shards - 1
        ).astype(np.int32)
    else:  # popularity: greedy LPT under an item-count capacity cap
        cap = int(np.ceil(n_items / n_shards))
        # heaviest first; ties by ascending id keep the build deterministic
        order = np.lexsort((np.arange(n_items), -w))
        load = np.zeros(n_shards, np.float64)
        counts = np.zeros(n_shards, np.int64)
        for i in order:
            open_shards = np.flatnonzero(counts < cap)
            s = open_shards[np.argmin(load[open_shards])]
            assignment[i] = s
            load[s] += w[i]
            counts[s] += 1

    per_shard = np.zeros(n_shards, np.float64)
    np.add.at(per_shard, assignment, w)
    total = per_shard.sum()
    load_share = (
        per_shard / total if total > 0
        else np.full(n_shards, 1.0 / n_shards)
    )
    plan = ShardingPlan(
        n_shards=n_shards,
        assignment=assignment,
        strategy=strategy,
        load_share=load_share,
        capacity_budget_bytes=capacity_budget_bytes,
        host_groups=int(host_groups),
    )
    plan.validate(n_items)
    return plan


def plan_from_assignment(
    assignment: np.ndarray,
    *,
    weights: Optional[np.ndarray] = None,
    strategy: str = "contiguous",
) -> ShardingPlan:
    """Build a plan from an EXPLICIT item→shard assignment.

    The layout/merge machinery (``build_layout``, fingerprinting, the
    sealed-blob round trip) only needs the assignment itself — this is
    the entry point for partitions computed elsewhere, e.g. the IVF
    coarse quantizer (``ops/ivf.py``) whose k-means clusters become the
    "shards" of a coarse-partition layout.  ``strategy`` is recorded
    verbatim in the plan (it names the producer, not one of
    :data:`STRATEGIES`); shard count is taken from the assignment, which
    must leave no shard empty (drop empty clusters before calling).
    """
    assignment = np.ascontiguousarray(assignment, np.int32)
    if assignment.ndim != 1 or assignment.size == 0:
        raise ValueError("assignment must be a non-empty 1-D item→shard map")
    n_items = int(assignment.shape[0])
    n_shards = int(assignment.max()) + 1
    if weights is None:
        w = np.ones(n_items, np.float64)
    else:
        w = np.asarray(weights, np.float64).reshape(-1)
        if w.shape[0] != n_items:
            raise ValueError(
                f"weights cover {w.shape[0]} items, catalog has {n_items}"
            )
    per_shard = np.zeros(n_shards, np.float64)
    np.add.at(per_shard, assignment, w)
    total = per_shard.sum()
    load_share = (
        per_shard / total if total > 0
        else np.full(n_shards, 1.0 / n_shards)
    )
    plan = ShardingPlan(
        n_shards=n_shards,
        assignment=assignment,
        strategy=strategy,
        load_share=load_share,
    )
    plan.validate(n_items)
    return plan


def plan_from_env(
    n_items: int,
    weights: Optional[np.ndarray] = None,
    bytes_per_item: Optional[float] = None,
) -> Optional[ShardingPlan]:
    """Publish-time plan declaration from the PIO_SHARD_* knobs.

    Returns None when neither ``PIO_SHARD_COUNT`` nor
    ``PIO_SHARD_HBM_BUDGET`` is set — the model publishes unsharded and
    every existing caller is untouched.
    """
    import os

    count = os.environ.get("PIO_SHARD_COUNT", "")
    budget = os.environ.get("PIO_SHARD_HBM_BUDGET", "")
    strategy = (
        os.environ.get("PIO_SHARD_STRATEGY") or "popularity"
    ).strip().lower()
    # pod layout: PIO_POD_HOST_GROUPS=H folds the plan's shards into H
    # host rows (must divide the shard count); 1/unset = single-host
    host_groups = os.environ.get("PIO_POD_HOST_GROUPS", "")
    if not count.strip() and not budget.strip():
        return None
    return build_plan(
        n_items,
        n_shards=int(count) if count.strip() else None,
        weights=weights,
        strategy=strategy,
        capacity_budget_bytes=int(budget) if budget.strip() else None,
        bytes_per_item=bytes_per_item,
        host_groups=int(host_groups) if host_groups.strip() else 1,
    )


def save_plan(path: str, plan: ShardingPlan) -> None:
    """Seal the plan into ``path`` through the checksum envelope
    (atomic tmp+rename — the same publish guarantee as ``quant.blob``)."""
    from predictionio_tpu.core import persistence as _persistence

    _persistence.seal_blob_file(path, plan.to_payload())


def load_plan(path: str) -> ShardingPlan:
    """Open a sealed plan; raises ``ModelIntegrityError`` on a torn blob,
    ``OSError`` when missing — callers degrade to replicated serving."""
    from predictionio_tpu.core import persistence as _persistence

    return ShardingPlan.from_payload(_persistence.open_blob_file(path))


# ---------------------------------------------------------------------------
# Device layout: plan → permuted/padded arrays the executor places
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ShardLayout:
    """Host-side arrays realizing a plan as equal-size device blocks.

    ``perm`` is ``(n_shards, cap_pad)`` of global item ids (−1 on padded
    slots); every shard's real slots are ascending by global id, which is
    what makes shard-local ``lax.top_k`` tie order compose with the
    global merge.  ``cap_pad`` is the common padded per-shard capacity
    (kernel block aligned), so the concatenated factor block is one
    ``(n_shards·cap_pad, rank)`` array sharded P("data", None).
    """

    n_shards: int
    cap_pad: int
    perm: np.ndarray  # (n_shards, cap_pad) int64, -1 = padding

    @property
    def gid(self) -> np.ndarray:
        """Flat (n_shards·cap_pad,) global ids; PAD_SENTINEL on padding."""
        g = np.where(self.perm >= 0, self.perm, PAD_SENTINEL)
        return g.reshape(-1).astype(np.int32)

    @property
    def pad_mask(self) -> np.ndarray:
        """Flat bool mask, True on padded (never-winning) slots."""
        return (self.perm < 0).reshape(-1)

    def take_rows(self, rows: np.ndarray, fill=0) -> np.ndarray:
        """Gather ``rows[global_id]`` into shard layout, ``fill`` on pads."""
        rows = np.asarray(rows)
        flat = self.perm.reshape(-1)
        out = rows[np.clip(flat, 0, None)].copy()
        out[flat < 0] = fill
        return out


def build_layout(plan: ShardingPlan, pad_to) -> ShardLayout:
    """Realize ``plan`` as equal padded shard blocks.

    ``pad_to`` maps the largest shard's item count to the common padded
    capacity — the fastpath passes the fused kernel's block padding or
    the reference path's multiple-of-8 rule, matching what the replicated
    scorer does to its single block.
    """
    sizes = plan.shard_sizes()
    cap_pad = int(pad_to(int(sizes.max())))
    perm = np.full((plan.n_shards, cap_pad), -1, np.int64)
    for s in range(plan.n_shards):
        ids = plan.shard_items(s)  # ascending — the tie-order invariant
        perm[s, : len(ids)] = ids
    return ShardLayout(
        n_shards=plan.n_shards, cap_pad=cap_pad, perm=perm
    )


# ---------------------------------------------------------------------------
# Runtime accounting: per-shard load realized by live traffic
# ---------------------------------------------------------------------------


class ShardAccounting:
    """Per-shard counters fed by the fastpath dispatch loop.

    All device work in one SPMD dispatch is simultaneous, so a shard's
    *busy seconds* are not separately observable; what IS measured per
    shard is its result load — how many top-k slots its items win, which
    drives the merge/readback traffic and downstream hydration a shard
    generates.  ``snapshot`` attributes the measured whole-mesh busy
    fraction across shards by that realized win share (documented in
    docs/operations.md as an attributed quantity; the max/min balance the
    bench gates on depends only on the shares).

    Counters are guarded by an internal lock: ``note`` runs on request
    threads while ``snapshot`` runs on the stats/metrics scrape thread.
    """

    def __init__(
        self, plan: ShardingPlan, local_k: int,
        merged_k: Optional[int] = None,
    ):
        import threading

        self.plan = plan
        self._assign = plan.assignment
        self.local_k = int(local_k)
        # width of each per-host leaderboard the cross-host tier ships —
        # the compiled program's k (pod layouts only; None = flat merge)
        self.merged_k = int(merged_k) if merged_k is not None else None
        self._lock = threading.Lock()
        n = plan.n_shards
        self.queries_routed = np.zeros(n, np.int64)  # fan-out: rows/shard
        self.result_wins = np.zeros(n, np.int64)  # top-k slots won
        self.merge_bytes = 0.0  # analytic all-gather payload
        self.merge_seconds = 0.0  # attributed share of device wall
        # two-tier pod merge: the cross-host (H, B, k) leaderboard gather
        # — the DCN term the roofline derivation bounds (0 when H == 1)
        self.pod_merge_bytes = 0.0
        self.pod_merge_seconds = 0.0
        self.pod_dispatches = 0

    def note(
        self, winner_ids: np.ndarray, batch_rows: int,
        device_seconds: float, dispatch_bytes: float,
    ) -> None:
        """Charge one dispatch: winners (B, k) global ids, real rows B."""
        ids = np.asarray(winner_ids).reshape(-1)
        ids = ids[(ids >= 0) & (ids < self._assign.shape[0])]
        # one all-gather of S leaderboards of (B, local_k) slots each —
        # under the pod layout this is the ON-HOST tier's total across
        # host rows (H rows × G·B·local_k slots each = S·B·local_k)
        mb = (
            float(self.plan.n_shards)
            * float(batch_rows)
            * float(self.local_k)
            * MERGE_SLOT_BYTES
        )
        # cross-host tier: H per-host (B, merged_k) leaderboards
        pod_mb = 0.0
        if self.plan.host_groups > 1 and self.merged_k is not None:
            pod_mb = (
                float(self.plan.host_groups)
                * float(batch_rows)
                * float(self.merged_k)
                * MERGE_SLOT_BYTES
            )
        with self._lock:
            if len(ids):
                np.add.at(
                    self.result_wins, self._assign[ids.astype(np.int64)], 1
                )
            self.queries_routed += int(batch_rows)
            self.merge_bytes += mb
            if dispatch_bytes > 0:
                self.merge_seconds += float(device_seconds) * min(
                    1.0, mb / float(dispatch_bytes)
                )
            if pod_mb > 0:
                self.pod_merge_bytes += pod_mb
                self.pod_dispatches += 1
                if dispatch_bytes > 0:
                    self.pod_merge_seconds += float(device_seconds) * min(
                        1.0, pod_mb / float(dispatch_bytes)
                    )

    def snapshot(
        self, busy_fraction: Optional[float],
        resident_bytes_per_shard: list,
    ) -> dict:
        n = self.plan.n_shards
        with self._lock:
            wins = self.result_wins.astype(np.float64)
            routed = self.queries_routed.tolist()
            raw_wins = self.result_wins.tolist()
            merge_bytes = self.merge_bytes
            merge_seconds = self.merge_seconds
            pod_merge_bytes = self.pod_merge_bytes
            pod_merge_seconds = self.pod_merge_seconds
            pod_dispatches = self.pod_dispatches
        total = wins.sum()
        if total > 0:
            share = wins / total
        else:
            # no traffic yet: fall back to the plan's expected shares
            share = np.asarray(self.plan.load_share, np.float64)
        busy = (
            [round(float(busy_fraction) * n * float(s), 6) for s in share]
            if busy_fraction is not None else None
        )
        return {
            "plan": self.plan.describe(),
            "local_k": self.local_k,
            "queries_routed": routed,
            "result_wins": raw_wins,
            "result_share": [round(float(s), 6) for s in share],
            "busy_fraction": busy,
            "resident_bytes": resident_bytes_per_shard,
            "merge_bytes": merge_bytes,
            "merge_seconds": round(merge_seconds, 6),
            "pod_merge_bytes": pod_merge_bytes,
            "pod_merge_seconds": round(pod_merge_seconds, 6),
            "pod_dispatches": pod_dispatches,
        }
