"""Multi-tenant serving: tenant registry, per-tenant QoS, A/B splits.

The reference platform hosts MANY apps behind one event+query surface
(apps, access keys, channels); our fleet served exactly one engine per
deployment until now.  This module is the missing tenancy layer
(ROADMAP open item 3):

* :class:`TenantSpec` — one tenant's contract: access key, traffic
  weight, qps quota, latency SLO, engine variant, and optional weighted
  A/B variant splits.
* :class:`TenantRegistry` — the runtime the query server and fleet
  router consult per request: access-key authentication, fair-share
  admission (per-tenant inflight caps derived from traffic weights ×
  ``PIO_TENANT_BURST``), token-bucket quota shedding (503 +
  ``Retry-After``), a per-tenant circuit breaker (one tenant's failing
  backend fails fast WITHOUT opening any other tenant's breaker — the
  chaos-isolation contract tested via the ``client:tenant:<id>`` fault
  site), per-variant online metrics, and per-tenant pressure signals
  for the autoscaler.
* :func:`pick_variant` — deterministic weighted A/B bucketing: the
  variant is a pure function of ``(tenant, user key)``, so the same
  user lands on the same variant on every replica and across restarts
  (no sticky-session state to lose).

Admission layers UNDER the existing global gates: a request must pass
its tenant's breaker, quota, and inflight share before it contends for
the server-wide ``max_inflight`` slot — one tenant saturating its
quota is shed at its own cap while other tenants' latency is
untouched.

Everything here is stdlib-only (no jax): the router imports it from
the fleet front-end process.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import threading
import time
from typing import Any, Callable, Iterable, Optional

from predictionio_tpu.common.resilience import CircuitBreaker
from predictionio_tpu.utils.profiling import LatencyHistogram

#: variant label used for tenants with no A/B split configured
DEFAULT_VARIANT = "-"


@dataclasses.dataclass(frozen=True)
class VariantSpec:
    """One arm of a tenant's A/B split.  ``engine_variant`` optionally
    routes this arm to a differently-trained engine variant; None serves
    the tenant's (or server's) default deployment."""

    name: str
    weight: float = 1.0
    engine_variant: Optional[str] = None

    def to_dict(self) -> dict:
        out: dict = {"name": self.name, "weight": self.weight}
        if self.engine_variant is not None:
            out["engineVariant"] = self.engine_variant
        return out

    @classmethod
    def from_dict(cls, d: dict) -> "VariantSpec":
        return cls(
            name=str(d["name"]),
            weight=float(d.get("weight", 1.0)),
            engine_variant=d.get("engineVariant"),
        )


@dataclasses.dataclass(frozen=True)
class TenantSpec:
    """One tenant's serving contract (the registry's unit of config)."""

    tenant_id: str
    access_key: str
    weight: float = 1.0
    quota_qps: Optional[float] = None
    slo_ms: Optional[float] = None
    engine_variant: Optional[str] = None
    variants: tuple[VariantSpec, ...] = ()

    def validate(self) -> None:
        if not self.tenant_id:
            raise ValueError("tenant_id must be non-empty")
        if not self.access_key:
            raise ValueError(f"tenant {self.tenant_id}: empty access_key")
        if self.weight <= 0:
            raise ValueError(f"tenant {self.tenant_id}: weight must be > 0")
        if self.quota_qps is not None and self.quota_qps <= 0:
            raise ValueError(
                f"tenant {self.tenant_id}: quota_qps must be > 0 or absent"
            )
        if self.slo_ms is not None and self.slo_ms <= 0:
            raise ValueError(
                f"tenant {self.tenant_id}: slo_ms must be > 0 or absent"
            )
        seen = set()
        for v in self.variants:
            if v.weight <= 0:
                raise ValueError(
                    f"tenant {self.tenant_id}: variant {v.name!r} weight "
                    "must be > 0"
                )
            if v.name in seen:
                raise ValueError(
                    f"tenant {self.tenant_id}: duplicate variant {v.name!r}"
                )
            seen.add(v.name)

    def to_dict(self) -> dict:
        out: dict = {
            "tenantId": self.tenant_id,
            "accessKey": self.access_key,
            "weight": self.weight,
        }
        if self.quota_qps is not None:
            out["quotaQps"] = self.quota_qps
        if self.slo_ms is not None:
            out["sloMs"] = self.slo_ms
        if self.engine_variant is not None:
            out["engineVariant"] = self.engine_variant
        if self.variants:
            out["variants"] = [v.to_dict() for v in self.variants]
        return out

    @classmethod
    def from_dict(cls, d: dict) -> "TenantSpec":
        spec = cls(
            tenant_id=str(d.get("tenantId") or d.get("tenant_id") or ""),
            access_key=str(d.get("accessKey") or d.get("access_key") or ""),
            weight=float(d.get("weight", 1.0)),
            quota_qps=(
                float(d["quotaQps"]) if d.get("quotaQps") is not None else None
            ),
            slo_ms=float(d["sloMs"]) if d.get("sloMs") is not None else None,
            engine_variant=d.get("engineVariant"),
            variants=tuple(
                VariantSpec.from_dict(v) for v in d.get("variants", [])
            ),
        )
        spec.validate()
        return spec


def pick_variant(
    tenant_id: str, user_key: str, variants: Iterable[VariantSpec],
    salt: str = "",
) -> str:
    """Deterministic weighted A/B bucketing.

    The bucket is a pure function of ``(tenant, salt, user key)`` — a
    sha256 digest mapped to [0, 1) and walked down the cumulative
    variant weights — so the same user hits the same variant on every
    replica and across restarts, with no session state.  An empty user
    key still buckets deterministically (all anonymous traffic lands on
    one arm rather than flapping per request).
    """
    arms = list(variants)
    if not arms:
        return DEFAULT_VARIANT
    digest = hashlib.sha256(
        f"{tenant_id}\x1f{salt}\x1f{user_key}".encode()
    ).digest()
    # 8 bytes of digest → uniform fraction in [0, 1)
    frac = int.from_bytes(digest[:8], "big") / float(1 << 64)
    total = sum(v.weight for v in arms)
    acc = 0.0
    for v in arms:
        acc += v.weight / total
        if frac < acc:
            return v.name
    return arms[-1].name


@dataclasses.dataclass(frozen=True)
class Admission:
    """One admit() verdict.  ``reason`` is None when admitted, else one
    of ``quota`` / ``inflight`` / ``breaker``."""

    ok: bool
    reason: Optional[str] = None
    retry_after_s: float = 1.0


class _TenantState:
    """Runtime counters for one tenant (guarded by the registry lock,
    except the per-variant latency histograms which lock themselves)."""

    def __init__(self, spec: TenantSpec, cap: int, burst: float):
        self.spec = spec
        self.cap = cap
        self.inflight = 0
        # token bucket: `burst` seconds of quota banked at full rate
        self.tokens = (
            spec.quota_qps * burst if spec.quota_qps is not None else 0.0
        )
        self.token_cap = self.tokens
        self.last_refill: Optional[float] = None
        self.breaker = CircuitBreaker(
            f"tenant:{spec.tenant_id}", failure_threshold=5,
            reset_timeout_s=5.0,
        )
        self.admitted = 0
        self.shed = {"quota": 0, "inflight": 0, "breaker": 0}
        self.slo_violations = 0
        # variant → online comparison stats (the A/B readout)
        self.variant_stats: dict[str, dict] = {}

    def variant_entry(self, variant: str) -> dict:
        entry = self.variant_stats.get(variant)
        if entry is None:
            entry = {"requests": 0, "errors": 0, "latency": LatencyHistogram()}
            self.variant_stats[variant] = entry
        return entry


class TenantRegistry:
    """Thread-safe tenant runtime: auth, fair-share admission, quotas,
    per-tenant breakers, A/B bucketing, and stats."""

    def __init__(
        self,
        specs: Iterable[TenantSpec],
        total_inflight: int = 256,
        burst: Optional[float] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        specs = list(specs)
        if not specs:
            raise ValueError("a tenant registry needs at least one tenant")
        for s in specs:
            s.validate()
        ids = [s.tenant_id for s in specs]
        if len(set(ids)) != len(ids):
            raise ValueError(f"duplicate tenant ids in {ids}")
        keys = [s.access_key for s in specs]
        if len(set(keys)) != len(keys):
            raise ValueError("tenants must have distinct access keys")
        if burst is None:
            try:
                burst = float(os.environ.get("PIO_TENANT_BURST", 2.0))
            except (TypeError, ValueError):
                burst = 2.0
        self.burst = max(1.0, float(burst))
        self.total_inflight = int(total_inflight)
        self._clock = clock
        self._lock = threading.Lock()
        total_weight = sum(s.weight for s in specs)
        self._tenants: dict[str, _TenantState] = {}
        for s in specs:
            # fair share of the server's admission budget, scaled by the
            # burst factor so an under-subscribed server still lets one
            # tenant use idle capacity — but never the whole gate
            cap = max(
                1,
                min(
                    self.total_inflight,
                    int(round(
                        self.total_inflight * (s.weight / total_weight)
                        * self.burst
                    )),
                ),
            )
            self._tenants[s.tenant_id] = _TenantState(s, cap, self.burst)
        self._by_key = {s.access_key: s.tenant_id for s in specs}

    # -- config introspection ------------------------------------------------
    def specs(self) -> list[TenantSpec]:
        with self._lock:
            return [st.spec for st in self._tenants.values()]

    def engine_variants(self) -> set[str]:
        """Every engine variant the registry can route to (tenant-level
        and A/B-arm-level) — the query server pre-deploys these."""
        out: set[str] = set()
        with self._lock:
            for st in self._tenants.values():
                if st.spec.engine_variant:
                    out.add(st.spec.engine_variant)
                for v in st.spec.variants:
                    if v.engine_variant:
                        out.add(v.engine_variant)
        return out

    # -- auth ----------------------------------------------------------------
    def authenticate(self, access_key: Optional[str]) -> Optional[TenantSpec]:
        if not access_key:
            return None
        with self._lock:
            tid = self._by_key.get(access_key)
            return self._tenants[tid].spec if tid is not None else None

    def get(self, tenant_id: str) -> Optional[TenantSpec]:
        with self._lock:
            st = self._tenants.get(tenant_id)
            return st.spec if st is not None else None

    # -- admission -----------------------------------------------------------
    def _refill_locked(self, st: _TenantState, now: float) -> None:
        qps = st.spec.quota_qps
        if qps is None:
            return
        if st.last_refill is None:
            st.last_refill = now
            return
        st.tokens = min(
            st.token_cap, st.tokens + (now - st.last_refill) * qps
        )
        st.last_refill = now

    def admit(self, tenant_id: str) -> Admission:
        """Fair-share admission: breaker → quota token → inflight share.

        Runs BEFORE the server-wide gate, so one tenant saturating its
        quota sheds at its own cap and never consumes another tenant's
        slots.  Shed answers carry a quota-aware ``Retry-After``.
        """
        now = self._clock()
        with self._lock:
            st = self._tenants.get(tenant_id)
            if st is None:
                return Admission(False, "breaker", 1.0)
            if not st.breaker.allow():
                st.shed["breaker"] += 1
                return Admission(
                    False, "breaker",
                    round(st.breaker.reset_timeout_s, 2),
                )
            self._refill_locked(st, now)
            if st.spec.quota_qps is not None and st.tokens < 1.0:
                st.shed["quota"] += 1
                # when the next token lands — the honest backoff hint
                retry = (1.0 - st.tokens) / st.spec.quota_qps
                return Admission(False, "quota", round(max(retry, 0.05), 2))
            if st.inflight >= st.cap:
                st.shed["inflight"] += 1
                return Admission(
                    False, "inflight",
                    round(max(0.1, st.inflight / (2.0 * st.cap)), 2),
                )
            if st.spec.quota_qps is not None:
                st.tokens -= 1.0
            st.inflight += 1
            st.admitted += 1
            return Admission(True)

    def release(self, tenant_id: str) -> None:
        with self._lock:
            st = self._tenants.get(tenant_id)
            if st is not None and st.inflight > 0:
                st.inflight -= 1

    def record_result(
        self,
        tenant_id: str,
        variant: str,
        ok: bool,
        latency_s: float,
    ) -> None:
        """Close the loop on one admitted request: feed THIS tenant's
        breaker (isolation: no other tenant's breaker sees it), the
        per-variant online comparison, and the SLO ledger."""
        with self._lock:
            st = self._tenants.get(tenant_id)
            if st is None:
                return
            entry = st.variant_entry(variant or DEFAULT_VARIANT)
            entry["requests"] += 1
            if ok:
                st.breaker.record_success()
                entry["latency"].observe(latency_s)
                if (
                    st.spec.slo_ms is not None
                    and latency_s * 1e3 > st.spec.slo_ms
                ):
                    st.slo_violations += 1
            else:
                entry["errors"] += 1
                st.breaker.record_failure()

    # -- A/B -----------------------------------------------------------------
    def pick_variant(self, tenant_id: str, user_key: Any) -> str:
        spec = self.get(tenant_id)
        if spec is None or not spec.variants:
            return DEFAULT_VARIANT
        return pick_variant(
            tenant_id, str(user_key if user_key is not None else ""),
            spec.variants,
        )

    def variant_spec(self, tenant_id: str, variant: str) -> Optional[VariantSpec]:
        spec = self.get(tenant_id)
        if spec is None:
            return None
        for v in spec.variants:
            if v.name == variant:
                return v
        return None

    # -- signals -------------------------------------------------------------
    def pressure(self) -> dict[str, float]:
        """Per-tenant pressure in [0, 1] for the autoscaler: inflight
        saturation against the fair-share cap (quota sheds are a
        contract, not pressure — a quota-shed tenant must NOT scale the
        fleet up)."""
        with self._lock:
            return {
                tid: round(min(1.0, st.inflight / float(st.cap)), 4)
                for tid, st in self._tenants.items()
            }

    def stats(self) -> dict:
        """One consistent snapshot for ``/metrics`` bridges and CLI."""
        with self._lock:
            out: dict = {}
            for tid, st in self._tenants.items():
                variants = {}
                for name, entry in st.variant_stats.items():
                    lat: LatencyHistogram = entry["latency"]
                    variants[name] = {
                        "requests": entry["requests"],
                        "errors": entry["errors"],
                        "p50_ms": round(lat.quantile(0.50), 3),
                        "p99_ms": round(lat.quantile(0.99), 3),
                    }
                out[tid] = {
                    "weight": st.spec.weight,
                    "cap": st.cap,
                    "inflight": st.inflight,
                    "quota_qps": st.spec.quota_qps,
                    "tokens": round(st.tokens, 2),
                    "slo_ms": st.spec.slo_ms,
                    "slo_violations": st.slo_violations,
                    "admitted": st.admitted,
                    "shed": dict(st.shed),
                    "breaker": st.breaker.state,
                    "variants": variants,
                }
            return out


def extract_access_key(
    params: Optional[dict] = None,
    headers: Any = None,
    data: Optional[dict] = None,
) -> Optional[str]:
    """The access key for one request: query param first (the event
    server's idiom), then the request body's ``accessKey`` field (what
    the loadtest/scenario drivers rotate per tenant).  Body keys are
    auth metadata, not query semantics — the result-cache fingerprint
    excludes them and namespaces by tenant instead."""
    if params:
        key = params.get("accessKey")
        if key:
            return key
    if headers is not None:
        try:
            key = headers.get("X-PIO-Access-Key")
        except AttributeError:
            key = None
        if key:
            return key
    if isinstance(data, dict):
        key = data.get("accessKey")
        if isinstance(key, str) and key:
            return key
    return None


def registry_from_config(
    config: Any, total_inflight: int = 256
) -> TenantRegistry:
    """Build a registry from parsed JSON config: either a bare list of
    tenant dicts or ``{"tenants": [...]}``."""
    if isinstance(config, dict):
        config = config.get("tenants", [])
    if not isinstance(config, list):
        raise ValueError(
            "tenant config must be a list of tenants or "
            '{"tenants": [...]}'
        )
    return TenantRegistry(
        [TenantSpec.from_dict(d) for d in config],
        total_inflight=total_inflight,
    )


def tenants_from_env(total_inflight: int = 256) -> Optional[TenantRegistry]:
    """Build the tenant registry from ``PIO_TENANTS``: a path to a JSON
    config file, or (for tests/dev) the JSON itself inline.  None when
    unset — single-tenant open access, byte-identical to the
    pre-tenancy server."""
    raw = os.environ.get("PIO_TENANTS", "")
    raw = raw.strip()
    if not raw:
        return None
    if raw.startswith("{") or raw.startswith("["):
        config = json.loads(raw)
    else:
        with open(raw, "r", encoding="utf-8") as f:
            config = json.load(f)
    return registry_from_config(config, total_inflight=total_inflight)
