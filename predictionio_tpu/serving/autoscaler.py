"""Signal-driven fleet autoscaler: elastic replica counts under SLO.

The fleet of ISSUE 10 has a fixed N; real traffic is diurnal, spiky,
and adversarial (ROADMAP item 4; Cloudburst's serverless prediction-
serving result is the reference pattern).  This control loop sizes the
supervised replica set from the ROUTER's own signals — no external
metrics plane:

* **inflight utilization** — total in-flight forwards over the admitted
  fleet's concurrency capacity (``PIO_FLEET_REPLICA_MAX_INFLIGHT`` ×
  admitted replicas);
* **shed rate** — 503s per routed request since the last tick (the
  router only sheds when admission is exhausted);
* **hedge rate** — hedges fired per request (tail latency pain the
  breakers and health gate cannot see);
* **device busy fraction** — the max ``pio_device_busy_fraction``
  scraped from each admitted replica's ``/metrics`` (the ISSUE 8
  accountant), so a compute-bound fleet scales before it sheds.

Each signal normalizes to [0, 1]; the composite **pressure** is their
max.  Decisions carry hysteresis (separate up/down thresholds), a
consecutive-low-tick requirement plus cooldowns against flapping, and
hard min/max bounds.  Scale-up spawns one replica through the
supervisor and registers it EJECTED at the router, so admission rides
the existing health gate + 10%→100% slow start — a cold process never
absorbs a full traffic share.  Scale-down reuses the roll machinery's
drain-before-kill: router DRAINING → ``POST /stop`` → reap.

The loop itself (``_control_loop``) paces on the stop Event and
delegates all I/O to ``_safe_tick`` — the blocking-call analyzer
(``analysis/blocking.py``) checks it alongside ``_health_loop`` and
``_monitor_loop``.  ``tick(now=...)`` is the deterministic core: tests
drive it with a simulated clock and stubbed signals, no threads.
"""

from __future__ import annotations

import logging
import os
import threading
import time
import urllib.request
from typing import Optional

logger = logging.getLogger(__name__)


def _env_num(name: str, default, cast):
    try:
        return cast(os.environ[name])
    except (KeyError, ValueError, TypeError):
        return default


class Autoscaler:
    """Router-signal control loop sizing a :class:`FleetSupervisor`."""

    def __init__(self, router, fleet):
        self.router = router
        self.fleet = fleet
        # knobs (documented in docs/operations.md — the knobs analyzer
        # diffs the defaults)
        self.interval_ms = _env_num("PIO_AUTOSCALE_INTERVAL_MS", 1000.0, float)
        self.min_replicas = _env_num("PIO_AUTOSCALE_MIN_REPLICAS", 1, int)
        self.max_replicas = _env_num("PIO_AUTOSCALE_MAX_REPLICAS", 8, int)
        self.up_threshold = _env_num("PIO_AUTOSCALE_UP_THRESHOLD", 0.7, float)
        self.down_threshold = _env_num(
            "PIO_AUTOSCALE_DOWN_THRESHOLD", 0.25, float
        )
        self.up_cooldown_s = _env_num("PIO_AUTOSCALE_UP_COOLDOWN_S", 5.0, float)
        self.down_cooldown_s = _env_num(
            "PIO_AUTOSCALE_DOWN_COOLDOWN_S", 30.0, float
        )
        self.down_after = _env_num("PIO_AUTOSCALE_DOWN_AFTER", 5, int)
        self.shed_ref = _env_num("PIO_AUTOSCALE_SHED_REF", 0.05, float)
        self.hedge_ref = _env_num("PIO_AUTOSCALE_HEDGE_REF", 0.5, float)
        self.busy_enabled = _env_num("PIO_AUTOSCALE_BUSY", 1, int) != 0
        self.scrape_timeout_s = 1.0

        self._lock = threading.Lock()
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._prev_counters: Optional[dict] = None
        self._no_up_before = 0.0
        self._no_down_before = 0.0
        self._low_streak = 0
        self._ups = 0
        self._downs = 0
        self._last_pressure = 0.0
        self._last_signals: dict = {}
        self._last_decision = "hold"
        self._last_n = 0

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._control_loop, name="autoscaler", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop_evt.set()

    def _control_loop(self):
        interval_s = self.interval_ms / 1e3
        while not self._stop_evt.wait(interval_s):
            self._safe_tick()

    def _safe_tick(self) -> None:
        try:
            self.tick()
        except Exception:
            logger.exception("autoscaler tick failed")

    # -- signals -------------------------------------------------------------
    def _busy_fraction(self, urls: list[str]) -> float:
        """Max ``pio_device_busy_fraction`` across admitted replicas.
        A replica without telemetry (404) or mid-restart contributes 0 —
        pressure from missing data must never spawn processes."""
        from predictionio_tpu.obs.metrics import parse_prometheus

        best = 0.0
        for url in urls:
            try:
                with urllib.request.urlopen(
                    url + "/metrics", timeout=self.scrape_timeout_s
                ) as r:
                    series = parse_prometheus(
                        r.read().decode("utf-8", "replace")
                    )
            except Exception:
                continue
            for (name, _labels), v in series.items():
                if name == "pio_device_busy_fraction":
                    best = max(best, float(v))
        return best

    def _signals(self) -> dict:
        """Normalized [0, 1] pressure per signal since the last tick."""
        sig = self.router.signals()
        snap = sig["counters"]
        with self._lock:
            prev = (
                self._prev_counters
                if self._prev_counters is not None else snap
            )
            self._prev_counters = snap
        req_delta = sum(
            snap.get(k, 0) - prev.get(k, 0)
            for k in ("ok", "client_error", "failed", "shed", "deadline")
        )
        shed_rate = (
            (snap.get("shed", 0) - prev.get("shed", 0))
            / max(1.0, float(req_delta))
        )
        hedge_rate = (
            (snap.get("hedges_fired", 0) - prev.get("hedges_fired", 0))
            / max(1.0, float(req_delta))
        )
        admitted = max(1, sig["admitted"])
        capacity = float(max(1, sig["replicaMaxInflight"]) * admitted)
        busy = (
            self._busy_fraction(sig["admittedUrls"])
            if self.busy_enabled
            else 0.0
        )
        signals = {
            "inflight": round(min(1.0, sig["inflight"] / capacity), 4),
            "shed": round(
                min(1.0, shed_rate / self.shed_ref)
                if self.shed_ref > 0 else 0.0, 4,
            ),
            "hedge": round(
                min(1.0, hedge_rate / self.hedge_ref)
                if self.hedge_ref > 0 else 0.0, 4,
            ),
            "busy": round(min(1.0, busy), 4),
        }
        if "tenantPressure" in sig:
            # hottest tenant's inflight saturation against its fair-share
            # cap (multi-tenant router).  Quota sheds are deliberately NOT
            # in this signal: a tenant over its paid quota must be shed,
            # not have the fleet scaled up for it.
            signals["tenant"] = round(
                min(1.0, float(sig["tenantPressure"])), 4
            )
        return {
            "rolling": bool(sig.get("rolling")),
            "signals": signals,
        }

    # -- the control decision ------------------------------------------------
    def tick(self, now: Optional[float] = None) -> str:
        """One control decision: gather signals, compare against the
        hysteresis band, act through the supervisor.  Deterministic given
        ``now`` and the router/fleet state — the unit tests drive this
        directly with a simulated clock."""
        now = time.monotonic() if now is None else now
        view = self._signals()
        signals = view["signals"]
        pressure = max(signals.values())
        n = len(self.fleet.status()["replicas"])
        action = "hold"
        with self._lock:
            if view["rolling"]:
                # never fight a roll: its drains look exactly like load
                # that should scale, and its restarts must not race a
                # scale-down
                pass
            elif n < self.min_replicas:
                action = "up"
            elif (
                pressure >= self.up_threshold
                and n < self.max_replicas
                and now >= self._no_up_before
            ):
                action = "up"
            elif pressure <= self.down_threshold and n > self.min_replicas:
                self._low_streak += 1
                if self._low_streak >= self.down_after \
                        and now >= self._no_down_before:
                    action = "down"
            else:
                self._low_streak = 0
        # the fleet calls spawn/drain processes — keep them outside the
        # lock so stats() readers never block on a slow drain
        decision = "hold"
        if action == "up":
            decision = self._scale_up(now)
        elif action == "down":
            decision = self._scale_down(now)
        with self._lock:
            self._last_pressure = round(pressure, 4)
            self._last_signals = signals
            self._last_decision = decision
            self._last_n = len(self.fleet.status()["replicas"])
        return decision

    def _scale_up(self, now: float) -> str:
        added = self.fleet.add_replica()
        if added is None:
            return "hold"
        with self._lock:
            self._ups += 1
            self._low_streak = 0
            self._no_up_before = now + self.up_cooldown_s
            # a fresh replica is cold: suppress scale-down until it has
            # had a chance to absorb its share, or flapping traffic
            # thrashes spawns
            self._no_down_before = max(
                self._no_down_before, now + self.down_cooldown_s
            )
        logger.info("autoscaler: scaled up (+%s)", added.get("url"))
        return "up"

    def _scale_down(self, now: float) -> str:
        removed = self.fleet.remove_replica()
        if removed is None:
            return "hold"
        with self._lock:
            self._downs += 1
            self._low_streak = 0
            self._no_down_before = now + self.down_cooldown_s
        logger.info("autoscaler: scaled down (-%s)", removed.get("url"))
        return "down"

    # -- observability -------------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            return {
                "enabled": True,
                "minReplicas": self.min_replicas,
                "maxReplicas": self.max_replicas,
                "replicas": self._last_n,
                "pressure": self._last_pressure,
                "signals": dict(self._last_signals),
                "lastDecision": self._last_decision,
                "scaleUps": self._ups,
                "scaleDowns": self._downs,
                "lowStreak": self._low_streak,
                "upThreshold": self.up_threshold,
                "downThreshold": self.down_threshold,
            }
