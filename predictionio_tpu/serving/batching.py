"""Request micro-batching: coalesce concurrent queries into one device pass.

TPU serving throughput comes from batching: a single (B, rank)×(rank, items)
scoring pass costs barely more than B=1, and on remote-tunnel backends each
device round trip has a fixed latency floor.  The reference has no analogue
(its predict path is per-request JVM work, ``CreateServer.scala:508``).

:class:`MicroBatcher` sits between HTTP handler threads and the engine:
handlers enqueue (query, event) pairs and block; a worker drains the queue,
coalesces a batch, routes it through ``Algorithm.batch_predict`` (which
engines like ALS vectorize on device), and wakes each handler with its
result.  Errors are delivered per-request.

The accumulation window is ADAPTIVE, not a fixed sleep:

* TRICKLE BYPASS: a request arriving to an empty queue with no run in
  flight executes inline on its own handler thread — zero added latency
  over the unbatched path.  Batches form exactly when they can help:
  while a run is in flight, arrivals queue up and dispatch together.
* A request is only worth delaying by about the cost of one extra device
  pass, so the wait budget is ``min(window_ms, EWMA(batch run time))`` —
  on a fast local backend the window collapses toward zero, on a
  remote-tunnel backend (ms-scale round trips) it opens up to the cap.
* Within the budget the worker stops as soon as the arrival stream goes
  quiet: it waits for the next item at most ``EWMA(inter-arrival gap) ×
  GAP_MULT`` past the last arrival (burst over ⇒ dispatch now).
* Dispatch drains to a BUCKET BOUNDARY of the compile-cache ladder
  (``serving/fastpath.py``): a 9-deep queue dispatches 8 + carries 1
  instead of padding 9→16, so device occupancy stays ≥ 50% by
  construction and the carried tail leads the next batch (FIFO).

SINGLE-FLIGHT COALESCING (opt-in via ``submit(key=...)``): identical
in-flight queries — same canonical fingerprint — attach to ONE pending
slot.  The first arrival is the leader and occupies a device row; later
identical arrivals become followers and never enter the queue at all.
When the leader's batch delivers, the one result fans out to every
follower (errors too: a failed batch fails all attached waiters, none
hang).  Under Zipf traffic a hot key therefore costs one device slot per
batch regardless of popularity.  If the leader's deadline lapses before
dispatch, a live follower is PROMOTED to leader so the survivors don't
inherit a 504 they didn't earn.
"""

from __future__ import annotations

import collections
import logging
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from predictionio_tpu.common.resilience import Deadline, DeadlineExceeded
from predictionio_tpu.obs import tracing as _tracing

logger = logging.getLogger(__name__)

# default ladder mirrors serving/fastpath.BUCKETS without importing jax here
_DEFAULT_BUCKETS = (1, 8, 16, 32, 64)


@dataclass
class _Pending:
    query: Any
    deadline: Optional[Deadline] = None
    event: threading.Event = field(default_factory=threading.Event)
    result: Any = None
    error: Optional[BaseException] = None
    # obs trace riding this query (captured from the submitting thread's
    # active scope) + enqueue stamp for the queue_wait stage
    trace: Any = None
    t_enq: float = 0.0
    # single-flight: the coalescing key this pending leads (None = not
    # coalescable) and the identical-query followers its result fans out to
    key: Any = None
    followers: list = field(default_factory=list)


class MicroBatcher:
    # dispatch when the stream has been quiet for GAP_MULT × the EWMA
    # inter-arrival gap (the burst is over; waiting longer is pure latency)
    GAP_MULT = 2.0
    # EWMA smoothing for both the gap and run-time estimators
    ALPHA = 0.2

    def __init__(
        self,
        run_batch: Callable[[list], list],
        max_batch: int = 64,
        window_ms: float = 2.0,
        buckets=_DEFAULT_BUCKETS,
    ):
        self._run_batch = run_batch
        self.max_batch = max_batch
        self.window_s = window_ms / 1e3
        self.buckets = tuple(
            sorted({b for b in buckets if b <= max_batch} | {max_batch})
        )
        self._queue: "queue.Queue[_Pending]" = queue.Queue()
        self._carry: collections.deque[_Pending] = collections.deque()
        self._stop = threading.Event()
        # arrival-side estimator state
        self._arr_lock = threading.Lock()
        self._last_arrival: Optional[float] = None
        self._ewma_gap = self.window_s  # pessimistic until traffic teaches it
        # worth-waiting budget: ~one batch run; 0 until the first run returns
        self._ewma_run = 0.0
        # held for the duration of every batch run (worker or inline)
        self._busy = threading.Lock()
        # single-flight: key → leader pending currently in flight.  The
        # lock guards the map AND every leader's followers list; delivery
        # pops the key first, so a follower can never attach to a pending
        # whose result already fanned out.
        self._key_lock = threading.Lock()
        self._inflight_keys: dict[Any, _Pending] = {}
        # counters (read by stats())
        self._stats_lock = threading.Lock()
        self._n_batches = 0
        self._n_queries = 0
        self._n_inline = 0
        self._n_coalesced = 0  # followers served by a leader's device slot
        self._n_expired = 0  # pendings dropped un-executed (deadline lapsed)
        self._size_hist: collections.Counter = collections.Counter()
        self._wait_s_total = 0.0
        self._worker = threading.Thread(
            target=self._loop, name="query-microbatcher", daemon=True
        )
        self._worker.start()

    def submit(
        self,
        query: Any,
        timeout: float = 30.0,
        deadline: Optional[Deadline] = None,
        key: Any = None,
    ) -> Any:
        """Enqueue one query; block until its batch runs or the deadline
        passes.

        The effective deadline is ``min(request deadline, now + timeout)``
        and travels WITH the pending: a request whose deadline lapses while
        queued is dropped at dispatch (never executed on device — the
        waiter already gave up, running it would burn a device pass on an
        answer nobody reads) and its waiter gets :class:`DeadlineExceeded`.

        ``key`` opts this query into single-flight coalescing: when an
        identical key is already in flight, this call attaches to the
        leader's pending and shares its result instead of occupying a
        device row of its own.  The key the server passes is the
        tenant-NAMESPACED canonical fingerprint (tenant + variant +
        engine instance prefix — ``result_cache.canonical_fingerprint``):
        two tenants sending byte-identical bodies must never share a
        leader slot, or one tenant's answer leaks to the other.
        """
        now = time.perf_counter()
        with self._arr_lock:
            if self._last_arrival is not None:
                # clamp: an idle night must not blow the estimator past any
                # useful scale — one window of silence already means "quiet"
                gap = min(now - self._last_arrival, self.window_s)
                self._ewma_gap += self.ALPHA * (gap - self._ewma_gap)
            self._last_arrival = now
        eff = Deadline.min(deadline, Deadline.after_ms(timeout * 1e3))
        active = _tracing.active_traces()
        p = _Pending(
            query, deadline=eff,
            trace=active[0] if active else None, t_enq=now, key=key,
        )
        if eff.expired():
            # already over budget at arrival: shed before any queue/device
            # work (the admission layer normally catches this first)
            with self._stats_lock:
                self._n_expired += 1
            raise DeadlineExceeded("query deadline expired before dispatch")
        if key is not None:
            with self._key_lock:
                leader = self._inflight_keys.get(key)
                if leader is not None:
                    # FOLLOWER: ride the leader's device slot; its delivery
                    # fans the one result (or error) out to us
                    leader.followers.append(p)
                else:
                    self._inflight_keys[key] = p
            if leader is not None:
                with self._stats_lock:
                    self._n_coalesced += 1
                if p.trace is not None:
                    # flight-recorder context: this request rode another
                    # identical query's device slot — its trace must NOT
                    # carry device stages (charged once, to the leader)
                    p.trace.annotate(coalesce="follower")
                if not p.event.wait(eff.remaining_s()):
                    # the leader's batch will still resolve this pending
                    # (harmlessly, after we've gone) — nothing dangles
                    raise DeadlineExceeded("coalesced query timed out")
                if p.error is not None:
                    raise p.error
                return p.result
        # TRICKLE BYPASS: nothing queued and no run in flight — execute the
        # singleton inline on this handler thread.  A lone request then pays
        # exactly the direct-path cost (no worker hop, no window), while
        # coalescing still happens whenever a run IS in flight: arrivals
        # pile into the queue and the worker drains them as one batch.
        if (
            self._queue.empty()
            and not self._carry
            and self._busy.acquire(blocking=False)
        ):
            try:
                self._execute([p], waited=0.0, inline=True)
            finally:
                self._busy.release()
            if p.error is not None:
                raise p.error
            return p.result
        self._queue.put(p)
        if not p.event.wait(eff.remaining_s()):
            # the pending stays queued, but its deadline has passed — the
            # worker is GUARANTEED to drop it at dispatch (same monotonic
            # clock), so the device never runs an abandoned query
            raise DeadlineExceeded("batched query timed out")
        if p.error is not None:
            raise p.error
        return p.result

    def stop(self) -> None:
        self._stop.set()
        self._worker.join(timeout=5)
        # wake anything still queued so handlers fail fast, not on timeout
        pending = list(self._carry)
        self._carry.clear()
        while True:
            try:
                pending.append(self._queue.get_nowait())
            except queue.Empty:
                break
        err = RuntimeError("server shutting down")
        for p in pending:
            self._resolve(p, error=err)

    def depth(self) -> int:
        """Queued + carried pendings (admission-control signal)."""
        return self._queue.qsize() + len(self._carry)

    def stats(self) -> dict:
        """Per-batch latency/size/occupancy counters (``GET /`` stats)."""
        with self._stats_lock:
            n_b, n_q = self._n_batches, self._n_queries
            return {
                "batches": n_b,
                "queries": n_q,
                "inline_batches": self._n_inline,
                "coalesced": self._n_coalesced,
                "expired_dropped": self._n_expired,
                "depth": self.depth(),
                "avg_batch": round(n_q / n_b, 3) if n_b else None,
                "batch_sizes": {str(k): v for k, v in sorted(self._size_hist.items())},
                "avg_window_wait_ms": round(self._wait_s_total / n_b * 1e3, 4)
                if n_b
                else None,
                "ewma_gap_ms": round(self._ewma_gap * 1e3, 4),
                "ewma_run_ms": round(self._ewma_run * 1e3, 4),
            }

    # -- worker -------------------------------------------------------------
    def _next(self, timeout: Optional[float]) -> Optional[_Pending]:
        """Carried tail first (FIFO), then the live queue."""
        if self._carry:
            return self._carry.popleft()
        try:
            if timeout is None or timeout <= 0:
                return self._queue.get_nowait()
            return self._queue.get(timeout=timeout)
        except queue.Empty:
            return None

    def _boundary(self, n: int) -> int:
        """Largest ladder rung ≤ n (ladder always contains 1)."""
        best = self.buckets[0]
        for b in self.buckets:
            if b <= n:
                best = b
        return best

    def _loop(self) -> None:
        while not self._stop.is_set():
            first = self._next(timeout=0.1)
            if first is None:
                continue
            t_first = time.perf_counter()
            last_arrival = t_first
            batch = [first]
            # budget: delaying a request more than one device pass costs
            # more latency than the coalescing saves
            budget = min(self.window_s, self._ewma_run)
            deadline = t_first + budget
            while len(batch) < self.max_batch:
                now = time.perf_counter()
                # stop early once the arrival stream has gone quiet
                quiet_cut = last_arrival + self._ewma_gap * self.GAP_MULT
                wait = min(deadline, quiet_cut) - now
                if wait <= 0:
                    break
                nxt = self._next(timeout=wait)
                if nxt is None:
                    break
                batch.append(nxt)
                last_arrival = time.perf_counter()
            # serialize with any inline run, THEN drain: everything that
            # arrived while the previous run was in flight coalesces here
            with self._busy:
                while len(batch) < self.max_batch:
                    nxt = self._next(timeout=None)
                    if nxt is None:
                        break
                    batch.append(nxt)
                # cut to a compile-cache bucket boundary; the tail leads
                # the next batch instead of padding this one
                size = self._boundary(len(batch))
                self._carry.extendleft(reversed(batch[size:]))
                batch = batch[:size]
                waited = time.perf_counter() - t_first
                self._execute(batch, waited)

    def _resolve(
        self,
        p: _Pending,
        result: Any = None,
        error: Optional[BaseException] = None,
    ) -> None:
        """Deliver one outcome to a pending AND its coalesced followers.

        The key is detached from the in-flight map FIRST (under the key
        lock), so no new follower can attach to a pending whose result has
        already fanned out — late identical arrivals become fresh leaders.
        A shared error fails every attached waiter; nobody hangs.
        """
        followers: list[_Pending] = []
        if p.key is not None:
            with self._key_lock:
                if self._inflight_keys.get(p.key) is p:
                    del self._inflight_keys[p.key]
                followers, p.followers = p.followers, []
        for waiter in [p, *followers]:
            waiter.result = result
            waiter.error = error
            waiter.event.set()

    def _expire_leader(self, p: _Pending) -> Optional[_Pending]:
        """An expired coalescing leader's followers must not inherit its
        504: promote the first still-live follower to leader (it takes the
        batch slot and the remaining followers) and return it; expired
        followers fail with the leader.  None when nobody survives."""
        with self._key_lock:
            owns_key = self._inflight_keys.get(p.key) is p
            followers, p.followers = p.followers, []
            promoted = None
            for i, f in enumerate(followers):
                if f.deadline is None or not f.deadline.expired():
                    promoted = f
                    promoted.followers = followers[i + 1:]
                    dead = followers[:i]
                    break
            else:
                dead = followers
            if owns_key:
                if promoted is not None:
                    self._inflight_keys[p.key] = promoted
                else:
                    del self._inflight_keys[p.key]
        if promoted is not None and promoted.trace is not None:
            # flight-recorder: this request entered as a follower and took
            # over an abandoned leader's batch slot — `coalesce` flips to
            # "leader" at dispatch, `promoted` records why (the routing
            # tier hedges leaders away; the invariant test pins that the
            # device is still charged exactly once, to the promoted trace)
            promoted.trace.annotate(promoted=True)
        err = DeadlineExceeded("query deadline expired in queue")
        for waiter in [p, *dead]:
            waiter.result = None
            waiter.error = err
            waiter.event.set()
        with self._stats_lock:
            self._n_expired += 1 + len(dead)
        return promoted

    def _execute(self, batch: list, waited: float, inline: bool = False) -> None:
        """Run one batch and deliver results/errors to every waiter.

        Expired pendings are dropped HERE, at dispatch: their waiters have
        already raised (or are about to), so executing them would spend a
        device pass on a result nobody will read.
        """
        live, expired = [], []
        for p in batch:
            if p.deadline is not None and p.deadline.expired():
                expired.append(p)
            else:
                live.append(p)
        for p in expired:
            if p.key is not None:
                promoted = self._expire_leader(p)
                if promoted is not None:
                    live.append(promoted)
            else:
                p.error = DeadlineExceeded("query deadline expired in queue")
                p.event.set()
                with self._stats_lock:
                    self._n_expired += 1
        batch = live
        if not batch:
            return
        t_run = time.perf_counter()
        traces = [p.trace for p in batch if p.trace is not None]
        for p in batch:
            if p.trace is not None:
                # time between enqueue and dispatch: the coalescing window
                # the request paid for (≈0 on the inline bypass)
                p.trace.add_stage("queue_wait", t_run - p.t_enq)
                # flight-recorder context: how this request's batch formed
                p.trace.annotate(
                    batch=len(batch),
                    dispatch="inline" if inline else "window",
                    **({"coalesce": "leader"} if p.key is not None else {}),
                )
        results: Optional[list] = None
        run_error: Optional[BaseException] = None
        try:
            # the worker thread runs ONE batch for many requests: install
            # every member's trace so shared stages (assembly, h2d, device
            # compute) are charged to each of them
            with _tracing.scope(traces):
                results = self._run_batch([p.query for p in batch])
            if len(results) != len(batch):
                raise RuntimeError(
                    f"batch_predict returned {len(results)} results for "
                    f"{len(batch)} queries"
                )
        except BaseException as e:  # propagate to EVERY waiter
            run_error = e
        run_dt = time.perf_counter() - t_run
        # both the worker thread and the trickle bypass land here; the
        # estimator shares _arr_lock with the gap EWMA
        with self._arr_lock:
            self._ewma_run += self.ALPHA * (run_dt - self._ewma_run)
        for i, p in enumerate(batch):
            if run_error is not None:
                self._resolve(p, error=run_error)
            else:
                self._resolve(p, result=results[i])
        with self._stats_lock:
            self._n_batches += 1
            self._n_queries += len(batch)
            self._size_hist[len(batch)] += 1
            self._wait_s_total += waited
            if inline:
                self._n_inline += 1
