"""Request micro-batching: coalesce concurrent queries into one device pass.

TPU serving throughput comes from batching: a single (B, rank)×(rank, items)
scoring pass costs barely more than B=1, and on remote-tunnel backends each
device round trip has a fixed latency floor.  The reference has no analogue
(its predict path is per-request JVM work, ``CreateServer.scala:508``).

:class:`MicroBatcher` sits between HTTP handler threads and the engine:
handlers enqueue (query, event) pairs and block; a worker drains the queue,
waits up to ``window_ms`` to let a batch form (bounded by ``max_batch``),
routes the whole batch through ``Algorithm.batch_predict`` (which engines
like ALS vectorize on device), and wakes each handler with its result.
Errors are delivered per-request.
"""

from __future__ import annotations

import logging
import queue
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

logger = logging.getLogger(__name__)


@dataclass
class _Pending:
    query: Any
    event: threading.Event = field(default_factory=threading.Event)
    result: Any = None
    error: Optional[BaseException] = None


class MicroBatcher:
    def __init__(
        self,
        run_batch: Callable[[list], list],
        max_batch: int = 64,
        window_ms: float = 2.0,
    ):
        self._run_batch = run_batch
        self.max_batch = max_batch
        self.window_s = window_ms / 1e3
        self._queue: "queue.Queue[_Pending]" = queue.Queue()
        self._stop = threading.Event()
        self._worker = threading.Thread(
            target=self._loop, name="query-microbatcher", daemon=True
        )
        self._worker.start()

    def submit(self, query: Any, timeout: float = 30.0) -> Any:
        p = _Pending(query)
        self._queue.put(p)
        if not p.event.wait(timeout):
            raise TimeoutError("batched query timed out")
        if p.error is not None:
            raise p.error
        return p.result

    def stop(self) -> None:
        self._stop.set()
        self._worker.join(timeout=5)
        # wake anything still queued so handlers fail fast, not on timeout
        while True:
            try:
                p = self._queue.get_nowait()
            except queue.Empty:
                break
            p.error = RuntimeError("server shutting down")
            p.event.set()

    # -- worker -------------------------------------------------------------
    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                first = self._queue.get(timeout=0.1)
            except queue.Empty:
                continue
            batch = [first]
            # brief accumulation window lets concurrent requests coalesce;
            # skipped when a full batch is already waiting
            if self._queue.qsize() < self.max_batch - 1:
                self._stop.wait(self.window_s)
            while len(batch) < self.max_batch:
                try:
                    batch.append(self._queue.get_nowait())
                except queue.Empty:
                    break
            try:
                results = self._run_batch([p.query for p in batch])
                if len(results) != len(batch):
                    raise RuntimeError(
                        f"batch_predict returned {len(results)} results for "
                        f"{len(batch)} queries"
                    )
                for p, r in zip(batch, results):
                    p.result = r
            except BaseException as e:  # propagate to EVERY waiter
                for p in batch:
                    p.error = e
            for p in batch:
                p.event.set()
