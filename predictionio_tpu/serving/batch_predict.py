"""Batch predict: bulk queries from a file, predictions to a file.

Parity: ``core/.../workflow/BatchPredict.scala:120-235`` — one JSON query per
input line; each line is parsed → supplemented → predicted per algorithm →
served → rendered as one JSON line.  Where the reference maps over a query
RDD on Spark executors, this streams through the in-process engine (the
per-query predict itself runs on-device for sharded models).

Multi-host (``pio launch -- batchpredict``): the reference's RDD map IS
distributed, and so is this — each process takes the input lines with
``line_index % N == process_index`` and writes ``<output>.part-<i>``
(Spark ``saveAsTextFile`` part-file semantics); single-host writes
``<output>`` directly. Every process deploys the same COMPLETED instance,
so results are identical to a single-host run, just split N ways.
"""

from __future__ import annotations

import json
import logging
from typing import Any, Optional

from predictionio_tpu.core.engine import Engine
from predictionio_tpu.core.workflow import (
    get_latest_completed_instance,
    prepare_deploy,
)
from predictionio_tpu.data.storage.registry import Storage
from predictionio_tpu.parallel.mesh import MeshContext
from predictionio_tpu.serving.query_server import _to_jsonable, bind_query

logger = logging.getLogger(__name__)

# Queries per engine pass: matches the serving fast path's top bucket rung
# (serving/fastpath.BUCKETS[-1]) so bulk prediction rides the same
# pre-compiled batched program the query server uses.
_CHUNK_QUERIES = 64


def run_batch_predict(
    engine: Engine,
    input_path: str,
    output_path: str,
    storage: Optional[Storage] = None,
    ctx: Optional[MeshContext] = None,
    engine_id: str = "default",
    engine_version: str = "default",
    engine_variant: str = "default",
) -> tuple[int, str]:
    """Returns (predictions written by THIS process, the path it wrote)."""
    from predictionio_tpu.parallel import distributed

    storage = storage or Storage.instance()
    ctx = ctx or MeshContext.create()
    pid, n_procs = distributed.process_slot()
    # the FALLIBLE deploy runs before output hygiene: a failed run must
    # leave the previous outputs untouched
    instance = get_latest_completed_instance(
        storage, engine_id, engine_version, engine_variant
    )
    _, algorithms, serving, models = prepare_deploy(
        engine, instance, storage=storage, ctx=ctx
    )
    # part-file path + stale-output hygiene: the shared distributed-writer
    # contract (a re-run with different N can never mix runs)
    _, _, output_path = distributed.shard_output_path(output_path)
    if n_procs > 1:
        logger.info(
            "batch predict p%d/%d: lines %%%d == %d -> %s",
            pid, n_procs, n_procs, pid, output_path,
        )
    n = 0
    with open(input_path) as fin, open(output_path, "w") as fout:
        # Queries are CHUNKED through Algorithm.batch_predict — the same
        # vectorized one-device-pass entry point the query server's
        # micro-batcher uses — instead of one predict per line.  Output
        # order stays line order: a chunk flushes before any out-of-band
        # (parse-error) line is written.
        chunk: list[tuple[int, Any, Any]] = []  # (line_no, data, query)

        def write_ok(data, result) -> None:
            nonlocal n
            fout.write(
                json.dumps({"query": data, "prediction": _to_jsonable(result)})
                + "\n"
            )
            n += 1

        def flush() -> None:
            if not chunk:
                return
            try:
                supplemented = [
                    (i, serving.supplement(q))
                    for i, (_, _, q) in enumerate(chunk)
                ]
                per_algo = [
                    dict(a.batch_predict(m, supplemented))
                    for a, m in zip(algorithms, models)
                ]
                for i, (_, data, _q) in enumerate(chunk):
                    preds = [d[i] for d in per_algo if i in d]
                    write_ok(data, serving.serve(supplemented[i][1], preds))
            except Exception as batch_err:
                # a poisoned chunk falls back to per-line prediction so one
                # bad query costs one error record, not the whole chunk
                logger.warning(
                    "chunk ending at line %d failed (%s); retrying per line",
                    chunk[-1][0], batch_err,
                )
                for line_no, data, q in chunk:
                    try:
                        sq = serving.supplement(q)
                        preds = [
                            a.predict(m, sq)
                            for a, m in zip(algorithms, models)
                        ]
                        write_ok(data, serving.serve(sq, preds))
                    except Exception as e:
                        logger.warning("line %d failed: %s", line_no, e)
                        fout.write(
                            json.dumps({"query": data, "error": str(e)}) + "\n"
                        )
            chunk.clear()

        for line_no, line in enumerate(fin, 1):
            if n_procs > 1 and (line_no - 1) % n_procs != pid:
                continue
            line = line.strip()
            if not line:
                continue
            try:
                data = json.loads(line)
                query = bind_query(engine.query_cls, data)
            except Exception as e:
                logger.warning("line %d failed: %s", line_no, e)
                flush()  # keep output in input-line order
                fout.write(json.dumps({"query": line, "error": str(e)}) + "\n")
                continue
            chunk.append((line_no, data, query))
            if len(chunk) >= _CHUNK_QUERIES:
                flush()
        flush()
    return n, output_path
