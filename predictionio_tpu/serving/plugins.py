"""Plugin auto-discovery — the ServiceLoader role.

The reference discovers engine-server and event-server plugins from the
classpath via ``java.util.ServiceLoader``
(``core/src/main/scala/org/apache/predictionio/workflow/
EngineServerPluginContext.scala:34-97``): dropping a jar on the classpath
registers its plugins with no flags. The Python-native equivalent is
package entry points: an installed plugin package declares

    [project.entry-points."predictionio_tpu.plugins"]
    my-blocker = my_pkg.plugins:MyBlocker

and it appears in ``/plugins.json`` on the next deploy with no CLI flag.
``PIO_PLUGINS`` (comma-separated dotted paths) covers environments where
installing a distribution isn't possible, and ``--plugin`` stays as the
explicit per-invocation override. Event-server plugins use the
``predictionio_tpu.event_plugins`` group.
"""

from __future__ import annotations

import logging
import os

logger = logging.getLogger(__name__)

ENGINE_GROUP = "predictionio_tpu.plugins"
EVENT_GROUP = "predictionio_tpu.event_plugins"

# PIO_PLUGINS lists BOTH kinds in one env var (the reference's classpath
# is similarly kind-blind, EngineServerPluginContext.scala:34-97 +
# EventServerPluginContext.scala); each server's discovery call keeps the
# entries whose plugin_type belongs to its group
_GROUP_TYPES = {
    ENGINE_GROUP: ("outputblocker", "outputsniffer"),
    EVENT_GROUP: ("inputblocker", "inputsniffer"),
}


def discover_plugins(group: str = ENGINE_GROUP) -> list:
    """Instantiate every plugin advertised for ``group``.

    Sources, in order: installed-package entry points, then the
    ``PIO_PLUGINS`` env var. A plugin that fails to load is logged and
    skipped — one broken package must not take the server down with it
    (the reference's ServiceLoader behaves the same way).
    """
    out = []
    from importlib import metadata

    try:
        eps = metadata.entry_points()
        selected = (
            eps.select(group=group)
            if hasattr(eps, "select")
            else eps.get(group, [])  # pre-3.10 mapping API
        )
        for ep in selected:
            try:
                out.append(ep.load()())
            except Exception:
                logger.exception(
                    "plugin entry point %r (%s) failed to load; skipping",
                    ep.name, group,
                )
    except Exception:
        logger.exception("entry-point scan failed; continuing without")
    group_types = _GROUP_TYPES.get(group)
    if group_types:
        from predictionio_tpu.core.persistence import resolve_class

        seen = {type(p) for p in out}
        for path in (os.environ.get("PIO_PLUGINS") or "").split(","):
            path = path.strip()
            if not path:
                continue
            try:
                cls = resolve_class(path)
            except Exception:
                logger.exception(
                    "PIO_PLUGINS entry %r failed to load; skipping", path
                )
                continue
            # filter on the CLASS attribute before instantiating: the
            # other group's plugin must not run its (possibly
            # side-effectful) __init__ in this server at all
            if getattr(cls, "plugin_type", None) not in group_types:
                logger.debug(
                    "PIO_PLUGINS entry %r is not a %s plugin; skipping "
                    "for this group", path, group,
                )
                continue
            # a plugin advertised BOTH ways (installed entry point + a
            # leftover PIO_PLUGINS entry) — or listed twice in the env
            # var — must run once: dedup BEFORE instantiating so a
            # duplicate's __init__ side effects never fire at all
            if cls in seen:
                continue
            try:
                plugin = cls()
            except Exception:
                logger.exception(
                    "PIO_PLUGINS entry %r failed to load; skipping", path
                )
                continue
            seen.add(cls)
            out.append(plugin)
    return out
