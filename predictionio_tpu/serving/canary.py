"""SLO-guarded canary rollout with automatic rollback and durable
generation quarantine (ISSUE 20; ROADMAP robustness track).

Deployment was all-or-nothing: ``fleet.roll()`` moves every replica to
the newest COMPLETED generation with zero online verification, so a
generation that *loads* fine but regresses under traffic (latency
blowup, error spike, quality drift) takes down the whole fleet.  The
:class:`CanaryController` turns deployment into a verified, reversible
dataflow stage:

1. **Canary** — ONE replica hot-swaps to the candidate generation via
   ``POST /reload?instanceId=`` (no respawn); the rest keep serving the
   baseline.  Blast radius is bounded at 1/N of the fleet.
2. **Verify** — the router attributes online metrics *per generation*
   (engine instance id, never the per-process generation counter):
   error rate, p99 — against an absolute SLO or a ratio of the
   baseline's live p99 — and top-k prediction overlap vs the baseline,
   measured by budget-capped **shadow-mirrored** queries: real captured
   bodies replayed to candidate + baseline, answers discarded.
3. **Promote or roll back** — after a minimum-sample verification
   window the remainder of the fleet rolls to the candidate; any SLO
   breach instead rolls the canary back to the baseline and writes a
   durable, epoch-fenced **quarantine receipt** (sealed through the
   core/persistence checksum envelope) that newest-COMPLETED selection,
   cold-start fallback, ``fleet.roll()`` and future canaries all
   consult — a bad generation is never auto-deployed twice.
4. **Soak** — a post-promotion watchdog keeps scoring the candidate
   fleet-wide; a breach triggers *runtime* fleet-wide rollback to the
   last known good generation (previously rollback only existed at
   cold start).

Crash safety: every state transition journals first (sealed + atomic,
``<base>/canary/<engine-key>/state.json``) so a kill -9 mid-promotion
resumes idempotently — or aborts to a consistent all-baseline fleet —
on restart (:meth:`CanaryController.resume`).  The journal carries a
monotonic epoch + owner token: a resumed controller bumps the epoch,
and any write from a stale controller raises :class:`FencedError`
(split-brain fencing).  The rollback intent (including the quarantine
verdict) is journaled BEFORE the receipt write, so a crash at the
``crash:canary:before_receipt`` fault site still quarantines on
resume.

Mutual exclusion with the autoscaler: for the whole canary window the
fleet's spawn pin holds new children on the BASELINE generation (a
scale-up must never come up on the unverified candidate) and the
canary replica's url is protected from scale-down.

Chaos sites: ``crash:canary:mid_promote`` (between per-replica
promotions), ``crash:canary:before_receipt`` (after rollback, before
the receipt lands), ``client:canary:shadow`` (the shadow-mirror hop).

Thread model: one worker thread per canary runs ``_verify_loop`` then
(after promotion) ``_soak_loop`` — both pace on the stop Event and
delegate all I/O to per-tick helpers (the blocking analyzer's hot-loop
contract).  Mutable controller state is guarded by ``_lock``.
"""

from __future__ import annotations

import json
import logging
import os
import secrets
import threading
import time
import urllib.error
import urllib.request
from typing import Optional

from predictionio_tpu.common import faults as _faults
from predictionio_tpu.common.resilience import (
    DEADLINE_HEADER,
    Deadline,
    ErrorCounters,
    RateLimitedLogger,
)
from predictionio_tpu.core import persistence

logger = logging.getLogger(__name__)

# controller states (journaled; the pio_canary_state gauge values)
IDLE = "idle"
VERIFYING = "verifying"
PROMOTING = "promoting"
SOAKING = "soaking"
ROLLING_BACK = "rolling_back"
STATE_VALUES = {
    IDLE: 0.0, VERIFYING: 1.0, PROMOTING: 2.0, SOAKING: 3.0,
    ROLLING_BACK: 4.0,
}

MID_PROMOTE_SITE = "crash:canary:mid_promote"
BEFORE_RECEIPT_SITE = "crash:canary:before_receipt"
SHADOW_SITE = "client:canary:shadow"


class FencedError(RuntimeError):
    """A newer controller owns the journal: this one must stop
    mutating the fleet immediately (split-brain fencing)."""


def _env_num(name: str, default, cast):
    try:
        return cast(os.environ[name])
    except (KeyError, ValueError, TypeError):
        return default


def _topk_overlap(a: dict, b: dict, k: int = 10) -> Optional[float]:
    """Fraction of the baseline's top-k item ids the candidate also
    ranks in ITS top-k — the canary's quality-drift signal.  None when
    either answer has no rankable item list (overlap then simply does
    not contribute to the verdict)."""
    def items(resp):
        scores = resp.get("itemScores") if isinstance(resp, dict) else None
        if not isinstance(scores, list):
            return None
        out = []
        for entry in scores[:k]:
            if isinstance(entry, dict) and "item" in entry:
                out.append(str(entry["item"]))
        return out or None

    ia, ib = items(a), items(b)
    if ia is None or ib is None:
        return None
    return len(set(ia) & set(ib)) / float(max(len(ib), 1))


class CanaryController:
    """Progressive-delivery controller for one engine key.

    ``router`` must be a :class:`~predictionio_tpu.serving.router.Router`
    (per-generation attribution + shadow capture); ``fleet`` is the
    optional FleetSupervisor (spawn pin + scale-down protection —
    without one those exclusions are skipped); ``storage`` resolves the
    candidate generation (defaults to the process Storage singleton at
    first use).
    """

    def __init__(
        self,
        router,
        fleet=None,
        storage=None,
        engine_id: str = "default",
        engine_version: str = "default",
        engine_variant: str = "default",
    ):
        self.router = router
        self.fleet = fleet
        self._storage = storage
        self.engine_id = engine_id
        self.engine_version = engine_version
        self.engine_variant = engine_variant
        self._lock = threading.Lock()
        self._stop_evt = threading.Event()
        self._worker: Optional[threading.Thread] = None
        self._rl_log = RateLimitedLogger(logger)
        # knobs (each read exactly once; documented in
        # docs/operations.md "Progressive delivery" — the knobs analyzer
        # diffs the defaults)
        self.tick_s = _env_num("PIO_CANARY_TICK_MS", 250.0, float) / 1e3
        self.min_samples = _env_num("PIO_CANARY_MIN_SAMPLES", 50, int)
        self.window_s = _env_num("PIO_CANARY_WINDOW_S", 30.0, float)
        self.max_error_rate = _env_num(
            "PIO_CANARY_MAX_ERROR_RATE", 0.02, float
        )
        self.p99_slo_ms = _env_num("PIO_CANARY_P99_SLO_MS", 0.0, float)
        self.p99_ratio = _env_num("PIO_CANARY_P99_RATIO", 2.0, float)
        self.min_overlap = _env_num("PIO_CANARY_MIN_OVERLAP", 0.5, float)
        self.shadow_budget = _env_num("PIO_CANARY_SHADOW_BUDGET", 200, int)
        self.shadow_timeout_ms = _env_num(
            "PIO_CANARY_SHADOW_TIMEOUT_MS", 1000.0, float
        )
        self.soak_s = _env_num("PIO_CANARY_SOAK_S", 30.0, float)
        # run state (guarded by _lock)
        self._state = IDLE
        self._epoch = 0
        self._token = ""
        self._candidate: Optional[str] = None
        self._baseline: Optional[str] = None
        self._canary_url: Optional[str] = None
        self._promote_urls: list[str] = []
        self._started_at = 0.0
        self._soak_started_at = 0.0
        self._soak_base: dict = {}
        self._shadow_pairs = 0
        self._shadow_overlap_sum = 0.0
        self._shadow_spent = 0
        self._force_promote = False
        self._abort = False
        self._last_outcome: Optional[dict] = None
        self.counters = ErrorCounters(
            "verifications_pass", "verifications_fail", "promotions",
            "rollbacks_verify", "rollbacks_soak", "aborts",
            "shadow_ok", "shadow_errors", "fenced", "resumed",
        )

    # -- storage / journal ----------------------------------------------------
    def _get_storage(self):
        if self._storage is None:
            from predictionio_tpu.data.storage.registry import Storage

            self._storage = Storage.instance()
        return self._storage

    def _journal_path(self) -> str:
        from predictionio_tpu.utils.fs import pio_base_dir

        key = persistence._engine_key(
            self.engine_id, self.engine_version, self.engine_variant
        )
        return os.path.join(pio_base_dir(), "canary", key, "state.json")

    def _read_journal(self) -> Optional[dict]:
        try:
            return json.loads(
                persistence.open_blob_file(self._journal_path())
                .decode("utf-8")
            )
        except (OSError, ValueError):
            return None
        except persistence.ModelIntegrityError:
            # a torn journal cannot name its owner: treat as absent —
            # the fleet stays consistent because every mutation path is
            # idempotent and quarantine receipts are separate artifacts
            self._rl_log.warning(
                "journal", "canary journal failed its checksum; ignoring"
            )
            return None

    def _journal(self, state: str, **extra) -> None:
        """Durably record a state transition.  FENCED: the write is
        refused (and this controller stops itself) when a newer epoch —
        or another controller's token on the same epoch — owns the
        journal."""
        disk = self._read_journal()
        if disk is not None:
            d_epoch = int(disk.get("epoch", 0))
            if d_epoch > self._epoch or (
                d_epoch == self._epoch
                and disk.get("token") not in ("", self._token)
            ):
                self.counters.inc("fenced")
                raise FencedError(
                    f"canary journal owned by epoch {d_epoch} "
                    f"token {disk.get('token')!r}"
                )
        entry = {
            "epoch": self._epoch,
            "token": self._token,
            "state": state,
            "candidate": self._candidate,
            "baseline": self._baseline,
            "canaryUrl": self._canary_url,
            "promoteUrls": self._promote_urls,
            "updatedAt": time.time(),
        }
        entry.update(extra)
        path = self._journal_path()
        os.makedirs(os.path.dirname(path), exist_ok=True)
        persistence.seal_blob_file(
            path, json.dumps(entry, sort_keys=True).encode("utf-8")
        )
        with self._lock:
            self._state = state

    # -- public control surface ----------------------------------------------
    def active(self) -> bool:
        with self._lock:
            return self._state != IDLE

    def start_canary(
        self, instance_id: Optional[str] = None, force: bool = False
    ) -> bool:
        """Begin a canary: resolve the candidate generation, hot-swap ONE
        replica to it, and start the verification window.  Returns False
        when a canary is already in flight; raises ValueError when no
        deployable candidate exists (all newer generations quarantined,
        or the fleet already serves the newest)."""
        with self._lock:
            if self._state != IDLE:
                return False
        baseline, canary_url, others = self._pick_replicas()
        candidate = self._resolve_candidate(instance_id, baseline, force)
        disk = self._read_journal()
        with self._lock:
            self._epoch = int((disk or {}).get("epoch", 0)) + 1
            self._token = secrets.token_hex(8)
            self._candidate = candidate
            self._baseline = baseline
            self._canary_url = canary_url
            self._promote_urls = others
            self._started_at = time.monotonic()
            self._shadow_pairs = 0
            self._shadow_overlap_sum = 0.0
            self._shadow_spent = 0
            self._force_promote = False
            self._abort = False
            self._stop_evt.clear()
        self._journal(VERIFYING)
        self._begin_exclusions()
        try:
            self._reload_replica(canary_url, candidate, force=force)
        except Exception:
            # the swap never landed: end the experiment cleanly (no
            # receipt — the candidate was never observed under traffic)
            self._end_exclusions()
            self._journal(IDLE, outcome="swap-failed")
            raise
        self._spawn_worker()
        logger.info(
            "canary started: candidate %s on %s (baseline %s, epoch %d)",
            candidate, canary_url, baseline, self._epoch,
        )
        return True

    def request_promote(self) -> bool:
        """Operator skip-ahead: promote at the next tick unless the
        window has already breached."""
        with self._lock:
            if self._state != VERIFYING:
                return False
            self._force_promote = True
            return True

    def request_abort(self) -> bool:
        """Roll the canary back to the baseline WITHOUT quarantining —
        an abort is an operator decision, not an online verdict."""
        with self._lock:
            if self._state not in (VERIFYING, SOAKING):
                return False
            self._abort = True
            return True

    def quarantine(self) -> list[dict]:
        return persistence.read_quarantine_receipts(
            self.engine_id, self.engine_version, self.engine_variant
        )

    def release_quarantine(self, instance_id: str) -> bool:
        return persistence.clear_quarantine(
            instance_id, self.engine_id, self.engine_version,
            self.engine_variant,
        )

    def stop(self) -> None:
        """Stop the worker thread; fleet state is left as-is (resume()
        on the next controller decides)."""
        self._stop_evt.set()
        with self._lock:
            worker = self._worker
        if worker is not None and worker.is_alive():
            worker.join(timeout=5.0)

    # -- resume (crash recovery) ---------------------------------------------
    def resume(self) -> Optional[str]:
        """Recover from a journal left by a dead controller.  Takes
        ownership (epoch bump — the dead controller, if actually alive,
        is fenced on its next write) and drives the fleet to a
        consistent state:

        * ``PROMOTING`` — finish the promotion idempotently, then soak.
        * ``ROLLING_BACK`` — finish the rollback; the journaled
          quarantine verdict still lands its receipt (this is what makes
          ``crash:canary:before_receipt`` safe).
        * ``VERIFYING`` — abort to baseline, NO quarantine: the
          controller died, not the candidate.
        * ``SOAKING`` — restart the soak watchdog.

        Returns the action taken, or None when the journal is absent or
        already idle."""
        disk = self._read_journal()
        if disk is None or disk.get("state") in (None, IDLE):
            return None
        state = disk["state"]
        with self._lock:
            if self._state != IDLE:
                return None
            self._epoch = int(disk.get("epoch", 0)) + 1
            self._token = secrets.token_hex(8)
            self._candidate = disk.get("candidate")
            self._baseline = disk.get("baseline")
            self._canary_url = disk.get("canaryUrl")
            self._promote_urls = list(disk.get("promoteUrls") or [])
            self._started_at = time.monotonic()
            self._stop_evt.clear()
        if not self._candidate or not self._baseline:
            self._journal(IDLE, outcome="unrecoverable-journal")
            return "cleared"
        self.counters.inc("resumed")
        if state == ROLLING_BACK:
            self._rollback(
                reason=str(disk.get("reason") or "resumed-rollback"),
                quarantine=bool(disk.get("quarantine", True)),
                fleet_wide=bool(disk.get("fleetWide", False)),
                counter=None,
            )
            return "rolled_back"
        if state == VERIFYING:
            self._rollback(
                reason="controller-restart", quarantine=False,
                fleet_wide=False, counter="aborts",
            )
            return "aborted"
        if state == PROMOTING:
            self._promote()
            self._spawn_worker(soak_only=True)
            return "promoted"
        if state == SOAKING:
            self._begin_soak()
            self._spawn_worker(soak_only=True)
            return "soaking"
        self._journal(IDLE, outcome=f"unknown-state-{state}")
        return "cleared"

    # -- candidate / replica resolution --------------------------------------
    def _pick_replicas(self) -> tuple[str, str, list[str]]:
        """(baseline instance id, canary replica url, other urls).  The
        canary replica is the LAST admitted replica (mirrors the
        scale-down pick: newest first, keep long-warm replicas on the
        baseline)."""
        view = self.router.replica_view()
        admitted = [
            r for r in view if r["state"] == "admitted" and r["instanceId"]
        ]
        if not admitted:
            raise ValueError(
                "no admitted replica advertises an engine instance id yet"
            )
        baseline = admitted[-1]["instanceId"]
        canary_url = admitted[-1]["url"]
        others = [r["url"] for r in admitted[:-1]]
        return baseline, canary_url, others

    def _resolve_candidate(
        self, instance_id: Optional[str], baseline: str, force: bool
    ) -> str:
        quarantined = persistence.quarantined_instance_ids(
            self.engine_id, self.engine_version, self.engine_variant
        )
        if instance_id:
            if instance_id == baseline:
                raise ValueError(
                    f"candidate {instance_id} is already the baseline"
                )
            if instance_id in quarantined and not force:
                raise ValueError(
                    f"candidate {instance_id} is quarantined; pass "
                    "force to override"
                )
            return instance_id
        completed = (
            self._get_storage().get_meta_data_engine_instances()
            .get_completed(
                self.engine_id, self.engine_version, self.engine_variant
            )
        )
        for inst in completed:
            if inst.id == baseline:
                break
            if inst.id in quarantined:
                continue
            return inst.id
        raise ValueError(
            "no candidate: the fleet already serves the newest "
            "non-quarantined COMPLETED generation"
        )

    # -- exclusions (autoscaler mutual exclusion) ----------------------------
    def _begin_exclusions(self) -> None:
        self.router.set_shadow_capture(True)
        if self.fleet is not None:
            self.fleet.set_spawn_pin(self._baseline)
            if self._canary_url:
                self.fleet.protect_replica(self._canary_url, True)

    def _end_exclusions(self) -> None:
        self.router.set_shadow_capture(False)
        if self.fleet is not None:
            self.fleet.set_spawn_pin(None)
            if self._canary_url:
                self.fleet.protect_replica(self._canary_url, False)

    # -- replica hot-swap -----------------------------------------------------
    def _reload_replica(
        self, url: str, instance_id: str, force: bool = False
    ) -> None:
        """Hot-swap one replica to a specific generation via its
        ``POST /reload?instanceId=`` (control plane; no respawn)."""
        qs = f"/reload?instanceId={instance_id}"
        if force:
            qs += "&force=1"
        req = urllib.request.Request(url + qs, method="POST", data=b"")
        with urllib.request.urlopen(req, timeout=30.0) as r:
            body = json.loads(r.read().decode("utf-8"))
        got = body.get("engineInstanceId")
        if got != instance_id:
            raise RuntimeError(
                f"replica {url} deployed {got!r}, wanted {instance_id!r}"
            )

    # -- worker ---------------------------------------------------------------
    def _spawn_worker(self, soak_only: bool = False) -> None:
        worker = threading.Thread(
            target=self._drive, args=(soak_only,),
            name="canary-controller", daemon=True,
        )
        with self._lock:
            self._worker = worker
        worker.start()

    def _drive(self, soak_only: bool) -> None:
        try:
            if not soak_only:
                self._verify_loop()
            with self._lock:
                soaking = self._state == SOAKING
            if soak_only or soaking:
                self._soak_loop()
        except FencedError:
            self._rl_log.warning(
                "fenced", "canary controller fenced by a newer epoch; "
                "standing down"
            )
        except Exception:
            self._rl_log.exception("canary", "canary worker crashed")

    def _verify_loop(self) -> None:
        # hot-loop contract: pace on the stop Event, delegate every
        # blocking step (HTTP, journal I/O) to the tick helper
        while not self._stop_evt.wait(self.tick_s):
            if self._verify_tick():
                return

    def _soak_loop(self) -> None:
        # same contract as _verify_loop (both names are registered with
        # the blocking analyzer's hot-loop set)
        while not self._stop_evt.wait(self.tick_s):
            if self._soak_tick():
                return

    # -- verification ---------------------------------------------------------
    def _verify_tick(self) -> bool:
        """One verification step; returns True when the canary reached a
        terminal decision (promoted / rolled back / aborted)."""
        with self._lock:
            if self._state != VERIFYING:
                return True
            abort = self._abort
            force = self._force_promote
        if abort:
            self._rollback(
                reason="operator-abort", quarantine=False,
                fleet_wide=False, counter="aborts",
            )
            return True
        self._shadow_tick()
        verdict, detail = self._evaluate()
        if verdict == "fail":
            self.counters.inc("verifications_fail")
            self._rollback(
                reason=detail, quarantine=True, fleet_wide=False,
                counter="rollbacks_verify",
            )
            return True
        if verdict == "pass" or (force and verdict != "fail"):
            self.counters.inc("verifications_pass")
            self._journal(PROMOTING, detail=detail)
            self._promote()
            return False  # _drive continues into _soak_loop
        return False

    def _shadow_tick(self) -> None:
        """Replay a handful of captured real queries against candidate
        and baseline; answers are discarded, only the top-k overlap
        survives.  Budget-capped per canary window."""
        with self._lock:
            remaining = self.shadow_budget - self._shadow_spent
            canary_url = self._canary_url
        if remaining <= 0 or canary_url is None:
            return
        baseline_url = self._baseline_url()
        if baseline_url is None:
            return
        for body in self.router.take_shadow_samples(min(remaining, 8)):
            with self._lock:
                self._shadow_spent += 1
            overlap = self._serve_shadow_pair(
                body, canary_url, baseline_url
            )
            if overlap is None:
                continue
            with self._lock:
                self._shadow_pairs += 1
                self._shadow_overlap_sum += overlap

    def _baseline_url(self) -> Optional[str]:
        with self._lock:
            baseline = self._baseline
        for r in self.router.replica_view():
            if r["state"] == "admitted" and r["instanceId"] == baseline:
                return r["url"]
        return None

    def _serve_shadow_pair(
        self, body: bytes, canary_url: str, baseline_url: str
    ) -> Optional[float]:
        """One shadow mirror: POST the captured body to candidate and
        baseline, discard both answers, return their top-k overlap.
        Any failure (including the ``client:canary:shadow`` chaos site)
        counts as a shadow error, never as a candidate verdict — only
        attributed REAL traffic and measured overlap decide."""
        act = _faults.check(SHADOW_SITE)
        if act is not None:
            if act.latency_s:
                time.sleep(act.latency_s)
            if act.kind in ("error", "drop", "crash"):
                self.counters.inc("shadow_errors")
                return None
        deadline = Deadline.after_ms(self.shadow_timeout_ms)
        answers = []
        for url in (canary_url, baseline_url):
            remaining_ms = deadline.remaining_ms()
            if remaining_ms <= 0:
                self.counters.inc("shadow_errors")
                return None
            headers = {
                "Content-Type": "application/json",
                # shadow hops carry the remaining budget like any other
                # downstream hop (deadline-propagation contract)
                DEADLINE_HEADER: f"{remaining_ms:.0f}",
            }
            req = urllib.request.Request(
                url + "/queries.json", data=body, method="POST",
                headers=headers,
            )
            try:
                with urllib.request.urlopen(
                    req, timeout=max(remaining_ms, 1.0) / 1e3
                ) as r:
                    answers.append(json.loads(r.read().decode("utf-8")))
            except (OSError, ValueError):
                self.counters.inc("shadow_errors")
                return None
        self.counters.inc("shadow_ok")
        return _topk_overlap(answers[0], answers[1])

    def _evaluate(self) -> tuple[str, str]:
        """Score the candidate against the baseline: ``("pass", why)``,
        ``("fail", why)`` or ``("wait", why)``."""
        gens = self.router.generation_stats()
        with self._lock:
            cand_id, base_id = self._candidate, self._baseline
            started = self._started_at
            pairs = self._shadow_pairs
            overlap_sum = self._shadow_overlap_sum
        cand = gens.get(cand_id) or {}
        base = gens.get(base_id) or {}
        requests = cand.get("requests", 0)
        if requests > 0 and cand.get("errorRate", 0.0) > self.max_error_rate:
            if requests >= max(10, self.min_samples // 5):
                # error breaches fire EARLY (a hard-failing candidate
                # must not absorb the whole window of client traffic)
                return (
                    "fail",
                    f"error rate {cand['errorRate']:.3f} > "
                    f"{self.max_error_rate:g} over {requests} requests",
                )
        p99 = cand.get("p99Ms")
        if p99 is not None and cand.get("latencySamples", 0) >= max(
            10, self.min_samples // 5
        ):
            if self.p99_slo_ms > 0 and p99 > self.p99_slo_ms:
                return (
                    "fail",
                    f"p99 {p99:.1f}ms > SLO {self.p99_slo_ms:g}ms",
                )
            base_p99 = base.get("p99Ms")
            if (
                self.p99_slo_ms <= 0
                and base_p99 is not None
                and base_p99 > 0
                and p99 > self.p99_ratio * base_p99
            ):
                return (
                    "fail",
                    f"p99 {p99:.1f}ms > {self.p99_ratio:g}x baseline "
                    f"{base_p99:.1f}ms",
                )
        if pairs > 0:
            mean_overlap = overlap_sum / pairs
            if pairs >= 5 and mean_overlap < self.min_overlap:
                return (
                    "fail",
                    f"top-k overlap {mean_overlap:.2f} < "
                    f"{self.min_overlap:g} over {pairs} shadow pairs",
                )
        elapsed = time.monotonic() - started
        if requests < self.min_samples:
            return ("wait", f"{requests}/{self.min_samples} samples")
        if elapsed < self.window_s:
            return ("wait", f"{elapsed:.1f}/{self.window_s:g}s window")
        return (
            "pass",
            f"{requests} requests, error rate "
            f"{cand.get('errorRate', 0.0):.3f}, p99 "
            f"{p99 if p99 is not None else float('nan'):.1f}ms",
        )

    # -- promotion ------------------------------------------------------------
    def _promote(self) -> None:
        """Roll every remaining baseline replica to the candidate —
        idempotent (a replica already on the candidate reloads to the
        same generation), so a crash at ``crash:canary:mid_promote``
        resumes by simply re-running the list."""
        with self._lock:
            candidate = self._candidate
            urls = list(self._promote_urls)
        for url in urls:
            _faults.crash_point(MID_PROMOTE_SITE)
            try:
                self._reload_replica(url, candidate)
            except Exception:
                self._rl_log.exception(
                    "promote", "promotion reload failed for %s", url
                )
        self.counters.inc("promotions")
        self._begin_soak()

    def _begin_soak(self) -> None:
        gens = self.router.generation_stats()
        with self._lock:
            cand = gens.get(self._candidate) or {}
            self._soak_started_at = time.monotonic()
            self._soak_base = {
                "requests": cand.get("requests", 0),
                "errors": cand.get("errors", 0),
            }
        self._journal(SOAKING)
        self._end_exclusions()

    def _soak_tick(self) -> bool:
        """Post-promotion watchdog step; returns True when the soak
        window closes (clean or rolled back)."""
        with self._lock:
            if self._state != SOAKING:
                return True
            abort = self._abort
            cand_id = self._candidate
            soak_started = self._soak_started_at
            base = dict(self._soak_base)
        if abort:
            self._rollback(
                reason="operator-abort-soak", quarantine=False,
                fleet_wide=True, counter="aborts",
            )
            return True
        gens = self.router.generation_stats()
        cand = gens.get(cand_id) or {}
        d_req = cand.get("requests", 0) - base["requests"]
        d_err = cand.get("errors", 0) - base["errors"]
        breach = None
        if d_req >= max(10, self.min_samples // 5):
            rate = d_err / float(d_req)
            if rate > self.max_error_rate:
                breach = (
                    f"soak error rate {rate:.3f} > "
                    f"{self.max_error_rate:g} over {d_req} requests"
                )
        p99 = cand.get("p99Ms")
        if (
            breach is None
            and self.p99_slo_ms > 0
            and p99 is not None
            and cand.get("latencySamples", 0) >= max(
                10, self.min_samples // 5
            )
            and p99 > self.p99_slo_ms
        ):
            breach = f"soak p99 {p99:.1f}ms > SLO {self.p99_slo_ms:g}ms"
        if breach is not None:
            # RUNTIME fleet-wide rollback to the last known good
            # generation — the capability that previously existed only
            # at cold start
            self._rollback(
                reason=breach, quarantine=True, fleet_wide=True,
                counter="rollbacks_soak",
            )
            return True
        if time.monotonic() - soak_started >= self.soak_s:
            with self._lock:
                outcome = {
                    "outcome": "promoted",
                    "candidate": self._candidate,
                }
                self._last_outcome = outcome
            self._journal(IDLE, **outcome)
            logger.info("canary soak clean: %s is the fleet generation",
                        cand_id)
            return True
        return False

    # -- rollback + quarantine ------------------------------------------------
    def _rollback(
        self,
        reason: str,
        quarantine: bool,
        fleet_wide: bool,
        counter: Optional[str],
    ) -> None:
        """Return the fleet to the baseline generation, then (for a
        verification verdict) write the durable quarantine receipt.

        Ordering is the crash-safety contract: the intent — INCLUDING
        the quarantine verdict — journals first, so a kill -9 anywhere
        in here (``crash:canary:before_receipt`` sits right before the
        receipt write) is finished by resume(), never lost."""
        with self._lock:
            candidate = self._candidate
            baseline = self._baseline
            canary_url = self._canary_url
            epoch = self._epoch
        self._journal(
            ROLLING_BACK, reason=reason, quarantine=quarantine,
            fleetWide=fleet_wide,
        )
        if counter is not None:
            self.counters.inc(counter)
        urls = []
        if fleet_wide:
            urls = [
                r["url"] for r in self.router.replica_view()
                if r["state"] != "ejected" or r["instanceId"] == candidate
            ]
        elif canary_url:
            urls = [canary_url]
        for url in urls:
            try:
                self._reload_replica(url, baseline)
            except Exception:
                self._rl_log.exception(
                    "rollback", "rollback reload failed for %s (child "
                    "selection skips the quarantined id on its next "
                    "restart)", url,
                )
        if quarantine:
            _faults.crash_point(BEFORE_RECEIPT_SITE)
            persistence.write_quarantine_receipt(
                candidate, reason,
                engine_id=self.engine_id,
                engine_version=self.engine_version,
                engine_variant=self.engine_variant,
                epoch=epoch,
                details={"baseline": baseline, "fleetWide": fleet_wide},
            )
        self._end_exclusions()
        outcome = {
            "outcome": "quarantined" if quarantine else "aborted",
            "candidate": candidate,
            "reason": reason,
        }
        with self._lock:
            self._last_outcome = outcome
        self._journal(IDLE, **outcome)
        logger.warning(
            "canary rollback (%s): candidate %s -> baseline %s%s",
            reason, candidate, baseline,
            " [quarantined]" if quarantine else "",
        )

    # -- stats ----------------------------------------------------------------
    def stats(self) -> dict:
        gens = self.router.generation_stats()
        with self._lock:
            state = self._state
            cand_id, base_id = self._candidate, self._baseline
            pairs = self._shadow_pairs
            overlap_sum = self._shadow_overlap_sum
            spent = self._shadow_spent
            out = {
                "state": state,
                "epoch": self._epoch,
                "candidate": cand_id,
                "baseline": base_id,
                "canaryUrl": self._canary_url,
                "lastOutcome": dict(self._last_outcome)
                if self._last_outcome else None,
            }
        out["candidateStats"] = gens.get(cand_id)
        out["baselineStats"] = gens.get(base_id)
        out["shadow"] = {
            "pairs": pairs,
            "spent": spent,
            "budget": self.shadow_budget,
            "meanOverlap": (overlap_sum / pairs) if pairs else None,
        }
        out["counters"] = self.counters.snapshot()
        out["quarantined"] = sorted(
            persistence.quarantined_instance_ids(
                self.engine_id, self.engine_version, self.engine_variant
            )
        )
        return out
