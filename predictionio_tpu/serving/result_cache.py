"""Result cache with event-driven invalidation for the serving hot path.

Real serving traffic is Zipf-distributed: a small head of hot users
generates most queries, and an identical query re-scored on device is pure
waste — the answer only changes when (a) a relevant event lands, or (b) a
new model generation deploys.  This module turns that observation into the
platform's ONE caching idiom (``tests/test_lint.py`` forbids ad-hoc caches
outside ``serving/``):

* :func:`canonical_fingerprint` — a stable key for "identical query":
  sorted-key compact JSON of the raw request body, minus fields that do
  not affect the prediction (``prId``).  The same fingerprint also keys
  single-flight coalescing in the micro-batcher.
* :class:`InvalidationIndex` — generation counters bumped by the ingest
  path.  A cached answer records the generations of every entity it
  depends on; a new event for user U bumps ``U``'s generation, so U's
  cached answers fail validation on the next lookup.  ``$``-prefixed
  events, deletes, and counter overflow bump the GLOBAL generation —
  conservative over clever: when attribution is unclear, everything
  invalidates.
* :class:`ResultCache` — bounded LRU of jsonable predictions, validated
  on ``get`` against TTL (the backstop for cross-process ingest, where no
  in-process hook fires), the invalidation token, and the model
  generation (a reload flushes everything).

Everything here is stdlib-only (no jax): the event server imports it for
the ingest-side hooks without touching accelerator code.
"""

from __future__ import annotations

import copy
import json
import os
import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Iterable, Optional

# query fields whose values name entities a cached answer depends on;
# override with PIO_RESULT_CACHE_KEYS=field1,field2
DEFAULT_KEY_FIELDS = ("user", "users", "item", "items")


def canonical_fingerprint(
    data: dict, namespace: Optional[str] = None
) -> Optional[str]:
    """Stable fingerprint of a raw query body; None when unfingerprintable.

    Sorted keys + compact separators make JSON-equal bodies collide
    regardless of field order; ``prId`` is excluded because the feedback
    tag never changes what the engine predicts, and ``accessKey`` because
    auth metadata never changes the answer — tenant identity lives in
    ``namespace`` instead.  ``namespace`` (tenant id + engine variant
    under multi-tenancy) prefixes the fingerprint so two tenants with
    byte-identical query bodies NEVER share a cache entry or a coalesced
    leader slot: the fingerprint doubles as the batcher coalescing key,
    so an un-namespaced key would leak one tenant's answer to another.
    """
    if not isinstance(data, dict):
        return None
    try:
        body = json.dumps(
            {k: v for k, v in data.items() if k not in ("prId", "accessKey")},
            sort_keys=True, separators=(",", ":"),
        )
    except (TypeError, ValueError):
        return None
    if namespace:
        return f"{namespace}\x1f{body}"
    return body


def entity_ids_from(data: dict, key_fields: Iterable[str]) -> tuple[str, ...]:
    """Entity ids a query touches, read from its well-known key fields.

    Scalars and flat lists both contribute; anything else is ignored (the
    TTL backstop still bounds staleness for exotic query shapes).
    """
    ids: list[str] = []
    for field in key_fields:
        v = data.get(field)
        if isinstance(v, (str, int)):
            ids.append(str(v))
        elif isinstance(v, (list, tuple)):
            ids.extend(str(x) for x in v if isinstance(x, (str, int)))
    return tuple(ids)


class InvalidationIndex:
    """Per-entity + global generation counters driven by the ingest path.

    ``token(ids)`` snapshots the generations a cached answer depends on;
    the answer is valid while a fresh snapshot compares equal.  The
    per-entity map is bounded: evicting an entity silently could let a
    stale token validate (entity bumped to gen 1, evicted, recomputed as
    gen 0 == the stale 0), so every eviction bumps the global generation —
    overflow degrades to coarser invalidation, never to staleness.
    """

    def __init__(self, max_entities: int = 100_000):
        self.max_entities = int(max_entities)
        self._lock = threading.Lock()
        self._gens: "OrderedDict[str, int]" = OrderedDict()
        self._global_gen = 0
        self._counts = {
            "entity_bumps": 0, "global_bumps": 0, "evictions": 0,
        }

    def bump_entities(self, ids: Iterable[str]) -> None:
        with self._lock:
            for eid in ids:
                self._gens[eid] = self._gens.get(eid, 0) + 1
                self._gens.move_to_end(eid)
                self._counts["entity_bumps"] += 1
            while len(self._gens) > self.max_entities:
                self._gens.popitem(last=False)
                self._counts["evictions"] += 1
                self._global_gen += 1
                self._counts["global_bumps"] += 1

    def bump_all(self) -> None:
        with self._lock:
            self._global_gen += 1
            self._counts["global_bumps"] += 1

    def token(self, ids: Iterable[str]) -> tuple:
        with self._lock:
            return (
                self._global_gen,
                tuple(self._gens.get(str(i), 0) for i in ids),
            )

    def stats(self) -> dict:
        with self._lock:
            return {
                "entities": len(self._gens),
                "global_gen": self._global_gen,
                **self._counts,
            }


# THE process-wide index: the event server's ingest hooks bump it, every
# in-process cache (result cache, serving event cache) validates against
# it.  Split-process deployments have no in-process hook — there the TTL
# backstop bounds staleness (docs/operations.md "Serving caches & skew").
INVALIDATIONS = InvalidationIndex()


def notify_event(event: Any) -> None:
    """Ingest-side hook: one committed event → the generations it moves.

    Called AFTER the storage write lands (direct insert, batch insert,
    buffer flush-commit, WAL replay) — bumping at ack time would let a
    query recompute from pre-flush storage and re-cache the stale answer.
    ``$``-prefixed events mutate entity properties with app-wide reach
    (``$set`` on a constraint entity changes every answer), so they bump
    globally.
    """
    name = str(getattr(event, "event", "") or "")
    if name.startswith("$"):
        INVALIDATIONS.bump_all()
        return
    ids = []
    for attr in ("entity_id", "target_entity_id"):
        v = getattr(event, attr, None)
        if v:
            ids.append(str(v))
    if ids:
        INVALIDATIONS.bump_entities(ids)
    else:
        INVALIDATIONS.bump_all()


def notify_delta(user_ids: Iterable[Any]) -> int:
    """Streaming micro-generation hook: a sealed delta touched these users.

    Delta apply rewrites factor rows for a *known* set of users, so the
    invalidation is entity-targeted — every other entity's cached answer
    stays hot (a full flush here would turn each micro-generation into a
    cache stampede, defeating the freshness pipeline's latency win).
    """
    ids = [str(u) for u in user_ids if u is not None and str(u)]
    if ids:
        INVALIDATIONS.bump_entities(ids)
    return len(ids)


def notify_delete() -> None:
    """Event deletion hook: the deleted row's entity is unknown by the
    time the DELETE returns, so invalidate globally (deletes are rare)."""
    INVALIDATIONS.bump_all()


class ResultCache:
    """Bounded LRU of jsonable predictions keyed by query fingerprint.

    Entries are validated on ``get`` in order of cheapness: model
    generation (a reload flushed the world), TTL (cross-process ingest
    backstop), then the invalidation token (an event moved a dependency).
    Values are deep-copied on both ``put`` and ``get`` — downstream code
    mutates results (``prId``, output-blocker plugins) and a shared
    reference would leak one caller's rewrite into another's answer.
    """

    def __init__(
        self,
        max_entries: int = 4096,
        ttl_s: float = 30.0,
        key_fields: Iterable[str] = DEFAULT_KEY_FIELDS,
        index: InvalidationIndex = INVALIDATIONS,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.max_entries = int(max_entries)
        self.ttl_s = float(ttl_s)
        self.key_fields = tuple(key_fields)
        self.index = index
        self._clock = clock
        self._lock = threading.Lock()
        # fp → (value, stored_at, entity_ids, token, model_gen)
        self._data: "OrderedDict[str, tuple]" = OrderedDict()
        self._counts = {
            "hits": 0, "misses": 0, "stores": 0, "evictions": 0,
            "invalidated_ttl": 0, "invalidated_event": 0,
            "invalidated_model": 0,
        }

    def get(self, fp: str, model_gen: int) -> Optional[dict]:
        now = self._clock()
        with self._lock:
            entry = self._data.get(fp)
            if entry is None:
                self._counts["misses"] += 1
                return None
            value, stored_at, entity_ids, token, gen = entry
            if gen != model_gen:
                reason = "invalidated_model"
            elif now - stored_at > self.ttl_s:
                reason = "invalidated_ttl"
            else:
                reason = None
            if reason is not None:
                del self._data[fp]
                self._counts[reason] += 1
                self._counts["misses"] += 1
                return None
        # token check outside this cache's lock: the index has its own
        if self.index.token(entity_ids) != token:
            with self._lock:
                # guard against a concurrent put having replaced the entry
                if self._data.get(fp) is entry:
                    del self._data[fp]
                self._counts["invalidated_event"] += 1
                self._counts["misses"] += 1
            return None
        with self._lock:
            if fp in self._data:
                self._data.move_to_end(fp)
            self._counts["hits"] += 1
        return copy.deepcopy(value)

    def put(
        self, fp: str, value: dict, entity_ids: tuple, model_gen: int
    ) -> None:
        # snapshot the token BEFORE copying: if an event lands mid-copy the
        # stored token is already stale and the entry self-invalidates
        token = self.index.token(entity_ids)
        stored = copy.deepcopy(value)
        with self._lock:
            self._data[fp] = (
                stored, self._clock(), entity_ids, token, model_gen
            )
            self._data.move_to_end(fp)
            self._counts["stores"] += 1
            while len(self._data) > self.max_entries:
                self._data.popitem(last=False)
                self._counts["evictions"] += 1

    def clear(self) -> None:
        with self._lock:
            self._data.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._data)

    def stats(self) -> dict:
        with self._lock:
            c = dict(self._counts)
            entries = len(self._data)
        lookups = c["hits"] + c["misses"]
        return {
            "entries": entries,
            "max_entries": self.max_entries,
            "ttl_s": self.ttl_s,
            "hit_rate": round(c["hits"] / lookups, 4) if lookups else None,
            **c,
        }


def _env_flag(name: str, default: bool = False) -> bool:
    raw = os.environ.get(name)
    if raw is None:
        return default
    return raw.strip().lower() not in ("", "0", "false", "off", "no")


def result_cache_from_env() -> Optional[ResultCache]:
    """Build the serving result cache from PIO_RESULT_CACHE_* knobs;
    None when the cache is off (the default — off-by-default-safe)."""
    if not _env_flag("PIO_RESULT_CACHE"):
        return None
    ttl_ms = float(os.environ.get("PIO_RESULT_CACHE_TTL_MS", 30_000.0))
    max_entries = int(os.environ.get("PIO_RESULT_CACHE_MAX", 4096))
    keys_raw = os.environ.get("PIO_RESULT_CACHE_KEYS", "")
    key_fields = tuple(
        k.strip() for k in keys_raw.split(",") if k.strip()
    ) or DEFAULT_KEY_FIELDS
    return ResultCache(
        max_entries=max_entries, ttl_s=ttl_ms / 1e3, key_fields=key_fields
    )


def coalesce_from_env() -> bool:
    """PIO_COALESCE: single-flight identical in-flight queries at the
    micro-batcher (off by default)."""
    return _env_flag("PIO_COALESCE")
