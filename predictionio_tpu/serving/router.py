"""Replica-fault-tolerant routing front-end for a query-server fleet.

One router process load-balances ``POST /queries.json`` across N
query-server replicas (ISSUE 10; ROADMAP item 5's routing tier).  Every
resilience property proven inside one process — deadlines, breakers,
drain — composes here across processes:

* **Active health checking** against each replica's ``GET /readyz``:
  consecutive probe failures eject a replica, consecutive successes
  re-admit it with a slow-start weight ramp so a cold process is not
  handed a full share of traffic on its first warm second.  The probe
  gates on *warm* (``fastpathWarm``), not merely *loaded*.
* **Outlier ejection**: a replica whose latency EWMA exceeds
  ``PIO_FLEET_OUTLIER_RATIO`` × the fleet median is ejected for a
  cooldown even while its ``/readyz`` is green (a wedged-but-listening
  process must not keep absorbing a share of traffic).
* **Per-replica circuit breakers + concurrency caps** reusing
  ``common/resilience.py`` — one replica OPEN never gates another.
* **Hedged requests**: when the primary attempt is still in flight
  after a rolling-quantile delay, the query is issued to a second
  replica and the first answer wins.  Hedges are budget-capped via
  :class:`~predictionio_tpu.common.resilience.RetryBudget` so a
  fleet-wide slowdown cannot double traffic.
* **Safe retry** of idempotent queries on connection failure / 5xx /
  replica shed — the mechanism that turns a kill -9 of one replica into
  zero client-visible failures.

The router→replica hop is a first-class fault-injection site
(``client:router:/queries.json`` — ``common/faults.py``), so the chaos
suite can exercise latency / error / drop on the hop itself.

Thread model: request handler threads (HttpService pool), one attempt
thread per forwarded try, and one ``_health_loop`` pacing on the stop
Event.  All router/replica mutable state is guarded by ``self._lock``;
breakers keep their own internal lock.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
import urllib.error
import urllib.request
import zlib
from collections import deque
from typing import Optional

from predictionio_tpu.common import faults as _faults
from predictionio_tpu.common.http import (
    HttpService, Request, Response, json_response,
)
from predictionio_tpu.common.resilience import (
    DEADLINE_HEADER,
    CircuitBreaker,
    Deadline,
    ErrorCounters,
    RateLimitedLogger,
    RetryBudget,
    parse_deadline_header,
)
from predictionio_tpu import obs
from predictionio_tpu.obs import bridges as _bridges
from predictionio_tpu.serving import tenancy as _tenancy

logger = logging.getLogger(__name__)

QUERY_PATH = "/queries.json"

# replica admission states (the pio_router_replica_state gauge values)
ADMITTED = "admitted"
EJECTED = "ejected"
DRAINING = "draining"
STATE_VALUES = {ADMITTED: 0.0, EJECTED: 1.0, DRAINING: 2.0}


def _env_num(name: str, default, cast):
    try:
        return cast(os.environ[name])
    except (KeyError, ValueError, TypeError):
        return default


class ReplicaState:
    """One replica's routing view.  Every field is guarded by the owning
    router's ``_lock`` except ``breaker``, which has its own."""

    def __init__(self, url: str, now: float):
        self.url = url.rstrip("/")
        self.state = ADMITTED
        self.admitted_at = now
        self.healthy_streak = 0
        self.unhealthy_streak = 0
        self.inflight = 0
        self.ewma_ms: Optional[float] = None
        self.samples = 0
        self.generation: Optional[int] = None
        # durable generation identity (engine instance id) from /readyz:
        # the `generation` counter above is a per-process int — canary
        # attribution and hot-swap targeting key on THIS instead
        self.instance_id: Optional[str] = None
        self.delta_epoch: Optional[int] = None
        # pod-scale serving: the host group this replica's serving mesh
        # belongs to, as advertised on /readyz (None = not pod-sharded)
        self.pod_group: Optional[int] = None
        self.pod_groups: Optional[int] = None
        self.pod_fingerprint: Optional[str] = None
        self.warm = True
        self.no_readmit_before = 0.0
        self.last_error = ""
        self.breaker = CircuitBreaker(
            endpoint=self.url,
            failure_threshold=_env_num("PIO_FLEET_BREAKER_THRESHOLD", 5, int),
            reset_timeout_s=_env_num(
                "PIO_FLEET_BREAKER_RESET_S", 5.0, float
            ),
        )


class _Slot:
    """First-answer-wins rendezvous between a request thread and its
    attempt threads (primary + optional hedge)."""

    __slots__ = ("event", "lock", "result", "winner_hedged", "outstanding",
                 "failure", "tried", "group")

    def __init__(self):
        self.event = threading.Event()
        self.lock = threading.Lock()
        self.result = None          # (status, body_bytes, headers)
        self.winner_hedged = False
        self.outstanding = 0
        self.failure = None         # last losing (status, body, headers)
        self.tried: set[str] = set()
        # pod owner group of this query (None = no affinity): retries
        # and hedges re-apply the same affinity the primary pick had
        self.group: Optional[int] = None


class Router:
    """HTTP front-end supervising N query-server replicas."""

    def __init__(
        self,
        replica_urls: list[str],
        default_deadline_ms: Optional[float] = None,
        hedge_enabled: Optional[bool] = None,
        telemetry: bool = True,
    ):
        now = time.monotonic()
        self._replicas = [ReplicaState(u, now) for u in replica_urls]
        self._lock = threading.Lock()
        self._stop_evt = threading.Event()
        self._draining = False
        self._fleet = None
        self._autoscaler = None
        self._tenants = None
        self._canary = None
        self._rolling = False
        # per-generation online attribution (canary verification input):
        # engine instance id → requests/errors/latency window, recorded on
        # every attempt outcome in _attempt_chain.  Bounded: only the most
        # recently touched generations are tracked (guarded by _lock).
        self._gen_stats: dict[str, dict] = {}
        # shadow-mirror capture: when a canary is verifying, recent REAL
        # query bodies are kept here (bounded, newest-wins) for the
        # controller to replay against candidate+baseline — answers
        # discarded, budget-capped at the controller (guarded by _lock)
        self._shadow_capture = False
        self._shadow_buf: deque[bytes] = deque(maxlen=64)
        self.default_deadline_ms = default_deadline_ms
        # knobs (each read in exactly one place; documented in
        # docs/operations.md — the knobs analyzer diffs the defaults)
        self.health_interval_ms = _env_num(
            "PIO_FLEET_HEALTH_INTERVAL_MS", 200.0, float
        )
        self.probe_timeout_ms = _env_num(
            "PIO_FLEET_PROBE_TIMEOUT_MS", 1000.0, float
        )
        self.eject_after = _env_num("PIO_FLEET_EJECT_AFTER", 3, int)
        self.readmit_after = _env_num("PIO_FLEET_READMIT_AFTER", 2, int)
        self.slow_start_s = _env_num("PIO_FLEET_SLOW_START_S", 3.0, float)
        self.outlier_ratio = _env_num("PIO_FLEET_OUTLIER_RATIO", 3.0, float)
        self.outlier_cooldown_s = _env_num(
            "PIO_FLEET_OUTLIER_COOLDOWN_S", 5.0, float
        )
        self.outlier_min_samples = _env_num(
            "PIO_FLEET_OUTLIER_MIN_SAMPLES", 20, int
        )
        self.replica_max_inflight = _env_num(
            "PIO_FLEET_REPLICA_MAX_INFLIGHT", 64, int
        )
        self.max_retries = _env_num("PIO_ROUTER_RETRIES", 2, int)
        self.request_timeout_s = (
            _env_num("PIO_ROUTER_TIMEOUT_MS", 30000.0, float) / 1e3
        )
        self.shed_retry_after_s = _env_num(
            "PIO_ROUTER_RETRY_AFTER_S", 1.0, float
        )
        self.hedge_enabled = (
            _env_num("PIO_HEDGE_ENABLED", 1, int) != 0
            if hedge_enabled is None
            else bool(hedge_enabled)
        )
        self.hedge_quantile = _env_num("PIO_HEDGE_QUANTILE", 0.95, float)
        self.hedge_min_ms = _env_num("PIO_HEDGE_MIN_MS", 20.0, float)
        self.budget = RetryBudget(
            ratio=_env_num("PIO_HEDGE_BUDGET_RATIO", 0.1, float)
        )
        # rolling latency window feeding the hedge-delay quantile; the
        # cached quantile is recomputed every _HEDGE_RECALC samples so the
        # hot path never sorts
        self._lat_window: deque[float] = deque(maxlen=256)
        self._hedge_delay_ms = self.hedge_min_ms * 5.0
        self._lat_since_recalc = 0
        self.counters = ErrorCounters(
            "ok", "client_error", "failed", "shed", "deadline", "retries",
            "hedges_fired", "hedges_won", "hedges_denied",
            "ejections_health", "ejections_outlier", "readmissions",
            "pod_fallback",
        )
        # shard-aware fan-out accounting: queries routed to the host
        # group that owns them, keyed by group id (guarded by _lock)
        self._pod_routed: dict[int, int] = {}
        self._rl_log = RateLimitedLogger(logger)
        # streaming delta propagation acks by outcome (push_delta); a
        # plain dict guarded by _lock — outcomes come from receipt shapes,
        # not a fixed counter list
        self._delta_propagated = {"applied": 0, "noop": 0, "refused": 0,
                                  "error": 0}
        self.service = HttpService("router")
        self.telemetry = (
            obs.Telemetry("router").install(self.service)
            if telemetry and obs.telemetry_enabled()
            else None
        )
        self._health_thread: Optional[threading.Thread] = None
        self._register_routes()
        if self.telemetry is not None:
            self._register_metrics()

    _HEDGE_RECALC = 32

    # -- replica selection ---------------------------------------------------
    def _weight(self, rep: ReplicaState, now: float) -> float:
        """Slow-start weight: ramps 0.1 → 1.0 over slow_start_s after
        (re-)admission so a cold replica earns traffic gradually."""
        if self.slow_start_s <= 0:
            return 1.0
        frac = (now - rep.admitted_at) / self.slow_start_s
        return min(1.0, max(0.1, frac))

    def _pick_locked(
        self, exclude: set[str], group: Optional[int] = None
    ) -> Optional[ReplicaState]:
        """Weighted least-loaded admitted replica whose breaker allows the
        call.  ``allow()`` is only consulted on a candidate we are about
        to use, so a half-open probe slot is never burnt on a bystander.

        ``group`` is the pod host group that OWNS this query's serving
        mesh (shard-aware fan-out): candidates in that group are strictly
        preferred; when none is eligible the pick falls back fleet-wide —
        the documented partial-group degrade, counted by the caller."""
        now = time.monotonic()
        cands = []
        owned = []
        for rep in self._replicas:
            if rep.url in exclude or rep.state != ADMITTED:
                continue
            if rep.inflight >= self.replica_max_inflight:
                continue
            load = (rep.inflight + 1.0) / self._weight(rep, now)
            cands.append((load, len(cands), rep))
            if group is not None and rep.pod_group == group:
                owned.append(cands[-1])
        for pool in (owned, cands) if group is not None else (cands,):
            pool.sort(key=lambda t: (t[0], t[1]))
            for _, _, rep in pool:
                if rep.breaker.allow():
                    return rep
        return None

    def available_count(self) -> int:
        with self._lock:
            return sum(1 for r in self._replicas if r.state == ADMITTED)

    # -- elastic replica set -------------------------------------------------
    def add_replica(self, url: str) -> bool:
        """Register a scale-up replica.  It starts EJECTED: the health
        gate must see /readyz + warm before any traffic lands, and then
        slow start ramps its share 10% → 100% — a cold process never
        absorbs a full split.  Returns False on a duplicate URL."""
        url = url.rstrip("/")
        now = time.monotonic()
        with self._lock:
            if any(r.url == url for r in self._replicas):
                return False
            rep = ReplicaState(url, now)
            rep.state = EJECTED
            self._replicas = self._replicas + [rep]
        logger.info("router: replica %s registered (awaiting health gate)",
                    url)
        return True

    def remove_replica(self, url: str) -> bool:
        """Forget a scaled-down replica entirely (probing included)."""
        url = url.rstrip("/")
        with self._lock:
            keep = [r for r in self._replicas if r.url != url]
            removed = len(keep) != len(self._replicas)
            self._replicas = keep
        if removed:
            logger.info("router: replica %s deregistered", url)
        return removed

    def signals(self) -> dict:
        """The autoscaler's input: one consistent snapshot of the load
        signals the router already maintains for its own decisions."""
        with self._lock:
            admitted = [r for r in self._replicas if r.state == ADMITTED]
            out = {
                "replicas": len(self._replicas),
                "admitted": len(admitted),
                "inflight": sum(r.inflight for r in self._replicas),
                "replicaMaxInflight": self.replica_max_inflight,
                "admittedUrls": [r.url for r in admitted],
                "counters": self.counters.snapshot(),
                "rolling": self._rolling,
            }
            reg = self._tenants
        if reg is not None:
            # per-tenant inflight saturation: the autoscaler treats the
            # hottest tenant's share as one more pressure component
            pressure = reg.pressure()
            out["tenantPressure"] = max(pressure.values(), default=0.0)
            out["tenants"] = pressure
        return out

    def _retry_after_s(self) -> float:
        """Backpressure-aware ``Retry-After``: PIO_ROUTER_RETRY_AFTER_S is
        the BASE, scaled by live fleet state so clients back off longer
        the deeper the overload.  With no admitted replica the hint is
        the health gate's readmission horizon (a fresh or restarted
        process cannot answer sooner than readmit_after probes)."""
        base = self.shed_retry_after_s
        with self._lock:
            admitted = [r for r in self._replicas if r.state == ADMITTED]
            inflight = sum(r.inflight for r in self._replicas)
        if not admitted:
            probe_s = (self.health_interval_ms / 1e3) * max(
                1, self.readmit_after
            )
            return round(min(max(base, probe_s), 30.0), 2)
        load = inflight / float(
            max(1, self.replica_max_inflight) * len(admitted)
        )
        return round(min(base * max(1.0, load), 30.0), 2)

    # -- per-generation attribution (canary verification input) --------------
    _GEN_TRACK_MAX = 8
    _GEN_LAT_WINDOW = 512

    def _note_gen_outcome(
        self, rep: ReplicaState, ok: bool,
        latency_ms: Optional[float] = None,
    ) -> None:
        """Attribute one attempt outcome to the engine instance the
        replica was serving.  Keyed by durable instance id (never the
        per-process generation counter); bounded to the most recently
        touched generations so a long-lived router can't grow this
        without bound."""
        iid = rep.instance_id
        if iid is None:
            return
        with self._lock:
            st = self._gen_stats.get(iid)
            if st is None:
                if len(self._gen_stats) >= self._GEN_TRACK_MAX:
                    oldest = min(
                        self._gen_stats.items(),
                        key=lambda kv: kv[1]["touched"],
                    )[0]
                    del self._gen_stats[oldest]
                st = {
                    "requests": 0, "errors": 0,
                    "lat": deque(maxlen=self._GEN_LAT_WINDOW),
                    "touched": 0.0,
                }
                self._gen_stats[iid] = st
            st["requests"] += 1
            if not ok:
                st["errors"] += 1
            if latency_ms is not None:
                st["lat"].append(latency_ms)
            st["touched"] = time.monotonic()

    def generation_stats(self) -> dict:
        """Per-generation online metrics: requests, server errors, error
        rate and p99 over the rolling latency window — the canary
        controller's verification input."""
        with self._lock:
            snap = {
                iid: (st["requests"], st["errors"], sorted(st["lat"]))
                for iid, st in self._gen_stats.items()
            }
        out = {}
        for iid, (requests, errors, lat) in snap.items():
            p99 = (
                lat[min(len(lat) - 1, int(0.99 * len(lat)))]
                if lat else None
            )
            out[iid] = {
                "requests": requests,
                "errors": errors,
                "errorRate": (errors / requests) if requests else 0.0,
                "p99Ms": p99,
                "latencySamples": len(lat),
            }
        return out

    # -- shadow-mirror capture (canary quality signal) ------------------------
    def set_shadow_capture(self, on: bool) -> None:
        """The canary controller turns capture on for the verification
        window only; turning it off drops any unclaimed bodies."""
        with self._lock:
            self._shadow_capture = bool(on)
            if not on:
                self._shadow_buf.clear()

    def take_shadow_samples(self, n: int) -> list[bytes]:
        """Up to ``n`` captured real query bodies, oldest first; each is
        handed out exactly once (the controller replays it against
        candidate + baseline and discards both answers)."""
        out: list[bytes] = []
        with self._lock:
            while self._shadow_buf and len(out) < n:
                out.append(self._shadow_buf.popleft())
        return out

    def replica_view(self) -> list[dict]:
        """Thin per-replica snapshot for the canary controller: which url
        serves which engine instance, and whether it takes traffic."""
        with self._lock:
            return [
                {
                    "url": r.url,
                    "state": r.state,
                    "instanceId": r.instance_id,
                    "warm": r.warm,
                }
                for r in self._replicas
            ]

    # -- latency window / hedge delay ----------------------------------------
    def _record_latency(self, rep: ReplicaState, ms: float) -> None:
        with self._lock:
            if rep.ewma_ms is None:
                rep.ewma_ms = ms
            else:
                rep.ewma_ms += 0.2 * (ms - rep.ewma_ms)
            rep.samples += 1
            self._lat_window.append(ms)
            self._lat_since_recalc += 1
            if (
                self._lat_since_recalc >= self._HEDGE_RECALC
                and len(self._lat_window) >= 16
            ):
                self._lat_since_recalc = 0
                ordered = sorted(self._lat_window)
                idx = min(
                    len(ordered) - 1,
                    int(self.hedge_quantile * len(ordered)),
                )
                self._hedge_delay_ms = max(
                    self.hedge_min_ms, ordered[idx]
                )

    def hedge_delay_ms(self) -> float:
        with self._lock:
            return self._hedge_delay_ms

    # -- shard-aware fan-out (pod host groups) -------------------------------
    def _pod_group_count_locked(self) -> Optional[int]:
        """The fleet's agreed host-group count, or None when the plan map
        is missing/inconsistent — in which case routing degrades to the
        plain fleet-wide broadcast pick (the documented fallback)."""
        groups: set[int] = set()
        fps: set[Optional[str]] = set()
        for rep in self._replicas:
            if rep.pod_group is None or not rep.pod_groups:
                continue
            groups.add(rep.pod_groups)
            fps.add(rep.pod_fingerprint)
        if len(groups) != 1 or len(fps) != 1:
            # no pod fleet, or replicas advertise mismatched plans
            # (mid-deploy fingerprint skew): don't guess ownership
            return None
        n = next(iter(groups))
        return n if n > 1 else None

    def _note_pod_pick_locked(
        self, rep: ReplicaState, group: Optional[int]
    ) -> None:
        """Charge one attempt's pick against the pod fan-out accounting
        (caller holds ``_lock``).  EVERY attempt that carries an owner
        group — primary, retry, hedge — is counted: owner-group hit in
        ``pio_pod_queries_routed_total{group}``, off-owner pick in
        ``pio_pod_fallback_broadcasts_total`` (the documented degrade the
        runbook tells operators to watch)."""
        if group is None:
            return
        if rep.pod_group == group:
            self._pod_routed[group] = self._pod_routed.get(group, 0) + 1
        else:
            self.counters.inc("pod_fallback")

    def _owner_group(self, body: bytes) -> Optional[int]:
        """The host group that owns this query's serving mesh, by stable
        user-key hash — or None when the fleet has no agreed pod map or
        the query carries no user key (both degrade to fleet-wide)."""
        with self._lock:
            n = self._pod_group_count_locked()
        if n is None:
            return None
        try:
            q = json.loads(body.decode("utf-8"))
        except (ValueError, UnicodeDecodeError):
            return None
        user = q.get("user") if isinstance(q, dict) else None
        if user is None:
            return None
        return zlib.crc32(str(user).encode("utf-8")) % n

    # -- forwarding ----------------------------------------------------------
    def _forward(
        self,
        rep: ReplicaState,
        body: bytes,
        deadline: Optional[Deadline],
        trace_id: Optional[str],
    ) -> tuple[int, bytes, dict]:
        """One HTTP try against one replica.  Returns (status, body,
        headers) for ANY HTTP answer; raises OSError for transport
        failures (refused / reset / timeout)."""
        act = _faults.check(f"client:router:{QUERY_PATH}")
        if act is not None:
            if act.latency_s:
                time.sleep(act.latency_s)
            if act.kind == "drop":
                raise ConnectionError("injected drop on router->replica hop")
            if act.kind == "error":
                return (
                    act.status,
                    b'{"message":"injected fault"}',
                    {},
                )
        if rep.pod_group is not None:
            # the pod-merge hop: a forward into a host group whose
            # cross-host leaderboard merge can tear when a member process
            # dies mid-collective (chaos site client:pod:merge)
            act = _faults.check("client:pod:merge")
            if act is not None:
                if act.latency_s:
                    time.sleep(act.latency_s)
                if act.kind == "drop":
                    raise ConnectionError(
                        "injected pod merge tear on router->group hop"
                    )
                if act.kind == "error":
                    return (
                        act.status,
                        b'{"message":"injected pod merge fault"}',
                        {},
                    )
        headers = {"Content-Type": "application/json"}
        timeout = self.request_timeout_s
        if deadline is not None:
            # satellite 2: every attempt (primary, hedge, retry) forwards
            # the budget REMAINING NOW — never the original header value,
            # which would hand later attempts time the client no longer has
            remaining_ms = deadline.remaining_ms()
            headers[DEADLINE_HEADER] = f"{remaining_ms:.0f}"
            timeout = min(timeout, max(remaining_ms, 1.0) / 1e3)
        if trace_id:
            from predictionio_tpu.obs import tracing as _tracing

            headers[_tracing.TRACE_HEADER] = trace_id
        req = urllib.request.Request(
            rep.url + QUERY_PATH, data=body, method="POST", headers=headers
        )
        try:
            with urllib.request.urlopen(req, timeout=timeout) as r:
                return r.status, r.read(), dict(r.headers)
        except urllib.error.HTTPError as e:
            data = e.read()
            return e.code, data, dict(e.headers or {})

    # -- streaming delta propagation -----------------------------------------
    def push_delta(
        self, payload: bytes, deadline: Optional[Deadline] = None
    ) -> dict:
        """Propagate one sealed delta blob to EVERY replica's ``POST
        /delta`` and collect per-replica apply acknowledgements.

        All replicas are pushed — ejected and draining included: a
        replica that misses the push is not wrong, merely stale, and its
        own catch-up from the sealed log (gated by /readyz) must close
        the gap before readmission.  A transport failure or 5xx becomes
        an ``{"error": ...}`` ack; the push itself never raises.
        """
        with self._lock:
            reps = list(self._replicas)
        acks = {}
        applied = 0
        for rep in reps:
            receipt = self._push_delta_one(rep, payload, deadline)
            acks[rep.url] = receipt
            if receipt.get("applied") or receipt.get("noop"):
                applied += 1
            outcome = (
                "applied" if receipt.get("applied")
                else "noop" if receipt.get("noop")
                else "refused" if receipt.get("refused")
                else "error"
            )
            with self._lock:
                self._delta_propagated[outcome] += 1
        return {"replicas": len(reps), "acked": applied, "acks": acks}

    def _push_delta_one(
        self, rep: ReplicaState, payload: bytes,
        deadline: Optional[Deadline] = None,
    ) -> dict:
        """One router→replica delta hop.  Any failure — injected tear,
        refused connect, 5xx — is shaped into an error ack so the caller
        always gets one receipt per replica."""
        headers = {"Content-Type": "application/octet-stream"}
        timeout = self.request_timeout_s
        if deadline is not None:
            # same contract as _forward: forward the budget REMAINING NOW
            remaining_ms = deadline.remaining_ms()
            headers[DEADLINE_HEADER] = f"{remaining_ms:.0f}"
            timeout = min(timeout, max(remaining_ms, 1.0) / 1e3)
        act = _faults.check("client:replica:delta")
        if act is not None:
            if act.latency_s:
                time.sleep(act.latency_s)
            if act.kind == "drop":
                return {"error": "injected drop on router->replica "
                                 "delta hop"}
            if act.kind == "error":
                return {"error": f"injected {act.status} on delta hop"}
        req = urllib.request.Request(
            rep.url + "/delta", data=payload, method="POST", headers=headers
        )
        try:
            with urllib.request.urlopen(req, timeout=timeout) as r:
                return json.loads(r.read().decode("utf-8"))
        except urllib.error.HTTPError as e:
            try:
                return json.loads(e.read().decode("utf-8"))
            except (ValueError, OSError):
                return {"error": f"http {e.code}"}
        except (OSError, ValueError) as e:
            return {"error": f"{type(e).__name__}: {e}"}

    # -- attempt threads -----------------------------------------------------
    def _spawn_attempt(self, slot, rep, body, deadline, hedged, trace_id):
        t = threading.Thread(
            target=self._attempt,
            args=(slot, rep, body, deadline, hedged, trace_id),
            name="router-attempt",
            daemon=True,
        )
        t.start()

    def _attempt(self, slot, rep, body, deadline, hedged, trace_id):
        try:
            self._attempt_chain(slot, rep, body, deadline, hedged, trace_id)
        except Exception:
            self._rl_log.exception("attempt", "router attempt crashed")
            self._abandon(slot, None)

    def _attempt_chain(self, slot, rep, body, deadline, hedged, trace_id):
        """Forward to ``rep``; on transport failure / 5xx / shed, retry a
        different replica (budget-capped, deadline-bounded)."""
        retries_left = self.max_retries
        current = rep
        last = None
        while True:
            if deadline is not None and deadline.expired():
                self._abandon(slot, last)
                return
            with self._lock:
                current.inflight += 1
            t0 = time.perf_counter()
            outcome = None
            try:
                outcome = self._forward(current, body, deadline, trace_id)
            except OSError as e:
                current.breaker.record_failure()
                # transport failure attributes against the generation the
                # replica was serving — a candidate that wedges its
                # process must show up in the canary's error rate
                self._note_gen_outcome(current, ok=False)
                with self._lock:
                    current.last_error = f"{type(e).__name__}: {e}"
            finally:
                with self._lock:
                    current.inflight -= 1
            if outcome is not None:
                status = outcome[0]
                if status < 500:
                    current.breaker.record_success()
                    if status < 400:
                        ms = (time.perf_counter() - t0) * 1e3
                        self._record_latency(current, ms)
                        self._note_gen_outcome(current, ok=True,
                                               latency_ms=ms)
                        self._complete(slot, outcome, hedged)
                        return
                    if status != 503:
                        # 4xx is the CLIENT's bug: pass through, no retry
                        # (and no generation attribution — the generation
                        # did nothing wrong)
                        self._complete(slot, outcome, hedged)
                        return
                    # 503 = replica shedding/draining: alive, just not for
                    # us — try another replica
                else:
                    current.breaker.record_failure()
                    self._note_gen_outcome(current, ok=False)
                last = outcome
            # retry path.  A transport failure (kill -9, refused connect)
            # retries FREE — the attempt consumed nothing downstream and
            # absorbing it is the availability contract.  An HTTP-level
            # failure (5xx / shed) retries only inside the shared budget:
            # re-offering work to an overloaded fleet is how retry storms
            # start.
            if retries_left <= 0:
                self._abandon(slot, last)
                return
            if outcome is not None and not self.budget.take():
                self._abandon(slot, last)
                return
            with slot.lock:
                tried = set(slot.tried)
            with self._lock:
                # retries keep the primary pick's group affinity (and its
                # routed/fallback accounting) — slot.group is written once
                # before the first attempt spawns, so this read is safe
                nxt = self._pick_locked(tried, group=slot.group)
                if nxt is not None:
                    self._note_pod_pick_locked(nxt, slot.group)
            if nxt is None:
                self._abandon(slot, last)
                return
            with slot.lock:
                slot.tried.add(nxt.url)
            self.counters.inc("retries")
            retries_left -= 1
            current = nxt

    def _complete(self, slot, result, hedged) -> bool:
        with slot.lock:
            slot.outstanding -= 1
            if slot.result is not None:
                return False
            slot.result = result
            slot.winner_hedged = bool(hedged)
        slot.event.set()
        return True

    def _abandon(self, slot, failure) -> None:
        with slot.lock:
            slot.outstanding -= 1
            if failure is not None:
                slot.failure = failure
            done = slot.outstanding <= 0 and slot.result is None
        if done:
            slot.event.set()

    # -- the query route -----------------------------------------------------
    def _serve_query(self, req: Request) -> Response:
        """Tenant edge gate, then replica routing.  With no registry
        attached this is a straight delegation — byte-identical to the
        pre-tenancy router."""
        reg = self._tenants
        if reg is None:
            return self._route_query(req)
        try:
            data = json.loads(req.body) if req.body else None
        except ValueError:
            data = None
        key = _tenancy.extract_access_key(
            req.params, req.headers, data if isinstance(data, dict) else None
        )
        if not key:
            return json_response(401, {"message": "Missing accessKey."})
        spec = reg.authenticate(key)
        if spec is None:
            return json_response(401, {"message": "Invalid accessKey."})
        tenant = spec.tenant_id
        adm = reg.admit(tenant)
        if not adm.ok:
            self.counters.inc("shed")
            return Response(
                status=503,
                body={"message": f"tenant {tenant} shed", "tenant": tenant,
                      "reason": adm.reason},
                headers={"Retry-After": f"{adm.retry_after_s:g}"},
            )
        variant = (
            reg.pick_variant(tenant, data.get("user"))
            if isinstance(data, dict) else None
        )
        ok = False
        t0 = time.perf_counter()
        try:
            resp = self._route_query(req)
            # 4xx and sheds are the contract working; only 5xx server
            # errors feed this tenant's breaker (tenant isolation)
            ok = resp.status < 500 or resp.status == 503
            return resp
        finally:
            reg.release(tenant)
            reg.record_result(
                tenant, variant, ok=ok,
                latency_s=time.perf_counter() - t0,
            )

    def _route_query(self, req: Request) -> Response:
        if self._draining:
            return Response(
                status=503,
                body={"message": "router draining"},
                headers={"Retry-After": f"{self._retry_after_s():g}"},
            )
        deadline = parse_deadline_header(req.headers.get(DEADLINE_HEADER))
        if deadline is None and self.default_deadline_ms is not None:
            deadline = Deadline.after_ms(self.default_deadline_ms)
        if deadline is not None and deadline.expired():
            self.counters.inc("deadline")
            return json_response(
                504, {"message": "deadline expired before routing"}
            )
        trace_id = getattr(req.trace, "request_id", None)
        if self._shadow_capture and req.body:
            # canary verification window: keep a bounded copy of real
            # traffic for the controller's shadow mirror (newest-wins)
            with self._lock:
                if self._shadow_capture:
                    self._shadow_buf.append(req.body)
        self.budget.on_attempt()
        group = self._owner_group(req.body)
        slot = _Slot()
        slot.group = group
        with self._lock:
            rep = self._pick_locked(slot.tried, group=group)
            if rep is not None:
                slot.tried.add(rep.url)
                slot.outstanding = 1
                # owner-group hit or the documented partial-group
                # degrade to fleet-wide — same accounting on every
                # attempt (retries and hedges included)
                self._note_pod_pick_locked(rep, group)
        if rep is None:
            self.counters.inc("shed")
            return Response(
                status=503,
                body={"message": "no replica available"},
                headers={"Retry-After": f"{self._retry_after_s():g}"},
            )
        self._spawn_attempt(slot, rep, req.body, deadline, False, trace_id)
        if self.hedge_enabled:
            delay_s = self.hedge_delay_ms() / 1e3
            if deadline is not None:
                delay_s = min(delay_s, max(deadline.remaining_s(), 0.0))
            if not slot.event.wait(delay_s):
                with slot.lock:
                    tried = set(slot.tried)
                with self._lock:
                    # hedges keep the query's group affinity too
                    hrep = self._pick_locked(tried, group=group)
                if hrep is not None:
                    if self.budget.take():
                        with slot.lock:
                            slot.tried.add(hrep.url)
                            slot.outstanding += 1
                        with self._lock:
                            self._note_pod_pick_locked(hrep, group)
                        self.counters.inc("hedges_fired")
                        self._spawn_attempt(
                            slot, hrep, req.body, deadline, True, trace_id
                        )
                    else:
                        self.counters.inc("hedges_denied")
        wait_s = (
            deadline.remaining_s() + 0.05
            if deadline is not None
            else self.request_timeout_s + 1.0
        )
        if not slot.event.wait(max(wait_s, 0.0)):
            self.counters.inc("deadline")
            return json_response(
                504, {"message": "deadline expired in router"}
            )
        with slot.lock:
            result = slot.result or slot.failure
            hedged_won = slot.winner_hedged and slot.result is not None
        if hedged_won:
            self.counters.inc("hedges_won")
        if result is None:
            self.counters.inc("failed")
            return Response(
                status=502,
                body={"message": "all replicas failed"},
                headers={"Retry-After": f"{self._retry_after_s():g}"},
            )
        status, rbody, rheaders = result
        if status < 400:
            self.counters.inc("ok")
        elif status < 500:
            self.counters.inc("client_error")
        else:
            self.counters.inc("failed")
        out = Response(
            status=status,
            body=rbody,
            content_type="application/json; charset=utf-8",
        )
        retry_after = (rheaders or {}).get("Retry-After")
        if status == 503:
            out.headers["Retry-After"] = (
                retry_after or f"{self._retry_after_s():g}"
            )
        return out

    # -- health checking -----------------------------------------------------
    def _probe_replica(self, rep: ReplicaState):
        """GET /readyz on one replica.  (ok, info-dict-or-None) — ok means
        200 AND the fast path reports warm (admission gates on warm)."""
        try:
            with urllib.request.urlopen(
                rep.url + "/readyz", timeout=self.probe_timeout_ms / 1e3
            ) as r:
                info = json.loads(r.read().decode("utf-8"))
                return bool(info.get("fastpathWarm", True)), info
        except urllib.error.HTTPError as e:
            try:
                info = json.loads(e.read().decode("utf-8"))
            except (ValueError, OSError):
                info = None
            return False, info
        except (OSError, ValueError):
            return False, None

    def _health_loop(self):
        interval_s = self.health_interval_ms / 1e3
        while not self._stop_evt.wait(interval_s):
            self._probe_cycle()

    def _probe_cycle(self):
        results = [(rep, self._probe_replica(rep)) for rep in self._replicas]
        now = time.monotonic()
        with self._lock:
            for rep, (ok, info) in results:
                self._apply_probe_locked(rep, ok, info, now)
            self._check_outliers_locked(now)

    def _apply_probe_locked(self, rep, ok, info, now):
        if info is not None:
            gen = info.get("generation")
            if isinstance(gen, int):
                rep.generation = gen
            iid = info.get("engineInstanceId")
            if isinstance(iid, str) and iid:
                rep.instance_id = iid
            de = info.get("deltaEpoch")
            if isinstance(de, int):
                rep.delta_epoch = de
            pod = info.get("pod")
            if isinstance(pod, dict) and not pod.get("spansProcesses"):
                g, n = pod.get("group"), pod.get("groups")
                rep.pod_group = int(g) if isinstance(g, int) else None
                rep.pod_groups = int(n) if isinstance(n, int) else None
                fp = pod.get("fingerprint")
                rep.pod_fingerprint = fp if isinstance(fp, str) else None
            else:
                # a replica whose serving mesh spans processes is bound
                # by the SPMD lockstep contract: every peer process must
                # dispatch the same batch, so routing it one group's
                # queries would wedge the cross-host collective — never
                # treat it as a routable pod group member
                rep.pod_group = None
                rep.pod_groups = None
                rep.pod_fingerprint = None
            rep.warm = bool(info.get("fastpathWarm", True))
        if ok:
            rep.healthy_streak += 1
            rep.unhealthy_streak = 0
            if (
                rep.state == EJECTED
                and rep.healthy_streak >= self.readmit_after
                and now >= rep.no_readmit_before
            ):
                rep.state = ADMITTED
                rep.admitted_at = now
                rep.ewma_ms = None
                rep.samples = 0
                self.counters.inc("readmissions")
                logger.info("replica %s re-admitted (slow start)", rep.url)
        else:
            rep.healthy_streak = 0
            rep.unhealthy_streak += 1
            if (
                rep.state == ADMITTED
                and rep.unhealthy_streak >= self.eject_after
            ):
                rep.state = EJECTED
                self.counters.inc("ejections_health")
                self._rl_log.warning(
                    "eject", "replica %s ejected (unready %d probes)",
                    rep.url, rep.unhealthy_streak,
                )

    def _check_outliers_locked(self, now):
        """Eject latency outliers: EWMA > ratio × fleet median.  Never
        ejects the last admitted replica."""
        admitted = [r for r in self._replicas if r.state == ADMITTED]
        sampled = [
            r for r in admitted
            if r.ewma_ms is not None and r.samples >= self.outlier_min_samples
        ]
        if len(admitted) < 2 or len(sampled) < 2:
            return
        ordered = sorted(r.ewma_ms for r in sampled)
        median = ordered[len(ordered) // 2]
        if median <= 0:
            return
        alive = len(admitted)
        for r in sampled:
            if alive <= 1:
                return
            if r.ewma_ms > self.outlier_ratio * median:
                r.state = EJECTED
                r.no_readmit_before = now + self.outlier_cooldown_s
                r.healthy_streak = 0
                r.ewma_ms = None
                r.samples = 0
                alive -= 1
                self.counters.inc("ejections_outlier")
                self._rl_log.warning(
                    "outlier", "replica %s ejected as latency outlier "
                    "(> %.1fx fleet median %.1fms)",
                    r.url, self.outlier_ratio, median,
                )

    # -- rolling deploys (fleet attachment) ----------------------------------
    def attach_fleet(self, fleet) -> None:
        """Wire a FleetSupervisor so `/fleet` + `/fleet/roll` are live and
        rolls can drain replicas at the ROUTER before the replica sheds."""
        with self._lock:
            self._fleet = fleet
        if self.telemetry is not None and hasattr(fleet, "stats"):
            _bridges.bridge_fleet(self.telemetry.registry, fleet.stats)

    def attach_autoscaler(self, scaler) -> None:
        """Wire an Autoscaler: its decisions surface on `/fleet` and as
        ``pio_autoscaler_*`` families on this router's /metrics."""
        with self._lock:
            self._autoscaler = scaler
        if self.telemetry is not None:
            _bridges.bridge_autoscaler(self.telemetry.registry, scaler.stats)

    def attach_tenants(self, registry) -> None:
        """Wire a TenantRegistry: the router authenticates and fair-share
        admits per tenant BEFORE picking a replica, so one tenant
        saturating its quota sheds here — at the fleet edge — and its
        traffic never occupies replica slots another tenant needs.
        Per-tenant sheds/pressure surface on signals() (the autoscaler's
        input) and as pio_tenant_* families on this router's /metrics."""
        with self._lock:
            self._tenants = registry
        if self.telemetry is not None:
            _bridges.bridge_tenancy(self.telemetry.registry, registry.stats)

    def attach_canary(self, controller) -> None:
        """Wire a CanaryController: `/canary/*` goes live, its state
        surfaces on stats()/signals(), and ``pio_canary_*`` families
        register on this router's /metrics."""
        with self._lock:
            self._canary = controller
        if self.telemetry is not None and hasattr(controller, "stats"):
            _bridges.bridge_canary(self.telemetry.registry, controller.stats)

    def set_replica_draining(self, url: str, draining: bool) -> None:
        """Roll orchestration: stop routing to a replica BEFORE its
        process drains, re-open it for probing afterwards."""
        url = url.rstrip("/")
        with self._lock:
            for rep in self._replicas:
                if rep.url != url:
                    continue
                if draining:
                    rep.state = DRAINING
                else:
                    # readmission goes through the health gate: the new
                    # process must prove /readyz + warm first
                    rep.state = EJECTED
                    rep.healthy_streak = 0
                    rep.unhealthy_streak = 0
                    rep.no_readmit_before = 0.0

    # -- stats / metrics -----------------------------------------------------
    def stats(self) -> dict:
        now = time.monotonic()
        with self._lock:
            replicas = [
                {
                    "url": r.url,
                    "state": r.state,
                    "inflight": r.inflight,
                    "weight": (
                        self._weight(r, now) if r.state == ADMITTED else 0.0
                    ),
                    "ewmaMs": r.ewma_ms,
                    "generation": r.generation,
                    "instanceId": r.instance_id,
                    "deltaEpoch": r.delta_epoch,
                    "podGroup": r.pod_group,
                    "warm": r.warm,
                    "lastError": r.last_error or None,
                    "breaker": r.breaker.stats(),
                }
                for r in self._replicas
            ]
            hedge_delay = self._hedge_delay_ms
            rolling = self._rolling
            pod_groups = self._pod_group_count_locked()
            pod_routed = {str(g): n for g, n in self._pod_routed.items()}
            canary = self._canary
        return {
            "generations": self.generation_stats(),
            "canary": canary.stats() if canary is not None else None,
            "status": "alive",
            "replicas": replicas,
            "pod": {
                "groups": pod_groups,
                "queriesRouted": pod_routed,
                "fallbackBroadcasts": self.counters.get("pod_fallback"),
            }
            if pod_groups is not None or pod_routed
            else None,
            "available": sum(
                1 for r in replicas if r["state"] == ADMITTED
            ),
            "counters": self.counters.snapshot(),
            "hedge": {
                "enabled": self.hedge_enabled,
                "delayMs": hedge_delay,
                "budgetTokens": self.budget.tokens(),
            },
            "rolling": rolling,
            "deltaPropagated": dict(self._delta_propagated),
        }

    def _resilience_stats(self) -> dict:
        return {
            "retries": self.counters.get("retries"),
            "retry_budget_tokens": self.budget.tokens(),
            "breakers": [r.breaker.stats() for r in self._replicas],
        }

    def _pod_stats(self) -> Optional[dict]:
        """The pod block for ``bridge_pod`` — None until any replica
        advertises a pod map (the families then stay absent, same
        presence contract as every other bridge)."""
        with self._lock:
            groups = self._pod_group_count_locked()
            routed = dict(self._pod_routed)
        if groups is None and not routed:
            return None
        return {
            "host_groups": groups,
            "queries_routed": routed,
            "fallback_broadcasts": self.counters.get("pod_fallback"),
        }

    def _register_metrics(self) -> None:
        reg = self.telemetry.registry
        _bridges.bridge_resilience(
            reg, self._resilience_stats, prefix="pio_router"
        )
        _bridges.bridge_pod(reg, self._pod_stats)

        def _router_families():
            now = time.monotonic()
            with self._lock:
                reps = [
                    (
                        r.url,
                        STATE_VALUES.get(r.state, -1.0),
                        float(r.inflight),
                        self._weight(r, now) if r.state == ADMITTED else 0.0,
                        float(r.generation or 0),
                    )
                    for r in self._replicas
                ]
                hedge_delay = self._hedge_delay_ms
                propagated = dict(self._delta_propagated)
            snap = self.counters.snapshot()
            F = _bridges.Family
            lbl = [(("replica", url),) for url, *_ in reps]
            return [
                F("pio_router_replicas", "gauge",
                  "Replicas configured behind this router.",
                  [("", (), float(len(reps)))]),
                F("pio_router_replicas_available", "gauge",
                  "Replicas currently admitted for traffic.",
                  [("", (), float(sum(1 for r in reps if r[1] == 0.0)))]),
                F("pio_router_replica_state", "gauge",
                  "Per-replica admission state: 0 admitted, 1 ejected, "
                  "2 draining.",
                  [("", lbl[i], reps[i][1]) for i in range(len(reps))]),
                F("pio_router_replica_inflight", "gauge",
                  "Requests in flight per replica.",
                  [("", lbl[i], reps[i][2]) for i in range(len(reps))]),
                F("pio_router_replica_weight", "gauge",
                  "Slow-start weight (0.1 → 1.0 after re-admission).",
                  [("", lbl[i], reps[i][3]) for i in range(len(reps))]),
                F("pio_router_replica_generation", "gauge",
                  "Model generation each replica reports on /readyz.",
                  [("", lbl[i], reps[i][4]) for i in range(len(reps))]),
                F("pio_router_requests_total", "counter",
                  "Routed requests by final outcome.",
                  [
                      ("", (("outcome", "ok"),), float(snap.get("ok", 0))),
                      ("", (("outcome", "client_error"),),
                       float(snap.get("client_error", 0))),
                      ("", (("outcome", "failed"),),
                       float(snap.get("failed", 0))),
                      ("", (("outcome", "shed"),),
                       float(snap.get("shed", 0))),
                      ("", (("outcome", "deadline"),),
                       float(snap.get("deadline", 0))),
                  ]),
                F("pio_router_hedges_total", "counter",
                  "Hedged attempts by outcome: fired (second replica "
                  "asked), won (hedge answered first), denied (budget "
                  "refused the hedge).",
                  [
                      ("", (("outcome", "fired"),),
                       float(snap.get("hedges_fired", 0))),
                      ("", (("outcome", "won"),),
                       float(snap.get("hedges_won", 0))),
                      ("", (("outcome", "denied"),),
                       float(snap.get("hedges_denied", 0))),
                  ]),
                F("pio_router_ejections_total", "counter",
                  "Replicas ejected, by reason.",
                  [
                      ("", (("reason", "health"),),
                       float(snap.get("ejections_health", 0))),
                      ("", (("reason", "outlier"),),
                       float(snap.get("ejections_outlier", 0))),
                  ]),
                F("pio_router_readmissions_total", "counter",
                  "Ejected replicas re-admitted after recovery probes.",
                  [("", (), float(snap.get("readmissions", 0)))]),
                F("pio_router_hedge_delay_ms", "gauge",
                  "Current hedge trigger delay (rolling latency "
                  "quantile, floored at PIO_HEDGE_MIN_MS).",
                  [("", (), float(hedge_delay))]),
                F("pio_delta_propagated_total", "counter",
                  "Per-replica delta push acknowledgements by outcome "
                  "(applied, noop, refused, error).",
                  [("", (("outcome", k),), float(v))
                   for k, v in sorted(propagated.items())]),
            ]

        reg.register_collector(_router_families)

    # -- routes --------------------------------------------------------------
    def _register_routes(self):
        svc = self.service

        @svc.route("GET", r"/")
        def index(req: Request):
            return json_response(200, self.stats())

        @svc.route("GET", r"/healthz")
        def healthz(req: Request):
            return json_response(200, {"status": "ok"})

        @svc.route("GET", r"/readyz")
        def readyz(req: Request):
            available = self.available_count()
            body = {
                "replicas": len(self._replicas),
                "available": available,
                "draining": self._draining,
            }
            if self._draining:
                body["status"] = "draining"
            elif available == 0:
                body["status"] = "no replica available"
            else:
                body["status"] = "ready"
                return json_response(200, body)
            return Response(
                status=503, body=body,
                headers={"Retry-After": f"{self._retry_after_s():g}"},
            )

        @svc.route("POST", r"/queries\.json")
        def queries(req: Request):
            return self._serve_query(req)

        @svc.route("GET", r"/fleet")
        def fleet_status(req: Request):
            with self._lock:
                fleet = self._fleet
                scaler = self._autoscaler
                rolling = self._rolling
            if fleet is None:
                return json_response(
                    404, {"message": "no fleet supervisor attached"}
                )
            body = {"rolling": rolling, "fleet": fleet.status()}
            if scaler is not None:
                body["autoscaler"] = scaler.stats()
            return json_response(200, body)

        @svc.route("POST", r"/fleet/roll")
        def fleet_roll(req: Request):
            with self._lock:
                fleet = self._fleet
                if fleet is None:
                    return json_response(
                        404, {"message": "no fleet supervisor attached"}
                    )
                if self._rolling:
                    return json_response(
                        409, {"message": "a roll is already in progress"}
                    )
                self._rolling = True

            def _do_roll():
                try:
                    fleet.roll()
                except Exception:
                    logger.exception("fleet roll failed")
                finally:
                    with self._lock:
                        self._rolling = False

            threading.Thread(
                target=_do_roll, name="fleet-roll", daemon=True
            ).start()
            return json_response(202, {"message": "roll started"})

        @svc.route("GET", r"/canary")
        def canary_status(req: Request):
            with self._lock:
                canary = self._canary
            if canary is None:
                return json_response(
                    404, {"message": "no canary controller attached"}
                )
            return json_response(200, canary.stats())

        @svc.route("POST", r"/canary/start")
        def canary_start(req: Request):
            with self._lock:
                canary = self._canary
            if canary is None:
                return json_response(
                    404, {"message": "no canary controller attached"}
                )
            try:
                data = json.loads(req.body) if req.body else {}
            except ValueError:
                data = {}
            try:
                started = canary.start_canary(
                    instance_id=(data or {}).get("instanceId"),
                    force=bool((data or {}).get("force")),
                )
            except ValueError as e:
                return json_response(409, {"message": str(e)})
            if not started:
                return json_response(
                    409, {"message": "a canary is already in flight"}
                )
            return json_response(202, canary.stats())

        @svc.route("POST", r"/canary/promote")
        def canary_promote(req: Request):
            with self._lock:
                canary = self._canary
            if canary is None:
                return json_response(
                    404, {"message": "no canary controller attached"}
                )
            if not canary.request_promote():
                return json_response(
                    409, {"message": "no canary verifying"}
                )
            return json_response(202, canary.stats())

        @svc.route("POST", r"/canary/abort")
        def canary_abort(req: Request):
            with self._lock:
                canary = self._canary
            if canary is None:
                return json_response(
                    404, {"message": "no canary controller attached"}
                )
            if not canary.request_abort():
                return json_response(409, {"message": "no canary active"})
            return json_response(202, canary.stats())

        @svc.route("GET", r"/canary/quarantine")
        def canary_quarantine(req: Request):
            with self._lock:
                canary = self._canary
            if canary is None:
                return json_response(
                    404, {"message": "no canary controller attached"}
                )
            return json_response(200, {"receipts": canary.quarantine()})

        @svc.route("POST", r"/canary/quarantine/release")
        def canary_release(req: Request):
            with self._lock:
                canary = self._canary
            if canary is None:
                return json_response(
                    404, {"message": "no canary controller attached"}
                )
            try:
                data = json.loads(req.body) if req.body else {}
            except ValueError:
                data = {}
            iid = (data or {}).get("instanceId")
            if not iid:
                return json_response(
                    400, {"message": "instanceId required"}
                )
            released = canary.release_quarantine(iid)
            return json_response(
                200 if released else 404,
                {"released": released, "instanceId": iid},
            )

        @svc.route("POST", r"/stop")
        def stop_route(req: Request):
            def _stop():
                time.sleep(0.3)  # let the response flush first
                self.shutdown()

            threading.Thread(target=_stop, daemon=True).start()
            return json_response(200, {"message": "Shutting down."})

    # -- lifecycle -----------------------------------------------------------
    def start(self, host: str = "0.0.0.0", port: int = 8000) -> int:
        actual = self.service.start(host, port)
        with self._lock:
            self._health_thread = threading.Thread(
                target=self._health_loop, name="router-health", daemon=True
            )
        self._health_thread.start()
        logger.info(
            "router listening on %s:%s (%d replicas)",
            host, actual, len(self._replicas),
        )
        return actual

    def drain(self) -> None:
        """SIGTERM contract (cli._install_drain_handler): same as
        shutdown — the router holds no queued work of its own; in-flight
        forwards ride daemon attempt threads to completion."""
        self.shutdown()

    def shutdown(self) -> None:
        """Drain: stop admitting, stop probing, stop the fleet children,
        stop listening."""
        with self._lock:
            self._draining = True
            fleet = self._fleet
            canary = self._canary
        self._stop_evt.set()
        if canary is not None:
            canary.stop()
        if fleet is not None:
            fleet.stop()
        self.service.stop()

    # used by tests to stop without killing fleet children
    def stop(self) -> None:
        with self._lock:
            self._draining = True
        self._stop_evt.set()
        self.service.stop()
