"""Fleet supervisor: replica subprocess lifecycle + zero-downtime rolls.

The :class:`~predictionio_tpu.serving.router.Router` decides where
traffic goes; this module decides which processes exist.  It spawns N
query-server replica subprocesses, restarts crashed ones with
exponential backoff (reset after a healthy period), and orchestrates
**rolling deploys**: one replica at a time it

1. marks the replica draining at the ROUTER (traffic routes away
   first — the replica's own shed path is only the safety net),
2. drains the process via the PR 5 ``POST /stop`` path,
3. restarts it — the new process deploys the latest COMPLETED model
   generation through the unchanged atomic-publish/LKG machinery,
4. verifies ``GET /readyz`` answers 200 **and warm**
   (``fastpathWarm``), and
5. re-opens the replica at the router (readmission still goes through
   the health gate + slow start), then moves on.

``pio deploy --fleet N`` builds one of these around child ``pio
deploy`` processes; ``pio fleet roll`` triggers ``roll()`` through the
router's ``POST /fleet/roll``.

The fleet is **elastic** (ISSUE 11): :meth:`add_replica` spawns one
more replica on a freshly allocated port and registers it EJECTED at
the router (admission rides the health gate + slow start), and
:meth:`remove_replica` retires one with the same drain-before-kill
sequence a roll uses.  ``_ops_lock`` serializes rolls against
scale-downs so the same process is never stopped twice and a drained
replica is never orphaned — the roll-vs-scale-down race has dedicated
test coverage.  The monitor loop doubles as the preemption chaos site:
each tick consults ``crash:fleet:replica`` through
:func:`~predictionio_tpu.common.faults.kill_point`, so a seeded fault
plan can SIGKILL a random replica *while* the fleet is scaling.

The supervisor is process-management only: it never sits on the query
path.  Spawning is delegated to a ``spawn_fn(port) -> subprocess.Popen``
so tests can run replicas from a ``python -c`` script and the CLI can
re-exec itself.
"""

from __future__ import annotations

import json
import logging
import os
import socket
import subprocess
import threading
import time
import urllib.error
import urllib.request
from typing import Callable, Optional

from predictionio_tpu.common import faults as _faults

logger = logging.getLogger(__name__)

#: Fault site the monitor loop exposes for preemption chaos: a matching
#: ``crash`` rule SIGKILLs one live replica per firing (seeded victim).
PREEMPT_SITE = "crash:fleet:replica"


def _env_num(name: str, default, cast):
    try:
        return cast(os.environ[name])
    except (KeyError, ValueError, TypeError):
        return default


class ReplicaProc:
    """One supervised replica slot.  Fields guarded by the supervisor's
    ``_lock``."""

    def __init__(self, port: int, url: str):
        self.port = port
        self.url = url
        self.proc: Optional[subprocess.Popen] = None
        self.restarts = 0
        self.backoff_s = 0.0
        self.next_restart_at = 0.0
        self.started_at = 0.0
        self.expected_down = False  # a roll is restarting it on purpose
        self.removing = False  # a scale-down is retiring it for good


class FleetSupervisor:
    """Spawn/respawn N replica subprocesses; orchestrate rolling deploys."""

    def __init__(
        self,
        spawn_fn: Callable[[int], subprocess.Popen],
        ports: list[int],
        host: str = "127.0.0.1",
        router=None,
        port_allocator: Optional[Callable[[], int]] = None,
    ):
        self.spawn_fn = spawn_fn
        self.host = host
        self.router = router
        self.port_allocator = port_allocator
        self._procs = [
            ReplicaProc(p, f"http://{host}:{p}") for p in ports
        ]
        self._lock = threading.Lock()
        # serializes whole-replica operations (roll step, scale-down) so
        # concurrent ops can never double-stop or orphan one process;
        # always acquired BEFORE _lock, never the other way around
        self._ops_lock = threading.Lock()
        self._stop_evt = threading.Event()
        self._monitor_thread: Optional[threading.Thread] = None
        self._transitions = {"up": 0, "down": 0}
        # canary mutual exclusion (guarded by _lock): while a canary is
        # in flight, every spawn is pinned to the verified BASELINE
        # generation (autoscaler scale-ups and crash restarts must never
        # come up on the unverified candidate), and the canary replica's
        # url is protected from scale-down (removing the one replica
        # under verification would silently end the experiment)
        self._spawn_pin: Optional[str] = None
        self._protected: set[str] = set()
        self.restart_backoff_s = _env_num(
            "PIO_FLEET_RESTART_BACKOFF_S", 0.5, float
        )
        self.restart_backoff_max_s = _env_num(
            "PIO_FLEET_RESTART_BACKOFF_MAX_S", 10.0, float
        )
        self.stop_timeout_s = _env_num("PIO_FLEET_STOP_TIMEOUT_S", 10.0, float)
        self.roll_timeout_s = _env_num("PIO_FLEET_ROLL_TIMEOUT_S", 60.0, float)

    def urls(self) -> list[str]:
        return [rp.url for rp in self._procs]

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> None:
        with self._lock:
            for rp in self._procs:
                self._spawn_locked(rp)
            self._monitor_thread = threading.Thread(
                target=self._monitor_loop, name="fleet-monitor", daemon=True
            )
        self._monitor_thread.start()

    def _spawn_locked(self, rp: ReplicaProc) -> None:
        # the pin rides the process environment: children inherit it at
        # spawn (cli re-exec) and the query server honors it at cold
        # start only — an explicit /reload?instanceId= still overrides
        pin = self._spawn_pin
        if pin:
            os.environ["PIO_PIN_INSTANCE"] = pin
        try:
            rp.proc = self.spawn_fn(rp.port)
        finally:
            if pin:
                os.environ.pop("PIO_PIN_INSTANCE", None)
        rp.started_at = time.monotonic()
        rp.expected_down = False
        self._transitions["up"] += 1
        logger.info(
            "fleet: replica on port %d spawned (pid %s)",
            rp.port, rp.proc.pid,
        )

    def _monitor_loop(self):
        while not self._stop_evt.wait(0.25):
            self._check_children()

    def _check_children(self) -> None:
        """Restart crashed replicas with exponential backoff; a replica
        that stayed up past its backoff window resets to the base."""
        self._preempt_point()
        now = time.monotonic()
        with self._lock:
            for rp in self._procs:
                if rp.proc is None or rp.expected_down:
                    continue
                if rp.proc.poll() is None:
                    # alive: a healthy stretch forgives past crashes
                    if (
                        rp.backoff_s
                        and now - rp.started_at > self.restart_backoff_max_s
                    ):
                        rp.backoff_s = 0.0
                    continue
                if rp.next_restart_at == 0.0:
                    # first observation of this crash: restart after the
                    # current backoff (0 after a healthy run), then double
                    # it for the next crash
                    delay = rp.backoff_s
                    rp.backoff_s = min(
                        max(rp.backoff_s * 2, self.restart_backoff_s),
                        self.restart_backoff_max_s,
                    )
                    rp.next_restart_at = now + delay
                    self._transitions["down"] += 1
                    logger.warning(
                        "fleet: replica on port %d exited rc=%s; restart "
                        "in %.1fs", rp.port, rp.proc.returncode, delay,
                    )
                if now >= rp.next_restart_at:
                    rp.restarts += 1
                    rp.next_restart_at = 0.0
                    self._spawn_locked(rp)

    def _preempt_point(self) -> None:
        """Preemption chaos site: let a seeded ``crash:fleet:replica``
        fault rule SIGKILL one live replica.  The monitor tick is the
        ordinal clock, so ``after=N`` schedules a kill ~N*0.25s in."""
        if _faults.active() is None:
            return
        with self._lock:
            pids = [
                rp.proc.pid
                for rp in self._procs
                if rp.proc is not None
                and not rp.expected_down
                and rp.proc.poll() is None
            ]
        pid = _faults.kill_point(PREEMPT_SITE, pids)
        if pid is not None:
            logger.warning(
                "fault shim preempted replica pid %d (kill -9)", pid
            )

    # -- canary mutual exclusion ---------------------------------------------
    def set_spawn_pin(self, instance_id: Optional[str]) -> None:
        """While set, children spawned by this supervisor (scale-ups,
        crash restarts) cold-start pinned to ``instance_id`` — the canary
        controller pins the BASELINE for the verification window so a
        mid-canary scale-up can never come up on the unverified
        candidate.  ``None`` clears the pin."""
        with self._lock:
            self._spawn_pin = instance_id or None

    def protect_replica(self, url: str, protected: bool) -> None:
        """Exempt one replica from scale-down (the canary replica during
        its verification window); clearing re-enables removal."""
        url = url.rstrip("/")
        with self._lock:
            if protected:
                self._protected.add(url)
            else:
                self._protected.discard(url)

    # -- elastic scaling -----------------------------------------------------
    def _alloc_port(self) -> int:
        if self.port_allocator is not None:
            return self.port_allocator()
        s = socket.socket()
        try:
            s.bind((self.host, 0))
            return s.getsockname()[1]
        finally:
            s.close()

    def add_replica(self) -> Optional[dict]:
        """Scale up by one: spawn a replica on a fresh port and register
        it at the router (EJECTED — the health gate + slow start admit
        it).  Returns the new slot, or None if the spawn failed."""
        with self._ops_lock:
            port = self._alloc_port()
            rp = ReplicaProc(port, f"http://{self.host}:{port}")
            try:
                with self._lock:
                    self._spawn_locked(rp)
                    self._procs.append(rp)
            except Exception:
                logger.exception(
                    "fleet: scale-up spawn on port %d failed", port
                )
                return None
            if self.router is not None:
                self.router.add_replica(rp.url)
            return {"port": rp.port, "url": rp.url}

    def remove_replica(self, url: Optional[str] = None) -> Optional[dict]:
        """Scale down by one: drain-before-kill (router DRAINING →
        ``POST /stop`` → reap), then forget the slot and deregister the
        URL at the router.  Picks the newest removable replica unless
        ``url`` names one.  Returns the retired slot, or None when the
        fleet has nothing removable (e.g. everything is mid-roll)."""
        with self._ops_lock:
            with self._lock:
                cands = [
                    rp for rp in self._procs
                    if not rp.expected_down and not rp.removing
                    and rp.url not in self._protected
                ]
                if url is not None:
                    cands = [rp for rp in cands if rp.url == url]
                if not cands:
                    return None
                rp = cands[-1]  # newest first: keep long-warm replicas
                rp.removing = True
                rp.expected_down = True  # monitor must not respawn it
                proc = rp.proc
            try:
                if self.router is not None:
                    self.router.set_replica_draining(rp.url, True)
                if proc is not None and proc.poll() is None:
                    self._post_stop(rp.url)
                    try:
                        proc.wait(timeout=self.stop_timeout_s)
                    except subprocess.TimeoutExpired:
                        logger.warning(
                            "fleet: replica on port %d ignored scale-down "
                            "drain; killing", rp.port,
                        )
                        proc.kill()
                        try:
                            proc.wait(timeout=5)
                        except subprocess.TimeoutExpired:
                            pass
            finally:
                with self._lock:
                    self._procs = [p for p in self._procs if p is not rp]
                    self._transitions["down"] += 1
                if self.router is not None:
                    self.router.remove_replica(rp.url)
            logger.info("fleet: replica on port %d scaled down", rp.port)
            return {"port": rp.port, "url": rp.url}

    # -- rolling deploy ------------------------------------------------------
    def roll(self) -> dict:
        """Drain → restart → verify each replica in sequence.  Returns a
        per-replica report; raises nothing (a failed replica is reported
        and the roll continues — partial fleets beat dead rolls).

        Target resolution happens in each restarted CHILD: it cold-starts
        on the newest COMPLETED generation via
        ``workflow.get_latest_completed_instance``, which skips
        quarantined instance ids — so a roll can never re-deploy a
        generation a canary rolled back."""
        with self._lock:
            procs = [rp for rp in self._procs if not rp.removing]
        report = []
        for rp in procs:
            entry = {"port": rp.port, "url": rp.url}
            try:
                if self._roll_one(rp):
                    entry["ok"] = True
                else:
                    # a concurrent scale-down retired it first — nothing
                    # to roll, and definitely nothing to stop twice
                    entry["ok"] = True
                    entry["skipped"] = "scaled down"
            except Exception as e:
                entry["ok"] = False
                entry["error"] = f"{type(e).__name__}: {e}"
                logger.exception(
                    "fleet roll: replica on port %d failed", rp.port
                )
            report.append(entry)
        return {"replicas": report, "ok": all(e["ok"] for e in report)}

    def _roll_one(self, rp: ReplicaProc) -> bool:
        """Roll one replica; returns False when a concurrent scale-down
        already retired it (the ops lock makes the check authoritative:
        whoever holds it owns the replica's process end to end)."""
        with self._ops_lock:
            return self._roll_one_owned(rp)

    def _roll_one_owned(self, rp: ReplicaProc) -> bool:
        deadline = time.monotonic() + self.roll_timeout_s
        with self._lock:
            if rp.removing or rp not in self._procs:
                return False
        if self.router is not None:
            self.router.set_replica_draining(rp.url, True)
        with self._lock:
            rp.expected_down = True
            proc = rp.proc
        try:
            if proc is not None and proc.poll() is None:
                self._post_stop(rp.url)
                try:
                    proc.wait(timeout=self.stop_timeout_s)
                except subprocess.TimeoutExpired:
                    logger.warning(
                        "fleet roll: replica on port %d ignored drain; "
                        "killing", rp.port,
                    )
                    proc.kill()
                    proc.wait(timeout=5)
            with self._lock:
                self._spawn_locked(rp)
            self._wait_ready(rp.url, deadline)
        finally:
            with self._lock:
                rp.expected_down = False
            if self.router is not None:
                self.router.set_replica_draining(rp.url, False)
        if self.router is not None:
            self._wait_admitted(rp.url, deadline)
        return True

    def _post_stop(self, url: str) -> None:
        try:
            with urllib.request.urlopen(
                urllib.request.Request(url + "/stop", method="POST"),
                timeout=5,
            ) as r:
                r.read()
        except OSError:
            # the process may tear the socket down mid-response, or be
            # dead already — either way the wait() below decides
            pass

    def _wait_ready(self, url: str, deadline: float) -> None:
        """Poll /readyz until 200 + warm; raise on timeout.

        Under streaming (PIO_STREAMING=1) a restarted or freshly spawned
        replica answers 503 ``delta catch-up`` until it has replayed the
        sealed delta log to the fleet's epoch — this wait is what keeps a
        behind replica out of rotation until it has caught up.
        """
        last = "no probe yet"
        while time.monotonic() < deadline:
            try:
                with urllib.request.urlopen(url + "/readyz", timeout=2) as r:
                    info = json.loads(r.read().decode("utf-8"))
                if info.get("fastpathWarm", True):
                    return
                last = "ready but not warm"
            except urllib.error.HTTPError as e:
                # surface WHY it is held out (draining / delta catch-up /
                # overloaded) instead of a bare status code
                try:
                    status = json.loads(
                        e.read().decode("utf-8")).get("status")
                except (ValueError, OSError, AttributeError):
                    status = None
                last = (
                    f"readyz {e.code} ({status})" if status
                    else f"readyz {e.code}"
                )
            except (OSError, ValueError) as e:
                last = f"{type(e).__name__}: {e}"
            time.sleep(0.1)
        raise TimeoutError(f"replica {url} never became ready ({last})")

    def _wait_admitted(self, url: str, deadline: float) -> None:
        """Wait for the router's health gate to readmit the replica so the
        fleet is back to full strength before the next one drains."""
        url = url.rstrip("/")
        while time.monotonic() < deadline:
            for rep in self.router.stats()["replicas"]:
                if rep["url"] == url and rep["state"] == "admitted":
                    return
            time.sleep(0.05)
        logger.warning(
            "fleet roll: %s not readmitted inside the roll budget", url
        )

    # -- status / shutdown ---------------------------------------------------
    def status(self) -> dict:
        with self._lock:
            return {
                "replicas": [
                    {
                        "port": rp.port,
                        "url": rp.url,
                        "pid": rp.proc.pid if rp.proc else None,
                        "alive": (
                            rp.proc is not None and rp.proc.poll() is None
                        ),
                        "restarts": rp.restarts,
                        "backoffMs": round(rp.backoff_s * 1e3, 1),
                        "rolling": rp.expected_down,
                        "removing": rp.removing,
                    }
                    for rp in self._procs
                ],
                "transitions": dict(self._transitions),
                "spawnPin": self._spawn_pin,
                "protected": sorted(self._protected),
            }

    def stats(self) -> dict:
        """Flat snapshot for the ``pio_fleet_*`` metrics bridge."""
        st = self.status()
        reps = st["replicas"]
        return {
            "replicas": len(reps),
            "alive": sum(1 for r in reps if r["alive"]),
            "restarts": sum(r["restarts"] for r in reps),
            "backoffMs": {r["url"]: r["backoffMs"] for r in reps},
            "transitions": st["transitions"],
        }

    def stop(self) -> None:
        """Stop supervising and tear the children down (drain first,
        then kill what lingers)."""
        self._stop_evt.set()
        with self._lock:
            procs = [rp.proc for rp in self._procs if rp.proc is not None]
            for rp in self._procs:
                rp.expected_down = True
        for rp in self._procs:
            self._post_stop(rp.url)
        for proc in procs:
            try:
                proc.wait(timeout=self.stop_timeout_s)
            except subprocess.TimeoutExpired:
                proc.kill()
                try:
                    proc.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    pass  # unkillable (D-state); nothing more to do
