"""Fleet supervisor: replica subprocess lifecycle + zero-downtime rolls.

The :class:`~predictionio_tpu.serving.router.Router` decides where
traffic goes; this module decides which processes exist.  It spawns N
query-server replica subprocesses, restarts crashed ones with
exponential backoff (reset after a healthy period), and orchestrates
**rolling deploys**: one replica at a time it

1. marks the replica draining at the ROUTER (traffic routes away
   first — the replica's own shed path is only the safety net),
2. drains the process via the PR 5 ``POST /stop`` path,
3. restarts it — the new process deploys the latest COMPLETED model
   generation through the unchanged atomic-publish/LKG machinery,
4. verifies ``GET /readyz`` answers 200 **and warm**
   (``fastpathWarm``), and
5. re-opens the replica at the router (readmission still goes through
   the health gate + slow start), then moves on.

``pio deploy --fleet N`` builds one of these around child ``pio
deploy`` processes; ``pio fleet roll`` triggers ``roll()`` through the
router's ``POST /fleet/roll``.

The supervisor is process-management only: it never sits on the query
path.  Spawning is delegated to a ``spawn_fn(port) -> subprocess.Popen``
so tests can run replicas from a ``python -c`` script and the CLI can
re-exec itself.
"""

from __future__ import annotations

import json
import logging
import os
import subprocess
import threading
import time
import urllib.error
import urllib.request
from typing import Callable, Optional

logger = logging.getLogger(__name__)


def _env_num(name: str, default, cast):
    try:
        return cast(os.environ[name])
    except (KeyError, ValueError, TypeError):
        return default


class ReplicaProc:
    """One supervised replica slot.  Fields guarded by the supervisor's
    ``_lock``."""

    def __init__(self, port: int, url: str):
        self.port = port
        self.url = url
        self.proc: Optional[subprocess.Popen] = None
        self.restarts = 0
        self.backoff_s = 0.0
        self.next_restart_at = 0.0
        self.started_at = 0.0
        self.expected_down = False  # a roll is restarting it on purpose


class FleetSupervisor:
    """Spawn/respawn N replica subprocesses; orchestrate rolling deploys."""

    def __init__(
        self,
        spawn_fn: Callable[[int], subprocess.Popen],
        ports: list[int],
        host: str = "127.0.0.1",
        router=None,
    ):
        self.spawn_fn = spawn_fn
        self.host = host
        self.router = router
        self._procs = [
            ReplicaProc(p, f"http://{host}:{p}") for p in ports
        ]
        self._lock = threading.Lock()
        self._stop_evt = threading.Event()
        self._monitor_thread: Optional[threading.Thread] = None
        self.restart_backoff_s = _env_num(
            "PIO_FLEET_RESTART_BACKOFF_S", 0.5, float
        )
        self.restart_backoff_max_s = _env_num(
            "PIO_FLEET_RESTART_BACKOFF_MAX_S", 10.0, float
        )
        self.stop_timeout_s = _env_num("PIO_FLEET_STOP_TIMEOUT_S", 10.0, float)
        self.roll_timeout_s = _env_num("PIO_FLEET_ROLL_TIMEOUT_S", 60.0, float)

    def urls(self) -> list[str]:
        return [rp.url for rp in self._procs]

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> None:
        with self._lock:
            for rp in self._procs:
                self._spawn_locked(rp)
            self._monitor_thread = threading.Thread(
                target=self._monitor_loop, name="fleet-monitor", daemon=True
            )
        self._monitor_thread.start()

    def _spawn_locked(self, rp: ReplicaProc) -> None:
        rp.proc = self.spawn_fn(rp.port)
        rp.started_at = time.monotonic()
        rp.expected_down = False
        logger.info(
            "fleet: replica on port %d spawned (pid %s)",
            rp.port, rp.proc.pid,
        )

    def _monitor_loop(self):
        while not self._stop_evt.wait(0.25):
            self._check_children()

    def _check_children(self) -> None:
        """Restart crashed replicas with exponential backoff; a replica
        that stayed up past its backoff window resets to the base."""
        now = time.monotonic()
        with self._lock:
            for rp in self._procs:
                if rp.proc is None or rp.expected_down:
                    continue
                if rp.proc.poll() is None:
                    # alive: a healthy stretch forgives past crashes
                    if (
                        rp.backoff_s
                        and now - rp.started_at > self.restart_backoff_max_s
                    ):
                        rp.backoff_s = 0.0
                    continue
                if rp.next_restart_at == 0.0:
                    # first observation of this crash: restart after the
                    # current backoff (0 after a healthy run), then double
                    # it for the next crash
                    delay = rp.backoff_s
                    rp.backoff_s = min(
                        max(rp.backoff_s * 2, self.restart_backoff_s),
                        self.restart_backoff_max_s,
                    )
                    rp.next_restart_at = now + delay
                    logger.warning(
                        "fleet: replica on port %d exited rc=%s; restart "
                        "in %.1fs", rp.port, rp.proc.returncode, delay,
                    )
                if now >= rp.next_restart_at:
                    rp.restarts += 1
                    rp.next_restart_at = 0.0
                    self._spawn_locked(rp)

    # -- rolling deploy ------------------------------------------------------
    def roll(self) -> dict:
        """Drain → restart → verify each replica in sequence.  Returns a
        per-replica report; raises nothing (a failed replica is reported
        and the roll continues — partial fleets beat dead rolls)."""
        report = []
        for rp in self._procs:
            entry = {"port": rp.port, "url": rp.url}
            try:
                self._roll_one(rp)
                entry["ok"] = True
            except Exception as e:
                entry["ok"] = False
                entry["error"] = f"{type(e).__name__}: {e}"
                logger.exception(
                    "fleet roll: replica on port %d failed", rp.port
                )
            report.append(entry)
        return {"replicas": report, "ok": all(e["ok"] for e in report)}

    def _roll_one(self, rp: ReplicaProc) -> None:
        deadline = time.monotonic() + self.roll_timeout_s
        if self.router is not None:
            self.router.set_replica_draining(rp.url, True)
        with self._lock:
            rp.expected_down = True
            proc = rp.proc
        try:
            if proc is not None and proc.poll() is None:
                self._post_stop(rp.url)
                try:
                    proc.wait(timeout=self.stop_timeout_s)
                except subprocess.TimeoutExpired:
                    logger.warning(
                        "fleet roll: replica on port %d ignored drain; "
                        "killing", rp.port,
                    )
                    proc.kill()
                    proc.wait(timeout=5)
            with self._lock:
                self._spawn_locked(rp)
            self._wait_ready(rp.url, deadline)
        finally:
            with self._lock:
                rp.expected_down = False
            if self.router is not None:
                self.router.set_replica_draining(rp.url, False)
        if self.router is not None:
            self._wait_admitted(rp.url, deadline)

    def _post_stop(self, url: str) -> None:
        try:
            with urllib.request.urlopen(
                urllib.request.Request(url + "/stop", method="POST"),
                timeout=5,
            ) as r:
                r.read()
        except OSError:
            # the process may tear the socket down mid-response, or be
            # dead already — either way the wait() below decides
            pass

    def _wait_ready(self, url: str, deadline: float) -> None:
        """Poll /readyz until 200 + warm; raise on timeout."""
        last = "no probe yet"
        while time.monotonic() < deadline:
            try:
                with urllib.request.urlopen(url + "/readyz", timeout=2) as r:
                    info = json.loads(r.read().decode("utf-8"))
                if info.get("fastpathWarm", True):
                    return
                last = "ready but not warm"
            except urllib.error.HTTPError as e:
                last = f"readyz {e.code}"
            except (OSError, ValueError) as e:
                last = f"{type(e).__name__}: {e}"
            time.sleep(0.1)
        raise TimeoutError(f"replica {url} never became ready ({last})")

    def _wait_admitted(self, url: str, deadline: float) -> None:
        """Wait for the router's health gate to readmit the replica so the
        fleet is back to full strength before the next one drains."""
        url = url.rstrip("/")
        while time.monotonic() < deadline:
            for rep in self.router.stats()["replicas"]:
                if rep["url"] == url and rep["state"] == "admitted":
                    return
            time.sleep(0.05)
        logger.warning(
            "fleet roll: %s not readmitted inside the roll budget", url
        )

    # -- status / shutdown ---------------------------------------------------
    def status(self) -> dict:
        with self._lock:
            return {
                "replicas": [
                    {
                        "port": rp.port,
                        "url": rp.url,
                        "pid": rp.proc.pid if rp.proc else None,
                        "alive": (
                            rp.proc is not None and rp.proc.poll() is None
                        ),
                        "restarts": rp.restarts,
                        "rolling": rp.expected_down,
                    }
                    for rp in self._procs
                ]
            }

    def stop(self) -> None:
        """Stop supervising and tear the children down (drain first,
        then kill what lingers)."""
        self._stop_evt.set()
        with self._lock:
            procs = [rp.proc for rp in self._procs if rp.proc is not None]
            for rp in self._procs:
                rp.expected_down = True
        for rp in self._procs:
            self._post_stop(rp.url)
        for proc in procs:
            try:
                proc.wait(timeout=self.stop_timeout_s)
            except subprocess.TimeoutExpired:
                proc.kill()
                try:
                    proc.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    pass  # unkillable (D-state); nothing more to do
