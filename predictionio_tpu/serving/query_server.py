"""Query server: low-latency REST serving of deployed engines.

Parity: ``core/.../workflow/CreateServer.scala:104-706``:

* ``POST /queries.json`` — parse query → ``serving.supplement`` → per-algorithm
  ``predict`` → ``serving.serve`` (the in-process hot loop,
  ``CreateServer.scala:484-634``).
* ``GET /`` — server info with request count / avg / last serving seconds
  (``:415-417,597-604``).
* ``GET|POST /reload`` — hot-swap to the latest COMPLETED instance without
  dropping queries (``:342-371,635-642``); models are re-placed on the mesh
  and the handle swapped atomically.
* ``POST /stop`` — undeploy (``commands/Engine.scala:245-268`` calls this).
* ``GET /plugins.json`` + outputblocker/outputsniffer plugin hooks
  (``EngineServerPlugin.scala:24-40``, ``CreateServer.scala:591-595,656-702``).
* feedback loop: when enabled, every prediction is POSTed back to the event
  server tagged with ``prId`` (``CreateServer.scala:527-589``).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import queue
import secrets
import threading
import time
import urllib.request
from typing import Any, Optional

from predictionio_tpu.common import faults as _faults
from predictionio_tpu.common.http import HttpService, Request, Response, json_response
from predictionio_tpu.common.resilience import (
    DEADLINE_HEADER,
    BreakerOpen,
    CircuitBreaker,
    Deadline,
    DeadlineExceeded,
    ErrorCounters,
    RateLimitedLogger,
    RetryPolicy,
    call_with_resilience,
    deadline_scope,
    parse_deadline_header,
)
from predictionio_tpu import obs
from predictionio_tpu.core import delta as _delta
from predictionio_tpu.core.engine import Engine
from predictionio_tpu.core import persistence
from predictionio_tpu.core.persistence import open_model_blob
from predictionio_tpu.core.workflow import (
    get_latest_completed_instance,
    prepare_deploy,
)
from predictionio_tpu.data.storage.registry import Storage
from predictionio_tpu.obs import bridges as _bridges
from predictionio_tpu.obs import devprof as _devprof
from predictionio_tpu.obs import tracing as _tracing
from predictionio_tpu.parallel.mesh import MeshContext
from predictionio_tpu.serving.pipeline import (
    build_pipeline_engine,
    pipeline_from_env,
)
from predictionio_tpu.serving.result_cache import (
    canonical_fingerprint,
    coalesce_from_env,
    entity_ids_from,
    result_cache_from_env,
)
from predictionio_tpu.serving.tenancy import (
    extract_access_key,
    tenants_from_env,
)
from predictionio_tpu.utils.profiling import LatencyHistogram

logger = logging.getLogger(__name__)


class EngineServerPlugin:
    """Parity: workflow/EngineServerPlugin.scala:24-40."""

    OUTPUT_BLOCKER = "outputblocker"
    OUTPUT_SNIFFER = "outputsniffer"

    name = "plugin"
    plugin_type = OUTPUT_SNIFFER

    def process(self, query: Any, prediction: Any, context: dict) -> Any:
        """Blockers return a (possibly rewritten) prediction; sniffers observe."""
        return prediction


# response-field plans: dataclasses.fields() re-derives the field tuple on
# every call; a deployed engine serves millions of instances of the SAME
# few result types, so the names are cached per class after the first walk
_FIELD_PLANS: dict[type, tuple[str, ...]] = {}


def _to_jsonable(obj: Any) -> Any:
    plan = _FIELD_PLANS.get(type(obj))
    if plan is not None:
        # None-valued fields are omitted, matching the reference's json4s
        # treatment of Option None (absent field, not null)
        return {
            k: _to_jsonable(v) for k in plan if (v := getattr(obj, k)) is not None
        }
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        plan = tuple(f.name for f in dataclasses.fields(obj))
        _FIELD_PLANS[type(obj)] = plan
        return {
            k: _to_jsonable(v) for k in plan if (v := getattr(obj, k)) is not None
        }
    if isinstance(obj, (list, tuple)):
        return [_to_jsonable(o) for o in obj]
    if isinstance(obj, dict):
        return {k: _to_jsonable(v) for k, v in obj.items()}
    return obj


def bind_query(query_cls: Optional[type], data: dict) -> Any:
    """Lenient query binding (parity: JsonExtractor dual Gson/json4s path —
    unknown JSON fields are ignored, missing ones take defaults)."""
    if query_cls is None or not dataclasses.is_dataclass(query_cls):
        return data
    names = {f.name for f in dataclasses.fields(query_cls)}
    return query_cls(**{k: v for k, v in data.items() if k in names})


@dataclasses.dataclass
class _Deployed:
    instance_id: str
    algorithms: list
    serving: Any
    models: list
    start_time: float


class QueryServer:
    def __init__(
        self,
        engine: Engine,
        storage: Optional[Storage] = None,
        ctx: Optional[MeshContext] = None,
        engine_id: str = "default",
        engine_version: str = "default",
        engine_variant: str = "default",
        feedback: bool = False,
        event_server_url: Optional[str] = None,
        access_key: Optional[str] = None,
        plugins: Optional[list[EngineServerPlugin]] = None,
        batching: bool = False,
        max_batch: int = 64,
        batch_window_ms: float = 2.0,
        max_inflight: int = 256,
        shed_retry_after_s: float = 1.0,
        default_deadline_ms: Optional[float] = None,
        warm_fastpath: Optional[bool] = None,
        telemetry: bool = True,
        result_cache=None,
        coalesce: Optional[bool] = None,
        tenants=None,
        pipeline=None,
    ):
        self.engine = engine
        self.storage = storage or Storage.instance()
        self.ctx = ctx or MeshContext.create()
        self.engine_id = engine_id
        self.engine_version = engine_version
        self.engine_variant = engine_variant
        self.feedback = feedback
        self.event_server_url = event_server_url
        self.access_key = access_key
        self.plugins = list(plugins or [])
        self._deployed: Optional[_Deployed] = None
        self._lock = threading.Lock()
        # latency bookkeeping (parity: CreateServer.scala:415-417) plus a
        # full histogram (TPU-build observability, SURVEY.md §5)
        self.request_count = 0
        self.avg_serving_sec = 0.0
        self.last_serving_sec = 0.0
        self.latency = LatencyHistogram()
        self.service = HttpService("queryserver")
        # unified observability (obs/): /metrics + /trace/recent.json, and
        # the HTTP layer's request counter / latency / trace hooks
        self.telemetry = (
            obs.Telemetry("queryserver").install(self.service)
            if telemetry and obs.telemetry_enabled()
            else None
        )
        # feedback POSTs ride a bounded background queue, never the request
        # thread; when the event server can't keep up we drop (and count)
        # rather than let feedback add to serve latency
        self._feedback_queue: "queue.Queue[dict]" = queue.Queue(maxsize=256)
        self._feedback_dropped = 0
        self._feedback_worker: Optional[threading.Thread] = None
        # -- resilience layer (ISSUE 2): admission control, deadlines,
        # degraded fallback, counted + rate-limited failure logging
        self.max_inflight = int(max_inflight)
        self.shed_retry_after_s = float(shed_retry_after_s)
        self.default_deadline_ms = default_deadline_ms
        self._inflight = 0
        self._inflight_lock = threading.Lock()
        self.counters = ErrorCounters(
            "shed", "deadline_exceeded", "breaker_open", "degraded",
            "query_errors", "warmup_errors", "sniffer_errors",
            "feedback_errors", "reload_failed", "drained",
            "drain_abandoned",
        )
        # graceful drain (SIGTERM / POST /stop): /readyz flips to draining,
        # new queries shed, in-flight work finishes inside the budget
        self._draining = False
        self.drain_timeout_ms = float(
            os.environ.get("PIO_DRAIN_TIMEOUT_MS", 5000.0)
        )
        self._rl_log = RateLimitedLogger(logger)
        # the feedback poster rides the shared retry/breaker policy: a dead
        # event server trips the breaker and feedback drops fast (counted)
        # instead of each event burning max_attempts × timeout
        self._feedback_policy = RetryPolicy(max_attempts=3, base_backoff_s=0.1)
        self._feedback_breaker = CircuitBreaker(
            "feedback", failure_threshold=5, reset_timeout_s=15.0
        )
        # degraded fallback: the most recent good (jsonable) prediction per
        # nothing-else-available queries; a scorer/model failure serves this
        # with {"degraded": true} instead of a 500
        self._last_good: Optional[dict] = None
        self._reload_degraded = False
        # AOT fastpath warmup: every bucket rung compiles at deploy/reload,
        # BEFORE the generation swap, so no live request ever pays
        # trace/compile latency.  Default follows `batching` (the fastpath
        # only serves formed batches; a plain per-request server — most
        # tests — skips the per-bucket compiles); pass warm_fastpath
        # explicitly to override either way.
        self._warm_fastpath = (
            batching if warm_fastpath is None else bool(warm_fastpath)
        )
        # /readyz reports whether the LIVE generation actually finished its
        # warmup compiles (routers gate admission on *warm*, not merely
        # *loaded*).  True when warmup is not configured: a server that never
        # warms is as warm as it will ever be.
        self._fastpath_warm = not self._warm_fastpath
        # skew hot path (ISSUE 6): result cache for identical queries +
        # single-flight coalescing at the batcher.  Both default from env
        # knobs (PIO_RESULT_CACHE / PIO_COALESCE, off-by-default-safe);
        # pass result_cache=ResultCache(...) or coalesce=True to force.
        # Must exist before the first reload(): a reload bumps the serving
        # generation and flushes the cache.
        self._result_cache = (
            result_cache_from_env() if result_cache is None else result_cache
        )
        self._coalesce = (
            coalesce_from_env() if coalesce is None else bool(coalesce)
        )
        # model-generation tag: every successful swap increments it, so
        # cached answers from the previous generation can never validate
        # even if clear() were to race a concurrent put
        self._serving_gen = 0
        # (generation, spans) memo behind _pod_lockstep(): whether the
        # live fastpath's pod mesh spans jax.distributed processes — such
        # a replica can only be driven in SPMD lockstep and must refuse
        # independently routed queries (guarded by _lock)
        self._pod_lockstep_memo: Optional[tuple] = None
        # on-demand profiler (POST /debug/profile): one capture at a time
        # (jax.profiler is process-global), bounded window, counted
        self._profile_lock = threading.Lock()
        self._profile_captures = 0
        self._profile_last_unix = 0.0
        # streaming micro-generations (PIO_STREAMING=1): per-replica delta
        # state dict built by enable_streaming() after each successful
        # deploy/reload; None whenever streaming is off or no foldable
        # model is live — every streaming touchpoint no-ops on None, which
        # is what makes PIO_STREAMING=0 bit-identical to the pre-streaming
        # server
        self._streaming: Optional[dict] = None
        # multi-tenancy (ISSUE 19): tenant registry consulted on every
        # /queries.json — access-key auth, fair-share admission ahead of
        # the server-wide gate, per-tenant breakers/SLO/variant metrics.
        # None (PIO_TENANTS unset) keeps the open single-tenant server.
        self._tenants = (
            tenants_from_env(total_inflight=self.max_inflight)
            if tenants is None else tenants
        )
        # composed retrieval→ranking pipeline: the sealed config loads
        # here; the ENGINE binds against the deployed model on every
        # generation swap (_note_generation_swap).  None ⇒ single-stage.
        self._pipeline_config = (
            pipeline_from_env() if pipeline is None else pipeline
        )
        self._pipeline_engine = None
        self._register_routes()
        self.reload()
        self._batcher = None
        if batching:
            from predictionio_tpu.serving import fastpath
            from predictionio_tpu.serving.batching import MicroBatcher

            self._batcher = MicroBatcher(
                self._run_query_batch, max_batch=max_batch,
                window_ms=batch_window_ms, buckets=fastpath.BUCKETS,
            )
        if self.telemetry is not None:
            self._register_metrics()

    # -- model lifecycle -----------------------------------------------------
    def reload(self, instance_id: Optional[str] = None,
               force: bool = False) -> str:
        """(Re)load the latest COMPLETED instance; atomic swap.

        ``instance_id`` pins the load to ONE specific generation — the
        canary controller's hot-swap primitive (roll the canary replica to
        the candidate, roll it back to the baseline) — and refuses a
        QUARANTINED id unless ``force`` is set (operator override).  With
        no ``instance_id`` the newest non-quarantined COMPLETED instance
        deploys; a cold start additionally honors ``PIO_PIN_INSTANCE``
        (injected by the fleet while a canary is in flight) so autoscaler
        scale-ups spawn on the verified baseline, never the candidate.

        Graceful degradation: when a RELOAD fails (storage down, corrupt
        blob, bad hot-swap) and a previous generation is live, the server
        KEEPS SERVING the last good generation — counted, flagged on
        ``/readyz`` and stats — instead of dying or swapping in garbage.
        A COLD START whose newest blob is unusable falls back to the
        persisted last-known-good pointer (then any older COMPLETED
        generation); only a cold start with nothing deployable left fails
        loudly.
        """
        if instance_id is None and self._deployed is None:
            pin = os.environ.get("PIO_PIN_INSTANCE", "").strip()
            if pin:
                instance_id = pin
        instance = None
        try:
            if instance_id is not None:
                if not force and persistence.is_quarantined(
                    instance_id, self.engine_id, self.engine_version,
                    self.engine_variant,
                ):
                    raise RuntimeError(
                        f"engine instance {instance_id} is quarantined "
                        "(failed online verification); pass force to "
                        "override"
                    )
                instance = self.storage.get_meta_data_engine_instances().get(
                    instance_id
                )
                if instance is None:
                    raise RuntimeError(
                        f"no engine instance {instance_id}"
                    )
            else:
                instance = get_latest_completed_instance(
                    self.storage, self.engine_id, self.engine_version,
                    self.engine_variant,
                )
            _, algorithms, serving, models = prepare_deploy(
                self.engine, instance, storage=self.storage, ctx=self.ctx
            )
        except Exception:
            with self._lock:
                last_good = self._deployed
            if last_good is not None:
                self.counters.inc("reload_failed")
                with self._lock:
                    self._reload_degraded = True
                self._rl_log.exception(
                    "reload", "reload failed; serving last good instance %s",
                    last_good.instance_id,
                )
                return last_good.instance_id
            # cold start: nothing in memory to keep serving — reach for the
            # on-disk last-known-good pointer, then older COMPLETED runs
            fallback = self._cold_start_fallback(
                failed_id=instance.id if instance is not None else None
            )
            if fallback is None:
                raise  # truly nothing deployable
            return fallback.instance_id
        warm_ok = not self._warm_fastpath
        if self._warm_fastpath:
            # pre-compile the serving fast path at deploy/reload so no live
            # request ever pays trace/compile latency (ISSUE: AOT warmup)
            warm_ok = True
            for algo, model in zip(algorithms, models):
                warm = getattr(algo, "warmup", None)
                if warm is None:
                    continue
                try:
                    warm(model)
                except Exception:
                    warm_ok = False
                    self.counters.inc("warmup_errors")
                    self._rl_log.exception(
                        "warmup", "fastpath warmup failed for %s",
                        type(algo).__name__,
                    )
        deployed = _Deployed(
            instance_id=instance.id,
            algorithms=algorithms,
            serving=serving,
            models=models,
            start_time=time.time(),
        )
        with self._lock:
            self._deployed = deployed
            self._fastpath_warm = warm_ok
        self._note_generation_swap()
        with self._lock:
            self._reload_degraded = False
        self._record_last_known_good(instance.id)
        # a new base generation subsumes all prior micro-generations:
        # re-base the delta pipeline on the freshly deployed factors
        self.enable_streaming()
        logger.info("deployed engine instance %s", instance.id)
        return instance.id

    def _note_generation_swap(self) -> None:
        """A new model generation is live: bump the serving generation (the
        result cache's model tag) and flush — answers computed against the
        previous generation must never be served against this one."""
        # handler threads read the generation per query; the bump comes
        # from reload/cold-start threads, so it takes the server lock
        with self._lock:
            self._serving_gen += 1
            deployed = self._deployed
        if self._result_cache is not None:
            self._result_cache.clear()
        # re-bind the pipeline against the new generation's algorithms/
        # models; a config that cannot bind (template without the ALS
        # surface) degrades to single-stage serving, never fails a swap
        if self._pipeline_config is not None and deployed is not None:
            try:
                engine = build_pipeline_engine(
                    self._pipeline_config, deployed.algorithms,
                    deployed.models,
                )
            except Exception:
                engine = None
                self._rl_log.exception(
                    "pipeline", "pipeline %s failed to bind; serving "
                    "single-stage", self._pipeline_config.name,
                )
            with self._lock:
                self._pipeline_engine = engine

    # -- last-known-good pointer (survives restarts) -------------------------
    def _lkg_path(self) -> str:
        from predictionio_tpu.utils.fs import pio_base_dir

        raw = f"{self.engine_id}-{self.engine_version}-{self.engine_variant}"
        safe = "".join(c if c.isalnum() or c in "-_." else "_" for c in raw)
        return os.path.join(pio_base_dir(), "last_known_good", safe + ".json")

    def _record_last_known_good(self, instance_id: str) -> None:
        """Persist the generation that just deployed successfully; a future
        cold start with a torn newest blob deploys this one instead.
        Best-effort: pointer write failure must never fail a deploy."""
        from predictionio_tpu.utils.fs import atomic_write_text

        path = self._lkg_path()
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            atomic_write_text(
                path, json.dumps({"instanceId": instance_id})
            )
        except OSError:
            logger.debug("last-known-good pointer write failed", exc_info=True)

    def _read_last_known_good(self) -> Optional[str]:
        try:
            with open(self._lkg_path(), "r", encoding="utf-8") as f:
                value = json.load(f).get("instanceId")
            return value if isinstance(value, str) else None
        except (OSError, ValueError):
            return None

    def _cold_start_fallback(self, failed_id: Optional[str]) -> Optional[_Deployed]:
        """Deploy an older generation when the newest is unusable at cold
        start: the persisted last-known-good pointer first, then every
        other COMPLETED instance newest-first. Serving stale beats not
        serving; the swap is flagged degraded on /readyz and counted."""
        try:
            completed = self.storage.get_meta_data_engine_instances().get_completed(
                self.engine_id, self.engine_version, self.engine_variant
            )
        except Exception:
            return None
        # quarantined generations failed ONLINE verification (canary
        # rollback) — the LKG pointer and the newest-first walk both skip
        # them, or a restart would re-deploy the exact generation the
        # canary just rolled back
        quarantined = persistence.quarantined_instance_ids(
            self.engine_id, self.engine_version, self.engine_variant
        )
        by_id = {i.id: i for i in completed}
        order: list[str] = []
        lkg_id = self._read_last_known_good()
        if (lkg_id and lkg_id != failed_id and lkg_id in by_id
                and lkg_id not in quarantined):
            order.append(lkg_id)
        for inst in completed:
            if (inst.id != failed_id and inst.id not in order
                    and inst.id not in quarantined):
                order.append(inst.id)
        for iid in order:
            try:
                _, algorithms, serving, models = prepare_deploy(
                    self.engine, by_id[iid], storage=self.storage, ctx=self.ctx
                )
            except Exception:
                self._rl_log.exception(
                    "reload", "fallback candidate %s failed to deploy", iid
                )
                continue
            deployed = _Deployed(
                instance_id=iid,
                algorithms=algorithms,
                serving=serving,
                models=models,
                start_time=time.time(),
            )
            with self._lock:
                self._deployed = deployed
                # the fallback path deploys without running warmup
                self._fastpath_warm = not self._warm_fastpath
            self._note_generation_swap()
            self.counters.inc("reload_failed")
            with self._lock:
                self._reload_degraded = True
            self._record_last_known_good(iid)
            logger.warning(
                "cold start: newest instance %s unusable; serving "
                "last-known-good %s (degraded)", failed_id, iid,
            )
            return deployed
        return None

    # -- observability -------------------------------------------------------
    # -- streaming micro-generations (crash-safe delta pipeline) -------------
    def enable_streaming(
        self, delta_dir: Optional[str] = None
    ) -> Optional[dict]:
        """Wire this replica into the sealed delta log (PIO_STREAMING=1).

        Finds the first deployed factor model, fingerprints its base
        generation, and builds the fenced :class:`DeltaApplier` over the
        per-generation delta log.  Catch-up runs SYNCHRONOUSLY here —
        before the caller (deploy/reload) lets ``/readyz`` go ready — so
        a crash-restarted or freshly autoscaled replica is readmitted
        only at the fleet's epoch, never behind it.  Returns the
        applier's stats, or None when streaming is off or no foldable
        model is deployed.
        """
        self._stop_streaming()
        if not _delta.streaming_enabled():
            return None
        with self._lock:
            d = self._deployed
        if d is None:
            return None
        target = None
        for algo, model in zip(d.algorithms, d.models):
            if (
                getattr(model, "user_factors", None) is not None
                and getattr(model, "item_factors", None) is not None
                and getattr(model, "user_map", None) is not None
            ):
                target = (algo, model)
                break
        if target is None:
            return None
        algo, model = target
        fp = _delta.model_fingerprint(model.user_factors, model.item_factors)
        directory = delta_dir or _delta.delta_dir_for(fp)
        delta_log = _delta.DeltaLog(directory)
        st: dict = {
            "algo": algo,
            "model": model,
            "log": delta_log,
            "dir": directory,
            "fingerprint": fp,
            # replica-local cooccurrence count accumulator (pair -> count)
            "cooc": {},
            "slo_ms": float(os.environ.get("PIO_FRESHNESS_SLO_MS", "5000")),
            "degraded_served": 0,
            "staleness_ms": 0.0,
            "staleness_checked": 0.0,
            "wedged": None,
            "wake": threading.Event(),
            "stop": threading.Event(),
            "thread": None,
        }
        st["applier"] = _delta.DeltaApplier(
            fp,
            lambda dl: self._apply_streaming_delta(st, dl),
            delta_log=delta_log,
        )
        # single-writer rebind: enable runs on the deploy/reload thread
        # before the catch-up worker starts; readers see None or a fully
        # built state dict, never a partial one
        self._streaming = st  # pio: ignore[race-unguarded-rebind]
        # catch-up before readmission: replay every already-sealed epoch
        # while /readyz still answers not-ready for this generation
        self._streaming_catch_up(st)
        t = threading.Thread(
            target=self._catchup_loop,
            name="queryserver-delta-catchup",
            daemon=True,
        )
        st["thread"] = t
        t.start()
        logger.info(
            "streaming enabled: base %s, delta log %s, epoch %d",
            fp, directory, st["applier"].applied_epoch,
        )
        return st["applier"].stats()

    def _stop_streaming(self) -> None:
        st = self._streaming
        self._streaming = None
        if st is not None:
            st["stop"].set()
            st["wake"].set()

    def _apply_streaming_delta(self, st: dict, dl) -> None:
        """In-place application of one fenced delta (DeltaApplier's
        apply_fn): device factor buffers first, then the host-side model
        copies, the cooccurrence counts, and the entity-targeted result
        cache invalidation.  Bucket shapes never change, so nothing here
        can trigger a recompile."""
        import numpy as np

        algo, model = st["algo"], st["model"]
        user_idx = np.asarray(dl.user_idx, dtype=np.int64)
        item_idx = (
            np.asarray(dl.item_idx, dtype=np.int64)
            if dl.item_idx is not None
            else np.zeros((0,), np.int64)
        )
        scorer = getattr(algo, "_fastpath", None)
        if scorer is not None:
            scorer.apply_delta_rows(
                dl.user_idx, dl.user_rows,
                item_idx=dl.item_idx, item_rows=dl.item_rows,
            )
        # host factors track the delta so the next reload's last-known-good
        # comparisons, fold-in gates and fallback paths all see fresh rows
        if user_idx.size:
            model.user_factors[user_idx] = np.asarray(
                dl.user_rows, dtype=model.user_factors.dtype
            )
        if item_idx.size:
            model.item_factors[item_idx] = np.asarray(
                dl.item_rows, dtype=model.item_factors.dtype
            )
        # ALSScorer's own lazy device copies (the unbatched _score_batch
        # path): U/V ride as call arguments, so a functional row patch
        # swaps data without touching any compiled executable
        dev_u = getattr(algo, "_U", None)
        if dev_u is not None and user_idx.size:
            algo._U = dev_u.at[user_idx].set(
                np.asarray(dl.user_rows).astype(dev_u.dtype)
            )
        dev_v = getattr(algo, "_V", None)
        if dev_v is not None and item_idx.size:
            algo._V = dev_v.at[item_idx].set(
                np.asarray(dl.item_rows).astype(dev_v.dtype)
            )
        if dl.cooc_updates is not None and len(dl.cooc_updates):
            from predictionio_tpu.models.cooccurrence import fold_increments

            fold_increments(dl.cooc_updates, st["cooc"])
        # entity-targeted: only the users this delta rewrote lose their
        # cached answers; everyone else stays hot
        from predictionio_tpu.serving import result_cache as _rc

        _rc.notify_delta(dl.user_ids)

    def _streaming_staleness_ms(self) -> float:
        """Age of the oldest sealed-but-unapplied epoch, cached for 250ms
        so the per-query SLO check never turns into a per-query listdir."""
        st = self._streaming
        if st is None:
            return 0.0
        now = time.monotonic()
        if now - st["staleness_checked"] >= 0.25:
            try:
                age = st["log"].oldest_unapplied_age_s(
                    st["applier"].applied_epoch
                )
            except OSError:
                age = 0.0
            st["staleness_ms"] = age * 1000.0
            st["staleness_checked"] = now
        return st["staleness_ms"]

    def _catchup_loop(self) -> None:
        """Delta catch-up worker: paces on Event.wait (woken early by
        /readyz when it spots the log ahead of us) and delegates the
        blob I/O to the applier."""
        st = self._streaming
        if st is None:
            return
        pace_s = float(os.environ.get("PIO_DELTA_CATCHUP_MS", "1000")) / 1e3
        while not st["stop"].is_set():
            st["wake"].wait(pace_s)
            st["wake"].clear()
            if st["stop"].is_set():
                return
            self._streaming_catch_up(st)

    def _streaming_catch_up(self, st: dict) -> None:
        try:
            rc = st["applier"].catch_up()
        except Exception:
            self._rl_log.exception("delta", "delta catch-up failed")
            return
        # a refused catch-up (torn blob, fingerprint fence, gap) wedges
        # at the last good epoch: remember the receipt so /readyz stops
        # holding the replica out — it serves degraded instead of
        # flapping between 503 and a replay that can never succeed
        st["wedged"] = rc if rc.get("refused") else None

    def streaming_stats(self) -> Optional[dict]:
        st = self._streaming
        if st is None:
            return None
        out = st["applier"].stats()
        out.update(
            log_epoch=st["log"].last_epoch(),
            staleness_ms=self._streaming_staleness_ms(),
            slo_ms=st["slo_ms"],
            degraded_served=st["degraded_served"],
            cooc_pairs=len(st["cooc"]),
            fingerprint=st["fingerprint"],
            dir=st["dir"],
        )
        return out

    def _fastpath_stats(self) -> Optional[dict]:
        """First deployed algorithm's serving_stats (registry bridge)."""
        with self._lock:
            d = self._deployed
        if d is None:
            return None
        for algo, model in zip(d.algorithms, d.models):
            get_stats = getattr(algo, "serving_stats", None)
            if get_stats is None:
                continue
            s = get_stats(model)
            if s is not None:
                return s
        return None

    def _pod_lockstep(self) -> bool:
        """True when the live fastpath's pod mesh spans processes.

        Such a mesh is bound by the SPMD dispatch contract (every
        ``jax.distributed`` process must execute the same compiled
        program for the same batch in the same order — the cross-host
        leaderboard gather is a collective ALL peers participate in), so
        this replica cannot answer queries routed to it alone: the first
        independent dispatch would wedge the whole pod in the collective.
        ``/queries.json`` refuses with 503 and ``/readyz`` reports
        not-ready instead; lockstep drivers (the pod bench harness, batch
        scoring run identically on every process) call the scorer
        directly and are unaffected.  Memoized per serving generation —
        the flag is a property of the deployed scorer's placement.
        """
        with self._lock:
            gen = self._serving_gen
            memo = self._pod_lockstep_memo
        if memo is not None and memo[0] == gen:
            return memo[1]
        pod = (self._fastpath_stats() or {}).get("pod") or {}
        spans = bool(pod.get("spans_processes"))
        with self._lock:
            self._pod_lockstep_memo = (gen, spans)
        return spans

    def _event_cache_stats(self) -> Optional[dict]:
        """First deployed algorithm's ServingEventCache stats, if any (the
        e-commerce template creates one lazily on its first predict)."""
        with self._lock:
            d = self._deployed
        if d is None:
            return None
        for algo in d.algorithms:
            cache = getattr(algo, "_event_cache", None)
            if cache is not None:
                return cache.stats_dict()
        return None

    @staticmethod
    def _train_kernel_stats() -> Optional[dict]:
        """Training-kernel dispatch stats recorded by the most recent
        in-process train (None until one runs)."""
        from predictionio_tpu.ops import train_kernel

        return train_kernel.stats() or None

    def _register_metrics(self) -> None:
        """Expose every scattered serving stat on the obs registry, making
        ``/metrics`` the single source of truth for this server."""
        reg = self.telemetry.registry
        _bridges.bridge_error_counters(
            reg, "pio_query_errors_total",
            "Serving failures by kind (shed, deadline 504, breaker_open, "
            "degraded, query/warmup/sniffer/feedback/reload).",
            self.counters,
        )
        _bridges.bridge_latency_histogram(
            reg, "pio_query_latency_seconds",
            "handle_query latency, bridged from the serving histogram.",
            self.latency,
        )
        reg.gauge_fn(
            "pio_query_inflight",
            "Queries currently inside the admission gate.",
            lambda: float(self._inflight),
        )
        reg.gauge_fn(
            "pio_query_max_inflight",
            "Admission-control bound; at or beyond it requests shed (503).",
            lambda: float(self.max_inflight),
        )
        if self._batcher is not None:
            _bridges.bridge_batcher(reg, self._batcher.stats)
        _bridges.bridge_fastpath(reg, self._fastpath_stats)
        # pio_shard_*: emits only while a ShardingPlan is live (the stats
        # block is absent under replicated placement)
        _bridges.bridge_sharding(reg, self._fastpath_stats)
        # pio_ivf_*: emits only while an IVF index is live (the stats
        # block is absent under exact retrieval)
        _bridges.bridge_ivf(reg, self._fastpath_stats)
        # pio_pod_*: emits only while a pod (multi-host-group) plan is
        # live — the fastpath publishes a "pod" stats block then
        _bridges.bridge_pod(
            reg, lambda: (self._fastpath_stats() or {}).get("pod")
        )
        # live device utilization: the scorer's cost-annotated dispatch
        # accountant, labeled with the generation it serves (the scorer —
        # and its accountant — are rebuilt on every successful reload)
        _bridges.bridge_devprof(
            reg,
            lambda: (self._fastpath_stats() or {}).get("devprof"),
            lambda: self._serving_gen,
        )
        # pio_train_kernel_*: the fused-training-kernel dispatch recorded
        # by the most recent in-process train (empty — and silent — until
        # one runs, e.g. the template train-then-serve flow)
        _bridges.bridge_train_kernel(reg, self._train_kernel_stats)
        if self._result_cache is not None:
            _bridges.bridge_result_cache(reg, self._result_cache.stats)
        reg.gauge_fn(
            "pio_result_cache_enabled",
            "1 when the serving result cache is active.",
            lambda: 0.0 if self._result_cache is None else 1.0,
        )
        reg.gauge_fn(
            "pio_coalesce_enabled",
            "1 when single-flight coalescing of identical queries is on.",
            lambda: 1.0 if self._coalesce else 0.0,
        )
        _bridges.bridge_event_cache(reg, self._event_cache_stats)
        # pio_tenant_*: emits only while a tenant registry is installed
        # (PIO_TENANTS unset keeps /metrics byte-identical); tenant and
        # variant labels ride under the PIO_METRICS_MAX_SERIES cap like
        # every other labeled family
        if self._tenants is not None:
            _bridges.bridge_tenancy(reg, self._tenants.stats)
        # pio_pipeline_*: emits only while a composed pipeline is bound
        _bridges.bridge_pipeline(
            reg,
            lambda: (
                self._pipeline_engine.stats()
                if self._pipeline_engine is not None else None
            ),
        )
        _bridges.bridge_resilience(
            reg,
            lambda: {"breakers": [self._feedback_breaker.stats()]},
            prefix="pio_feedback",
        )
        storage_rs = getattr(self.storage, "resilience_stats", None)
        if callable(storage_rs):
            _bridges.bridge_resilience(reg, storage_rs)

        def _serving_families():
            with self._lock:
                rc = self.request_count
                avg = self.avg_serving_sec
                last = self.last_serving_sec
                dropped = self._feedback_dropped
            F = _bridges.Family
            return [
                F("pio_query_requests_total", "counter",
                  "Queries served by the predict hot loop.",
                  [("", (), float(rc))]),
                F("pio_query_avg_serving_seconds", "gauge",
                  "Running mean serving seconds (parity: CreateServer "
                  "avg gauge).", [("", (), float(avg))]),
                F("pio_query_last_serving_seconds", "gauge",
                  "Most recent serving seconds.", [("", (), float(last))]),
                F("pio_feedback_dropped_total", "counter",
                  "Feedback events dropped on a full queue.",
                  [("", (), float(dropped))]),
                F("pio_reload_degraded", "gauge",
                  "1 while serving the last good generation after a "
                  "failed reload.",
                  [("", (), 1.0 if self._reload_degraded else 0.0)]),
                F("pio_draining", "gauge",
                  "1 while the server is draining toward shutdown.",
                  [("", (), 1.0 if self._draining else 0.0)]),
                F("pio_profile_captures_total", "counter",
                  "On-demand jax.profiler captures served by "
                  "POST /debug/profile.",
                  [("", (), float(self._profile_captures))]),
                F("pio_profile_last_capture_unix", "gauge",
                  "Wall-clock time of the most recent profile capture "
                  "(0 when none has run).",
                  [("", (), float(self._profile_last_unix))]),
            ]

        reg.register_collector(_serving_families)

        def _streaming_families():
            # emits only while streaming is live: PIO_STREAMING=0 keeps
            # /metrics byte-identical to the pre-streaming server
            st = self._streaming
            if st is None:
                return []
            a = st["applier"].stats()
            refused = a["refused"] or {}
            F = _bridges.Family
            return [
                F("pio_delta_epoch", "gauge",
                  "Micro-generation epoch applied by this replica.",
                  [("", (), float(a["applied_epoch"]))]),
                F("pio_delta_log_epoch", "gauge",
                  "Newest epoch sealed in this replica's delta log.",
                  [("", (), float(st["log"].last_epoch()))]),
                F("pio_delta_applied_total", "counter",
                  "Deltas applied in place on the serving factors.",
                  [("", (), float(a["applied"]))]),
                F("pio_delta_noop_total", "counter",
                  "Replayed already-applied epochs acked as no-ops "
                  "(the exactly-once path).",
                  [("", (), float(a["noops"]))]),
                F("pio_delta_refused_total", "counter",
                  "Deltas refused by reason (fingerprint fence, gap, "
                  "integrity).",
                  [("", (("reason", r),), float(n))
                   for r, n in sorted(refused.items())] or
                  [("", (("reason", "none"),), 0.0)]),
                F("pio_delta_cooc_pending", "gauge",
                  "Distinct cooccurrence pairs accumulated from applied "
                  "deltas since the last full retrain.",
                  [("", (), float(len(st["cooc"])))]),
                F("pio_freshness_staleness_ms", "gauge",
                  "Age of the oldest sealed-but-unapplied delta epoch.",
                  [("", (), float(self._streaming_staleness_ms()))]),
                F("pio_freshness_slo_ms", "gauge",
                  "Configured freshness SLO (PIO_FRESHNESS_SLO_MS).",
                  [("", (), float(st["slo_ms"]))]),
                F("pio_freshness_visible_p99_ms", "gauge",
                  "p99 event-committed to prediction-visible latency "
                  "over recent applied deltas.",
                  [("", (), float(a["visible_p99_ms"]))]),
                F("pio_freshness_degraded_total", "counter",
                  "Answers served with degraded:true because staleness "
                  "exceeded the freshness SLO.",
                  [("", (), float(st["degraded_served"]))]),
            ]

        reg.register_collector(_streaming_families)

    # -- batched path: one Algorithm.batch_predict pass for N queries --------
    def _run_query_batch(self, queries: list) -> list:
        with self._lock:
            deployed = self._deployed
        with _tracing.stage("batch_assembly"):
            supplemented = [
                (i, deployed.serving.supplement(q))
                for i, q in enumerate(queries)
            ]
        per_algo = [
            dict(algo.batch_predict(model, supplemented))
            for algo, model in zip(deployed.algorithms, deployed.models)
        ]
        out = []
        for i, (_, sq) in enumerate(supplemented):
            preds = [d[i] for d in per_algo if i in d]
            # pair the supplemented query with its prediction so the serving
            # pipeline downstream of the batch (plugins, feedback) sees the
            # same supplemented query as the unbatched path
            out.append((sq, deployed.serving.serve(sq, preds)))
        return out

    # -- degraded fallback ---------------------------------------------------
    def _fallback_result(self, query: Any, deployed: _Deployed) -> Optional[dict]:
        """Best degraded answer when the scorer fails.

        Preference order: an algorithm's own ``fallback_predict`` (e.g. a
        popularity list computed at train time), else the last good
        prediction this server produced (stale beats empty for a
        recommendation surface).  None ⇒ no fallback, caller 500s.
        """
        for algo, model in zip(deployed.algorithms, deployed.models):
            fb = getattr(algo, "fallback_predict", None)
            if fb is None:
                continue
            try:
                out = _to_jsonable(fb(model, query))
                if isinstance(out, dict):
                    return out
            except Exception:
                self._rl_log.exception(
                    "fallback", "fallback_predict failed for %s",
                    type(algo).__name__,
                )
        if self._last_good is not None:
            return dict(self._last_good)
        return None

    # -- query hot loop (parity: CreateServer.scala:484-634) -----------------
    def handle_query(
        self,
        data: dict,
        deadline: Optional[Deadline] = None,
        tenant: Optional[str] = None,
        variant: Optional[str] = None,
    ) -> dict:
        t0 = time.perf_counter()
        with self._lock:
            deployed = self._deployed
            pipe = self._pipeline_engine
        with _tracing.stage("decode"):
            query = bind_query(self.engine.query_cls, data)
        degraded = False
        cache = self._result_cache
        # one canonical fingerprint serves both layers: the result-cache
        # key here and the single-flight coalescing key at the batcher.
        # Under multi-tenancy the fingerprint is NAMESPACED by tenant +
        # A/B variant + live engine instance: identical bodies from two
        # tenants must never share a cache entry or a coalesced leader
        # slot (cross-tenant answer leakage)
        namespace = None
        if tenant is not None:
            namespace = "\x1f".join(
                (tenant, variant or "-",
                 deployed.instance_id if deployed else "")
            )
        fp = (
            canonical_fingerprint(data, namespace=namespace)
            if (cache is not None or self._coalesce)
            else None
        )
        cache_hit = False
        if cache is not None and fp is not None:
            cached = cache.get(fp, self._serving_gen)
            if cached is not None:
                cache_hit = True
                result = cached
                # no supplemented form exists on a hit; plugins and
                # feedback see the bound query, as on the degraded path
                supplemented = query
        # flight-recorder context: which generation answered and whether
        # the device was skipped (a cache hit never dispatches — its trace
        # must carry no device stages)
        for t in _tracing.active_traces():
            t.annotate(
                generation=self._serving_gen,
                **(
                    {"cache": "hit" if cache_hit else "miss"}
                    if cache is not None
                    else {}
                ),
            )
        if not cache_hit:
            try:
                if deadline is not None and deadline.expired():
                    raise DeadlineExceeded("deadline expired before predict")
                pmeta = None
                if pipe is not None:
                    # composed dataflow: retrieval → ranking under
                    # per-stage shares of this request's deadline; a
                    # late/failed ranking stage yields the retrieval-only
                    # answer with degraded:true instead of blowing the SLO
                    supplemented = deployed.serving.supplement(query)
                    prediction, pmeta = pipe.run_pipeline(
                        supplemented, deadline
                    )
                    prediction = deployed.serving.serve(
                        supplemented, [prediction]
                    )
                elif self._batcher is not None:
                    supplemented, prediction = self._batcher.submit(
                        query, deadline=deadline,
                        key=fp if self._coalesce else None,
                    )
                else:
                    supplemented = deployed.serving.supplement(query)
                    predictions = [
                        algo.predict(model, supplemented)
                        for algo, model in zip(
                            deployed.algorithms, deployed.models
                        )
                    ]
                    prediction = deployed.serving.serve(
                        supplemented, predictions
                    )
                with _tracing.stage("serialize"):
                    result = _to_jsonable(prediction)
                if pmeta is not None and pmeta.get("degraded"):
                    # a stage overran its deadline share: the answer is
                    # retrieval-only — flagged, counted, never cached
                    # (it must not outlive the pressure that caused it)
                    if isinstance(result, dict):
                        result["degraded"] = True
                        result["pipelineStage"] = pmeta.get("stage")
                    degraded = True
                    self.counters.inc("degraded")
            except DeadlineExceeded:
                self.counters.inc("deadline_exceeded")
                raise
            except TypeError:
                # malformed query values are a CLIENT bug: surface them
                # through the route's TypeError → 400 mapping, never mask
                # them behind a stale degraded 200 (which would also pollute
                # the `degraded` counter bench.py's clean gate reads as a
                # server regression)
                self.counters.inc("query_errors")
                raise
            except Exception as e:
                # scorer/model failure: serve the degraded fallback rather
                # than a 500 — availability beats freshness for serving
                fallback = self._fallback_result(query, deployed)
                if fallback is None:
                    self.counters.inc("query_errors")
                    raise
                self.counters.inc("degraded")
                self._rl_log.warning(
                    "degraded", "prediction failed (%s); serving degraded "
                    "fallback", e,
                )
                result = fallback
                result["degraded"] = True
                supplemented = query
                degraded = True
        if not degraded:
            # remember the newest good answer for the degraded path; shallow
            # copy so prId/plugin rewrites never leak back into the cache
            if isinstance(result, dict):
                # every handler thread writes this; order the rebinds so
                # the degraded path always sees a complete answer
                with self._lock:
                    self._last_good = dict(result)
            if (
                cache is not None
                and fp is not None
                and not cache_hit
                and isinstance(result, dict)
            ):
                # store the pre-plugin, pre-prId answer: plugins rewrite
                # per caller and run on every hit; degraded answers are
                # never cached (they would outlive the failure)
                cache.put(
                    fp, result,
                    entity_ids_from(data, cache.key_fields),
                    self._serving_gen,
                )
        # freshness SLO: when the sealed delta log is ahead of this
        # replica by more than PIO_FRESHNESS_SLO_MS, the answer is still
        # served — annotated, never failed.  Runs AFTER cache.put (the
        # cache deep-copies, so the annotation never sticks to the cached
        # answer) and applies to hits too: a hot cache entry is exactly as
        # stale as the factors that computed it.
        st = self._streaming
        if st is not None and isinstance(result, dict):
            stale = self._streaming_staleness_ms()
            if stale > st["slo_ms"]:
                result["degraded"] = True
                result["staleness_ms"] = round(stale, 1)
                st["degraded_served"] += 1
                st["wake"].set()
        # plugins see JSON values, as in the reference (JValue-based process)
        for p in self.plugins:
            if p.plugin_type == EngineServerPlugin.OUTPUT_BLOCKER:
                result = p.process(supplemented, result, {})
        for p in self.plugins:
            if p.plugin_type == EngineServerPlugin.OUTPUT_SNIFFER:
                try:
                    p.process(supplemented, result, {})
                except Exception:
                    self.counters.inc("sniffer_errors")
                    self._rl_log.exception(
                        "sniffer", "sniffer plugin %s failed", p.name
                    )
        if self.feedback:
            pr_id = data.get("prId") or secrets.token_hex(8)
            result["prId"] = pr_id
            self._send_feedback(data, result, pr_id, deployed.instance_id)
        dt = time.perf_counter() - t0
        self.latency.observe(dt)
        with self._lock:
            self.request_count += 1
            self.last_serving_sec = dt
            self.avg_serving_sec += (dt - self.avg_serving_sec) / self.request_count
        return result

    def _send_feedback(self, query, prediction, pr_id, instance_id) -> None:
        """Async POST back to the event server (CreateServer.scala:563-569).

        Enqueues onto a bounded queue drained by one daemon worker — the
        request thread never blocks on the event server, and a slow or dead
        event server drops feedback (counted) instead of backing up serving.
        """
        if not self.event_server_url:
            return
        event = {
            "event": "predict",
            "entityType": "pio_pr",
            "entityId": pr_id,
            "properties": {
                "engineInstanceId": instance_id,
                "query": query,
                "prediction": prediction,
            },
        }
        if self._feedback_worker is None:
            with self._lock:
                if self._feedback_worker is None:
                    self._feedback_worker = threading.Thread(
                        target=self._feedback_loop,
                        name="queryserver-feedback",
                        daemon=True,
                    )
                    self._feedback_worker.start()
        try:
            self._feedback_queue.put_nowait(event)
        except queue.Full:
            with self._lock:
                self._feedback_dropped += 1
            logger.warning("feedback queue full; dropping event %s", pr_id)

    def _feedback_loop(self) -> None:
        url = f"{self.event_server_url}/events.json"
        if self.access_key:
            url += f"?accessKey={self.access_key}"
        while True:
            event = self._feedback_queue.get()
            if event is None:  # sentinel from stop()
                return
            payload = json.dumps(event).encode()

            def post():
                req = urllib.request.Request(
                    url,
                    data=payload,
                    method="POST",
                    headers={"Content-Type": "application/json"},
                )
                # fire-and-forget by design: feedback is decoupled from
                # the request that produced it (the caller already got
                # its answer), so there is no deadline to propagate —
                # the fixed timeout + breaker bound the loop instead
                # pio: ignore[deadline-drop]
                urllib.request.urlopen(req, timeout=5)

            try:
                # pio: ignore[deadline-not-forwarded] (see post() above)
                call_with_resilience(
                    post,
                    self._feedback_policy,
                    breaker=self._feedback_breaker,
                )
            except BreakerOpen:
                # event server is down: drop fast (counted) instead of each
                # event burning max_attempts × timeout behind an open breaker
                self.counters.inc("breaker_open")
            except Exception:
                self.counters.inc("feedback_errors")
                self._rl_log.exception("feedback", "feedback POST failed")

    # -- routes ----------------------------------------------------------------
    def retry_after_s(self) -> float:
        """Backpressure-aware ``Retry-After``: ``shed_retry_after_s`` is
        the BASE.  While draining the hint is the drain budget (the
        earliest a replacement process could answer here); under load
        it scales with queue depth — inflight plus batcher backlog over
        the admission cap — so clients back off longer the deeper the
        overload.  Reads ``_inflight`` without its lock: a torn read
        costs at most one slightly-off hint, and one shed site calls
        this while already holding the lock."""
        if self._draining:
            return max(self.shed_retry_after_s, self.drain_timeout_ms / 1e3)
        depth = float(self._inflight)
        if self._batcher is not None:
            try:
                depth += float(self._batcher.stats().get("depth") or 0)
            except Exception:
                pass
        load = depth / float(max(1, self.max_inflight))
        return round(min(self.shed_retry_after_s * max(1.0, load), 30.0), 2)

    def _register_routes(self):
        svc = self.service

        @svc.route("GET", r"/")
        def index(req: Request):
            with self._lock:
                d = self._deployed
                info = {
                    "status": "alive",
                    "engineInstanceId": d.instance_id if d else None,
                    "engineVariant": self.engine_variant,
                    "startTime": d.start_time if d else None,
                    "requestCount": self.request_count,
                    "avgServingSec": self.avg_serving_sec,
                    "lastServingSec": self.last_serving_sec,
                    "latency": self.latency.summary(),
                    "feedback": self.feedback,
                    "feedbackDropped": self._feedback_dropped,
                }
                algorithms = d.algorithms if d else []
                models = d.models if d else []
            info["batching"] = (
                self._batcher.stats() if self._batcher is not None else None
            )
            info["resultCache"] = (
                self._result_cache.stats()
                if self._result_cache is not None
                else None
            )
            info["coalesce"] = self._coalesce
            info["tenancy"] = (
                self._tenants.stats() if self._tenants is not None else None
            )
            info["pipeline"] = (
                self._pipeline_engine.stats()
                if self._pipeline_engine is not None
                else None
            )
            fp = []
            for algo, model in zip(algorithms, models):
                get_stats = getattr(algo, "serving_stats", None)
                if get_stats is None:
                    continue
                s = get_stats(model)
                if s is not None:
                    fp.append(s)
            info["fastpath"] = fp or None
            with self._inflight_lock:
                inflight = self._inflight
            info["resilience"] = {
                "inflight": inflight,
                "maxInflight": self.max_inflight,
                "counters": self.counters.snapshot(),
                "feedbackBreaker": self._feedback_breaker.stats(),
                "reloadDegraded": self._reload_degraded,
            }
            return json_response(200, info)

        @svc.route("GET", r"/healthz")
        def healthz(req: Request):
            # liveness: the process is up and the route table answers
            return json_response(200, {"status": "ok"})

        @svc.route("GET", r"/readyz")
        def readyz(req: Request):
            # readiness: safe to route traffic here — a model is deployed
            # and the admission gate has headroom.  reloadDegraded is
            # reported but does NOT fail readiness: the last good
            # generation is still serving.
            with self._lock:
                dep = self._deployed
                deployed = dep is not None
                generation = self._serving_gen
                warm = self._fastpath_warm
            with self._inflight_lock:
                inflight = self._inflight
            body = {
                "deployed": deployed,
                "inflight": inflight,
                "maxInflight": self.max_inflight,
                "reloadDegraded": self._reload_degraded,
                "draining": self._draining,
                # router admission context: which model generation is live
                # and whether its warmup compiles completed — balancers gate
                # on *warm*, not merely *loaded*
                "generation": generation,
                "fastpathWarm": warm,
                # the durable identity of the live generation: the local
                # `generation` counter differs per process, so the canary
                # controller attributes per-generation metrics (and targets
                # hot-swaps) by engine instance id
                "engineInstanceId": dep.instance_id if dep else None,
            }
            # sharded placement: surface backend + plan fingerprint so a
            # rebalance is visible as a generation identity change to
            # anything probing readiness (pio shards, the fleet router)
            fps = self._fastpath_stats()
            if fps and fps.get("serving_backend"):
                body["servingBackend"] = fps["serving_backend"]
                plan = (fps.get("sharding") or {}).get("plan") or {}
                if plan.get("fingerprint"):
                    body["shardingFingerprint"] = plan["fingerprint"]
            # pod placement: advertise this replica's host group so the
            # fleet router can fan each query to the group that owns its
            # serving mesh (PIO_POD_GROUP pins the group in fleet
            # deployments of SELF-CONTAINED replicas).  A mesh that spans
            # jax.distributed processes is lockstep-only — advertising a
            # routable group would invite per-group batches its SPMD
            # peers never dispatch, wedging the cross-host collective —
            # so `group` is withheld (null) and the replica reports
            # not-ready below; PIO_POD_GROUP cannot override this.
            pod = (fps or {}).get("pod")
            pod_spans = bool((pod or {}).get("spans_processes"))
            if pod:
                group_env = os.environ.get("PIO_POD_GROUP", "")
                body["pod"] = {
                    "group": None if pod_spans
                    else int(group_env) if group_env.strip()
                    else int(pod.get("process_index") or 0),
                    "groups": int(pod.get("host_groups") or 1),
                    "fingerprint": pod.get("fingerprint"),
                    "processIndex": pod.get("process_index"),
                    "processCount": pod.get("process_count"),
                    "spansProcesses": pod_spans,
                }
            # streaming: expose the applied micro-generation epoch and
            # current staleness so the router/fleet can see exactly where
            # this replica sits in the delta sequence
            st = self._streaming
            delta_behind = False
            if st is not None:
                applied = st["applier"].applied_epoch
                head = st["log"].last_epoch()
                body["deltaEpoch"] = applied
                body["deltaLogEpoch"] = head
                body["stalenessMs"] = round(self._streaming_staleness_ms(), 1)
                # a wedged log (torn blob / fence refusal with no progress
                # since) must not hold the replica out forever: it rejoins
                # at its last good epoch and serves degraded instead
                wedged = st.get("wedged")
                stuck = (
                    wedged is not None
                    and applied <= int(wedged.get("applied_epoch", -1))
                )
                if stuck:
                    body["deltaWedged"] = wedged.get("reason")
                delta_behind = head > applied and not stuck
            # every not-ready answer carries Retry-After, as the shed paths
            # do — docs/operations.md promises the header on all 503s
            retry = {"Retry-After": f"{self.retry_after_s():g}"}
            if self._draining:
                body["status"] = "draining"
                return Response(status=503, body=body, headers=retry)
            if not deployed:
                body["status"] = "no engine instance deployed"
                return Response(status=503, body=body, headers=retry)
            if delta_behind:
                # catch-up before readmission: wake the worker and refuse
                # traffic until this replica reaches the fleet's epoch
                st["wake"].set()
                body["status"] = "delta catch-up"
                return Response(status=503, body=body, headers=retry)
            if inflight >= self.max_inflight:
                body["status"] = "overloaded"
                return Response(status=503, body=body, headers=retry)
            if pod_spans:
                # never admitted into a routed fleet: this process can
                # only score in SPMD lockstep with its pod peers
                body["status"] = "pod mesh spans processes (lockstep only)"
                return Response(status=503, body=body, headers=retry)
            body["status"] = "ready"
            return json_response(200, body)

        def _serve_admitted(req, data, tenant, variant):
            # admission control: beyond max_inflight, queueing only adds
            # latency to requests that will miss their deadlines anyway —
            # shed with 503 + Retry-After so callers back off
            with self._inflight_lock:
                if self._inflight >= self.max_inflight:
                    self.counters.inc("shed")
                    return Response(
                        status=503,
                        body={"message": "server overloaded; request shed"},
                        headers={"Retry-After": f"{self.retry_after_s():g}"},
                    )
                self._inflight += 1
            try:
                deadline = parse_deadline_header(req.headers.get(DEADLINE_HEADER))
                if deadline is None and self.default_deadline_ms is not None:
                    deadline = Deadline.after_ms(self.default_deadline_ms)
                if deadline is not None and deadline.expired():
                    # already over budget on arrival: never touches the device
                    self.counters.inc("deadline_exceeded")
                    return json_response(
                        504, {"message": "deadline expired before execution"}
                    )
                try:
                    # ambient binding: storage/cache hops under this
                    # request see the budget via current_deadline() even
                    # where no deadline parameter reaches them
                    with deadline_scope(deadline):
                        # untenanted servers keep the two-arg calling
                        # convention — handle_query is a documented
                        # wrap/override point (drain tests, operators)
                        # and must not grow required kwargs under them
                        if tenant is None:
                            result = self.handle_query(data, deadline)
                        else:
                            result = self.handle_query(
                                data, deadline,
                                tenant=tenant, variant=variant,
                            )
                        return json_response(200, result)
                except DeadlineExceeded as e:
                    return json_response(504, {"message": str(e)})
                except TypeError as e:
                    return json_response(400, {"message": str(e)})
            finally:
                with self._inflight_lock:
                    self._inflight -= 1

        @svc.route("POST", r"/queries\.json")
        def queries(req: Request):
            with _tracing.stage("decode"):
                data = req.json()
            if not isinstance(data, dict):
                return json_response(400, {"message": "query must be a JSON object"})
            if self._draining:
                # draining: in-flight work finishes, new work goes elsewhere
                return Response(
                    status=503,
                    body={"message": "server draining; retry against "
                          "another instance"},
                    headers={"Retry-After": f"{self.retry_after_s():g}"},
                )
            if self._pod_lockstep():
                # refusing beats deadlocking: one process of a
                # process-spanning pod mesh cannot dispatch alone — its
                # SPMD peers would never join the cross-host collective
                return Response(
                    status=503,
                    body={"message": "pod mesh spans processes: queries "
                          "must be dispatched in SPMD lockstep on every "
                          "process, not routed to one — serve through "
                          "self-contained host-local replicas instead"},
                    headers={"Retry-After": f"{self.retry_after_s():g}"},
                )
            if _faults.active() is not None:
                # generation-keyed chaos: a rule on server:generation:<id>
                # degrades ONLY the replica serving that engine instance —
                # how the canary bench injects a bad candidate generation
                # without touching its baseline siblings in the same image
                with self._lock:
                    live = self._deployed
                if live is not None:
                    act = _faults.check(
                        f"server:generation:{live.instance_id}"
                    )
                    if act is not None:
                        if act.latency_s:
                            time.sleep(act.latency_s)
                        if act.kind in ("error", "drop", "crash"):
                            return json_response(
                                act.status or 500,
                                {"message": "injected generation fault",
                                 "injected": True},
                            )
            reg = self._tenants
            if reg is None:
                return _serve_admitted(req, data, None, None)
            # multi-tenant surface: the event-server auth contract on the
            # query plane — key from ?accessKey=, X-PIO-Access-Key, or the
            # body's accessKey field (stripped from cache fingerprints)
            key = extract_access_key(req.params, req.headers, data)
            if not key:
                return json_response(401, {"message": "Missing accessKey."})
            spec = reg.authenticate(key)
            if spec is None:
                return json_response(401, {"message": "Invalid accessKey."})
            tenant = spec.tenant_id
            act = _faults.check(f"client:tenant:{tenant}")
            if act is not None:
                # a chaos-injected bad request FROM this tenant: it feeds
                # this tenant's breaker only — the isolation contract the
                # chaos suite asserts on every other tenant's breaker
                if act.latency_s:
                    time.sleep(act.latency_s)
                if act.kind in ("error", "drop", "crash"):
                    reg.record_result(tenant, None, ok=False, latency_s=0.0)
                    return json_response(
                        act.status or 503,
                        {"message": "injected fault", "injected": True},
                    )
            adm = reg.admit(tenant)
            if not adm.ok:
                # per-tenant shed: quota exhausted, fair-share inflight
                # cap, or this tenant's breaker open — 503 with a
                # quota-aware Retry-After, never touching other tenants
                return Response(
                    status=503,
                    body={"message": f"tenant {tenant} shed", "tenant": tenant,
                          "reason": adm.reason},
                    headers={"Retry-After": f"{adm.retry_after_s:g}"},
                )
            variant = reg.pick_variant(tenant, data.get("user"))
            ok = False
            t0 = time.perf_counter()
            try:
                resp = _serve_admitted(req, data, tenant, variant)
                # 4xx/503 are the contract working, not tenant failures;
                # only 5xx server errors feed this tenant's breaker
                ok = resp.status < 500 or resp.status == 503
                return resp
            finally:
                reg.release(tenant)
                reg.record_result(
                    tenant, variant, ok=ok,
                    latency_s=time.perf_counter() - t0,
                )

        @svc.route("GET", r"/reload")
        @svc.route("POST", r"/reload")
        def reload_route(req: Request):
            # ?instanceId= pins the swap to one generation (the canary
            # controller's promote/rollback hop); quarantined ids refuse
            # with 409 unless ?force=1 (operator override)
            target = (req.params.get("instanceId") or "").strip() or None
            force = (req.params.get("force") or "") in ("1", "true", "yes")
            try:
                iid = self.reload(instance_id=target, force=force)
            except RuntimeError as e:
                if "quarantined" in str(e):
                    return json_response(409, {"message": str(e)})
                raise
            return json_response(200, {"message": "Reloaded", "engineInstanceId": iid})

        @svc.route("POST", r"/delta")
        def delta_route(req: Request):
            # router → replica delta hop: body is the sealed checksum
            # envelope, verbatim.  Every answer is a receipt the router
            # records as this replica's apply acknowledgement.  A torn or
            # forged payload is an integrity REFUSAL (200 + receipt), not
            # a 5xx — the replica keeps serving its last good epoch.
            st = self._streaming
            if st is None:
                return json_response(
                    409,
                    {"refused": True, "reason": "streaming disabled",
                     "streaming": _delta.streaming_enabled()},
                )
            try:
                payload = open_model_blob(req.body)
                dl = _delta.Delta.from_payload(payload)
            except Exception as e:
                # legacy passthrough means garbage survives the envelope
                # check and dies at unpickle — either way it never reaches
                # the factors
                receipt = st["applier"].refuse("integrity", error=str(e))
                return json_response(200, receipt)
            receipt = st["applier"].apply(dl)
            if receipt.get("applied"):
                st["wedged"] = None
            return json_response(200, receipt)

        @svc.route("GET", r"/delta/stats")
        def delta_stats_route(req: Request):
            stats = self.streaming_stats()
            if stats is None:
                return json_response(
                    404, {"message": "streaming disabled"}
                )
            return json_response(200, stats)

        @svc.route("POST", r"/stop")
        def stop_route(req: Request):
            def _stop():
                time.sleep(0.3)  # let the response flush before the socket dies
                self.drain()

            threading.Thread(target=_stop, daemon=True).start()
            return json_response(200, {"message": "Shutting down."})

        @svc.route("POST", r"/debug/profile")
        def profile_route(req: Request):
            # guarded, bounded, single-flight: jax.profiler is process-
            # global, so concurrent captures are refused (409) rather
            # than interleaved; the window is capped so a fat-fingered
            # ms can't hold the trace machinery open for minutes
            if os.environ.get("PIO_PROFILE_ENDPOINT", "1") == "0":
                return json_response(
                    403,
                    {"message": "profile endpoint disabled "
                     "(PIO_PROFILE_ENDPOINT=0)"},
                )
            try:
                ms = int(req.params.get("ms") or 500)
            except (TypeError, ValueError):
                return json_response(
                    400, {"message": "ms must be an integer"}
                )
            ms = max(1, min(ms, 10_000))
            if not self._profile_lock.acquire(blocking=False):
                return json_response(
                    409, {"message": "a profile capture is already running"}
                )
            try:
                path = _devprof.capture_profile(ms)
            except Exception as e:
                self._rl_log.exception(
                    "profile", "profile capture failed"
                )
                return json_response(
                    500, {"message": f"profile capture failed: {e}"}
                )
            finally:
                self._profile_lock.release()
            with self._lock:
                self._profile_captures += 1
                self._profile_last_unix = time.time()
            return json_response(200, {"path": path, "ms": ms})

        @svc.route("GET", r"/plugins\.json")
        def plugins_route(req: Request):
            return json_response(
                200,
                {
                    "plugins": {
                        "outputblockers": {
                            p.name: {"class": type(p).__name__}
                            for p in self.plugins
                            if p.plugin_type == EngineServerPlugin.OUTPUT_BLOCKER
                        },
                        "outputsniffers": {
                            p.name: {"class": type(p).__name__}
                            for p in self.plugins
                            if p.plugin_type == EngineServerPlugin.OUTPUT_SNIFFER
                        },
                    }
                },
            )

    # -- lifecycle ---------------------------------------------------------------
    def start(self, host: str = "0.0.0.0", port: int = 8000, **tls) -> int:
        actual = self.service.start(host, port, **tls)
        logger.info("query server listening on %s:%s", host, actual)
        return actual

    def drain(self, timeout_ms: Optional[float] = None) -> bool:
        """Graceful shutdown: flip /readyz to draining (new queries shed),
        wait for in-flight queries — including queued micro-batches — to
        finish inside the budget, then stop. Returns True when nothing
        was abandoned; abandoned work is counted either way."""
        budget_s = (
            timeout_ms if timeout_ms is not None else self.drain_timeout_ms
        ) / 1e3
        with self._lock:
            self._draining = True
        deadline = time.monotonic() + max(budget_s, 0.0)
        while time.monotonic() < deadline:
            with self._inflight_lock:
                inflight = self._inflight
            if inflight == 0:
                break
            time.sleep(0.005)
        with self._inflight_lock:
            abandoned = self._inflight
        if abandoned:
            self.counters.inc("drain_abandoned", abandoned)
            logger.warning(
                "drain budget (%.0fms) lapsed with %d queries in flight",
                budget_s * 1e3, abandoned,
            )
        else:
            self.counters.inc("drained")
        self.stop()
        return abandoned == 0

    def stop(self) -> None:
        self._stop_streaming()
        if self._batcher is not None:
            self._batcher.stop()
        if self._feedback_worker is not None:
            try:
                self._feedback_queue.put_nowait(None)  # drain-and-exit sentinel
            except queue.Full:
                pass  # worker is wedged; it's a daemon thread, let it die
        self.service.stop()
