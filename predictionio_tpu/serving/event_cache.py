"""In-process TTL cache with async refresh for serving-time event lookups.

Why this exists (SURVEY.md §7 "hard parts"): the e-commerce template's
predict path consults the live event store per query — the user's seen-item
set and the ``unavailableItems`` constraint entity (reference parity:
``examples/scala-parallel-ecommercerecommendation/adjust-score/src/main/
scala/ECommAlgorithm.scala:332-360``, which does a timed
``LEventStore.findByEntity`` on every request).  A storage round-trip in
the <10 ms REST predict path makes filtered-query latency storage-bound;
with a remote (network-driver) event store it dominates outright.

:class:`ServingEventCache` keeps the hot path in process memory:

* **miss** → load synchronously (first query for a user pays one read);
* **hit** → return the cached value immediately, never touching storage;
* **stale hit** (older than ``refresh_interval``) → still returns the
  cached value with zero storage reads, and schedules a refresh on a
  single background worker thread (deduplicated per key), so new events
  appear within one refresh interval without a query ever blocking.

Steady state therefore makes ZERO storage round-trips on the request path.
Thread-safe; the query server handles requests on multiple threads.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from collections import OrderedDict
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Hashable, Optional

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class CacheStats:
    hits: int = 0
    misses: int = 0
    refreshes: int = 0
    evictions: int = 0
    invalidated: int = 0  # entries dropped by an invalidation-token change

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class ServingEventCache:
    """Key → value cache with background refresh after ``refresh_interval``.

    ``loader`` callables are supplied per ``get`` so one cache can serve
    heterogeneous lookups (seen-sets keyed by user, constraint entities,
    item properties...).  A failed refresh keeps the previous value and
    logs — serving stays up on a flaky store (matching the template's
    existing degrade-gracefully behavior on lookup errors).
    """

    def __init__(
        self,
        refresh_interval: float = 5.0,
        max_entries: int = 100_000,
        clock: Callable[[], float] = time.monotonic,
        refresh_timeout: float = 30.0,
        refresh_workers: int = 4,
    ):
        self.refresh_interval = float(refresh_interval)
        self.max_entries = int(max_entries)
        self.refresh_timeout = float(refresh_timeout)
        self.refresh_workers = int(refresh_workers)
        self._clock = clock
        self._lock = threading.Lock()
        # insertion/refresh-ordered so eviction is O(1) popitem(last=False)
        # instead of a min-scan under the lock on the serving path
        self._data: "OrderedDict[Hashable, tuple[Any, float]]" = OrderedDict()
        # key → wall-clock start of the in-flight refresh; entries older
        # than refresh_timeout are presumed hung (e.g. a TCP black hole on
        # a remote store) and no longer block a new refresh of that key
        self._inflight: dict[Hashable, float] = {}
        self._executor: Optional[ThreadPoolExecutor] = None
        self.stats = CacheStats()

    # -- core ---------------------------------------------------------------
    def get(
        self,
        key: Hashable,
        loader: Callable[[], Any],
        token: Any = None,
    ) -> Any:
        """Cached value for ``key``; loads synchronously on a miss.

        ``token`` opts the entry into event-driven invalidation: pass the
        current invalidation token for the entities this lookup depends on
        (``result_cache.INVALIDATIONS.token(...)``).  A stored entry whose
        token no longer matches is reloaded SYNCHRONOUSLY — the caller
        sees the post-event value immediately instead of one refresh
        interval later.  ``token=None`` keeps the pure TTL behavior.
        """
        now = self._clock()
        with self._lock:
            entry = self._data.get(key)
            if entry is not None and token is not None and entry[2] != token:
                # an event moved a dependency: the stale value must not be
                # served even once, so this is a hard miss, not a refresh
                del self._data[key]
                self.stats.invalidated += 1
                entry = None
            if entry is not None:
                self.stats.hits += 1
        if entry is not None:
            value, loaded_at, _ = entry
            if now - loaded_at >= self.refresh_interval:
                self._schedule_refresh(key, loader, token)
            return value
        value = loader()
        with self._lock:
            self.stats.misses += 1
            self._data[key] = (value, now, token)
            self._data.move_to_end(key)
            self._evict_locked()
        return value

    def invalidate(self, key: Hashable) -> None:
        with self._lock:
            self._data.pop(key, None)

    def clear(self) -> None:
        with self._lock:
            self._data.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._data)

    def stats_dict(self) -> dict:
        """Counter snapshot + sizing for the obs bridge
        (``pio_event_cache_*``).  Named ``stats_dict`` because ``stats``
        is the live :class:`CacheStats` attribute."""
        with self._lock:
            out = self.stats.to_dict()
            out["entries"] = len(self._data)
            out["max_entries"] = self.max_entries
            out["refresh_interval_s"] = self.refresh_interval
            out["inflight_refreshes"] = len(self._inflight)
        return out

    # -- internals ----------------------------------------------------------
    def _evict_locked(self) -> None:
        # stalest-first (insertion/refresh order) O(1) eviction; max_entries
        # bounds resident memory for unbounded user populations
        while len(self._data) > self.max_entries:
            self._data.popitem(last=False)
            self.stats.evictions += 1

    def _schedule_refresh(
        self, key: Hashable, loader: Callable[[], Any], token: Any = None
    ) -> None:
        # same clock as entry ages: with an injected test clock the staleness
        # and hung-refresh timeout domains must not diverge
        started = self._clock()
        with self._lock:
            inflight_since = self._inflight.get(key)
            if (
                inflight_since is not None
                and started - inflight_since < self.refresh_timeout
            ):
                return  # a live refresh is already running for this key
            # either no refresh in flight, or the previous one is presumed
            # hung (its thread, if still alive, loses the write race below)
            self._inflight[key] = started
            if self._executor is None:
                self._executor = ThreadPoolExecutor(
                    max_workers=self.refresh_workers,
                    thread_name_prefix="event-cache-refresh",
                )
            executor = self._executor

        def work():
            try:
                value = loader()
                with self._lock:
                    # a superseded (hung-then-completed) refresh must not
                    # clobber a newer one's in-flight bookkeeping
                    if self._inflight.get(key) == started:
                        self._data[key] = (value, self._clock(), token)
                        self._data.move_to_end(key)
                        self.stats.refreshes += 1
            except Exception:
                logger.exception("cache refresh for %r failed; keeping stale", key)
            finally:
                with self._lock:
                    if self._inflight.get(key) == started:
                        del self._inflight[key]

        try:
            executor.submit(work)
        except RuntimeError:
            # a concurrent close() shut the executor down between the lock
            # release and submit; serving is winding down — drop the refresh
            # (the stale value was already returned) and clear bookkeeping
            with self._lock:
                if self._inflight.get(key) == started:
                    del self._inflight[key]

    def wait_refreshes(self, timeout: float = 5.0) -> None:
        """Block until no refresh is in flight (tests / graceful shutdown)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                if not self._inflight:
                    return
            time.sleep(0.005)
        raise TimeoutError("cache refreshes still in flight")

    def close(self) -> None:
        with self._lock:
            executor, self._executor = self._executor, None
        if executor is not None:
            executor.shutdown(wait=False)
