"""SQL-queryable event views (parity: ``data/view/DataView.scala``).

The reference's ``DataView.create`` turns an app's events into a Spark SQL
DataFrame via a user conversion function, caching the materialized view as a
parquet file under ``$PIO_FS_BASEDIR/view`` keyed by a hash of the time range,
a user ``version`` tag, and the conversion class
(``DataView.scala:56-110``).  The deprecated ``LBatchView``/``PBatchView``
layer is intentionally not reproduced (deprecated since 0.9.2 upstream).

Here the view is a pandas DataFrame (the notebook surface — pypio's
``find_events`` returns the same shape) and the SQL engine is sqlite, which
ships with CPython: :func:`sql` loads one or more DataFrames into an
in-memory sqlite database and runs arbitrary SQL against them.  The TPU is
for training/serving math; ad-hoc relational queries over event logs are a
host-side concern, so a host SQL engine is the idiomatic seat for them.

Usage::

    from predictionio_tpu.data import view

    df = view.create("myapp", conversion=lambda e: {
        "user": e.entity_id, "item": e.target_entity_id,
        "rating": e.properties.get("rating"),
    } if e.event == "rate" else None)

    top = view.sql(
        "SELECT item, COUNT(*) AS n FROM rates GROUP BY item ORDER BY n DESC",
        rates=df)

    # or one-shot over the default flat event columns:
    view.events_sql("myapp", "SELECT event, COUNT(*) FROM events GROUP BY 1")
"""

from __future__ import annotations

import datetime as _dt
import hashlib
import json
import logging
import os
import sqlite3
from typing import Any, Callable, Mapping, Optional

from predictionio_tpu.data.event import Event, utcnow
from predictionio_tpu.utils.fs import pio_base_dir

logger = logging.getLogger(__name__)

Conversion = Callable[[Event], Optional[Mapping[str, Any]]]


_DEFAULT_COLUMNS = (
    "eventId", "event", "entityType", "entityId", "targetEntityType",
    "targetEntityId", "properties", "eventTime", "creationTime",
)


def _default_conversion(e: Event) -> Mapping[str, Any]:
    """Flat, SQL-friendly row: scalar columns + properties as JSON text."""
    return {
        "eventId": e.event_id,
        "event": e.event,
        "entityType": e.entity_type,
        "entityId": e.entity_id,
        "targetEntityType": e.target_entity_type,
        "targetEntityId": e.target_entity_id,
        "properties": json.dumps(e.properties.to_dict(), sort_keys=True),
        "eventTime": e.event_time.timestamp(),
        "creationTime": e.creation_time.timestamp(),
    }


def _conversion_hash(conversion: Optional[Conversion]) -> str:
    """Stable-ish fingerprint of the conversion function.

    Plays the role of the serialVersionUID in the reference's cache key
    (``DataView.scala:77-79``); ``version`` remains the user's explicit
    escape hatch when the body changes in ways the fingerprint misses
    (e.g. a closed-over global).
    """
    if conversion is None:
        return "default"
    code = getattr(conversion, "__code__", None)
    if code is None:  # builtins / callables: name is the best we can do
        return getattr(conversion, "__qualname__", repr(conversion))
    h = hashlib.sha1()

    def feed(c) -> None:
        h.update(c.co_code)
        # names matter: `e.entity_id` vs `e.target_entity_id` differ only
        # in co_names, not co_code
        for names in (c.co_names, c.co_varnames, c.co_freevars):
            h.update("\0".join(names).encode())
        for const in c.co_consts:
            if hasattr(const, "co_code"):  # nested lambda/comprehension:
                feed(const)  # repr() would embed a memory address
            else:
                h.update(repr(const).encode())

    feed(code)
    return h.hexdigest()[:16]


def create(
    app_name: str,
    channel_name: Optional[str] = None,
    start_time: Optional[_dt.datetime] = None,
    until_time: Optional[_dt.datetime] = None,
    conversion: Optional[Conversion] = None,
    name: str = "",
    version: str = "",
    cache: Optional[bool] = None,
):
    """Materialize an app's events as a DataFrame view.

    ``conversion`` maps each :class:`Event` to a row mapping (``None`` drops
    the event), like the reference's ``conversionFunction``; by default
    events become flat columns with properties as a JSON text column.

    ``cache``: ``True`` reads/writes a parquet copy under
    ``$PIO_FS_BASEDIR/view`` keyed like the reference
    (time-range + version + conversion fingerprint).  ``None`` (auto)
    caches only when ``until_time`` is pinned — an unbounded view is a
    different result every call, so caching it would either be stale or,
    as in the reference (which keys on ``DateTime.now()``), never hit.
    """
    import pandas as pd

    from predictionio_tpu.data.store import PEventStore

    # normalize tz-naive bounds to UTC: comparing naive against the tz-aware
    # utcnow() below would raise a bare TypeError mid-call otherwise
    if start_time is not None and start_time.tzinfo is None:
        start_time = start_time.replace(tzinfo=_dt.timezone.utc)
    if until_time is not None and until_time.tzinfo is None:
        until_time = until_time.replace(tzinfo=_dt.timezone.utc)
    begin = start_time or _dt.datetime.fromtimestamp(0, _dt.timezone.utc)
    end = until_time or utcnow()  # fix the current time (DataView.scala:73-76)
    if cache is None:
        # only a CLOSED window is immutable; a future until_time still
        # admits new events, so freezing it at first call would drop them
        cache = until_time is not None and until_time <= utcnow()

    cache_path = None
    if cache:
        key = hashlib.sha1(
            f"{channel_name or ''}-{begin.isoformat()}-{end.isoformat()}-"
            f"{version}-{_conversion_hash(conversion)}".encode()
        ).hexdigest()[:20]
        view_dir = os.path.join(pio_base_dir(), "view")
        cache_path = os.path.join(view_dir, f"{name or 'view'}-{app_name}-{key}.parquet")
        if os.path.exists(cache_path):
            try:
                return pd.read_parquet(cache_path)
            except Exception as exc:  # corrupt cache: rebuild
                logger.warning("view cache %s unreadable (%s); rebuilding", cache_path, exc)

    batch = PEventStore.find(
        app_name,
        channel_name=channel_name,
        start_time=start_time,
        until_time=end,
    )
    conv = conversion or _default_conversion
    rows = []
    for event in batch:
        row = conv(event)
        if row is not None:
            rows.append(dict(row))
    if not rows and conversion is None:
        # zero events must still yield a well-formed (SQL-loadable) view
        df = pd.DataFrame(columns=list(_DEFAULT_COLUMNS))
    else:
        df = pd.DataFrame(rows)

    if cache_path is not None:
        try:
            os.makedirs(os.path.dirname(cache_path), exist_ok=True)
            df.to_parquet(cache_path)
        except Exception as exc:  # pyarrow missing etc.: view still works
            logger.info("view cache write skipped (%s)", exc)
    return df


def sql(query: str, views: Optional[Mapping[str, Any]] = None, **named_views):
    """Run SQL over DataFrame views (parity role: Spark SQL over DataView).

    Each keyword (or ``views`` entry) becomes a table in an in-memory
    sqlite database; returns the result as a DataFrame.
    """
    import pandas as pd

    if views is not None and not hasattr(views, "items"):
        raise TypeError(
            "views must be a mapping of {table_name: DataFrame}; to query a "
            "table named 'views' pass it via the views mapping: "
            "sql(query, {'views': df})"
        )
    if isinstance(views, pd.DataFrame):
        raise TypeError(
            "a bare DataFrame was passed as `views`; pass {'views': df} to "
            "name a table 'views', or use a different keyword"
        )
    tables = dict(views or {})
    tables.update(named_views)
    if not tables:
        raise ValueError("sql() needs at least one named view")
    conn = sqlite3.connect(":memory:")
    try:
        for table_name, df in tables.items():
            if df.shape[1] == 0:
                raise ValueError(
                    f"view {table_name!r} has no columns (empty conversion "
                    "view?) — sqlite cannot create a column-less table"
                )
            df.to_sql(table_name, conn, index=False)
        return pd.read_sql_query(query, conn)
    finally:
        conn.close()


def events_sql(
    app_name: str,
    query: str,
    table: str = "events",
    channel_name: Optional[str] = None,
    start_time: Optional[_dt.datetime] = None,
    until_time: Optional[_dt.datetime] = None,
):
    """One-shot SQL over an app's default flat event view."""
    df = create(
        app_name,
        channel_name=channel_name,
        start_time=start_time,
        until_time=until_time,
    )
    return sql(query, {table: df})
