"""Columnar event batches — the bulk-read currency of the framework.

The reference's bulk path returns ``RDD[Event]``
(``data/.../data/storage/PEvents.scala:38-189``); rows are then re-shaped by
every template into id-indexed matrices.  TPU-first, the bulk path instead
yields an :class:`EventBatch`: column-oriented numpy arrays that convert to
integer/float columns in one vectorized pass, ready to be placed on a device
mesh as sharded ``jax.Array``s.  Row-wise :class:`Event` iteration is still
available for code that wants it.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Iterable, Iterator, Optional, Sequence

import numpy as np

from predictionio_tpu.data.bimap import BiMap
from predictionio_tpu.data.event import Event


class LazyJsonProperties(Sequence):
    """Row-aligned property dicts decoded from JSON strings on access.

    Bulk storage keeps properties as JSON; decoding 25M rows eagerly costs
    minutes, and most pipelines touch only a numeric key or two (via
    promoted columns) or a small row subset. Decoded rows are cached.
    """

    __slots__ = ("_raw", "_cache")

    def __init__(self, raw: np.ndarray):
        self._raw = raw  # object array of JSON strings
        self._cache: dict[int, dict] = {}

    def __len__(self) -> int:
        return len(self._raw)

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [self[j] for j in range(*i.indices(len(self)))]
        i = int(i)
        got = self._cache.get(i)
        if got is None:
            got = self.decode(i)
            self._cache[i] = got
        return got

    def decode(self, i: int) -> dict:
        """Decode one row WITHOUT caching (full-scan iteration stays O(1))."""
        import json

        raw = self._raw[int(i)]
        return json.loads(raw) if raw else {}

    def subset(self, idx: np.ndarray) -> "LazyJsonProperties":
        return LazyJsonProperties(self._raw[idx])


@dataclass
class EventBatch:
    """A set of events in structure-of-arrays form."""

    event: np.ndarray  # object (str)
    entity_type: np.ndarray  # object (str)
    entity_id: np.ndarray  # object (str)
    target_entity_type: np.ndarray  # object (str | None)
    target_entity_id: np.ndarray  # object (str | None)
    event_time: np.ndarray  # float64 epoch seconds
    properties: Sequence  # row-aligned property dicts (list or LazyJson)
    event_id: np.ndarray = None  # object (str | None)
    tags: list[tuple] = None  # row-aligned tag tuples
    pr_id: np.ndarray = None  # object (str | None)
    creation_time: np.ndarray = None  # float64 epoch seconds
    # storage-promoted numeric property columns (e.g. parquet parts):
    # property_column() serves from here without touching JSON
    numeric_properties: Optional[dict] = None

    def __post_init__(self):
        n = len(self.event)
        if self.event_id is None:
            self.event_id = np.full(n, None, dtype=object)
        if self.tags is None:
            self.tags = [()] * n
        if self.pr_id is None:
            self.pr_id = np.full(n, None, dtype=object)
        if self.creation_time is None:
            self.creation_time = self.event_time.copy()

    @staticmethod
    def from_events(events: Iterable[Event]) -> "EventBatch":
        evs = list(events)
        n = len(evs)

        def col(f: Callable[[Event], object]) -> np.ndarray:
            a = np.empty(n, dtype=object)
            for i, e in enumerate(evs):
                a[i] = f(e)
            return a

        return EventBatch(
            event=col(lambda e: e.event),
            entity_type=col(lambda e: e.entity_type),
            entity_id=col(lambda e: e.entity_id),
            target_entity_type=col(lambda e: e.target_entity_type),
            target_entity_id=col(lambda e: e.target_entity_id),
            event_time=np.array(
                [e.event_time.timestamp() for e in evs], dtype=np.float64
            ),
            properties=[e.properties.to_dict() for e in evs],
            event_id=col(lambda e: e.event_id),
            tags=[e.tags for e in evs],
            pr_id=col(lambda e: e.pr_id),
            creation_time=np.array(
                [e.creation_time.timestamp() for e in evs], dtype=np.float64
            ),
        )

    def __len__(self) -> int:
        return len(self.event)

    def __iter__(self) -> Iterator[Event]:
        lazy = isinstance(self.properties, LazyJsonProperties)
        for i in range(len(self)):
            yield Event(
                event=self.event[i],
                entity_type=self.entity_type[i],
                entity_id=self.entity_id[i],
                target_entity_type=self.target_entity_type[i],
                target_entity_id=self.target_entity_id[i],
                # full scans must not populate the per-row decode cache
                properties=(
                    self.properties.decode(i) if lazy else self.properties[i]
                ),
                event_time=float(self.event_time[i]),
                tags=self.tags[i],
                pr_id=self.pr_id[i],
                event_id=self.event_id[i],
                creation_time=float(self.creation_time[i]),
            )

    def select(self, mask: np.ndarray) -> "EventBatch":
        idx = np.nonzero(mask)[0]
        props = (
            self.properties.subset(idx)
            if isinstance(self.properties, LazyJsonProperties)
            else [self.properties[i] for i in idx]
        )
        return EventBatch(
            event=self.event[idx],
            entity_type=self.entity_type[idx],
            entity_id=self.entity_id[idx],
            target_entity_type=self.target_entity_type[idx],
            target_entity_id=self.target_entity_id[idx],
            event_time=self.event_time[idx],
            properties=props,
            event_id=self.event_id[idx],
            tags=[self.tags[i] for i in idx],
            pr_id=self.pr_id[idx],
            creation_time=self.creation_time[idx],
            numeric_properties=(
                {k: v[idx] for k, v in self.numeric_properties.items()}
                if self.numeric_properties
                else None
            ),
        )

    def filter_events(self, names: Sequence[str]) -> "EventBatch":
        names_set = set(names)
        return self.select(
            np.fromiter((e in names_set for e in self.event), dtype=bool, count=len(self))
        )

    def to_dataframe(self):
        """Events as a pandas DataFrame (parity: data/view DataView and
        PPythonEventStore's DataFrame-returning reads — the notebook
        surface)."""
        import pandas as pd

        return pd.DataFrame(
            {
                "eventId": self.event_id,
                "event": self.event,
                "entityType": self.entity_type,
                "entityId": self.entity_id,
                "targetEntityType": self.target_entity_type,
                "targetEntityId": self.target_entity_id,
                "properties": self.properties,
                "eventTime": pd.to_datetime(self.event_time, unit="s", utc=True),
                "creationTime": pd.to_datetime(
                    self.creation_time, unit="s", utc=True
                ),
            }
        )

    # Id-index helpers ------------------------------------------------------
    def entity_bimap(self) -> BiMap[str, int]:
        return BiMap.string_int(self.entity_id)

    def target_bimap(self) -> BiMap[str, int]:
        import pandas as pd

        mask = pd.notna(self.target_entity_id)
        return BiMap.string_int(self.target_entity_id[mask])

    def property_column(self, key: str, default: float = np.nan) -> np.ndarray:
        """Extract one numeric property across all rows as float64.

        Served from storage-promoted columns when available (no JSON touch).
        """
        if self.numeric_properties is not None and key in self.numeric_properties:
            col = self.numeric_properties[key].astype(np.float64)
            return np.where(np.isnan(col), default, col)
        return np.array(
            [float(p.get(key, default)) for p in self.properties], dtype=np.float64
        )

    def interactions(
        self,
        user_map: Optional[BiMap[str, int]] = None,
        item_map: Optional[BiMap[str, int]] = None,
        rating_key: Optional[str] = None,
        default_rating: float = 1.0,
    ) -> "Interactions":
        """Convert (entity → target) events into integer-indexed triples."""
        if user_map is None:
            user_map = self.entity_bimap()
        if item_map is None:
            item_map = self.target_bimap()
        users = user_map.to_index_array(self.entity_id)
        items = item_map.to_index_array(
            ["" if t is None else t for t in self.target_entity_id]
        )
        if rating_key is None:
            ratings = np.full(len(self), default_rating, dtype=np.float32)
        else:
            ratings = self.property_column(rating_key, default_rating).astype(np.float32)
        ok = (users >= 0) & (items >= 0)
        return Interactions(
            user=users[ok].astype(np.int32),
            item=items[ok].astype(np.int32),
            rating=ratings[ok],
            t=self.event_time[ok],
            user_map=user_map,
            item_map=item_map,
        )


def merge_interactions(parts: "Sequence[Interactions]") -> "Interactions":
    """Concatenate Interactions with differing id maps into shared maps.

    Each part's codes are remapped through its uniques (small arrays), so
    merging N bulk reads (e.g. one per event type, different weights) stays
    O(rows) with no per-row Python.
    """
    parts = [p for p in parts if len(p)]
    if not parts:
        raise ValueError("nothing to merge")
    if len(parts) == 1:
        return parts[0]
    user_map = BiMap.string_int(
        np.concatenate([np.array(list(p.user_map.keys()), object) for p in parts])
    )
    item_map = BiMap.string_int(
        np.concatenate([np.array(list(p.item_map.keys()), object) for p in parts])
    )
    users, items, ratings, ts = [], [], [], []
    for p in parts:
        u_remap = user_map.to_index_array(list(p.user_map.keys()))
        i_remap = item_map.to_index_array(list(p.item_map.keys()))
        users.append(u_remap[p.user].astype(np.int32))
        items.append(i_remap[p.item].astype(np.int32))
        ratings.append(p.rating)
        ts.append(p.t)
    return Interactions(
        user=np.concatenate(users),
        item=np.concatenate(items),
        rating=np.concatenate(ratings),
        t=np.concatenate(ts),
        user_map=user_map,
        item_map=item_map,
    )


class EntityMap:
    """Entity ids ↔ indices plus their property snapshots.

    Parity: ``data/.../storage/EntityMap.scala`` (extractEntityMap) — the
    view templates use to turn aggregated entity properties into an
    index-aligned table.
    """

    def __init__(self, properties: dict):
        from predictionio_tpu.data.bimap import BiMap as _BiMap

        self._properties = dict(properties)
        self.id_map = _BiMap.string_int(self._properties.keys())

    def __len__(self) -> int:
        return len(self._properties)

    def __contains__(self, entity_id) -> bool:
        return entity_id in self._properties

    def properties(self, entity_id):
        return self._properties[entity_id]

    def index_of(self, entity_id) -> int:
        return self.id_map[entity_id]

    def entity_of(self, index: int):
        return self.id_map.inverse[index]

    def items(self):
        return self._properties.items()


@dataclass
class Interactions:
    """Integer-indexed (user, item, rating, time) triples + their id tables."""

    user: np.ndarray  # int32
    item: np.ndarray  # int32
    rating: np.ndarray  # float32
    t: np.ndarray  # float64
    user_map: BiMap[str, int] = field(repr=False, default=None)
    item_map: BiMap[str, int] = field(repr=False, default=None)

    def __len__(self) -> int:
        return len(self.user)

    @property
    def n_users(self) -> int:
        return len(self.user_map) if self.user_map is not None else int(self.user.max()) + 1

    @property
    def n_items(self) -> int:
        return len(self.item_map) if self.item_map is not None else int(self.item.max()) + 1

    def subset(self, mask: np.ndarray) -> "Interactions":
        """Row-select by boolean mask or index array; id maps carry over."""
        return Interactions(
            user=self.user[mask],
            item=self.item[mask],
            rating=self.rating[mask],
            t=self.t[mask],
            user_map=self.user_map,
            item_map=self.item_map,
        )

    def drop_items(self, item_indices: np.ndarray) -> "Interactions":
        """Remove the given items' rows AND compact both id spaces.

        Unlike ``subset`` (which keeps the maps), dropped items leave
        ``item_map`` entirely — and users whose every interaction involved a
        dropped item leave ``user_map`` — so downstream models cannot score
        them.  An entity absent from training must be unknown to the model,
        not a zero-factor row (reference behavior: maps are built from the
        already-filtered ratings).
        """
        if self.item_map is None:
            raise ValueError("drop_items requires an item_map")
        n = len(self.item_map)
        keep_item = np.ones(n, bool)
        idx = np.asarray(item_indices, dtype=np.int64)
        keep_item[idx[(idx >= 0) & (idx < n)]] = False
        if keep_item.all():
            return self
        row_keep = keep_item[self.item]

        def _compact(mask: np.ndarray, bimap: BiMap):
            new_of_old = np.cumsum(mask) - 1
            inv = bimap.inverse
            new_map = BiMap(
                {inv[o]: int(new_of_old[o]) for o in range(len(mask)) if mask[o]}
            )
            return new_of_old, new_map

        item_of_old, new_item_map = _compact(keep_item, self.item_map)
        if self.user_map is None:
            return Interactions(
                user=self.user[row_keep],
                item=item_of_old[self.item[row_keep]].astype(self.item.dtype),
                rating=self.rating[row_keep],
                t=self.t[row_keep],
                user_map=None,
                item_map=new_item_map,
            )
        keep_user = np.zeros(len(self.user_map), bool)
        keep_user[self.user[row_keep]] = True
        user_of_old, new_user_map = _compact(keep_user, self.user_map)
        return Interactions(
            user=user_of_old[self.user[row_keep]].astype(self.user.dtype),
            item=item_of_old[self.item[row_keep]].astype(self.item.dtype),
            rating=self.rating[row_keep],
            t=self.t[row_keep],
            user_map=new_user_map,
            item_map=new_item_map,
        )
