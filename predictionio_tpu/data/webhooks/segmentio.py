"""Segment.io webhook connector.

Parity: ``data/.../data/webhooks/segmentio/SegmentIOConnector.scala:31-98``
(identify / track / alias / page / screen / group messages → events named
``$identify``-style ``<type>`` with userId as the entity).
"""

from __future__ import annotations

from typing import Mapping

from predictionio_tpu.data.webhooks.connector import ConnectorError, JsonConnector

SUPPORTED = {"identify", "track", "alias", "page", "screen", "group"}


class SegmentIOConnector(JsonConnector):
    def to_event_json(self, data: Mapping) -> dict:
        msg_type = data.get("type")
        if msg_type not in SUPPORTED:
            raise ConnectorError(
                f"segmentio message type {msg_type!r} not supported "
                f"(supported: {sorted(SUPPORTED)})"
            )
        user_id = data.get("userId") or data.get("anonymousId")
        if not user_id:
            raise ConnectorError("segmentio message has no userId/anonymousId")
        properties: dict = {}
        if msg_type == "identify":
            properties = dict(data.get("traits") or {})
        elif msg_type == "track":
            properties = {
                "event": data.get("event"),
                **(data.get("properties") or {}),
            }
        elif msg_type in ("page", "screen"):
            properties = {
                "name": data.get("name"),
                **(data.get("properties") or {}),
            }
        elif msg_type == "group":
            properties = {
                "groupId": data.get("groupId"),
                **(data.get("traits") or {}),
            }
        elif msg_type == "alias":
            properties = {"previousId": data.get("previousId")}
        out = {
            "event": msg_type,
            "entityType": "user",
            "entityId": str(user_id),
            "properties": {k: v for k, v in properties.items() if v is not None},
        }
        if data.get("timestamp"):
            out["eventTime"] = data["timestamp"]
        return out
